"""CLI entry: ``zest <command>`` (reference: src/main.zig:33-81).

Commands: pull | seed | serve | start | stop | bench | version | help —
the reference's full surface, plus ``--device=tpu`` on pull (the north-star
flag) and ``models`` for cache introspection. Daemon lifecycle uses a PID
file under the cache dir exactly like cmdServe/cmdStop
(src/main.zig:436,550-590,592-636).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

from zest_tpu.config import Config
from zest_tpu.version import __version__


def _pid_file(cfg: Config) -> Path:
    return cfg.cache_dir / "zest.pid"


def _write_pid_file(cfg: Config) -> None:
    cfg.cache_dir.mkdir(parents=True, exist_ok=True)
    _pid_file(cfg).write_text(str(os.getpid()))


def _remove_pid_file(cfg: Config) -> None:
    """Remove BOTH daemon state files (pid + recorded http port) — a
    surviving port record would keep ephemeral-port clients dialing a
    dead daemon's port."""
    for path in (_pid_file(cfg), cfg.http_port_file()):
        try:
            path.unlink()
        except OSError:
            pass


def _daemon_get(cfg: Config, path: str, timeout: float = 2.0) -> dict | None:
    """GET a daemon endpoint; None on ANY failure — daemon down, requests
    missing (not a core dependency; every local-only path must still
    work), or a foreign service on a stale recorded port answering
    something that isn't the daemon's JSON-dict shape."""
    try:
        import requests
    except ImportError:
        return None
    try:
        r = requests.get(
            f"http://127.0.0.1:{cfg.effective_http_port()}{path}",
            timeout=timeout,
        )
        if not r.ok:
            return None
        payload = r.json()
    except (requests.RequestException, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def _server_running(cfg: Config) -> bool:
    """Health-check the daemon (reference isServerRunning, main.zig:532-548)."""
    return _daemon_get(cfg, "/v1/health", timeout=1.0) is not None


def auto_start_server(cfg: Config) -> bool:
    """Detached ``serve`` spawn after a pull so the node seeds what it just
    cached — "the package IS the seeder" (reference main.zig:485-508)."""
    if _server_running(cfg):
        return False
    subprocess.Popen(
        [sys.executable, "-m", "zest_tpu", "serve",
         "--http-port", str(cfg.http_port),
         "--listen-port", str(cfg.listen_port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    return True


def _build_swarm(cfg: Config, tracker: str | None = None, dht: bool = True):
    """SwarmDownloader with the configured discovery sources: DHT (UDP on
    the listen port, reference swarm.zig:221) and/or an HTTP tracker."""
    from zest_tpu.p2p import peer_id as peer_id_mod
    from zest_tpu.transfer.swarm import SwarmDownloader

    sources = []
    if dht:
        try:
            from zest_tpu.p2p.dht import Dht

            sources.append(Dht(bind=("0.0.0.0", cfg.listen_port)))
        except OSError:  # port busy (daemon already owns it): client-only
            try:
                from zest_tpu.p2p.dht import Dht

                sources.append(Dht(bind=("0.0.0.0", 0)))
            except OSError:
                pass
    if tracker:
        from zest_tpu.p2p.tracker import TrackerClient

        sources.append(TrackerClient(tracker, peer_id_mod.generate(),
                                     listen_port=cfg.listen_port))
    swarm = SwarmDownloader(cfg, peer_sources=sources)
    _attach_fleet_gossip(cfg, swarm)
    return swarm


def _attach_fleet_gossip(cfg: Config, swarm, dcn_server=None):
    """Fleet gossip wiring (transfer.gossip; ISSUE 16): with a coop
    identity (host index + fleet size) and ZEST_GOSSIP on, the node
    becomes the swarm's primary discovery source (tracker/DHT demote
    to bootstrap announce) and, when a DcnServer is given, answers
    anti-entropy exchanges on its listener. Returns the node or None
    (no identity / ZEST_GOSSIP=0 — tracker-only, bit-for-bit)."""
    if cfg.coop_index is None or not cfg.coop_hosts \
            or cfg.coop_hosts < 2:
        return None
    from zest_tpu.transfer.gossip import node_from_config

    node = node_from_config(cfg, cfg.coop_index, cfg.coop_hosts,
                            cfg.coop_addrs or None)
    if node is None:
        return None
    swarm.attach_gossip(node)
    if dcn_server is not None:
        dcn_server.attach_gossip(node)
    return node


# ── Commands ──


def cmd_pull(args) -> int:
    cfg = Config.load()
    if args.http_port is not None:
        # Unlike `serve` (which binds the port and may take 0 =
        # ephemeral), pull uses it to *reach* the daemon — 0 would
        # health-check 127.0.0.1:0, never find the daemon, and spawn an
        # unreachable orphan on every pull.
        if args.http_port == 0:
            print("error: --http-port 0 (ephemeral) is only valid for "
                  "`serve`; pull needs the daemon's actual port",
                  file=sys.stderr)
            return 2
        cfg.http_port = args.http_port
    if args.dtype:
        cfg.land_dtype = args.dtype
    swarm = None
    if not args.no_p2p:
        try:
            swarm = _build_swarm(cfg, tracker=args.tracker,
                                 dht=not args.no_dht)
            for spec in args.peer or []:
                host, _, port = spec.rpartition(":")
                swarm.add_direct_peer(host, int(port))
        except Exception as exc:  # noqa: BLE001 - degrade to CDN-only
            print(f"p2p unavailable ({exc}); continuing CDN-only",
                  file=sys.stderr)
    from zest_tpu.transfer.pull import pull_model

    pod = True if args.pod else (False if args.no_pod else None)
    if (args.pods is None) != (args.pod_index is None):
        print("error: --pods and --pod-index must be given together",
              file=sys.stderr)
        return 2
    if args.pods is not None and not 0 <= args.pod_index < args.pods:
        print(f"error: --pod-index {args.pod_index} outside [0,{args.pods})",
              file=sys.stderr)
        return 2
    def parse_addr_flags(flag: str, specs) -> dict | None:
        from zest_tpu.config import parse_host_addr

        out = {}
        for spec in specs or []:
            try:
                idx, addr = parse_host_addr(spec)
            except ValueError:
                print(f"error: {flag} {spec!r} is not I=HOST:PORT",
                      file=sys.stderr)
                return None
            out[idx] = addr
        return out

    pod_addrs = parse_addr_flags("--pod-addr", args.pod_addr)
    if pod_addrs is None:
        return 2
    coop = True if args.coop else (False if args.no_coop else None)
    coop_addrs = parse_addr_flags("--coop-addr", args.coop_addr)
    if coop_addrs is None:
        return 2
    import contextlib

    profile_ctx = contextlib.nullcontext()
    if args.profile:
        # Standard JAX profiler hook (SURVEY.md §5 tracing): the whole
        # pull — CAS, distribution round, HBM commit — lands in one
        # TensorBoard/Perfetto trace directory.
        import jax

        profile_ctx = jax.profiler.trace(args.profile)
    # Validate cheap config up front with the CLI's error contract; a
    # blanket except around the pull would misreport deep failures
    # (e.g. requests' JSONDecodeError subclasses ValueError) as config
    # errors.
    try:
        cfg.model_cache_dir(args.repo)  # repo-id syntax
        if args.device == "tpu":
            from zest_tpu.models.loader import resolve_dtype

            resolve_dtype(cfg.land_dtype)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    with profile_ctx:
        res = pull_model(cfg, args.repo, revision=args.revision,
                         device=args.device, swarm=swarm,
                         no_p2p=args.no_p2p, pod=pod, pods=args.pods,
                         pod_index=args.pod_index, pod_addrs=pod_addrs,
                         coop=coop, coop_hosts=args.coop_hosts,
                         coop_index=args.coop_index,
                         coop_addrs=coop_addrs)
    if args.profile:
        print(f"profiler trace written to {args.profile}")
    print(f"✓ {args.repo} -> {res.snapshot_dir}")
    _print_pull_stats(res.stats)
    if not args.no_seed:
        if auto_start_server(cfg):
            print("seeding daemon started in the background")
    return 0


def _print_pull_stats(stats: dict) -> None:
    fetch = stats.get("fetch") or {}
    if fetch:
        nbytes = fetch.get("bytes", {})
        print(f"  From cache: {nbytes.get('cache', 0)} bytes")
        print(f"  From peers: {nbytes.get('peer', 0)} bytes")
        print(f"  From CDN:   {nbytes.get('cdn', 0)} bytes")
        print(f"  P2P ratio:  {fetch.get('p2p_ratio', 0.0):.1%}")
    print(f"  Elapsed:    {stats.get('elapsed_s', 0)}s")
    stages = stats.get("stages") or {}
    if stages:
        # SURVEY §5 per-stage tracing, in pipeline order (the reference
        # prints only end-of-pull totals, swarm.zig:472-485).
        order = ("resolve", "cas_metadata", "fetch", "hbm_commit",
                 "files")
        parts = [f"{name} {stages[name]:.2f}s"
                 for name in order if name in stages]
        parts += [f"{name} {val:.2f}s" for name, val in stages.items()
                  if name not in order]
        print(f"  Stages:     {'  '.join(parts)}")
        # Pipelined stages: busy (thread-seconds) above the stage wall
        # means work ran concurrently — show only where it did.
        busy = stats.get("stages_busy") or {}
        pipelined = [f"{name} {busy[name]:.2f}s" for name in stages
                     if busy.get(name, 0.0) > stages[name] + 0.05]
        if pipelined:
            print(f"  Busy:       {'  '.join(pipelined)} "
                  "(thread-seconds > stage wall: pipelined)")
    if "coop" in stats and not stats["coop"].get("skipped"):
        c = stats["coop"]
        ex = c.get("exchange", {})
        print(f"  Coop:       host {c['host']}/{c['hosts']}: "
              f"{(c.get('fetch') or {}).get('units', 0)} fetched, "
              f"{ex.get('units', 0)} over DCN "
              f"({ex.get('wire_bytes', 0)} wire bytes), "
              f"{c.get('fallbacks', 0)} fallback — peer-served "
              f"{c.get('peer_served_ratio', 0.0):.1%}")
        cx = c.get("collective")
        if cx:
            links = " ".join(f"{lk}={b}" for lk, b in
                             sorted((cx.get("link_bytes") or {}).items())
                             if b)
            line = (f"  Collective: {cx.get('schedule')} "
                    f"{cx.get('phases', 0)} phase(s), "
                    f"{cx.get('windows', 0)} window(s)")
            if links:
                line += f" [{links}]"
            if cx.get("aborted"):
                line += (f" — aborted ({cx['aborted']}), degraded to "
                         "point-to-point")
            print(line)
    if "federated" in stats:
        f = stats["federated"]
        print(f"  Federated:  pod {f['pod']}/{f['pods']}: {f['own_units']} "
              f"own, {f['dcn_units']} over DCN ({f['dcn_bytes']} bytes), "
              f"{f['fallback_units']} CDN-fallback")
    if "pod" in stats and not stats["pod"].get("skipped"):
        p = stats["pod"]
        print(f"  Pod round:  {p['filled']}/{p['units']} units over "
              f"{p['slots']} slots, gather {p['gather_s']}s")
    if "delta" in stats:
        d = stats["delta"]
        line = (f"  Delta:      {d['changed_bytes']} of "
                f"{d['total_bytes']} bytes changed vs "
                f"{d['base_revision'][:12]} "
                f"({d['delta_bytes_ratio']:.1%})")
        if "fetched_bytes" in d:
            line += f"; fetched {d['fetched_bytes']} bytes"
        if "tensors" in d:
            line += (f"; {d['tensors']['reused']} tensors reused, "
                     f"{d['tensors']['landed']} landed")
        print(line)
    if "hbm" in stats:
        h = stats["hbm"]
        if "error" in h:
            print(f"  HBM commit: FAILED ({h['error']})")
        else:
            print(f"  HBM commit: {h['tensors']} tensors, {h['bytes']} "
                  f"bytes ({h['gbps']} GB/s)"
                  + (" [direct]" if h.get("direct") else ""))
        fl = stats.get("time_to_first_layer_s")
        hbm_s = stats.get("time_to_hbm_s")
        if fl is not None and hbm_s:
            print(f"  First layer: {fl}s of {hbm_s}s to HBM "
                  f"({fl / hbm_s:.0%})")
        swap_s = stats.get("time_to_swap_s")
        if swap_s is not None:
            print(f"  Hot swap:   mesh swapped in {swap_s}s")


def cmd_generate(args) -> int:
    """Pull (idempotent) then greedy-decode with the family model — the
    reference's verify loop ("pull, load, generate",
    test/local/verify-model.sh:103-147) as a first-class command, running
    on the pure-JAX models instead of torch."""
    cfg = Config.load()
    from zest_tpu.models.generate import (
        UnsupportedModelError, load_generator, try_tokenizer,
    )
    from zest_tpu.transfer.pull import pull_model

    # Flag validation is pull-independent — do it before a possibly
    # multi-GB download (only the tokenizer lookup needs the snapshot).
    prompt = None
    if args.steps < 1:
        print(f"error: --steps must be positive (got {args.steps})",
              file=sys.stderr)
        return 2
    if args.ids:
        try:
            prompt = [int(t) for t in args.ids.split(",")]
        except ValueError:
            print(f"error: --ids {args.ids!r} is not a comma-separated "
                  "list of ints", file=sys.stderr)
            return 2
    elif args.prompt is None:
        print("error: one of --prompt or --ids is required",
              file=sys.stderr)
        return 2

    res = pull_model(cfg, args.repo, revision=args.revision,
                     no_p2p=args.no_p2p)
    tok = try_tokenizer(res.snapshot_dir)
    if prompt is None:
        if tok is None:
            print("error: snapshot has no tokenizer; pass token ids via "
                  "--ids", file=sys.stderr)
            return 2
        prompt = tok.encode(args.prompt)
    try:
        model_type, generate = load_generator(res.snapshot_dir)
        out = generate(prompt, args.steps, temperature=args.temperature,
                       top_k=args.top_k, top_p=args.top_p, seed=args.seed,
                       stop_at_eos=not args.ignore_eos)
    except (UnsupportedModelError, FileNotFoundError, ValueError) as exc:
        # ValueError: context overflow (prompt+steps > n_ctx) and kin —
        # a usage problem, not a crash.
        print(f"error: {exc}", file=sys.stderr)
        return 1
    new = out[len(prompt):]
    print(f"[{model_type}] {len(prompt)} prompt + {len(new)} new tokens")
    if tok is not None:
        print(tok.decode(list(out)))
    else:
        print(",".join(str(int(t)) for t in out))
    return 0


def cmd_seed(args) -> int:
    """Announce every cached xorb to the swarm (reference main.zig:307-369)."""
    cfg = Config.load()
    from zest_tpu import storage

    hashes = storage.list_cached_xorbs(cfg)
    if not hashes:
        print("nothing cached to seed")
        return 0
    swarm = _build_swarm(cfg, tracker=args.tracker)
    n = swarm.announce_xorbs(hashes)
    print(f"announced {n}/{len(hashes)} xorbs to the swarm")
    return 0


def cmd_serve(args) -> int:
    """Foreground seeding server + REST API (reference main.zig:403-469)."""
    cfg = Config.load()
    # `is not None`, not truthiness: port 0 means "bind ephemeral" for
    # every transport here, and a falsy check silently ignored it.
    if args.http_port is not None:
        cfg.http_port = args.http_port
    if args.listen_port is not None:
        cfg.listen_port = args.listen_port
    if args.dcn_port is not None:
        cfg.dcn_port = args.dcn_port

    from zest_tpu import storage
    from zest_tpu.api.http_api import HttpApi
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.server import BtServer

    from zest_tpu.p2p.health import HealthRegistry
    from zest_tpu.transfer.swarm import SwarmDownloader

    registry = storage.XorbRegistry()
    n = registry.scan(cfg)
    print(f"indexed {n} cached xorbs")

    # One health registry for the whole daemon: the serving tier's
    # reciprocity/unchoke ranking, stalled-reader strikes, and the
    # quarantine oracle behind source-refusal all read/write the same
    # book the pull side writes. The shared SwarmDownloader below is
    # what feeds it — HttpApi threads it into every /v1/pull
    # (pull_model(swarm=)), so bytes peers serve US rank their unchoke
    # slots and a peer quarantined mid-pull is refused by the server.
    health = HealthRegistry()
    swarm = SwarmDownloader(cfg, health=health)
    bt = BtServer(cfg, health=health)
    port = bt.start()
    shaped = ""
    if cfg.seed_rate_bps or cfg.seed_peer_bps:
        shaped = (f", shaped {cfg.seed_rate_bps or '∞'} B/s global"
                  f" / {cfg.seed_peer_bps or '∞'} B/s per-peer")
    print(f"seeding on :{port} (BT wire, {cfg.seed_slots}+1 upload "
          f"slots{shaped})")

    # Same cache, second transport: the lean chunk RPC other zest hosts
    # use across DCN (foreign BT clients keep the wire protocol above).
    # A taken port degrades to BT-only serving, not a dead daemon.
    dcn_server = DcnServer(cfg, bt.cache)
    try:
        dcn_port = dcn_server.start()
        print(f"seeding on :{dcn_port} (DCN chunk RPC)")
    except OSError as exc:
        print(f"DCN listener disabled (port {cfg.dcn_port}: {exc})")

    # Fleet gossip (ISSUE 16): the daemon both answers anti-entropy
    # exchanges (piggybacked on the DCN listener) and runs the active
    # tick loop against its coop peers' DCN endpoints.
    gossip_stop = None
    gossip_node = _attach_fleet_gossip(cfg, swarm, dcn_server)
    if gossip_node is not None and cfg.coop_addrs:
        import threading

        from zest_tpu.transfer.dcn import DcnPool
        from zest_tpu.transfer.gossip import DcnGossipTransport

        transport = DcnGossipTransport(DcnPool(), cfg.coop_addrs)
        gossip_stop = threading.Event()

        def _gossip_loop():
            while not gossip_stop.wait(cfg.gossip_interval_s):
                try:
                    gossip_node.tick(transport)
                except Exception:  # noqa: BLE001 - gossip best-effort
                    pass

        threading.Thread(target=_gossip_loop, name="zest-gossip",
                         daemon=True).start()
        print(f"gossip: fanout {gossip_node.fanout()} over "
              f"{len(cfg.coop_addrs)} peers, "
              f"every {cfg.gossip_interval_s:g}s")

    _write_pid_file(cfg)
    api = HttpApi(cfg, bt_server=bt, registry=registry,
                  dcn_server=dcn_server, swarm=swarm,
                  gossip_node=gossip_node)
    api.start()
    # Record the BOUND port (http_port=0 binds ephemeral): status/stop/
    # the Python client resolve it via Config.effective_http_port.
    cfg.http_port_file().write_text(str(api.port))
    print(f"dashboard: http://127.0.0.1:{api.port}/")

    def on_signal(sig, _frm):
        if sig == signal.SIGTERM:
            # Flight-recorder contract (ISSUE 7): a SIGTERM'd daemon
            # leaves its last-events crash report behind — the k8s/OOM
            # eviction story is otherwise unreconstructable.
            from zest_tpu.telemetry import recorder

            path = recorder.dump_crash_report(cfg.cache_dir, "SIGTERM")
            if path:
                print(f"flight-recorder report: {path}")
        api.trigger_shutdown()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    try:
        api.shutdown_event.wait()
    finally:
        if gossip_stop is not None:
            gossip_stop.set()
        api.close()
        dcn_server.shutdown()
        bt.shutdown()
        swarm.close()
        _remove_pid_file(cfg)
    return 0


def _announce_dashboard(cfg: Config) -> None:
    """Print the dashboard + metrics URLs once the daemon is healthy
    (reference: main.zig:471-482 opens the browser after serve comes
    up); with ``ZEST_OPEN_DASHBOARD=1`` also open it in the default
    browser — opt-in, because `start` runs headless in CI and on pod
    hosts."""
    url = f"http://127.0.0.1:{cfg.effective_http_port()}/"
    print(f"dashboard: {url}")
    print(f"metrics:   {url}v1/metrics  (?scope=pod on the coordinator)")
    if os.environ.get("ZEST_OPEN_DASHBOARD") == "1":
        import webbrowser

        try:
            webbrowser.open(url)
        except Exception:  # noqa: BLE001 - no browser is not an error
            pass


def cmd_start(_args) -> int:
    cfg = Config.load()
    if _server_running(cfg):
        print("already running")
        _announce_dashboard(cfg)
        return 0
    auto_start_server(cfg)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if _server_running(cfg):
            print(f"started (http :{cfg.effective_http_port()})")
            _announce_dashboard(cfg)
            return 0
        time.sleep(0.1)
    print("daemon failed to become healthy", file=sys.stderr)
    return 1


def cmd_stop(_args) -> int:
    """REST stop with PID-file kill fallback (reference main.zig:550-590)."""
    cfg = Config.load()
    import requests

    # Health-check FIRST (ADVICE r5): with the http_port=0 convention a
    # stale zest.http_port record can point at whatever foreign loopback
    # service reused the port, and a blind POST /v1/stop would land on
    # it. Only a responder answering the daemon's /v1/health JSON shape
    # gets the stop POST; anything else falls to the pid-file kill.
    if _daemon_get(cfg, "/v1/health", timeout=1.0) is not None:
        try:
            r = requests.post(
                f"http://127.0.0.1:{cfg.effective_http_port()}/v1/stop",
                timeout=5,
            )
            # Only a 2xx proves the daemon acknowledged: anything else
            # may still be a foreign service — fall through to the
            # pid-file kill rather than reporting success.
            if r.ok:
                print("stopped")
                return 0
        except requests.RequestException:
            pass
    pid_file = _pid_file(cfg)
    if pid_file.exists():
        try:
            pid = int(pid_file.read_text().strip())
            os.kill(pid, signal.SIGTERM)
            print(f"sent SIGTERM to pid {pid}")
        except (ValueError, ProcessLookupError):
            print("stale pid file removed")
        _remove_pid_file(cfg)
        return 0
    print("not running")
    return 0


def cmd_status(_args) -> int:
    cfg = Config.load()
    payload = _daemon_get(cfg, "/v1/status")
    if payload is None:
        print("daemon not running")
        return 1
    print(json.dumps(payload, indent=2))
    return 0


def cmd_stats(args) -> int:
    """Process-wide metrics from the daemon's registry: ``GET
    /v1/metrics`` verbatim (Prometheus text — pipe it anywhere a scraper
    would), the ``/v1/status`` telemetry/faults/peer-health blocks with
    ``--json``, or a 1 Hz live redraw with ``--watch`` (the operator's
    top(1) over the new ``/v1/debug`` surface)."""
    cfg = Config.load()
    if args.watch:
        return _stats_watch(cfg, interval=args.interval,
                            count=args.count)
    if args.json:
        payload = _daemon_get(cfg, "/v1/status")
        if payload is None:
            print("daemon not running", file=sys.stderr)
            return 1
        keep = {k: payload[k] for k in
                ("telemetry", "faults", "swarm", "peers", "hbm", "dcn")
                if k in payload}
        print(json.dumps(keep, indent=2))
        return 0
    try:
        import requests
    except ImportError:
        print("error: `zest stats` needs the requests package",
              file=sys.stderr)
        return 1
    scope = "?scope=pod" if args.pod else ""
    try:
        r = requests.get(
            f"http://127.0.0.1:{cfg.effective_http_port()}"
            f"/v1/metrics{scope}",
            timeout=10.0 if args.pod else 2.0,
        )
        r.raise_for_status()
    except requests.RequestException:
        print("daemon not running", file=sys.stderr)
        return 1
    sys.stdout.write(r.text)
    return 0


def _stats_watch_lines(debug: dict, status: dict) -> list[str]:
    """One redraw frame of ``zest stats --watch`` (pure — testable)."""
    lines = [f"zest-tpu v{status.get('version', '?')}  "
             f"http_requests={status.get('http_requests', 0)}  "
             f"xorbs={status.get('xorbs_cached', 0)}"]
    landing = debug.get("landing") or {}
    if landing:
        fl = landing.get("first_layer_s")
        hbm = landing.get("time_to_hbm_s")
        ratio = landing.get("first_layer_ratio")
        lane = "landing:"
        if fl is not None:
            lane += f" first_layer={fl}s"
        if hbm is not None:
            lane += f" hbm={hbm}s"
        if ratio is not None:
            lane += f" ({ratio:.0%} of hbm)"
        if "ring_stalls" in landing:
            lane += f"  ring_stalls={landing['ring_stalls']}"
        lines.append(lane)
        if "delta_ratio" in landing or "swap_s" in landing:
            dline = "delta:"
            if "delta_ratio" in landing:
                dline += f" fetched={landing['delta_ratio']:.1%} of bytes"
            if "swap_s" in landing:
                dline += f"  swap={landing['swap_s']}s"
            lines.append(dline)
    coop = debug.get("coop") or {}
    if coop:
        ratio = coop.get("peer_served_ratio")
        tiers = " ".join(f"{t}={b}" for t, b in
                         sorted((coop.get("tier_bytes") or {}).items()))
        lines.append(
            "coop: peer_served="
            + (f"{ratio:.1%}" if ratio is not None else "n/a")
            + (f"  wall={coop['exchange_wall_s']}s"
               if "exchange_wall_s" in coop else "")
            + (f"  fallbacks={coop['fallbacks']}"
               if "fallbacks" in coop else "")
            + (f"  [{tiers}]" if tiers else ""))
        cx = coop.get("collective") or {}
        if cx:
            links = " ".join(
                f"{lk}={b}" for lk, b in
                sorted((cx.get("link_bytes") or {}).items()))
            lines.append(
                f"collective: phases={cx.get('phases', 0)}"
                + (f"  wall={cx['wall_s']}s" if "wall_s" in cx else "")
                + (f"  aborts={cx['aborts']}" if cx.get("aborts") else "")
                + (f"  [{links}]" if links else ""))
    seeding = status.get("seeding") or {}
    if seeding.get("chunks_served") or seeding.get("active_leechers"):
        sline = (f"seed: {seeding.get('bytes_served', 0)}B in "
                 f"{seeding.get('chunks_served', 0)} chunks  "
                 f"unchoked={seeding.get('unchoked', 0)}"
                 f"/{seeding.get('unchoked', 0) + seeding.get('choked', 0)}")
        if seeding.get("choke_events"):
            sline += f"  choke_events={seeding['choke_events']}"
        if seeding.get("refused_quarantined"):
            sline += f"  refused={seeding['refused_quarantined']}"
        if seeding.get("uploads_expired"):
            sline += f"  expired={seeding['uploads_expired']}"
        if seeding.get("rate_bps"):
            sline += f"  rate={seeding['rate_bps']}B/s"
        lines.append(sline)
    quarantined = debug.get("quarantined_peers") or []
    if quarantined:
        lines.append("quarantined: "
                     + ", ".join(p["peer"] for p in quarantined))
    faults_fired = debug.get("faults") or {}
    if faults_fired:
        lines.append("faults: " + " ".join(
            f"{k}={v}" for k, v in sorted(faults_fired.items())))
    events = (debug.get("recorder") or {}).get("events") or []
    if events:
        lines.append("recorder tail:")
        for ev in events[-8:]:
            extra = " ".join(f"{k}={v}" for k, v in ev.items()
                             if k not in ("t", "kind"))
            lines.append(f"  {ev.get('t', 0):.3f} {ev.get('kind')} {extra}")
    return lines


def _stats_watch(cfg: Config, interval: float = 1.0,
                 count: int = 0) -> int:
    """Redraw loop: ANSI home+clear per frame, Ctrl-C exits clean.
    ``count`` bounds the frames (0 = until interrupted; tests use 1)."""
    frames = 0
    try:
        while True:
            debug = _daemon_get(cfg, "/v1/debug?tail=8") or {}
            status = _daemon_get(cfg, "/v1/status") or {}
            if not status:
                print("daemon not running", file=sys.stderr)
                return 1
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[H\x1b[2J")
            print("\n".join(_stats_watch_lines(debug, status)))
            frames += 1
            if count and frames >= count:
                return 0
            time.sleep(max(0.1, interval))
    except KeyboardInterrupt:
        return 0


def _ps_lines(payload: dict) -> list[str]:
    """One ``zest ps`` frame from the ``/v1/pulls`` document (pure —
    testable). Active sessions first, then the recent ring."""
    rows = [("ID", "REPO@REV", "TENANT", "STATUS", "PHASE", "PROG",
             "ELAPSED")]

    def row(s: dict) -> tuple:
        rev = str(s.get("revision", ""))[:12]
        prog = ""
        if s.get("progress") is not None:
            prog = f"{s['progress']:.0%}"
            if s.get("eta_s") is not None:
                prog += f" eta {s['eta_s']}s"
        status = s.get("status", "?")
        if s.get("slo") and any(v.get("breached")
                                for v in s["slo"].values()):
            status += "!slo"
        return (s.get("id", "?"), f"{s.get('repo', '?')}@{rev}",
                s.get("tenant") or "-", status, s.get("phase", ""),
                prog, f"{s.get('elapsed_s', 0)}s")

    for s in payload.get("active") or []:
        rows.append(row(s))
    for s in payload.get("recent") or []:
        rows.append(row(s))
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    if len(rows) == 1:
        lines.append("no pull sessions (daemon idle, or ZEST_TELEMETRY=0)")
    # Admission column (ISSUE 13): queued vs active against the budget,
    # plus total typed-429 rejects — queued sessions also show
    # individually above with phase "queued".
    tn = payload.get("tenancy") or {}
    if tn:
        line = (f"tenancy: {tn.get('active', 0)}/{tn.get('max_pulls', '?')}"
                f" active  {tn.get('queued', 0)}/{tn.get('queue_cap', '?')}"
                " queued")
        if tn.get("rejected_total"):
            line += f"  rejected {tn['rejected_total']}"
        lines.append(line)
    burn = payload.get("slo") or {}
    if burn:
        lines.append("slo burn: " + "  ".join(
            f"{k}={v['breaches']}/{v['pulls']} ({v['burn']:.1%})"
            for k, v in sorted(burn.items())))
    return lines


def _fmt_rate(bps: float) -> str:
    """Human B/s: 1.25 GB/s, 310 MB/s, 12 kB/s, 0 B/s."""
    if bps >= 1e9:
        return f"{bps / 1e9:.2f} GB/s"
    if bps >= 1e6:
        return f"{bps / 1e6:.1f} MB/s"
    if bps >= 1e3:
        return f"{bps / 1e3:.0f} kB/s"
    return f"{bps:.0f} B/s"


def _bar(frac: float, width: int = 24) -> str:
    frac = min(1.0, max(0.0, frac))
    filled = int(round(frac * width))
    return "[" + "#" * filled + "-" * (width - filled) + "]"


def _series_last(tl: dict, name: str):
    s = (tl.get("series") or {}).get(name)
    if not s or not s.get("samples"):
        return None
    return s["samples"][-1][1]


def _series_rate(tl: dict, name: str):
    """Latest value-per-second of a gauge series (e.g. a session's
    byte-progress series) from its last two samples."""
    s = (tl.get("series") or {}).get(name)
    pts = (s or {}).get("samples") or []
    if len(pts) < 2:
        return None
    (t0, v0), (t1, v1) = pts[-2], pts[-1]
    if t1 <= t0:
        return None
    return max(0.0, (v1 - v0) / (t1 - t0))


def _top_lines(status: dict, pulls: dict, tl: dict,
               width: int = 78) -> list[str]:
    """One ``zest top`` frame (pure — testable): header, per-session
    progress bars with live byte rates, tier-rate and queue/ring lines
    from the timeline's latest samples, and the anomaly tail."""
    active = pulls.get("active") or []
    recent = pulls.get("recent") or []
    tn = pulls.get("tenancy") or {}
    anomalies = tl.get("anomalies") or []
    lines = [
        f"zest top — v{status.get('version', '?')}  "
        f"active {len(active)}"
        + (f"  queued {tn.get('queued', 0)}" if tn else "")
        + f"  recent {len(recent)}"
        + (f"  anomalies {len(anomalies)}" if anomalies else "")
    ]
    for s in active:
        sid = s.get("id", "?")
        frac = s.get("progress")
        rate = _series_rate(tl, f"session.{sid}.bytes")
        anns = ",".join(sorted((s.get("anomalies") or {})))
        row = (f"  {sid}  {s.get('repo', '?')}  "
               f"{s.get('phase', ''):<10} ")
        row += _bar(frac if frac is not None else 0.0)
        if frac is not None:
            row += f" {frac:>4.0%}"
        if rate is not None:
            row += f"  {_fmt_rate(rate)}"
        if s.get("eta_s") is not None:
            row += f"  eta {s['eta_s']}s"
        if anns:
            row += f"  !{anns}"
        lines.append(row[:width + 30])
    if not active:
        lines.append("  (no active pulls)")
    # Tier rates: the latest per-tier fetch B/s samples, then the other
    # wire lanes when they have history.
    tiers = []
    for tier in ("cdn", "peer", "cache", "dcn"):
        v = _series_last(tl, f"fetch.{tier}_bps")
        if v is not None:
            tiers.append(f"{tier}={_fmt_rate(v)}")
    for name, label in (("dcn.bps", "dcn_serve"), ("seed.bps", "seed"),
                        ("collective.ici_bps", "coll_ici"),
                        ("collective.dcn_bps", "coll_dcn")):
        v = _series_last(tl, name)
        if v:
            tiers.append(f"{label}={_fmt_rate(v)}")
    if tiers:
        lines.append("rates: " + "  ".join(tiers))
    ring = _series_last(tl, "ring.in_use_bytes")
    if ring is not None:
        stalls = _series_last(tl, "ring.stalls")
        lines.append(f"ring:  {int(ring):,} B in flight"
                     + (f"  stalls={int(stalls)}" if stalls else ""))
    depth = _series_last(tl, "tenancy.queue_depth")
    if depth is not None:
        adm = int(_series_last(tl, "tenancy.active_pulls") or 0)
        flights = int(_series_last(tl, "tenancy.inflight_fetches") or 0)
        lines.append(f"queue: depth={int(depth)}  active={adm}"
                     f"  inflight_fetches={flights}")
    for ev in anomalies[-4:]:
        row = f"anomaly: {ev.get('kind')}"
        if ev.get("session"):
            row += f"  session={ev['session']}"
        for k in ("phase", "partner", "depth", "rate_bps"):
            if k in ev:
                row += f"  {k}={ev[k]}"
        lines.append(row)
    if tl.get("enabled") is False:
        lines.append("timelines off (ZEST_TIMELINE=0) — rates and "
                     "anomalies unavailable")
    return lines


def cmd_top(args) -> int:
    """``zest top`` — the operator's live full-screen view over
    ``/v1/pulls`` + ``/v1/timeline``: per-session progress bars with
    live rates, tier throughput, queue/ring state, anomaly tail.
    Redraws until Ctrl-C (or ``--count`` frames, for tests)."""
    cfg = Config.load()
    frames = 0
    try:
        while True:
            status = _daemon_get(cfg, "/v1/status")
            if status is None:
                print("daemon not running", file=sys.stderr)
                return 1
            pulls = _daemon_get(cfg, "/v1/pulls") or {}
            tl = _daemon_get(cfg, "/v1/timeline") or {}
            if args.json:
                print(json.dumps({"status": status, "pulls": pulls,
                                  "timeline": tl}, indent=2))
            else:
                if sys.stdout.isatty():
                    sys.stdout.write("\x1b[H\x1b[2J")
                print("\n".join(_top_lines(status, pulls, tl)))
            frames += 1
            if args.count and frames >= args.count:
                return 0
            if args.json:
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def cmd_ps(args) -> int:
    """``zest ps [--watch]`` — the daemon's live pull sessions
    (``GET /v1/pulls``): id, repo@rev, tenant, phase, progress/ETA,
    plus the recent ring and the SLO burn line."""
    cfg = Config.load()
    frames = 0
    try:
        while True:
            payload = _daemon_get(cfg, "/v1/pulls")
            if payload is None:
                print("daemon not running", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(payload, indent=2))
            else:
                if args.watch and sys.stdout.isatty():
                    sys.stdout.write("\x1b[H\x1b[2J")
                print("\n".join(_ps_lines(payload)))
            frames += 1
            if not args.watch or (args.count and frames >= args.count):
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def _heal_lines(doc: dict) -> list[str]:
    """Render ``/v1/remediations`` as the operator view: engine mode,
    live knob overrides, per-action outcome counts, recent decisions."""
    lines: list[str] = []
    if not doc.get("enabled"):
        return ["self-healing off (ZEST_REMEDIATE=0 or timelines off) "
                "— the process is a pure observer"]
    mode = "DRY-RUN (decisions only)" if doc.get("dry_run") else "live"
    lines.append(
        f"self-healing: {mode}  "
        f"actions={','.join(doc.get('actions') or []) or '-'}  "
        f"rate={doc.get('rate_s')}s/token burst={doc.get('burst')}")
    if doc.get("shedding"):
        lines.append("LOAD SHEDDING ACTIVE — new queued pulls answer "
                     "429 until the SLO burn recovers")
    for name, k in sorted((doc.get("knobs") or {}).items()):
        if k.get("value") != k.get("base"):
            lines.append(
                f"knob {name}: {k.get('value')} "
                f"(base {k.get('base')}, rails "
                f"[{k.get('min')}, {k.get('max')}])")
    counts = doc.get("counts") or {}
    if counts:
        lines.append("decisions:")
        for action, outcomes in sorted(counts.items()):
            pairs = "  ".join(f"{o}={n}"
                              for o, n in sorted(outcomes.items()))
            lines.append(f"  {action:<8} {pairs}")
    else:
        lines.append("decisions: none yet")
    recent = doc.get("recent") or []
    if recent:
        lines.append("recent:")
    for e in recent[-10:]:
        ts = time.strftime("%H:%M:%S", time.localtime(e.get("t", 0)))
        row = (f"  {ts}  {e.get('action', '?'):<8} "
               f"{e.get('outcome', '?'):<12} {e.get('reason', '')}")
        if e.get("session"):
            row += f"  session={e['session']}"
        lines.append(row)
    return lines


def cmd_heal(args) -> int:
    """``zest heal [--watch|--json|--dry-run on|off]`` — the daemon's
    self-healing control plane (``/v1/remediations``): what the policy
    engine decided, on which anomaly, with which outcome, plus live
    knob overrides and shed state."""
    cfg = Config.load()
    if args.dry_run is not None:
        try:
            import requests
        except ImportError:
            print("daemon not running", file=sys.stderr)
            return 1
        want = args.dry_run == "on"
        try:
            r = requests.post(
                f"http://127.0.0.1:{cfg.effective_http_port()}"
                "/v1/remediations",
                json={"dry_run": want}, timeout=2.0)
            ok = r.ok
        except requests.RequestException:
            ok = False
        if not ok:
            print("daemon not running", file=sys.stderr)
            return 1
        print(f"dry-run {'on' if want else 'off'}")
        return 0
    frames = 0
    try:
        while True:
            payload = _daemon_get(cfg, f"/v1/remediations?limit="
                                       f"{args.limit}")
            if payload is None:
                print("daemon not running", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(payload, indent=2))
            else:
                if args.watch and sys.stdout.isatty():
                    sys.stdout.write("\x1b[H\x1b[2J")
                print("\n".join(_heal_lines(payload)))
            frames += 1
            if not args.watch or (args.count and frames >= args.count):
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def cmd_analyze(args) -> int:
    """``zest analyze <trace.json>`` — automated critical-path
    attribution over a completed trace export (solo or a
    ``zest trace --merge``d multi-host doc): the blame-attributed
    longest path through the span DAG, per-stage and per-tier
    exclusive seconds, and the top blocking spans. The
    bottleneck-attribution tool of record (SCALING.md)."""
    from zest_tpu.telemetry import critpath

    try:
        doc = json.loads(Path(args.trace).read_text())
    except (OSError, ValueError) as exc:
        print(f"error: cannot read trace: {exc}", file=sys.stderr)
        return 1
    try:
        report = critpath.analyze_doc(doc, host=args.host,
                                      top_k=args.top)
    except critpath.AnalyzeError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print("\n".join(critpath.render_text(report)))
    return 0


def cmd_debug(args) -> int:
    """Dump the daemon's ``/v1/debug`` surface — the flight-recorder
    tail, live coop summary, quarantine list — to stdout or, with
    ``--out``, to a JSON report file (the post-hoc triage artifact)."""
    cfg = Config.load()
    payload = _daemon_get(cfg, f"/v1/debug?tail={args.tail}",
                          timeout=5.0)
    if payload is None:
        print("daemon not running", file=sys.stderr)
        return 1
    body = json.dumps(payload, indent=2)
    if args.out:
        Path(args.out).write_text(body + "\n")
        n = len((payload.get("recorder") or {}).get("events") or [])
        print(f"debug report: {args.out} ({n} recorder events)")
    else:
        print(body)
    return 0


def _trace_merge_files(paths: list[str], out: str) -> int:
    """Offline merge: N per-host trace exports → one Perfetto file.
    Host keys come from each doc's recorded context (falling back to
    the file's position)."""
    from zest_tpu.telemetry import fleet

    docs = {}
    for i, p in enumerate(paths):
        doc = json.loads(Path(p).read_text())
        key = doc.get("otherData", {}).get("context", {}).get("host", i)
        docs[key] = doc
    merged = fleet.merge_traces(docs)
    Path(out).write_text(json.dumps(merged))
    meta = merged["otherData"]
    print(f"merged trace: {out} ({len(meta['merged_hosts'])} host "
          f"tracks, {meta['flow_links']} cross-host flow links)")
    print("view:  https://ui.perfetto.dev or chrome://tracing")
    return 0


def _gather_and_merge(cfg, own_doc, own_host, peer_apis, out) -> int:
    """``--coop`` tail: snapshot every peer daemon's ``/v1/trace`` and
    merge with this host's trace into ONE multi-track file."""
    from zest_tpu.telemetry import fleet

    docs, errors = fleet.gather_traces(peer_apis)
    for key, err in sorted(errors.items(), key=lambda i: str(i)):
        print(f"host {key}: trace unavailable ({err})", file=sys.stderr)
    # Prefer the host identity each doc recorded for itself.
    keyed = {}
    for key, doc in docs.items():
        keyed[doc.get("otherData", {}).get("context", {})
              .get("host", key)] = doc
    keyed[own_host] = own_doc
    merged = fleet.merge_traces(keyed, reference=own_host)
    Path(out).write_text(json.dumps(merged))
    meta = merged["otherData"]
    print(f"merged trace: {out} ({len(meta['merged_hosts'])} host "
          f"tracks, {meta['flow_links']} cross-host flow links)")
    return 0


def cmd_trace(args) -> int:
    """Pull with the span tracer armed and write a Chrome/Perfetto
    trace — the measurement tool of record for per-stage attribution
    (open the JSON at ui.perfetto.dev or chrome://tracing). Equivalent
    to ``ZEST_TRACE=out.json zest pull ...`` but also prints the span
    count and wall-coverage so scripts can gate on a healthy trace.

    Fleet workflows (ISSUE 7): ``--merge a.json b.json`` merges
    already-exported per-host traces offline (no pull); ``--coop``
    runs the traced pull, then gathers every pod peer's live trace
    (``GET /v1/trace`` at the ``--peer-api``/ZEST_POD_PEERS endpoints)
    and writes ONE merged multi-track file instead of this host's
    slice."""
    if args.merge:
        return _trace_merge_files(args.merge, args.out)
    if args.repo is None:
        print("error: a repo id is required unless --merge is given",
              file=sys.stderr)
        return 2
    cfg = Config.load()
    try:
        cfg.model_cache_dir(args.repo)  # repo-id syntax, pre-network
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    peer_apis = {}
    if args.coop:
        from zest_tpu.config import parse_host_addr

        for spec in args.peer_api or []:
            try:
                idx, addr = parse_host_addr(spec)
            except ValueError:
                print(f"error: --peer-api {spec!r} is not I=HOST:PORT",
                      file=sys.stderr)
                return 2
            peer_apis[idx] = addr
        if not peer_apis:
            peer_apis = dict(cfg.pod_peers)
        if not peer_apis:
            print("error: --coop needs peer API endpoints (--peer-api "
                  "I=HOST:PORT or ZEST_POD_PEERS)", file=sys.stderr)
            return 2
    from zest_tpu import telemetry
    from zest_tpu.telemetry import trace as trace_mod
    from zest_tpu.transfer.pull import pull_model

    # The command IS the opt-in: a ZEST_TELEMETRY=0 environment must not
    # silently turn an explicitly requested trace into 0 events.
    telemetry.set_enabled(True)
    tracer = trace_mod.install(None)  # explicit export below, not atexit
    t0 = time.monotonic()
    failed = None
    try:
        res = pull_model(cfg, args.repo, revision=args.revision,
                         device=args.device, no_p2p=args.no_p2p,
                         coop=True if args.coop else None)
    except Exception as exc:  # noqa: BLE001 - trace of a failed pull is
        failed = exc          # exactly what the operator wants to see
    elapsed = time.monotonic() - t0
    if args.coop:
        own_host = cfg.coop_index if cfg.coop_index is not None \
            else cfg.mesh.process_id
        rc = _gather_and_merge(cfg, tracer.to_chrome(), own_host,
                               peer_apis, args.out)
        if rc:
            return rc
    else:
        n = tracer.export(args.out)
        cov = tracer.coverage_s()
        print(f"trace: {args.out} ({n} events, spans cover {cov:.2f}s "
              f"of {elapsed:.2f}s wall)")
    print("view:  https://ui.perfetto.dev or chrome://tracing")
    if failed is not None:
        print(f"error: pull failed: {failed}", file=sys.stderr)
        return 1
    print(f"✓ {args.repo} -> {res.snapshot_dir}")
    return 0


def cmd_diff(args) -> int:
    """``zest diff REPO@revA REPO@revB`` — dry-run the DeltaPlan
    against the local cache: changed/unchanged chunk counts, byte
    totals, and per-file delta ratios, without fetching a single
    payload byte (reconstruction metadata only; local manifests answer
    fully offline)."""
    from zest_tpu.transfer import delta

    def parse_spec(spec: str) -> tuple[str, str]:
        repo, sep, rev = spec.partition("@")
        return (repo, rev) if sep and rev else (repo, "main")

    repo_a, rev_a = parse_spec(args.base)
    cfg = Config.load()
    if args.push_preview:
        # ``zest diff REPO[@BASE] --push-preview DIR`` (ISSUE 19): the
        # would-be outcome of pushing DIR — dedup ratio + new-xorb
        # bytes against the cached base — with zero writes.
        from zest_tpu.transfer import push as push_mod

        try:
            out = push_mod.preview_push(
                cfg, repo_a, args.push_preview,
                base_revision=rev_a if "@" in args.base else None)
        except (ValueError, OSError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(out, indent=2))
        else:
            print(f"push preview: {repo_a} <- {args.push_preview}")
            print(f"  base revision : {str(out['parent'])[:12] or '(none)'}")
            print(f"  files         : {out['files']} "
                  f"({out['xet_files']} xet)")
            print(f"  total bytes   : {out['total_bytes']:,}")
            print(f"  reused bytes  : {out['reused_bytes']:,}")
            print(f"  new xorbs     : {out['new_xorbs']} "
                  f"({out['new_xorb_bytes']:,} bytes)")
            print(f"  dedup ratio   : {out['dedup_ratio']:.4f}")
        return 0
    if args.target is None:
        print("error: diff needs a target revision "
              "(or --push-preview DIR)", file=sys.stderr)
        return 2
    repo_b, rev_b = parse_spec(args.target)
    try:
        cfg.model_cache_dir(repo_a)
        cfg.model_cache_dir(repo_b)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    try:
        out = delta.diff_revisions(cfg, repo_a, rev_a, repo_b, rev_b)
    except Exception as exc:  # noqa: BLE001 - CLI boundary
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(delta.format_diff(out))
    return 0


def cmd_push(args) -> int:
    """``zest push REPO_ID CHECKPOINT_DIR`` (ISSUE 19): publish a
    checkpoint directory as a new revision — gearhash-CDC dedup against
    the cached base, new xorbs into the local (seedable) cache, a
    lineage-carrying manifest, refs/main bump — then notify the local
    daemon so every ``/v1/watch`` subscriber starts its delta pull."""
    from zest_tpu.transfer import push as push_mod

    cfg = Config.load()
    try:
        res = push_mod.push_checkpoint(
            cfg, args.repo, args.checkpoint_dir,
            base_revision=args.base, notify=not args.no_notify)
    except (ValueError, OSError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(res.summary(), indent=2))
        return 0
    print(f"✓ pushed {args.repo}@{res.revision[:12]} "
          f"(parent {str(res.parent)[:12] if res.parent else '(none)'})")
    print(f"  files {res.files} ({res.xet_files} xet), "
          f"{res.total_bytes:,} bytes")
    print(f"  new xorbs {res.new_xorbs} ({res.new_xorb_bytes:,} bytes), "
          f"dedup ratio {res.dedup_ratio:.4f}")
    if res.notified:
        print(f"  fan-out: {res.notified.get('delivered', 0)} watcher(s) "
              "notified")
    elif not args.no_notify:
        print("  fan-out: no daemon reachable (revision still "
              "published locally)")
    return 0


def cmd_watch(args) -> int:
    """``zest watch REPO_ID`` (ISSUE 19): subscribe to a publisher
    daemon's ``/v1/watch`` and auto-delta-pull + hot-swap each pushed
    revision — the serving-pod side of continuous weight fan-out."""
    from zest_tpu.transfer import push as push_mod

    cfg = Config.load()
    try:
        records = push_mod.watch_and_swap(
            cfg, args.repo, publisher_url=args.publisher,
            device=args.device, base_revision=args.base,
            max_events=args.count, timeout_s=args.timeout,
            no_p2p=args.no_p2p)
    except (ValueError, OSError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({"swaps": records}, indent=2))
        return 0
    for r in records:
        prop = r.get("propagation_s")
        print(f"✓ swapped to {r['revision'][:12]}"
              + (f"  propagation {prop:.2f}s" if prop is not None else ""))
    if not records:
        print("watch ended with no revision events", file=sys.stderr)
        return 1
    return 0


def cmd_models(args) -> int:
    """Cache introspection: pulled models + xorb cache totals. Asks the
    daemon (/v1/models) when one is running — same payload the dashboard
    shows — else scans the caches directly; ``--json`` prints the raw
    payload either way."""
    from zest_tpu import storage

    cfg = Config.load()
    payload = _daemon_get(cfg, "/v1/models")
    if args.resident:
        # HBM-pool residency (ISSUE 18): which trees the serving daemon
        # holds in HBM right now. Pool state lives in the daemon
        # process — without one (or with ZEST_HBM_POOL=0, when the
        # payload has no 'resident' key) there is nothing to list.
        resident = (payload.get("resident")
                    if isinstance(payload, dict) else None)
        if not isinstance(resident, list):
            print("no HBM pool state (daemon not running, or "
                  "ZEST_HBM_POOL=0)", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps({"resident": resident}))
            return 0
        if not resident:
            print("HBM pool empty")
        for r in resident:
            line = (f"{r.get('repo')}  {r.get('state')}  "
                    f"{r.get('bytes', 0) / 1e6:.1f} MB  "
                    f"pins {r.get('pins', 0)}  lands {r.get('lands', 0)}")
            ex = r.get("experts")
            if isinstance(ex, dict):
                line += (f"  experts {ex.get('residency', 0) * 100:.0f}%"
                         " resident")
            print(line)
        return 0
    models = payload.get("models") if payload is not None else None
    if not isinstance(models, list) or any(
            not isinstance(m, dict) or not m.get("repo_id")
            for m in models):
        # Row-shape defense (ADVICE r5): an older/foreign daemon on a
        # stale recorded port can pass the envelope checks yet key rows
        # differently (the reference uses 'name') — scan the caches
        # directly rather than KeyError-crashing the CLI.
        models = storage.list_models(cfg)

    xorbs = storage.list_cached_xorbs(cfg)
    xorb_bytes = 0
    for hex_key in xorbs:
        try:
            xorb_bytes += cfg.xorb_cache_path(hex_key).stat().st_size
        except OSError:
            pass
    if args.json:
        print(json.dumps({"models": models, "xorbs": len(xorbs),
                          "xorb_bytes": xorb_bytes}))
        return 0
    if not models:
        print("no models pulled")
    for m in models:
        rev = (m.get("revision") or "?")[:12]
        pool = (f"  [hbm:{m['pool_state']}]"
                if m.get("pool_state") else "")
        print(f"{m.get('repo_id')}  rev {rev}  "
              f"{m.get('files', 0)} files{pool}")
    print(f"xorb cache: {len(xorbs)} xorbs, {xorb_bytes / 1e6:.1f} MB")
    return 0


def cmd_bench(args) -> int:
    from zest_tpu import bench_suite

    results = bench_suite.run_synthetic(device=not args.no_device)
    print(bench_suite.format_results(results, as_json=args.json))
    return 0


def cmd_version(_args) -> int:
    print(f"zest-tpu {__version__}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="zest",
        description="TPU-native P2P model distribution",
    )
    sub = p.add_subparsers(dest="command")

    pull = sub.add_parser("pull", help="download a model through the swarm")
    pull.add_argument("repo")
    pull.add_argument("--revision", default="main")
    pull.add_argument("--device", choices=["tpu"], default=None)
    pull.add_argument("--profile", metavar="DIR", default=None,
                      help="write a JAX profiler trace of the pull "
                           "(view with TensorBoard/Perfetto)")
    pull.add_argument("--dtype", choices=["bf16", "f16", "f32"],
                      default=None,
                      help="cast tensors when landing in HBM "
                           "(bf16 halves HBM; default keeps checkpoint "
                           "dtype; also ZEST_TPU_DTYPE)")
    pull.add_argument("--peer", action="append",
                      help="direct peer host:port (repeatable)")
    pull.add_argument("--tracker", default=None, help="tracker announce URL")
    pull.add_argument("--no-p2p", action="store_true")
    pull.add_argument("--no-dht", action="store_true",
                      help="skip DHT discovery (direct peers/tracker only)")
    pull.add_argument("--no-seed", action="store_true",
                      help="don't auto-start the seeding daemon after pull")
    pod_group = pull.add_mutually_exclusive_group()
    pod_group.add_argument("--pod", action="store_true",
                           help="run the pod distribution round (default "
                                "with --device=tpu; one collective fetch "
                                "per mesh)")
    pod_group.add_argument("--no-pod", action="store_true",
                           help="skip the pod round even with --device=tpu")
    pull.add_argument("--pods", type=int, default=None,
                      help="total pods in a federated multi-pod pull "
                           "(separate processes linked over DCN)")
    pull.add_argument("--pod-index", type=int, default=None,
                      help="this process' pod index (0-based)")
    pull.add_argument("--pod-addr", action="append", metavar="I=HOST:PORT",
                      help="DCN endpoint of pod I (repeatable); units "
                           "owned by unreachable pods degrade to CDN")
    coop_group = pull.add_mutually_exclusive_group()
    coop_group.add_argument("--coop", action="store_true",
                            help="cooperative pod-scale pull: this host "
                                 "fetches ~1/N of the CDN bytes and "
                                 "exchanges compressed chunks with the "
                                 "other hosts over DCN (auto when a "
                                 "multi-host topology is configured; "
                                 "also ZEST_COOP=1)")
    coop_group.add_argument("--no-coop", action="store_true",
                            help="never run the cooperative round")
    pull.add_argument("--coop-hosts", type=int, default=None,
                      help="total hosts in the cooperative pull "
                           "(also ZEST_COOP_HOSTS)")
    pull.add_argument("--coop-index", type=int, default=None,
                      help="this host's index, 0-based "
                           "(also ZEST_COOP_INDEX)")
    pull.add_argument("--coop-addr", action="append", metavar="I=HOST:PORT",
                      help="DCN endpoint of coop host I (repeatable; "
                           "omit to discover via the jax.distributed "
                           "KV store)")
    pull.add_argument("--http-port", type=int, default=None)
    pull.set_defaults(fn=cmd_pull)

    gen = sub.add_parser(
        "generate", help="pull a model and greedy-decode with it"
    )
    gen.add_argument("repo")
    gen.add_argument("--revision", default="main")
    gen.add_argument("--prompt", default=None,
                     help="text prompt (needs a tokenizer in the snapshot)")
    gen.add_argument("--ids", default=None,
                     help="comma-separated prompt token ids")
    gen.add_argument("--steps", type=int, default=20,
                     help="new tokens to decode (default 20)")
    gen.add_argument("--temperature", type=float, default=0.0,
                     help="0 = greedy (default); >0 samples")
    gen.add_argument("--top-k", type=int, default=None,
                     help="restrict sampling to the k most likely tokens")
    gen.add_argument("--top-p", type=float, default=None,
                     help="nucleus sampling: restrict to the smallest set "
                          "of tokens with cumulative probability top_p")
    gen.add_argument("--seed", type=int, default=0,
                     help="sampling PRNG seed (default 0)")
    gen.add_argument("--ignore-eos", action="store_true",
                     help="decode all --steps tokens even past the "
                          "model's eos_token_id")
    gen.add_argument("--no-p2p", action="store_true")
    gen.set_defaults(fn=cmd_generate)

    seed = sub.add_parser("seed", help="announce cached xorbs to the swarm")
    seed.add_argument("--tracker", default=None)
    seed.set_defaults(fn=cmd_seed)

    serve = sub.add_parser("serve", help="run the seeding server (foreground)")
    serve.add_argument("--http-port", type=int, default=None)
    serve.add_argument("--listen-port", type=int, default=None)
    serve.add_argument("--dcn-port", type=int, default=None,
                       help="DCN chunk-RPC port (0 = ephemeral)")
    serve.set_defaults(fn=cmd_serve)

    sub.add_parser("start", help="start the daemon in the background") \
        .set_defaults(fn=cmd_start)
    sub.add_parser("stop", help="stop the daemon").set_defaults(fn=cmd_stop)
    sub.add_parser("status", help="print daemon status") \
        .set_defaults(fn=cmd_status)
    stats_p = sub.add_parser(
        "stats", help="print the daemon's metrics (Prometheus text)")
    stats_p.add_argument("--json", action="store_true",
                         help="telemetry/faults/peer-health blocks from "
                              "/v1/status as JSON instead")
    stats_p.add_argument("--pod", action="store_true",
                         help="pod-scope aggregation (/v1/metrics"
                              "?scope=pod on the coordinator)")
    stats_p.add_argument("--watch", action="store_true",
                         help="live 1 Hz redraw over /v1/debug "
                              "(Ctrl-C exits)")
    stats_p.add_argument("--interval", type=float, default=1.0,
                         help="redraw interval seconds (default 1.0)")
    stats_p.add_argument("--count", type=int, default=0,
                         help="stop after N frames (0 = forever)")
    stats_p.set_defaults(fn=cmd_stats)

    debug_p = sub.add_parser(
        "debug", help="dump the daemon's flight recorder + live "
                      "coop summary (/v1/debug)")
    debug_p.add_argument("--out", default=None, metavar="PATH",
                         help="write the JSON report here instead of "
                              "stdout")
    debug_p.add_argument("--tail", type=int, default=100,
                         help="recorder events to include (default 100)")
    debug_p.set_defaults(fn=cmd_debug)

    ps_p = sub.add_parser(
        "ps", help="list the daemon's pull sessions (live + recent)")
    ps_p.add_argument("--json", action="store_true",
                      help="raw /v1/pulls document")
    ps_p.add_argument("--watch", action="store_true",
                      help="live redraw (Ctrl-C exits)")
    ps_p.add_argument("--interval", type=float, default=1.0,
                      help="redraw interval seconds (default 1.0)")
    ps_p.add_argument("--count", type=int, default=0,
                      help="with --watch: stop after N frames")
    ps_p.set_defaults(fn=cmd_ps)

    heal_p = sub.add_parser(
        "heal", help="self-healing control plane: the remediation "
                     "engine's decisions, knob overrides, shed state")
    heal_p.add_argument("--json", action="store_true",
                        help="raw /v1/remediations document")
    heal_p.add_argument("--watch", action="store_true",
                        help="live redraw (Ctrl-C exits)")
    heal_p.add_argument("--interval", type=float, default=1.0,
                        help="redraw interval seconds (default 1.0)")
    heal_p.add_argument("--count", type=int, default=0,
                        help="with --watch: stop after N frames")
    heal_p.add_argument("--limit", type=int, default=50,
                        help="recent decisions to fetch (default 50)")
    heal_p.add_argument("--dry-run", choices=["on", "off"], default=None,
                        help="flip decision-only mode on the live "
                             "engine (no action executes)")
    heal_p.set_defaults(fn=cmd_heal)

    top_p = sub.add_parser(
        "top", help="live full-screen view: session progress bars, "
                    "tier rates, queue, anomalies (/v1/timeline)")
    top_p.add_argument("--json", action="store_true",
                       help="one raw status+pulls+timeline document")
    top_p.add_argument("--interval", type=float, default=1.0,
                       help="redraw interval seconds (default 1.0)")
    top_p.add_argument("--count", type=int, default=0,
                       help="stop after N frames (0 = until Ctrl-C)")
    top_p.set_defaults(fn=cmd_top)

    analyze_p = sub.add_parser(
        "analyze", help="critical-path attribution over a trace export")
    analyze_p.add_argument("trace", metavar="TRACE.json",
                           help="a zest trace export (solo or merged)")
    analyze_p.add_argument("--json", action="store_true")
    analyze_p.add_argument("--host", default=None,
                           help="merged docs: analyze this host's spans "
                                "(default: the dominant pull's host)")
    analyze_p.add_argument("--top", type=int, default=8,
                           help="top blocking spans to list (default 8)")
    analyze_p.set_defaults(fn=cmd_analyze)

    trace_p = sub.add_parser(
        "trace", help="pull with the span tracer on; write a Chrome trace")
    trace_p.add_argument("repo", nargs="?", default=None)
    trace_p.add_argument("--revision", default="main")
    trace_p.add_argument("--device", choices=["tpu"], default=None)
    trace_p.add_argument("--out", default="zest-trace.json",
                         metavar="PATH",
                         help="trace file (default zest-trace.json); "
                              "view at ui.perfetto.dev")
    trace_p.add_argument("--no-p2p", action="store_true")
    trace_p.add_argument("--coop", action="store_true",
                         help="after the traced coop pull, gather every "
                              "pod peer's /v1/trace and write ONE "
                              "merged multi-track file")
    trace_p.add_argument("--peer-api", action="append",
                         metavar="I=HOST:PORT",
                         help="pod peer HTTP API endpoint for --coop "
                              "(repeatable; default ZEST_POD_PEERS)")
    trace_p.add_argument("--merge", nargs="+", default=None,
                         metavar="TRACE.json",
                         help="offline: merge per-host trace exports "
                              "into --out (no pull)")
    trace_p.set_defaults(fn=cmd_trace)
    diff_p = sub.add_parser(
        "diff", help="chunk-level delta between two revisions "
                     "(dry-run; metadata only, no payload fetch)")
    diff_p.add_argument("base", metavar="REPO@REV",
                        help="base revision (what is cached/resident)")
    diff_p.add_argument("target", metavar="REPO@REV", nargs="?",
                        default=None,
                        help="target revision (what a pull would fetch)")
    diff_p.add_argument("--push-preview", metavar="DIR", default=None,
                        help="dry-run a push of checkpoint DIR against "
                             "the cached base: dedup ratio + new-xorb "
                             "bytes, no writes")
    diff_p.add_argument("--json", action="store_true")
    diff_p.set_defaults(fn=cmd_diff)

    push_p = sub.add_parser(
        "push", help="publish a checkpoint dir as a new revision "
                     "(CDC dedup vs cached base) and notify watchers")
    push_p.add_argument("repo", metavar="REPO_ID")
    push_p.add_argument("checkpoint_dir", metavar="CHECKPOINT_DIR")
    push_p.add_argument("--base", metavar="REV", default=None,
                        help="base revision to dedup against "
                             "(default: refs/main)")
    push_p.add_argument("--no-notify", action="store_true",
                        help="skip the daemon /v1/push notification "
                             "(publish locally only)")
    push_p.add_argument("--json", action="store_true")
    push_p.set_defaults(fn=cmd_push)

    watch_p = sub.add_parser(
        "watch", help="subscribe to a publisher's /v1/watch and "
                      "delta-pull + hot-swap each pushed revision")
    watch_p.add_argument("repo", metavar="REPO_ID")
    watch_p.add_argument("--publisher", metavar="URL", default=None,
                         help="publisher daemon base URL "
                              "(default: local daemon)")
    watch_p.add_argument("--base", metavar="REV", default=None,
                         help="currently-resident revision (delta "
                              "evidence for the first swap)")
    watch_p.add_argument("--device", default=None,
                         help="land target (e.g. tpu) for hot-swap")
    watch_p.add_argument("--count", type=int, default=1,
                         help="stop after N revision events "
                              "(default 1; 0 = until the stream ends)")
    watch_p.add_argument("--timeout", type=float, default=120.0,
                         help="idle-stream timeout seconds (default 120)")
    watch_p.add_argument("--no-p2p", action="store_true")
    watch_p.add_argument("--json", action="store_true")
    watch_p.set_defaults(fn=cmd_watch)

    models_p = sub.add_parser(
        "models", help="list pulled models and xorb cache totals")
    models_p.add_argument("--json", action="store_true")
    models_p.add_argument(
        "--resident", action="store_true",
        help="only models resident/landing in the serving HBM pool")
    models_p.set_defaults(fn=cmd_models)

    bench = sub.add_parser("bench", help="run the synthetic benchmark suite")
    bench.add_argument("--json", action="store_true")
    bench.add_argument("--no-device", action="store_true",
                       help="host-only benches (skip TPU)")
    bench.add_argument("--synthetic", action="store_true",
                       help="accepted for reference CLI parity (default)")
    bench.set_defaults(fn=cmd_bench)

    sub.add_parser("version", help="print version") \
        .set_defaults(fn=cmd_version)
    return p


def _provision_virtual_devices() -> None:
    """``ZEST_VIRTUAL_DEVICES=N`` → N-device virtual CPU mesh for this
    process. Testing/CI knob for driving mesh-dependent CLI paths
    (``pull --device=tpu`` with ``ZEST_TPU_MESH``) without N chips —
    same mechanism as the driver's dryrun self-provision
    (__graft_entry__._provision_virtual_mesh): env vars alone don't
    stick once sitecustomize has imported jax, so go through jax.config
    before the first device query."""
    n = os.environ.get("ZEST_VIRTUAL_DEVICES")
    if not n:
        return
    try:
        count = int(n)
    except ValueError:
        print(f"ignoring malformed ZEST_VIRTUAL_DEVICES={n!r}",
              file=sys.stderr)
        return
    import jax

    try:  # no public API for this query; degrade to "not initialized"
        from jax._src import xla_bridge
        initialized = xla_bridge.backends_are_initialized()
    except Exception:  # noqa: BLE001 - private import may break on upgrade
        initialized = False
    if initialized:
        # Re-provisioning now would raise inside jax.config; run on
        # whatever is attached, but say so — a silent 1-device run makes
        # downstream mesh failures undiagnosable.
        print(f"ZEST_VIRTUAL_DEVICES={count} ignored: jax backend "
              "already initialized", file=sys.stderr)
        return
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", count)
    except AttributeError:
        # Older jax spells it via XLA_FLAGS only; the backend is not
        # initialized yet (checked above), so the flag still applies.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={count}"
            ).strip()


def main(argv: list[str] | None = None) -> int:
    _provision_virtual_devices()
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 0
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    raise SystemExit(main())
