"""Host-disk storage tier: HF cache layout, refs, xorb/chunk caches, registry.

The reference's L1 (src/storage.zig, plus XorbCache from src/swarm.zig:57-148).
This is the *disk* tier; the TPU build adds an HBM tier on top
(zest_tpu.parallel.hbm) with the same range-aware get/put semantics so the
waterfall code is tier-agnostic.

Improvement over the reference (SURVEY.md "quirks to not replicate"):
``atomic_write`` here is actually atomic (tmp file + rename), where the
reference's ``writeFileAtomic`` was plain create+write (storage.zig:29-41).
"""

from __future__ import annotations

import errno
import os
import tempfile
import threading
from dataclasses import dataclass
from pathlib import Path

from zest_tpu.config import Config


class CacheFullError(OSError):
    """Typed ENOSPC for cache writes (ISSUE 13 satellite): the write's
    temp file is already cleaned up, a ``disk_pressure`` flight event
    has fired, and — when a disk-full hook is installed (the tenancy
    layer's eviction pass) — eviction already ran. Callers on the
    fetch path treat it as "couldn't cache" and keep serving; a pull
    that cannot make progress at all fails with THIS error, not a raw
    mid-pull ``OSError`` over half-written temp files."""

    def __init__(self, msg: str, path: Path | str | None = None):
        super().__init__(errno.ENOSPC, msg)
        self.path = str(path) if path is not None else None


# The tenancy layer's eviction pass (transfer.tenancy installs it; None
# when tenancy is off/unconfigured). Called on ENOSPC; returns True
# when it freed anything, which earns the write exactly one retry.
_disk_full_hook = None


def set_disk_full_hook(fn) -> None:
    global _disk_full_hook
    _disk_full_hook = fn


def note_disk_full(path) -> bool:
    """Record disk pressure (flight recorder) and run the eviction
    hook; True when the hook reports freed space. Shared by every
    cache-write site that converts ENOSPC to :class:`CacheFullError`."""
    from zest_tpu import telemetry

    telemetry.record("disk_pressure", path=str(path))
    hook = _disk_full_hook
    if hook is None:
        return False
    try:
        return bool(hook())
    except Exception:  # noqa: BLE001 - eviction is advisory
        return False


def atomic_write(path: Path, data: bytes) -> None:
    """Write via tmp file + rename so readers never observe partial
    content. ENOSPC is typed (:class:`CacheFullError`) and — because
    the payload is replayable bytes, unlike the streaming variant —
    retried once after the eviction hook frees space."""
    try:
        atomic_write_stream(path, (data,))
    except CacheFullError:
        # note_disk_full (and with it the eviction pass) already ran
        # inside atomic_write_stream; one retry against the freed space.
        atomic_write_stream(path, (data,), _retry=True)


def atomic_write_stream(path: Path, chunks, _retry: bool = False) -> int:
    """``atomic_write`` fed by an iterator of byte chunks; returns the
    byte count. The GB-scale fetch path streams network bodies straight
    to their cache file through this — each ~1 MiB chunk is written
    while still cache-hot, and no whole-unit buffer is ever built
    (one full memory pass fewer than fetch-then-put).

    ENOSPC surfaces as :class:`CacheFullError` after the temp file is
    unlinked and :func:`note_disk_full` ran (``disk_pressure`` event +
    the tenancy eviction pass); no retry here — the chunk iterator is
    consumed — callers with replayable payloads retry themselves
    (:func:`atomic_write`)."""
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-")
    n = 0
    try:
        with os.fdopen(fd, "wb") as f:
            for chunk in chunks:
                f.write(chunk)
                n += len(chunk)
        os.replace(tmp, path)
    except BaseException as exc:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        if isinstance(exc, OSError) and exc.errno == errno.ENOSPC \
                and not isinstance(exc, CacheFullError):
            if not _retry:
                note_disk_full(path)
            raise CacheFullError(
                f"cache write of {path} hit ENOSPC", path) from exc
        raise
    return n


def durable_replace(tmp: str | Path, dest: Path) -> None:
    """The durability half of the partial-file contract: fsync ``tmp``,
    then atomically rename it over ``dest``.

    The materialization lane writes payload under a temp name and calls
    this only at its commit barrier, so a pull killed mid-write leaves
    *no* complete-named partial file — a crash survivor either sees the
    old state or a fully written, fsynced file. The fd is opened here,
    per call, so a many-shard pull holds O(pool-width) fds instead of
    one per pending commit (EMFILE at ~1000 shards otherwise). fsync
    failure aborts the rename (a rename over unsynced data would defeat
    the barrier)."""
    fd = os.open(tmp, os.O_RDWR)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, dest)


# ── HF refs (reference: storage.zig:57-86) ──


def write_ref(cfg: Config, repo_id: str, ref: str, commit_sha: str) -> None:
    """Record ``refs/{ref} -> commit_sha`` in the HF cache layout so
    ``from_pretrained(revision=ref)`` resolves offline."""
    atomic_write(cfg.model_refs_dir(repo_id) / ref, commit_sha.encode())


def read_ref(cfg: Config, repo_id: str, ref: str) -> str | None:
    try:
        return (cfg.model_refs_dir(repo_id) / ref).read_text().strip()
    except OSError:
        return None


def list_models(cfg: Config) -> list[dict]:
    """Scan the HF hub cache for pulled models (reference:
    http_api.zig:152-210): one row per ``models--*`` dir with its
    latest snapshot revision and file count. Shared by the REST
    ``/v1/models`` payload and the ``zest-tpu models`` CLI."""
    models = []
    hub = cfg.hf_home / "hub"
    if hub.is_dir():
        for d in sorted(hub.iterdir()):
            if not d.name.startswith("models--") or not d.is_dir():
                continue
            repo_id = d.name[len("models--"):].replace("--", "/", 1)
            snapshots = d / "snapshots"
            n_files = 0
            revision = None
            if snapshots.is_dir():
                # Dirs only: tools drop sibling FILES next to snapshots
                # (e.g. the lifecycle example's exported safetensors) and
                # a file must not masquerade as the latest revision.
                revs = sorted(
                    (p for p in snapshots.iterdir() if p.is_dir()),
                    key=lambda p: p.stat().st_mtime,
                )
                if revs:
                    revision = revs[-1].name
                    n_files = sum(
                        1 for f in revs[-1].rglob("*") if f.is_file()
                    )
            models.append({
                "repo_id": repo_id,
                "revision": revision,
                "files": n_files,
            })
    return models


# ── Chunk cache (reference: storage.zig:102-143; plain-hex keys) ──


def write_chunk(cfg: Config, chunk_hash: bytes, data: bytes) -> None:
    atomic_write(cfg.chunk_cache_path(chunk_hash.hex()), data)


def read_chunk(cfg: Config, chunk_hash: bytes) -> bytes | None:
    try:
        return cfg.chunk_cache_path(chunk_hash.hex()).read_bytes()
    except OSError:
        return None


# ── Xorb cache (reference: swarm.zig:57-148; LE-u64-hex keys) ──


def _touch_for_lru(fileno_or_path) -> None:
    """Freshen an entry's mtime on READ so the tenancy evictor's
    oldest-mtime-first pass is true LRU, not write-time FIFO — without
    this, a hot entry written an hour ago is the first eviction victim
    while a cold one written a minute ago survives (the same bug PR 1
    fixed in the peer pool, at the disk tier). Best-effort: one utime
    syscall per entry read, dwarfed by the MB-scale read itself."""
    try:
        os.utime(fileno_or_path)
    except OSError:
        pass


def _read_with_readahead(path: Path) -> bytes | None:
    """Whole-file read with an aggressive readahead hint (the
    madvise/fadvise WILLNEED from ISSUE 3): GB-scale warm-cache landings
    read back tens of ~32 MB cache entries moments after the fetch wrote
    them, and on a cold page cache each read stalls the decode pool on
    demand page-in. WILLNEED starts the whole entry's page-in before the
    copying read walks it, so the decode workers stream instead of
    faulting."""
    try:
        with open(path, "rb") as f:
            if hasattr(os, "posix_fadvise"):
                try:
                    os.posix_fadvise(f.fileno(), 0, 0,
                                     os.POSIX_FADV_WILLNEED)
                except OSError:
                    pass  # advisory only; the read below still works
            _touch_for_lru(f.fileno())
            return f.read()
    except OSError:
        return None


@dataclass(frozen=True)
class CacheResult:
    """Range-aware lookup result: ``data`` is a serialized xorb whose chunk 0
    corresponds to absolute chunk index ``chunk_offset`` in the full xorb."""

    data: bytes
    chunk_offset: int


class XorbCache:
    """Full and partial xorbs on disk: ``{hash_hex}`` and
    ``{hash_hex}.{range_start}``.

    Every CDN- or peer-fetched entry is cached so this host can seed it —
    "the package IS the seeder". Partial entries are complete ZXORB1 blobs
    covering a chunk sub-range; ``chunk_offset`` rebases term indices.
    """

    def __init__(self, cfg: Config):
        self.cfg = cfg

    def _path(self, key: str) -> Path:
        return self.cfg.xorb_cache_path(key)

    def has(self, hash_hex: str) -> bool:
        return self._path(hash_hex).exists()

    def get(self, hash_hex: str) -> bytes | None:
        return _read_with_readahead(self._path(hash_hex))

    def get_with_range(self, hash_hex: str, range_start: int,
                       covers=None) -> CacheResult | None:
        """Full xorb first (offset 0), then exact partial entry
        ``{hash_hex}.{range_start}`` (reference: swarm.zig:81-95).

        ``covers`` (optional ``CacheResult -> bool``): the caller's
        coverage predicate. Without it, the FULL entry — when present —
        always wins, even if it doesn't actually hold the chunks the
        caller needs: a full key written from incomplete reference
        evidence (the resolve-order race, ISSUE 13) would then
        permanently shadow a correct partial entry at the same hash,
        turning every read of the uncovered range into a cache miss +
        refetch. With ``covers``, a non-covering candidate falls
        through to the next one instead of masking it."""
        data = self.get(hash_hex)
        if data is not None:
            result = CacheResult(data, 0)
            if covers is None or covers(result):
                return result
        data = self.get(f"{hash_hex}.{range_start}")
        if data is not None:
            result = CacheResult(data, range_start)
            if covers is None or covers(result):
                return result
        return None

    def _get_mapped(self, key: str):
        """Read-only mmap view of one entry (WILLNEED-advised), or None.

        The decode engine reads cache entries through here: an mmap
        view hands the decoder page-cache bytes directly — the whole-
        file ``read()`` copy (a full extra memory pass per GB on the
        landing path) disappears, and MADV_WILLNEED starts the entry's
        page-in before the decode walks it. The map lives exactly as
        long as the returned view (and anything sliced from it); the
        atomic-rename write discipline means an overwritten entry's old
        inode stays valid for existing maps."""
        import mmap

        try:
            with open(self._path(key), "rb") as f:
                size = os.fstat(f.fileno()).st_size
                if size == 0:
                    return memoryview(b"")
                _touch_for_lru(f.fileno())
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError):
            return None
        try:
            mm.madvise(mmap.MADV_WILLNEED)
        except (AttributeError, OSError):
            pass  # advisory only
        return memoryview(mm)

    def get_with_range_mapped(self, hash_hex: str,
                              range_start: int) -> CacheResult | None:
        """``get_with_range`` with mmap-backed ``data`` (see
        :meth:`_get_mapped`); falls back to None exactly like the
        copying lookup."""
        data = self._get_mapped(hash_hex)
        if data is not None:
            return CacheResult(data, 0)
        data = self._get_mapped(f"{hash_hex}.{range_start}")
        if data is not None:
            return CacheResult(data, range_start)
        return None

    def locate_with_range(self, hash_hex: str,
                          range_start: int) -> tuple[Path, int] | None:
        """``(path, chunk_offset)`` of the on-disk entry serving this
        range — full xorb first, then the exact partial — or None.

        The zero-copy file-materialization lane needs the entry as a
        *file* (a ``copy_file_range`` source fd), not as bytes; the
        atomic-rename write discipline means a path observed here is
        always a complete entry (an in-flight write lives under a
        ``.tmp-`` name until its rename)."""
        p = self._path(hash_hex)
        if p.exists():
            _touch_for_lru(p)
            return p, 0
        p = self._path(f"{hash_hex}.{range_start}")
        if p.exists():
            _touch_for_lru(p)
            return p, range_start
        return None

    def put(self, hash_hex: str, data: bytes) -> None:
        atomic_write(self._path(hash_hex), data)

    def put_partial(self, hash_hex: str, range_start: int, data: bytes) -> None:
        atomic_write(self._path(f"{hash_hex}.{range_start}"), data)

    def put_stream(self, hash_hex: str, chunks) -> int:
        return atomic_write_stream(self._path(hash_hex), chunks)

    def put_partial_stream(self, hash_hex: str, range_start: int,
                           chunks) -> int:
        return atomic_write_stream(
            self._path(f"{hash_hex}.{range_start}"), chunks)


def list_cached_xorbs(cfg: Config) -> list[str]:
    """All full-xorb hex keys in the cache (reference: storage.zig:199-228).

    Partial entries (``{hex}.{start}``) are excluded — seeding announces
    only complete xorbs, matching ``cmdSeed``'s behavior.
    """
    root = cfg.xorb_cache_dir()
    if not root.is_dir():
        return []
    out = []
    for sub in sorted(root.iterdir()):
        if not sub.is_dir():
            continue
        for f in sorted(sub.iterdir()):
            name = f.name
            if len(name) == 64 and "." not in name:
                out.append(name)
    return out


@dataclass
class RegistryEntry:
    hash_hex: str
    size: int
    partial_starts: tuple[int, ...] = ()


class XorbRegistry:
    """In-memory index of locally available xorbs (reference:
    storage.zig:148-196). The seeding server consults this instead of
    stat()ing the disk per request; ``scan()`` rebuilds it from the cache
    directory at startup."""

    def __init__(self) -> None:
        self._entries: dict[str, RegistryEntry] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def add(self, hash_hex: str, size: int,
            partial_starts: tuple[int, ...] = ()) -> None:
        with self._lock:
            prev = self._entries.get(hash_hex)
            if prev is not None:
                partial_starts = tuple(
                    sorted(set(prev.partial_starts) | set(partial_starts))
                )
                size = max(size, prev.size)
            self._entries[hash_hex] = RegistryEntry(hash_hex, size, partial_starts)

    def has(self, hash_hex: str) -> bool:
        with self._lock:
            return hash_hex in self._entries

    def get(self, hash_hex: str) -> RegistryEntry | None:
        with self._lock:
            return self._entries.get(hash_hex)

    def all_hashes(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def scan(self, cfg: Config) -> int:
        """Rebuild from the on-disk cache; returns the number of entries."""
        root = cfg.xorb_cache_dir()
        found: dict[str, RegistryEntry] = {}
        if root.is_dir():
            for sub in root.iterdir():
                if not sub.is_dir():
                    continue
                for f in sub.iterdir():
                    name = f.name
                    if name.startswith(".tmp-"):
                        continue
                    try:
                        size = f.stat().st_size
                    except OSError:
                        continue
                    if len(name) == 64:
                        e = found.setdefault(name, RegistryEntry(name, 0))
                        found[name] = RegistryEntry(
                            name, size, e.partial_starts
                        )
                    elif len(name) > 65 and name[64] == ".":
                        hex_part, _, start = name.partition(".")
                        if len(hex_part) == 64 and start.isdigit():
                            e = found.setdefault(
                                hex_part, RegistryEntry(hex_part, 0)
                            )
                            found[hex_part] = RegistryEntry(
                                hex_part, e.size,
                                tuple(sorted(set(e.partial_starts) | {int(start)})),
                            )
        with self._lock:
            self._entries = found
            return len(self._entries)
