"""Version constants for zest-tpu.

The wire-visible client version string rides in the BEP 10 extended
handshake ("v" key) and the Azureus-style peer-id prefix, mirroring the
reference's conventions (reference: src/peer_id.zig:10, src/bep_xet.zig:191).
"""

__version__ = "0.1.0"

# Azureus-style prefix: ZT = zest-tpu, 01 = v0.1, 00 = patch 0.
# The reference uses "-ZE0200-" (src/peer_id.zig:10); the prefix is client
# identity only and does not affect swarm interop.
CLIENT_PREFIX = b"-ZT0100-"

# Client string advertised in the BEP 10 extended handshake.
CLIENT_STRING = f"zest-tpu/{__version__}"
