"""GB-scale end-to-end pull benchmark (BASELINE "time-to-HBM").

The reference never measured an end-to-end number (BASELINE.md: the
Hetzner harness records wall-clocks but none are checked in); the
TPU build's north star IS an end-to-end number — Llama-3.1-70B
(~140 GB) into v5p-64 HBM in <60 s. This module measures the full
pipeline at GB scale on one host so the per-host throughput and its
stage decomposition are *measured*, not guessed, and the extrapolation
to the target (SCALING.md) starts from recorded data.

What it does: build a synthetic checkpoint at real Llama-8B tensor
geometry (4096 hidden / 14336 FFN / 8 KV heads, bf16) sized to
``gb`` gigabytes, serve it from the loopback fixture hub (the zero-
egress stand-in for the CDN), and pull it with ``device="tpu"`` —
CAS metadata, ranged xorb fetch, chunk verify, direct HBM landing —
three times cold, reporting per-stage medians and the max relative
spread. A spread beyond ±20% marks the run unstable (loudly, in the
output) instead of printing a number the bench itself can't defend.

Stage semantics (from transfer.pull.StageClock):
- ``resolve``      — Hub API: revision + file listing
- ``cas_metadata`` — auth + reconstruction terms + file headers
- ``fetch``        — ranged xorb GETs + decompress + BLAKE3 verify +
                     cache write (the CDN→verified-cache stage)
- ``hbm_commit``   — verified cache → sharded device arrays
- ``files``        — HF-cache file writes (served from the warm cache)

Since the pipelined pull, ``files`` and ``hbm_commit`` OVERLAP (file
reconstruction runs on a worker pool while shards decode and commit):
per-stage wall times are union coverage (each bounded by the pull wall,
but no longer additive), and the pull additionally reports
``stages_busy`` (per-stage thread-seconds). The bench surfaces the
overlap attribution directly: ``overlap.overlap_s =
busy(files) + busy(hbm_commit) - span(files ∪ hbm_commit)`` — positive
means the stages genuinely ran concurrently. ``time_to_hbm_s`` is the
pull's own wall-clock-to-params-resident (stats["time_to_hbm_s"]), not
a stage sum, which an overlapped pipeline would double-count.
"""

from __future__ import annotations

import gc
import json
import pathlib
import statistics
import tempfile
import time

import numpy as np

__all__ = ["llama_checkpoint_files", "mutate_tensors", "bench_gb_pull",
           "bench_coop_pull", "bench_collective_transports",
           "bench_delta_pull", "bench_swarm",
           "bench_tenants", "bench_fleet", "bench_serve_pool"]


def mutate_tensors(tensors: dict, fraction: float, seed: int = 1) -> None:
    """Perturb ~``fraction`` of the checkpoint's BYTES in place —
    the deterministic "revision B" generator (ISSUE 10): same names,
    shapes, and dtypes, with seeded contiguous byte runs XOR-flipped in
    a seeded subset of tensors. Localized updates are the shape a
    fine-tune/RL delta actually has, and localization is what keeps the
    CDC chunk damage proportional to the byte fraction (every chunk a
    run touches changes, ±1 boundary chunk per run) — the property the
    delta-pull bench and smoke gates measure against.

    Spread over ~4 tensors when the budget allows, so the delta is
    neither one trivially contiguous region nor a scatter that would
    dirty every chunk."""
    total = sum(int(a.nbytes) for a in tensors.values())
    budget = max(1, int(total * fraction))
    rng = np.random.default_rng([int(seed), 0xDE17A])
    names = list(tensors)
    per = max(1, budget // 4)
    for k in rng.permutation(len(names)):
        if budget <= 0:
            break
        flat = tensors[names[k]].reshape(-1).view(np.uint8)
        take = min(int(flat.size), per, budget) or 1
        start = int(rng.integers(0, flat.size - take + 1))
        # XOR with bytes in [1, 255]: every touched byte provably
        # changes (a 0 patch byte would silently no-op).
        flat[start:start + take] ^= rng.integers(
            1, 256, take, dtype=np.uint8)
        budget -= take

# Llama-8B geometry (hidden/FFN/heads as in Llama-3-8B; vocab reduced to
# keep the embedding from dominating a small-N-layer checkpoint).
_HIDDEN = 4096
_FFN = 14336
_HEAD_DIM = 128
_N_HEADS = 32
_N_KV = 8
_VOCAB = 32000
_BF16 = 2  # bytes/param


def _layer_bytes(hidden: int, ffn: int, kv_dim: int) -> int:
    return _BF16 * (
        2 * hidden * hidden      # q_proj, o_proj
        + 2 * hidden * kv_dim    # k_proj, v_proj
        + 3 * hidden * ffn       # gate, up, down
        + 2 * hidden             # the two RMSNorm weights
    )


def _edge_bytes(hidden: int, vocab: int) -> int:
    return _BF16 * (2 * vocab * hidden + hidden)  # embed, head, norm


_LAYER_BYTES = _layer_bytes(_HIDDEN, _FFN, _N_KV * _HEAD_DIM)
_EDGE_BYTES = _edge_bytes(_HIDDEN, _VOCAB)


def llama_checkpoint_files(gb: float, seed: int = 0,
                           shard_bytes: int = 700 * 1024 * 1024,
                           scale: int = 1,
                           smooth: bool = False,
                           mutate_fraction: float | None = None,
                           mutate_seed: int = 1) -> dict[str, bytes]:
    """Synthetic Llama-shaped checkpoint of ~``gb`` GB as HF repo files.

    Real tensor names and Llama-8B shapes (so the landing registry
    applies the llama shard rules), bf16 random bytes (incompressible —
    the worst-case, zero-dedup transfer load), sharded into
    ``model-xxxxx-of-xxxxx.safetensors`` files capped at
    ``shard_bytes``. Returns {path: bytes} for FixtureRepo.

    ``scale`` divides every dimension (tests use scale=8 for MB-size
    checkpoints with the same tensor *structure*; the driver bench runs
    scale=1, i.e. true 8B geometry — one layer alone is ~436 MB, so
    sub-GB requests at scale=1 still come out ~1 GB).

    ``smooth`` draws N(0, 0.02) weights instead of uniform random bit
    patterns — the *realistic* compressibility case (trained weights'
    bf16 exponent bytes are low-entropy; that structure is exactly what
    BG4's byte planes exploit). The default stays the incompressible
    worst case so ``pull_gb`` artifacts remain comparable across
    rounds; the cooperative bench uses ``smooth=True`` because its
    compressed-on-the-wire evidence is only visible when the payload
    compresses at all.

    ``mutate_fraction`` derives the deterministic "revision B" of the
    same checkpoint (ISSUE 10): the base tensors are generated
    identically from ``seed``, then :func:`mutate_tensors` flips
    ~that fraction of the bytes (seeded by ``mutate_seed``; shapes
    unchanged) — the 1%-changed revision the delta-pull bench diffs
    against the base.
    """
    from zest_tpu.models.safetensors_io import write_safetensors

    try:
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover - ml_dtypes ships with jax
        bf16 = np.dtype(np.uint16)

    hidden, ffn = _HIDDEN // scale, _FFN // scale
    vocab = _VOCAB // scale
    kv_dim = (_N_KV * _HEAD_DIM) // scale
    n_layer = max(1, int(np.ceil(
        (gb * 1e9 - _edge_bytes(hidden, vocab))
        / _layer_bytes(hidden, ffn, kv_dim)
    )))
    rng = np.random.default_rng(seed)

    def t(*shape):
        n = int(np.prod(shape))
        if smooth and bf16 != np.dtype(np.uint16):
            return rng.normal(0.0, 0.02, n).astype(np.float32).astype(
                bf16).reshape(shape)
        return rng.integers(0, 1 << 16, n, dtype=np.uint16).view(
            bf16).reshape(shape)

    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": t(vocab, hidden),
    }
    for i in range(n_layer):
        p = f"model.layers.{i}"
        tensors[f"{p}.self_attn.q_proj.weight"] = t(hidden, hidden)
        tensors[f"{p}.self_attn.k_proj.weight"] = t(kv_dim, hidden)
        tensors[f"{p}.self_attn.v_proj.weight"] = t(kv_dim, hidden)
        tensors[f"{p}.self_attn.o_proj.weight"] = t(hidden, hidden)
        tensors[f"{p}.mlp.gate_proj.weight"] = t(ffn, hidden)
        tensors[f"{p}.mlp.up_proj.weight"] = t(ffn, hidden)
        tensors[f"{p}.mlp.down_proj.weight"] = t(hidden, ffn)
        tensors[f"{p}.input_layernorm.weight"] = t(hidden)
        tensors[f"{p}.post_attention_layernorm.weight"] = t(hidden)
    tensors["model.norm.weight"] = t(hidden)
    tensors["lm_head.weight"] = t(vocab, hidden)
    if mutate_fraction:
        mutate_tensors(tensors, mutate_fraction, seed=mutate_seed)

    config = {
        "model_type": "llama",
        "architectures": ["LlamaForCausalLM"],
        "hidden_size": hidden,
        "intermediate_size": ffn,
        "num_attention_heads": _N_HEADS // scale,
        "num_key_value_heads": max(1, _N_KV // min(scale, _N_KV)),
        "num_hidden_layers": n_layer,
        "vocab_size": vocab,
        "max_position_embeddings": 8192,
        "rms_norm_eps": 1e-5,
        "rope_theta": 500000.0,
        "torch_dtype": "bfloat16",
    }

    # Pack tensors into <= shard_bytes safetensors files, in order.
    shards: list[dict[str, np.ndarray]] = [{}]
    size = 0
    for name, arr in tensors.items():
        if size and size + arr.nbytes > shard_bytes:
            shards.append({})
            size = 0
        shards[-1][name] = arr
        size += arr.nbytes

    files: dict[str, bytes] = {"config.json": json.dumps(config).encode()}
    n = len(shards)
    with tempfile.TemporaryDirectory() as tmp:
        for i, shard in enumerate(shards, 1):
            name = (f"model-{i:05d}-of-{n:05d}.safetensors"
                    if n > 1 else "model.safetensors")
            p = pathlib.Path(tmp) / "shard.safetensors"
            write_safetensors(p, shard)
            files[name] = p.read_bytes()
    return files


def _import_fixtures():
    """tests/fixtures scoped import (same rationale as bench_gb_pull:
    the loopback hub is a test double, not product code)."""
    import sys

    tests_dir = str(pathlib.Path(__file__).resolve().parent.parent
                    / "tests")
    sys.path.insert(0, tests_dir)
    try:
        import fixtures
    finally:
        try:
            sys.path.remove(tests_dir)
        except ValueError:
            pass
    return fixtures


def bench_coop_pull(gb: float = 0.064, n_hosts: int = 8,
                    shaped_bps: int | None = None,
                    chunks_per_xorb: int = 16, scale: int = 8,
                    dcn_rtt_s: float = 0.0,
                    dcn_bps: int | None = None,
                    topology: str | None = None) -> dict:
    """Multi-host cooperative pull vs the per-host-CDN baseline, plus
    the collective-vs-point-to-point exchange race (ROADMAP items 1+3;
    headlines: peer_served_ratio and the exchange speedup).

    ``n_hosts`` simulated hosts (isolated cache dirs + bridges, DCN
    servers on loopback — the same in-process multi-host shape the
    MULTICHIP dryrun uses) race to a fully-populated verified cache on
    EVERY host:

    - **baseline**: each host independently fetches all units from the
      (optionally shaped) CDN — the per-host waterfall;
    - **coop**: each host fetches its ~1/N plan share, then the
      collective exchange redistributes compressed frames
      (transfer.collective over transfer.coop);
    - **exchange race** (``exchange`` block): with every host's plan
      share pre-warmed (so the round wall IS the exchange wall) and
      the DCN hub shaped — ``dcn_bps`` token-buckets each host's serve
      plane, ``dcn_rtt_s`` charges one WAN round trip per request
      WINDOW — the point-to-point exchange and the collective run the
      same redistribution; the collective's O(log N) pre-sized phase
      windows against the P2P path's per-owner windows + NOT_FOUND
      retry rounds is exactly what the RTT term measures.

    ``shaped_bps`` token-buckets the hub's CDN data plane *globally*
    (one WAN-rate origin shared by all hosts) — the asymmetry under
    which cooperation's N-fold CDN-demand cut turns into wall-clock.
    The wire block records compressed bytes crossing the exchange vs
    their unpacked size — the EQuARX-grounded compressed-in-flight
    evidence. ``topology`` is a ZEST_COOP_TOPOLOGY-grammar slice spec
    classing exchange links ici/dcn."""
    import tempfile as _tempfile
    import threading

    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config, parse_topology
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.coop import CoopPlan, coop_round
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.federated import warm_units_parallel

    fixtures = _import_fixtures()
    repo_id = "bench/coop-llama"
    files = llama_checkpoint_files(gb, scale=scale, smooth=True,
                                   shard_bytes=32 * 1024 * 1024)
    total = sum(len(b) for b in files.values())
    repo = fixtures.FixtureRepo(repo_id, files,
                                chunks_per_xorb=chunks_per_xorb)
    topo = parse_topology(topology) if topology else None

    def make_host(root: pathlib.Path, tag: str, i: int,
                  collective: bool = True):
        cfg = Config(hf_home=root / f"{tag}{i}/hf",
                     cache_dir=root / f"{tag}{i}/zest",
                     hf_token="hf_test", endpoint=hub.url, dcn_port=0,
                     coop_collective=collective, coop_topology=topo)
        bridge = XetBridge(cfg)
        bridge.authenticate(repo_id)
        recs = [bridge.get_reconstruction(e.xet_hash)
                for e in HubClient(cfg).list_files(repo_id) if e.is_xet]
        return bridge, recs

    out: dict = {
        "model_bytes": total,
        "hosts": n_hosts,
        "chunks_per_xorb": chunks_per_xorb,
        "cdn_bps": shaped_bps,
        "dcn_shaping": {"rtt_s": dcn_rtt_s, "bps": dcn_bps},
        "topology": topology,
    }
    errors: list[str] = []

    def coop_leg(rootp, tag, collective, prewarm):
        """One n-host cooperative round; returns (wall, per-host walls,
        per-host stats). ``prewarm`` warms each host's own plan share
        first so the timed wall is the exchange, not the CDN fetch."""
        hosts = [make_host(rootp, tag, i, collective=collective)
                 for i in range(n_hosts)]
        servers, addrs = [], {}
        for i, (bridge, _recs) in enumerate(hosts):
            # With a topology, shaping narrows to cross-slice (DCN-
            # class) links: intra-slice serving stays loopback-fast,
            # exactly the ICI-vs-DCN asymmetry of a real pod.
            s = DcnServer(bridge.cfg, bridge.cache,
                          rate_bps=dcn_bps or 0,
                          window_rtt_s=dcn_rtt_s,
                          shape_slices=topo, shape_host=i)
            addrs[i] = ("127.0.0.1", s.start())
            servers.append(s)
        if prewarm:
            def warm(i):
                bridge, recs = hosts[i]
                plan = CoopPlan.build(recs, n_hosts)
                warm_units_parallel(bridge, recs,
                                    units=plan.for_host(i))
            ws = [threading.Thread(target=warm, args=(i,))
                  for i in range(n_hosts)]
            for t in ws:
                t.start()
            for t in ws:
                t.join()
        results: list[dict | None] = [None] * n_hosts
        walls = [0.0] * n_hosts

        def run(i):
            bridge, recs = hosts[i]
            t0 = time.perf_counter()
            try:
                results[i] = coop_round(bridge, recs, i, n_hosts,
                                        addrs, server=servers[i])
            except Exception as exc:  # noqa: BLE001
                errors.append(f"{tag} host {i}: {exc}")
            walls[i] = time.perf_counter() - t0

        t0 = time.perf_counter()
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        for s in servers:
            s.shutdown()
        for b, _r in hosts:
            b.close()
        return wall, walls, results

    def summarize(wall, results):
        done = [r for r in results if r]
        ratios = sorted(r["peer_served_ratio"] for r in done) or [0.0]
        wire = sum(r["exchange"]["wire_bytes"] for r in done)
        unpacked = sum(r["exchange"]["unpacked_bytes"] for r in done)
        cx = [r.get("collective") for r in done if r.get("collective")]
        block = {
            "wall_s": round(wall, 3),
            "hosts_completed": len(done),
            "peer_served_ratio": ratios[len(ratios) // 2],
            "peer_served_ratio_min": ratios[0],
            "cdn_bytes": sum(
                r["fetch"]["tiers"].get("cdn", 0)
                + r["exchange"].get("fallback_tiers", {}).get("cdn", 0)
                for r in done),
            "fallbacks": sum(r["fallbacks"] for r in done),
            "plan_skew": done[0]["plan"]["skew"] if done else None,
            "wire": {
                "dcn_bytes": wire,
                "unpacked_bytes": unpacked,
                # <1.0 = compressed frames crossed the exchange, not
                # expanded tensors (bf16 random data compresses
                # little; real checkpoints more).
                "compressed_ratio": round(wire / unpacked, 4)
                if unpacked else None,
            },
            "gbps_per_host": round(total / wall / 1e9, 4)
            if wall > 0 else None,
        }
        if cx:
            block["collective"] = {
                "schedule": cx[0]["schedule"],
                "phases": cx[0]["phases"],
                "windows": sum(c["windows"] for c in cx),
                "retry_windows": sum(c["retry_windows"] for c in cx),
                "unit_round_trips": sum(c["unit_round_trips"]
                                        for c in cx),
                "matrix_skew": cx[0]["matrix_skew"],
                "link_bytes": {
                    lk: sum(c["link_bytes"].get(lk, 0) for c in cx)
                    for lk in ("ici", "dcn")},
                "barrier_wait_s": round(
                    sum(c["barrier_wait_s"] for c in cx), 3),
                "aborts": sum(1 for c in cx if c.get("aborted")),
            }
        return block

    with fixtures.FixtureHub(repo, throttle_bps=shaped_bps) as hub, \
            _tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)

        # Baseline: every host pulls everything through the CDN.
        hosts = [make_host(rootp, "base", i) for i in range(n_hosts)]
        walls = [0.0] * n_hosts

        def base_run(i):
            bridge, recs = hosts[i]
            t0 = time.perf_counter()
            try:
                warm_units_parallel(bridge, recs)
            except Exception as exc:  # noqa: BLE001 - reported, not raised
                errors.append(f"baseline host {i}: {exc}")
            walls[i] = time.perf_counter() - t0

        t0 = time.perf_counter()
        threads = [threading.Thread(target=base_run, args=(i,))
                   for i in range(n_hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        base_wall = time.perf_counter() - t0
        cdn_bytes = sum(b.stats.bytes_from_cdn for b, _r in hosts)
        out["baseline"] = {
            "wall_s": round(base_wall, 3),
            "per_host_wall_s": [round(w, 3) for w in walls],
            "cdn_bytes": cdn_bytes,
            "gbps_per_host": round(total / base_wall / 1e9, 4),
        }
        for b, _r in hosts:
            b.close()

        # Cooperative end-to-end (collective exchange): 1/N fetch each
        # + the phase-scheduled redistribution.
        coop_wall, _cw, coop_results = coop_leg(rootp, "coop",
                                                collective=True,
                                                prewarm=False)
        out["coop"] = summarize(coop_wall, coop_results)
        out["speedup"] = (round(base_wall / coop_wall, 2)
                          if coop_wall > 0 else None)

        # Exchange race: pre-warmed shares, shaped DCN — the wall IS
        # the exchange. Point-to-point first, then the collective.
        p2p_wall, p2p_walls, p2p_results = coop_leg(
            rootp, "xp2p", collective=False, prewarm=True)
        col_wall, col_walls, col_results = coop_leg(
            rootp, "xcol", collective=True, prewarm=True)
        out["exchange"] = {
            "p2p": summarize(p2p_wall, p2p_results),
            "collective": summarize(col_wall, col_results),
            "p2p_wall_s": round(p2p_wall, 3),
            "collective_wall_s": round(col_wall, 3),
            "p2p_host_wall_max_s": round(max(p2p_walls), 3),
            "collective_host_wall_max_s": round(max(col_walls), 3),
            "collective_speedup": round(p2p_wall / col_wall, 2)
            if col_wall > 0 else None,
        }
    if errors:
        out["errors"] = errors
    return out


def bench_collective_transports(mb: float = 24.0, n_hosts: int = 8,
                                chunks_per_xorb: int = 4,
                                dcn_bps: int = 1_000_000,
                                dcn_rtt_s: float = 0.004,
                                topology: str = "0,0,0,0,1,1,1,1",
                                preadv_repeats: int = 5) -> dict:
    """Transport/schedule split + lossy-tier headline bench (ISSUE 20).

    An 8-host two-slice exchange (every host's plan share pre-warmed, so
    each leg's wall IS the exchange; cross-slice links shaped to
    ``dcn_bps``/``dcn_rtt_s``, intra-slice loopback-fast) runs the SAME
    redistribution three ways:

    - **wire**  — ``ZEST_COLLECTIVE_BACKEND=dcn``: PR-13's pooled
      DcnChannel path, byte-exact (the pre-split reference);
    - **split** — ``backend=jax`` over a registered loopback fabric:
      intra-slice phases ride the ICI lane-permute backend, cross-slice
      phases stay on the shaped wire — byte-exact, digest-identical to
      the wire leg (the transport/schedule-split pin, end to end);
    - **lossy** — ``ZEST_COLLECTIVE_LOSSY=dcn``: cross-slice BG4 float
      payloads quantize to the ZQLS int8 tier; lossy units land in the
      HBM staging overlay only (never the xorb cache), and the leg must
      beat the wire leg >=1.2x at equal peer-served ratio — the
      EQuARX-grounded headline.

    Payloads are fp32 random-normal shards (the dtype the lossy tier's
    error bound is stated for — bf16 reinterpreted as f4 would perturb
    low mantissa bytes) plus one incompressible blob that must cross
    every leg byte-exact. Byte-exact legs prove digest identity by
    reconstructing every file on every host from that host's own cache
    with NO bridge (a missing or corrupted unit fails loudly, it cannot
    heal from the CDN).

    The ``preadv`` block is the full-buffer-pass kill measured: the
    stored-scheme blob read through ``CachedFileReader`` with the
    preadv lane on vs off (min-of-N walls, byte-identity asserted)."""
    import hashlib
    import tempfile as _tempfile
    import threading

    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config, parse_topology
    from zest_tpu.models.direct import CachedFileReader
    from zest_tpu.transfer import lossy as lossy_mod
    from zest_tpu.transfer import transport
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.coop import CoopPlan, coop_round
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.federated import warm_units_parallel

    fixtures = _import_fixtures()
    repo_id = "bench/transport-split"
    rng = np.random.default_rng(20)
    shard_vals = max(1, int(mb * 1e6) // 3 // 4)
    files = {f"shard{i}.f32.bin":
             rng.standard_normal(shard_vals).astype("<f4").tobytes()
             for i in range(3)}
    files["blob.bin"] = rng.bytes(8 * 1024 * 1024)
    total = sum(len(b) for b in files.values())
    source_sha = {k: hashlib.sha256(v).hexdigest()
                  for k, v in files.items()}
    repo = fixtures.FixtureRepo(repo_id, files,
                                chunks_per_xorb=chunks_per_xorb)
    topo = parse_topology(topology)
    errors: list[str] = []

    out: dict = {
        "model_bytes": total,
        "hosts": n_hosts,
        "topology": topology,
        "chunks_per_xorb": chunks_per_xorb,
        "dcn_shaping": {"bps": dcn_bps, "rtt_s": dcn_rtt_s},
    }

    def make_host(hub, root: pathlib.Path, tag: str, i: int,
                  backend: str, lossy_tier: str):
        cfg = Config(hf_home=root / f"{tag}{i}/hf",
                     cache_dir=root / f"{tag}{i}/zest",
                     hf_token="hf_test", endpoint=hub.url, dcn_port=0,
                     coop_collective=True, coop_topology=topo,
                     collective_backend=backend,
                     collective_lossy=lossy_tier)
        bridge = XetBridge(cfg)
        bridge.authenticate(repo_id)
        recs = {e.path: bridge.get_reconstruction(e.xet_hash)
                for e in HubClient(cfg).list_files(repo_id) if e.is_xet}
        return bridge, recs

    def leg(hub, rootp: pathlib.Path, tag: str, backend: str,
            lossy_tier: str, fabric: bool) -> dict:
        transport.reset_loopback()
        hosts = [make_host(hub, rootp, tag, i, backend, lossy_tier)
                 for i in range(n_hosts)]
        servers, addrs = [], {}
        for i, (bridge, _recs) in enumerate(hosts):
            s = DcnServer(bridge.cfg, bridge.cache, rate_bps=dcn_bps,
                          window_rtt_s=dcn_rtt_s, shape_slices=topo,
                          shape_host=i)
            addrs[i] = ("127.0.0.1", s.start())
            servers.append(s)
        if fabric:
            for i, (bridge, _recs) in enumerate(hosts):
                transport.register_loopback(addrs[i], bridge.cfg,
                                            bridge.cache)

        def warm(i):
            bridge, recs = hosts[i]
            rl = list(recs.values())
            plan = CoopPlan.build(rl, n_hosts)
            warm_units_parallel(bridge, rl, units=plan.for_host(i))

        ws = [threading.Thread(target=warm, args=(i,))
              for i in range(n_hosts)]
        for t in ws:
            t.start()
        for t in ws:
            t.join()

        results: list[dict | None] = [None] * n_hosts
        walls = [0.0] * n_hosts

        def run(i):
            bridge, recs = hosts[i]
            t0 = time.perf_counter()
            try:
                results[i] = coop_round(bridge, list(recs.values()), i,
                                        n_hosts, addrs,
                                        server=servers[i])
            except Exception as exc:  # noqa: BLE001 - reported below
                errors.append(f"{tag} host {i}: {exc}")
            walls[i] = time.perf_counter() - t0

        t0 = time.perf_counter()
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

        digest_ok = None
        if lossy_tier == "0":
            # Byte-exact legs: every file on every host reconstructs
            # from that host's own cache (no bridge — missing units
            # fail, they cannot silently heal from the CDN).
            digest_ok = True
            for i, (bridge, recs) in enumerate(hosts):
                for path, rec in recs.items():
                    try:
                        reader = CachedFileReader(bridge.cache, rec)
                        sha = hashlib.sha256(
                            reader.read(0, reader.size)).hexdigest()
                    except Exception as exc:  # noqa: BLE001
                        digest_ok = False
                        errors.append(
                            f"{tag} host {i}: {path} unreadable: {exc}")
                        continue
                    if sha != source_sha[path]:
                        digest_ok = False
                        errors.append(
                            f"{tag} host {i}: digest mismatch on {path}")
        staged_units = sum(
            lossy_mod.staging_for(b.cfg.cache_dir).units()
            for b, _r in hosts)
        staged_bytes = sum(
            lossy_mod.staging_for(b.cfg.cache_dir).total_bytes()
            for b, _r in hosts)
        for s in servers:
            s.shutdown()
        for b, _r in hosts:
            b.close()
        transport.reset_loopback()

        done = [r for r in results if r]
        ratios = sorted(r["peer_served_ratio"] for r in done) or [0.0]
        cx = [r.get("collective") for r in done if r.get("collective")]
        saved = [r["exchange"].get("bits_saved_ratio") for r in done
                 if r["exchange"].get("bits_saved_ratio") is not None]
        block = {
            "backend": backend,
            "lossy": lossy_tier,
            "wall_s": round(wall, 3),
            "host_wall_max_s": round(max(walls), 3),
            "hosts_completed": len(done),
            "peer_served_ratio": ratios[len(ratios) // 2],
            "peer_served_ratio_min": ratios[0],
            "fallbacks": sum(r["fallbacks"] for r in done),
            "aborts": sum(1 for c in cx if c.get("aborted")),
            "exchange": {
                "wire_bytes": sum(r["exchange"]["wire_bytes"]
                                  for r in done),
                "unpacked_bytes": sum(r["exchange"]["unpacked_bytes"]
                                      for r in done),
                "lossy_bytes": sum(r["exchange"].get("lossy_bytes", 0)
                                   for r in done),
                "bits_saved_ratio": (
                    round(sorted(saved)[len(saved) // 2], 4)
                    if saved else None),
            },
            "link_bytes": {
                lk: sum(c["link_bytes"].get(lk, 0) for c in cx)
                for lk in ("ici", "dcn")},
            "staging": {"units": staged_units, "bytes": staged_bytes},
        }
        if digest_ok is not None:
            block["digest_identical"] = digest_ok
        return block

    with fixtures.FixtureHub(repo) as hub, \
            _tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        wire = leg(hub, rootp, "wire", "dcn", "0", fabric=False)
        split = leg(hub, rootp, "split", "jax", "0", fabric=True)
        lossy = leg(hub, rootp, "lossy", "dcn", "dcn", fabric=False)

        # preadv micro-leg: one fully-warmed host, the stored-scheme
        # blob read whole through both lanes. Fresh reader per rep
        # (term memo off the table); min-of-N against timer noise.
        pb, precs = make_host(hub, rootp, "pre", 0, "dcn", "0")
        warm_units_parallel(pb, list(precs.values()))
        blob_rec = precs["blob.bin"]

        def read_once(use_preadv: bool):
            r = CachedFileReader(pb.cache, blob_rec,
                                 use_preadv=use_preadv)
            t0 = time.perf_counter()
            data = r.read(0, r.size)
            return time.perf_counter() - t0, data, r.preadv_stats

        read_once(False)  # page-cache warmup, untimed
        on_t, off_t = [], []
        identity = True
        stats_on = {"terms": 0, "bytes": 0, "syscalls": 0}
        for _ in range(preadv_repeats):
            dt, data, stats_on = read_once(True)
            on_t.append(dt)
            identity &= (hashlib.sha256(data).hexdigest()
                         == source_sha["blob.bin"])
            dt, data, _st = read_once(False)
            off_t.append(dt)
            identity &= (hashlib.sha256(data).hexdigest()
                         == source_sha["blob.bin"])
        pb.close()
        preadv = {
            "on_s": round(min(on_t), 5),
            "off_s": round(min(off_t), 5),
            "speedup": (round(min(off_t) / min(on_t), 3)
                        if min(on_t) > 0 else None),
            "terms": stats_on["terms"],
            "bytes": stats_on["bytes"],
            "syscalls": stats_on["syscalls"],
            "identity": identity,
        }
    lossy_mod.reset_stagings()

    out["legs"] = {"wire": wire, "split": split, "lossy": lossy}
    speedup = (round(wire["wall_s"] / lossy["wall_s"], 3)
               if lossy["wall_s"] > 0 else None)
    out["lossy"] = {
        "speedup_vs_wire": speedup,
        "lossy_bytes": lossy["exchange"]["lossy_bytes"],
        "bits_saved_ratio": lossy["exchange"]["bits_saved_ratio"],
        "peer_served_ratio_delta": round(
            abs(wire["peer_served_ratio"]
                - lossy["peer_served_ratio"]), 4),
        "staging_units": lossy["staging"]["units"],
    }
    out["preadv"] = preadv
    gates = {
        "digest_identical": bool(wire.get("digest_identical")
                                 and split.get("digest_identical")),
        "lossy_speedup_ge_1.2": bool(speedup and speedup >= 1.2),
        "lossy_bytes_positive":
            lossy["exchange"]["lossy_bytes"] > 0,
        "lossy_cache_untouched": lossy["staging"]["units"] > 0,
        "peer_served_ratio_equal":
            out["lossy"]["peer_served_ratio_delta"] <= 0.05,
        "no_aborts": (wire["aborts"] + split["aborts"]
                      + lossy["aborts"]) == 0,
        "no_fallbacks": (wire["fallbacks"] + split["fallbacks"]
                         + lossy["fallbacks"]) == 0,
        "split_used_ici_lane": split["link_bytes"]["ici"] > 0,
        "preadv_identity": preadv["identity"],
        "preadv_engaged": preadv["terms"] > 0,
    }
    gates["all_ok"] = all(gates.values()) and not errors
    out["gates"] = gates
    if errors:
        out["errors"] = errors
    return out


def bench_swarm(gb: float = 0.064, m_pullers: int = 4, k_seeders: int = 4,
                fault_spec: str | None = None, fault_seed: int = 1337,
                shaped_bps: int | None = None,
                seed_rate_bps: int | None = None,
                seed_peer_bps: int | None = None,
                seed_slots: int | None = None,
                chunks_per_xorb: int = 16, scale: int = 8) -> dict:
    """Fleet-scale chaos capacity model (ROADMAP item 4, ISSUE 12).

    M concurrent pullers × K always-on seeders × an injected
    ``ZEST_FAULTS`` matrix × shaped links — the swarm the ≥90%
    peer-served BASELINE claim must survive OUTSIDE loopback-perfect
    conditions. Phases:

    1. **Warm** (unshaped, unmetered): each seeder pulls the checkpoint
       via CDN once — the steady-state fleet where every node already
       seeds what it cached.
    2. **Measured**: the CDN re-opens behind a global
       ``shaped_bps`` token bucket (one WAN-rate origin for everyone),
       each seeder serves through the production upload policy
       (``seed_rate_bps``/``seed_peer_bps``/``seed_slots`` — the
       ZEST_SEED_* knobs), the fault matrix arms, and M pullers race
       concurrent full pulls with all K seeders as direct peers
       (candidate order rotated per puller so load spreads by policy,
       not by list position).

    Reported: swarm-wide ``peer_served_ratio`` (sum of peer bytes over
    peer+cdn), per-pull p50/p99 wall, ``upload_fairness_skew``
    (max/mean of per-seeder served bytes — the choke policy's
    worst-case concentration), ``corrupt_bytes_admitted`` (every pulled
    file byte-compared against the fixture source — MUST be 0: faults
    may slow the swarm, never poison it), corruption detections/heals,
    and the fired-fault counters proving the matrix actually ran."""
    import tempfile as _tempfile
    import threading

    from zest_tpu import faults
    from zest_tpu.config import Config
    from zest_tpu.p2p.health import PROVENANCE
    from zest_tpu.transfer.pull import pull_model
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    fixtures = _import_fixtures()
    repo_id = "bench/swarm-llama"
    files = llama_checkpoint_files(gb, scale=scale, smooth=True,
                                   shard_bytes=16 * 1024 * 1024)
    total = sum(len(b) for b in files.values())
    repo = fixtures.FixtureRepo(repo_id, files,
                                chunks_per_xorb=chunks_per_xorb)
    quiet = {"log": lambda *a, **k: None}

    out: dict = {
        "model_bytes": total,
        "pullers": m_pullers,
        "seeders": k_seeders,
        "cdn_bps": shaped_bps,
        "seed_rate_bps": seed_rate_bps,
        "seed_peer_bps": seed_peer_bps,
        "faults": fault_spec,
        "fault_seed": fault_seed if fault_spec else None,
    }
    with _tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)

        def seeder_cfg(i: int) -> Config:
            cfg = Config(hf_home=rootp / f"seed{i}/hf",
                         cache_dir=rootp / f"seed{i}/zest",
                         hf_token="hf_test", endpoint="unused",
                         listen_port=0)
            if seed_rate_bps:
                cfg.seed_rate_bps = seed_rate_bps
            if seed_peer_bps:
                cfg.seed_peer_bps = seed_peer_bps
            if seed_slots:
                cfg.seed_slots = seed_slots
            return cfg

        # Phase 1: warm the seeder fleet against an UNSHAPED origin
        # (fleet steady state is not what's being measured).
        scfgs = [seeder_cfg(i) for i in range(k_seeders)]
        with fixtures.FixtureHub(repo) as warm_hub:
            for cfg in scfgs:
                cfg.endpoint = warm_hub.url
                pull_model(cfg, repo_id, no_p2p=True, **quiet)

        servers = [BtServer(cfg) for cfg in scfgs]
        ports = [s.start() for s in servers]
        PROVENANCE.reset()
        faults.install(fault_spec, fault_seed)
        walls: list[float] = [0.0] * m_pullers
        stats: list[dict | None] = [None] * m_pullers
        corrupt_admitted = [0] * m_pullers
        errors: list[str] = []

        try:
            with fixtures.FixtureHub(repo,
                                     throttle_bps=shaped_bps) as hub:
                def pull_run(i: int) -> None:
                    cfg = Config(hf_home=rootp / f"pull{i}/hf",
                                 cache_dir=rootp / f"pull{i}/zest",
                                 hf_token="hf_test", endpoint=hub.url)
                    swarm = SwarmDownloader(cfg)
                    for j in range(k_seeders):
                        k = (i + j) % k_seeders
                        swarm.add_direct_peer("127.0.0.1", ports[k])
                    t0 = time.perf_counter()
                    try:
                        res = pull_model(cfg, repo_id, swarm=swarm,
                                         **quiet)
                        walls[i] = time.perf_counter() - t0
                        stats[i] = res.stats
                        for name, want in files.items():
                            got = (res.snapshot_dir / name).read_bytes()
                            if got != want:
                                corrupt_admitted[i] += sum(
                                    a != b for a, b in zip(got, want)
                                ) + abs(len(got) - len(want))
                    except Exception as exc:  # noqa: BLE001 - reported
                        errors.append(f"puller {i}: {exc}")
                    finally:
                        swarm.close()

                threads = [threading.Thread(target=pull_run, args=(i,))
                           for i in range(m_pullers)]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                swarm_wall = time.perf_counter() - t0
            fired = faults.counters()
        finally:
            faults.install(None)
            for s in servers:
                s.shutdown()

        seeder_bytes = [s.get_stats().bytes_served for s in servers]
        seeder_stats = [s.get_stats() for s in servers]
        done = [s for s in stats if s]
        peer = sum(s["fetch"]["bytes"].get("peer", 0) for s in done)
        cdn = sum(s["fetch"]["bytes"].get("cdn", 0) for s in done)
        ok_walls = sorted(w for w, s in zip(walls, stats) if s)
        mean_served = (sum(seeder_bytes) / len(seeder_bytes)
                       if seeder_bytes else 0)
        out.update({
            "swarm_wall_s": round(swarm_wall, 3),
            "pulls_completed": len(done),
            "peer_served_ratio": (round(peer / (peer + cdn), 4)
                                  if peer + cdn else None),
            "pull_latency_s": {
                "p50": round(ok_walls[len(ok_walls) // 2], 3)
                if ok_walls else None,
                "p99": round(ok_walls[min(len(ok_walls) - 1,
                                          int(len(ok_walls) * 0.99))], 3)
                if ok_walls else None,
            },
            "upload_fairness": {
                "per_seeder_bytes": seeder_bytes,
                "skew": (round(max(seeder_bytes) / mean_served, 3)
                         if mean_served else None),
            },
            "corrupt_bytes_admitted": sum(corrupt_admitted),
            # The swarm counter alone: every peer-attributed detection
            # lands there via report_corrupt (the bridge's own
            # resilience counter records the SAME events — summing
            # both would double-count).
            "corrupt_detected": sum(
                s.get("swarm", {}).get("corrupt_from_peer", 0)
                for s in done),
            "choke_events": sum(s.choke_events for s in seeder_stats),
            "uploads_expired": sum(s.uploads_expired
                                   for s in seeder_stats),
            "refused_quarantined": sum(s.refused_quarantined
                                       for s in seeder_stats),
            "faults_fired": dict(sorted(fired.items())),
        })
        if seed_rate_bps and ok_walls:
            # Observed per-seeder upload rate vs the knob — the smoke
            # gate's ±20% enforcement evidence.
            out["upload_fairness"]["observed_bps"] = [
                round(b / swarm_wall) for b in seeder_bytes]
        if errors:
            out["errors"] = errors
    return out


def bench_mttr(gb: float = 0.02, runs: int = 2,
               chunks_per_xorb: int = 8, scale: int = 8,
               window_s: float = 0.6, hz: float = 20.0,
               stall_s: float = 6.0,
               corrupt_seed_bps: int = 1_000_000,
               dcn_chunks_per_xorb: int = 32,
               fault_seed: int = 1337,
               dcn_fault_seed: int = 9) -> dict:
    """Measured-MTTR chaos bench (ISSUE 17): detection-to-recovery with
    the self-healing policy engine ON vs the same faults ridden out
    hands-off (``ZEST_REMEDIATE=0`` — observer identical, actions off).

    One fault class per scenario, each run twice (hands-off arm, then
    policy arm) over ``runs`` cold pulls:

    - **seeder_stall**: every seeder stalls ``stall_s`` per upload —
      below the io-timeout floor, so the hands-off swarm never strikes
      or reroutes; it just grinds one stall per request wave. The
      policy arm's stall anomaly arms the mid-flight hedge, so every
      wave after the first races the CDN with a sub-window peer head
      start.
    - **seeder_choke_flap**: spurious chokes. Honest non-win — a choke
      is a fast refusal and the waterfall already falls through to the
      CDN at full speed; reported, not gated.
    - **cdn_503**: origin 5xx bursts on a peer-less pull. Honest
      non-win (the retry/backoff path is the remedy in both arms).
    - **upload_corrupt**: the ONLY seeder serves flipped bytes, with
      ``ZEST_PEER_STRIKES=99`` so the hands-off registry never
      quarantines — every term pays a shaped corrupt fetch + CDN heal.
      The policy arm's seeder scan demotes the peer on corrupt-strike
      evidence (never *creating* a strike) and the rest of the pull is
      pure fast CDN.
    - **dcn_reset**: 2-host cooperative round where the partner owns
      half the plan but has an EMPTY cache (permanent NOT_FOUND), and
      the injected reset kills the channel a few barrier rounds in.
      Hands-off rides the backoff ladder until the reset aborts it;
      ``ZEST_REMEDIATE_PATIENCE=1`` aborts on the first straggler
      firing instead.
    - **control**: healthy swarm, no faults — proves the policy arm
      executes ZERO actions and holds the peer-served ratio when
      nothing is wrong (over-healing is itself a failure mode).

    MTTR = last-byte time minus detection time, where detection is the
    first ``anomaly`` flight event (falling back to the first
    ``fault_fired`` event for classes the detector has no signature
    for, e.g. corrupt bytes — identical definition in both arms; the
    detector runs in both, only actions differ). The ``gates`` block is
    the acceptance surface: ≥3 classes at ≤0.5× hands-off MTTR, zero
    corrupt bytes admitted, every fault actually fired in the hands-off
    arm (the policy arm may legitimately short-circuit a fault site —
    an aborted exchange never rolls the reset dice again), every
    executed action carrying before/after series, and the control
    scenario clean."""
    import contextlib
    import os
    import tempfile as _tempfile

    from zest_tpu import faults, telemetry
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config
    from zest_tpu.p2p.health import PROVENANCE
    from zest_tpu.telemetry import recorder
    from zest_tpu.telemetry import remediate as remediate_mod
    from zest_tpu.telemetry import timeline as timeline_mod
    from zest_tpu.transfer import bridge as bridge_mod
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.coop import coop_round
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.pull import pull_model
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    fixtures = _import_fixtures()
    # Keep the armed hedge's peer head start under the anomaly window:
    # at the default 1 s wait every hedged wave opens with a
    # window-length zero-rate gap that re-arms the stall episode AND
    # dominates the policy arm's per-term cost (the quantity under
    # measurement is detection-to-recovery, not the evidence pause).
    saved_wait = bridge_mod._HEDGE_EVIDENCE_WAIT_S
    bridge_mod._HEDGE_EVIDENCE_WAIT_S = min(saved_wait, window_s / 2.0)
    files = llama_checkpoint_files(gb, scale=scale, smooth=True,
                                   shard_bytes=8 * 1024 * 1024)
    total = sum(len(b) for b in files.values())
    quiet = {"log": lambda *a, **k: None}

    @contextlib.contextmanager
    def _env(overlay: dict[str, str]):
        saved = {k: os.environ.get(k) for k in overlay}
        os.environ.update(overlay)
        try:
            yield
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def _measure(fault_spec, seed, extra_env, policy_on, run_fn):
        """One arm of one run: env → fresh telemetry → faults → pull.
        Env lands BEFORE reset_all so the rebuilt store/engine read it;
        the fault injector is (re)installed per run so the deterministic
        trial sequence restarts identically in both arms."""
        overlay = {
            "ZEST_TIMELINE_HZ": str(hz),
            "ZEST_ANOMALY_WINDOW_S": str(window_s),
            "ZEST_REMEDIATE": "1" if policy_on else "0",
            **(extra_env or {}),
        }
        with _env(overlay):
            telemetry.reset_all()
            faults.install(fault_spec, seed)
            try:
                t0 = time.time()
                extra = run_fn()
                t1 = time.time()
                fired = dict(faults.counters())
            finally:
                faults.install(None)
            events = recorder.tail()
        anomaly_ts = [e["t"] for e in events
                      if e.get("kind") == "anomaly"]
        fault_ts = [e["t"] for e in events
                    if e.get("kind") == "fault_fired"]
        rems = [e for e in events if e.get("kind") == "remediation"]
        detect = (min(anomaly_ts) if anomaly_ts
                  else min(fault_ts) if fault_ts else t0)
        return {
            "wall_s": t1 - t0,
            "mttr_s": max(0.0, t1 - detect),
            "detect_lag_s": max(0.0, detect - t0),
            "detected": bool(anomaly_ts),
            "faults_fired": fired,
            "remediations": [
                {"action": e.get("action"),
                 "outcome": e.get("outcome"),
                 "has_series": isinstance(e.get("before"), dict)
                 and isinstance(e.get("after"), dict)}
                for e in rems],
            **extra,
        }

    def swarm_case(name, spec, k_seeders, extra_env=None, seed_bps=None,
                   seed=fault_seed, no_p2p=False, cfg_overrides=None):
        """Warm K seeders once (unfaulted), then both arms × runs of a
        cold single-puller pull against them + the loopback hub."""
        repo_id = f"bench/mttr-{name}"
        repo = fixtures.FixtureRepo(repo_id, dict(files),
                                    chunks_per_xorb=chunks_per_xorb)
        with _tempfile.TemporaryDirectory() as root:
            rootp = pathlib.Path(root)
            scfgs = []
            for i in range(k_seeders):
                cfg = Config(hf_home=rootp / f"seed{i}/hf",
                             cache_dir=rootp / f"seed{i}/zest",
                             hf_token="hf_test", endpoint="unused",
                             listen_port=0)
                if seed_bps:
                    cfg.seed_rate_bps = seed_bps
                scfgs.append(cfg)
            with fixtures.FixtureHub(repo) as warm_hub:
                for cfg in scfgs:
                    cfg.endpoint = warm_hub.url
                    pull_model(cfg, repo_id, no_p2p=True, **quiet)
            servers = [BtServer(cfg) for cfg in scfgs]
            ports = [s.start() for s in servers]
            try:
                with fixtures.FixtureHub(repo) as hub:
                    def one_pull(tag):
                        PROVENANCE.reset()
                        cfg = Config(hf_home=rootp / f"{tag}/hf",
                                     cache_dir=rootp / f"{tag}/zest",
                                     hf_token="hf_test",
                                     endpoint=hub.url)
                        for k, v in (cfg_overrides or {}).items():
                            setattr(cfg, k, v)
                        swarm = None
                        if not no_p2p:
                            swarm = SwarmDownloader(cfg)
                            for p in ports:
                                swarm.add_direct_peer("127.0.0.1", p)
                        try:
                            res = pull_model(cfg, repo_id, swarm=swarm,
                                             no_p2p=no_p2p, **quiet)
                            bad = 0
                            for fname, want in files.items():
                                got = (res.snapshot_dir
                                       / fname).read_bytes()
                                if got != want:
                                    bad += sum(
                                        a != b for a, b in
                                        zip(got, want)
                                    ) + abs(len(got) - len(want))
                            fb = res.stats["fetch"]["bytes"]
                            return {
                                "corrupt_bytes_admitted": bad,
                                "peer_bytes": fb.get("peer", 0),
                                "cdn_bytes": fb.get("cdn", 0),
                            }
                        finally:
                            if swarm is not None:
                                swarm.close()

                    arms = {}
                    for arm, on in (("hands_off", False),
                                    ("policy_on", True)):
                        arms[arm] = [
                            _measure(spec, seed, extra_env, on,
                                     lambda r=r, a=arm:
                                     one_pull(f"{a}{r}"))
                            for r in range(runs)]
                    return arms
            finally:
                for s in servers:
                    s.shutdown()

    def coop_case(spec, seed, extra_env):
        """2-host collective round: host 1 serves an EMPTY cache (every
        exchange window a NOT_FOUND barrier retry) so host 0's round
        lives or dies by the abort policy."""
        repo_id = "bench/mttr-dcn_reset"
        repo = fixtures.FixtureRepo(repo_id, dict(files),
                                    chunks_per_xorb=dcn_chunks_per_xorb)
        with fixtures.FixtureHub(repo) as hub, \
                _tempfile.TemporaryDirectory() as root:
            rootp = pathlib.Path(root)

            def one_round(tag):
                def mk(i):
                    cfg = Config(hf_home=rootp / f"{tag}h{i}/hf",
                                 cache_dir=rootp / f"{tag}h{i}/zest",
                                 hf_token="hf_test", endpoint=hub.url,
                                 dcn_port=0, coop_collective=True)
                    b = XetBridge(cfg)
                    b.authenticate(repo_id)
                    return b
                b0, b1 = mk(0), mk(1)
                s1 = DcnServer(b1.cfg, b1.cache)
                port1 = s1.start()
                try:
                    recs = [b0.get_reconstruction(e.xet_hash)
                            for e in HubClient(b0.cfg)
                            .list_files(repo_id) if e.is_xet]
                    # A bare coop_round has no pull entry to start the
                    # observer for it: start the sampler (BOTH arms —
                    # detection is measured hands-off too) and, when
                    # ZEST_REMEDIATE=1, the policy engine.
                    timeline_mod.ensure_started()
                    remediate_mod.ensure_started()
                    coop_round(b0, recs, 0, 2,
                               {1: ("127.0.0.1", port1)})
                    bad = 0
                    out_f = rootp / f"{tag}.check"
                    for e in HubClient(b0.cfg).list_files(repo_id):
                        if not e.is_xet:
                            continue
                        b0.reconstruct_to_file(e.xet_hash, out_f)
                        got = out_f.read_bytes()
                        want = files[e.path]
                        if got != want:
                            bad += sum(a != b for a, b in
                                       zip(got, want)) \
                                + abs(len(got) - len(want))
                    st = b0.stats
                    return {
                        "corrupt_bytes_admitted": bad,
                        "peer_bytes": getattr(st, "bytes_from_peer",
                                              0),
                        "cdn_bytes": getattr(st, "bytes_from_cdn", 0),
                    }
                finally:
                    s1.shutdown()
                    b0.close()
                    b1.close()

            arms = {}
            for arm, on in (("hands_off", False), ("policy_on", True)):
                arms[arm] = [
                    _measure(spec, seed, extra_env, on,
                             lambda r=r, a=arm: one_round(f"{a}{r}"))
                    for r in range(runs)]
            return arms

    def _agg(rs):
        ms = sorted(r["mttr_s"] for r in rs)
        peer = sum(r.get("peer_bytes", 0) for r in rs)
        cdn = sum(r.get("cdn_bytes", 0) for r in rs)
        fired: dict[str, int] = {}
        for r in rs:
            for k, v in r["faults_fired"].items():
                fired[k] = fired.get(k, 0) + v
        actions: dict[str, int] = {}
        series_ok = True
        for r in rs:
            for e in r["remediations"]:
                k = f'{e["action"]}:{e["outcome"]}'
                actions[k] = actions.get(k, 0) + 1
                if not e["has_series"]:
                    series_ok = False
        return {
            "runs": len(rs),
            "mttr_s": {"p50": round(ms[len(ms) // 2], 3),
                       "p99": round(ms[-1], 3)},
            "detect_lag_s": round(
                sorted(r["detect_lag_s"]
                       for r in rs)[len(rs) // 2], 3),
            "detected_runs": sum(1 for r in rs if r["detected"]),
            "wall_s": round(
                sorted(r["wall_s"] for r in rs)[len(rs) // 2], 3),
            "peer_served_ratio": (round(peer / (peer + cdn), 4)
                                  if peer + cdn else None),
            "corrupt_bytes_admitted": sum(
                r["corrupt_bytes_admitted"] for r in rs),
            "faults_fired": fired,
            "actions": dict(sorted(actions.items())),
            "remediations_have_series": series_ok,
        }

    cases = [
        ("seeder_stall", {"kind": "swarm",
                          "spec": f"seeder_stall:1.0@{stall_s}",
                          # Narrow pipe (same rationale as the corrupt
                          # case): the unhedged FIRST wave — workers
                          # already inside the peer tier when the
                          # detector arms the hedge — is one stall per
                          # concurrent slot, so a wide pipe front-loads
                          # stalls the policy can never race.
                          "cfg_overrides":
                              {"max_concurrent_downloads": 4},
                          "k": 2}),
        ("seeder_choke_flap", {"kind": "swarm",
                               "spec": "seeder_choke_flap:0.6",
                               "k": 2}),
        ("cdn_503", {"kind": "swarm", "spec": "cdn_503:0.3", "k": 0,
                     "no_p2p": True}),
        ("upload_corrupt", {"kind": "swarm",
                            "spec": "upload_corrupt:1.0", "k": 1,
                            "seed_bps": corrupt_seed_bps,
                            # Narrow pipe: the corrupt-fetch tax is per
                            # connection; wide concurrency would hide
                            # the shaped seeder behind the loopback CDN.
                            "cfg_overrides":
                                {"max_concurrent_downloads": 4},
                            "env": {"ZEST_PEER_STRIKES": "99"}}),
        ("dcn_reset", {"kind": "coop", "spec": "dcn_reset:0.05",
                       "seed": dcn_fault_seed,
                       "env": {"ZEST_REMEDIATE_PATIENCE": "1"}}),
        ("control", {"kind": "swarm", "spec": None, "k": 2}),
    ]
    out: dict = {
        "model_bytes": total,
        "runs": runs,
        "window_s": window_s,
        "hz": hz,
        "cases": {},
    }
    try:
        for name, c in cases:
            if c["kind"] == "coop":
                arms = coop_case(c["spec"], c.get("seed", fault_seed),
                                 c.get("env"))
            else:
                arms = swarm_case(name, c["spec"], c["k"],
                                  extra_env=c.get("env"),
                                  seed_bps=c.get("seed_bps"),
                                  seed=c.get("seed", fault_seed),
                                  no_p2p=c.get("no_p2p", False),
                                  cfg_overrides=c.get("cfg_overrides"))
            ho, po = _agg(arms["hands_off"]), _agg(arms["policy_on"])
            ratio = (round(po["mttr_s"]["p50"] / ho["mttr_s"]["p50"], 3)
                     if ho["mttr_s"]["p50"] > 0 else None)
            out["cases"][name] = {
                "fault_spec": c["spec"],
                "hands_off": ho,
                "policy_on": po,
                "mttr_ratio": ratio,
                "win": bool(name != "control" and ratio is not None
                            and ratio <= 0.5),
            }
    finally:
        bridge_mod._HEDGE_EVIDENCE_WAIT_S = saved_wait
        # Rebuild the default store/engine once the env games are over.
        telemetry.reset_all()

    fault_cases = [n for n, _ in cases if n != "control"]
    wins = [n for n in fault_cases if out["cases"][n]["win"]]
    corrupt = sum(out["cases"][n][arm]["corrupt_bytes_admitted"]
                  for n, _ in cases
                  for arm in ("hands_off", "policy_on"))
    ctl = out["cases"]["control"]
    ctl_exec = sum(v for k, v in ctl["policy_on"]["actions"].items()
                   if k.endswith(":success") or k.endswith(":failed"))
    out["gates"] = {
        "classes_at_half": wins,
        "classes_at_half_ok": len(wins) >= 3,
        "corrupt_bytes_admitted": corrupt,
        "all_faults_fired": all(
            out["cases"][n]["hands_off"]["faults_fired"].get(n, 0) > 0
            for n in fault_cases),
        "remediations_have_series": all(
            out["cases"][n]["policy_on"]["remediations_have_series"]
            for n in fault_cases),
        "control_actions_executed": ctl_exec,
        "peer_ratio_ok": (
            (ctl["policy_on"]["peer_served_ratio"] or 0.0)
            >= (ctl["hands_off"]["peer_served_ratio"] or 0.0) - 0.05),
    }
    return out


def bench_delta_pull(gb: float = 2.0, runs: int = 3,
                     chunks_per_xorb: int = 512, scale: int = 2,
                     mutate_fraction: float = 0.01,
                     budget_s: float | None = None) -> dict:
    """Delta pull vs cold pull (ISSUE 10 acceptance bench).

    Per run: a cold ``--device`` pull of revision A (the baseline
    ``time_to_hbm_s``), then a delta pull of the seeded
    ``mutate_fraction``-changed revision B into the SAME cache with the
    resident rev-A tree hot-swapped in place. Headlines:
    ``delta_bytes_ratio`` (network-fetched fraction — the ≤3% gate on a
    1%-changed revision), ``time_to_swap_s`` vs the cold median (the
    ≤0.3× gate), and ``digest_identical`` — the swapped tree's
    ``params_digest`` against a cold pull of B (checked once; it costs
    a third full pull)."""
    import sys

    tests_dir = str(pathlib.Path(__file__).resolve().parent.parent
                    / "tests")
    sys.path.insert(0, tests_dir)
    try:
        from fixtures import FixtureHub, FixtureRepo
    finally:
        try:
            sys.path.remove(tests_dir)
        except ValueError:
            pass

    from zest_tpu.config import Config
    from zest_tpu.models.loader import params_digest
    from zest_tpu.transfer.pull import pull_model

    t_bench0 = time.perf_counter()
    files_a = llama_checkpoint_files(gb, scale=scale)
    files_b = llama_checkpoint_files(gb, scale=scale,
                                     mutate_fraction=mutate_fraction)
    total = sum(len(b) for b in files_b.values())
    repo = FixtureRepo("bench/delta-llama", files_a,
                       chunks_per_xorb=chunks_per_xorb)
    sha_a = repo.commit_sha
    sha_b = repo.add_revision(files_b)
    gc.collect()

    quiet = {"log": lambda *a, **k: None}
    cold_s: list[float] = []
    swap_s: list[float] = []
    ratios: list[float] = []
    fetched: list[int] = []
    reused_tensors: list[int] = []
    digest_identical = None
    with FixtureHub(repo) as hub:
        for run_i in range(runs):
            if run_i and budget_s is not None \
                    and time.perf_counter() - t_bench0 > budget_s:
                break  # keep what's measured (bench_gb_pull's rule)
            _settle_page_cache(False)
            with tempfile.TemporaryDirectory() as root:
                rootp = pathlib.Path(root)
                cfg = Config(hf_home=rootp / "hf",
                             cache_dir=rootp / "zest",
                             hf_token="hf_test", endpoint=hub.url)
                res_a = pull_model(cfg, "bench/delta-llama",
                                   revision=sha_a, device="tpu",
                                   no_p2p=True, **quiet)
                cold_s.append(res_a.stats["time_to_hbm_s"])
                _settle_page_cache(False)
                res_b = pull_model(cfg, "bench/delta-llama",
                                   revision=sha_b, device="tpu",
                                   no_p2p=True,
                                   base_params=res_a.params,
                                   base_revision=sha_a, **quiet)
                d = res_b.stats.get("delta") or {}
                swap_s.append(res_b.stats.get("time_to_swap_s")
                              or res_b.stats["time_to_hbm_s"])
                ratios.append(d.get("fetched_ratio",
                                    d.get("delta_bytes_ratio", 1.0)))
                fetched.append(d.get("fetched_bytes", 0))
                reused_tensors.append(
                    (d.get("tensors") or {}).get("reused", 0))
                if digest_identical is None:
                    dig_swap = params_digest(res_b.params)
                    with tempfile.TemporaryDirectory() as root2:
                        r2 = pathlib.Path(root2)
                        cfg2 = Config(hf_home=r2 / "hf",
                                      cache_dir=r2 / "zest",
                                      hf_token="hf_test",
                                      endpoint=hub.url)
                        res_cold = pull_model(cfg2, "bench/delta-llama",
                                              revision=sha_b,
                                              device="tpu", no_p2p=True,
                                              **quiet)
                        digest_identical = (
                            params_digest(res_cold.params) == dig_swap)
                        res_cold.params = None
                res_a.params = None
                res_b.params = None
                del res_a, res_b
                gc.collect()

    med_cold = statistics.median(cold_s)
    med_swap = statistics.median(swap_s)
    return {
        "checkpoint_gb": round(total / 1e9, 3),
        "mutate_fraction": mutate_fraction,
        "runs": len(swap_s),
        "cold_time_to_hbm_s": round(med_cold, 3),
        "time_to_swap_s": round(med_swap, 3),
        "time_to_swap_runs_s": [round(t, 3) for t in swap_s],
        "speedup_vs_cold": round(med_cold / med_swap, 2)
        if med_swap else None,
        "swap_ratio": round(med_swap / med_cold, 3) if med_cold else None,
        "delta_bytes_ratio": round(statistics.median(ratios), 4),
        "fetched_bytes": int(statistics.median(fetched)),
        "tensors_reused": int(statistics.median(reused_tensors)),
        "digest_identical": digest_identical,
    }


def _settle_page_cache(drop: bool) -> str:
    """Between-run page-cache discipline (ISSUE 5: spread must measure
    the system, not the previous run's dirty pages).

    Always ``sync()``s so the prior run's writeback drains *outside*
    the next timed window (the dominant cross-run contamination: a
    2 GB pull leaves ~2 GB of dirty cache+HF pages whose flush used to
    land mid-next-run). With ``drop`` (``ZEST_BENCH_DROP_CACHES=1``)
    it additionally drops the clean page cache via
    ``/proc/sys/vm/drop_caches`` — the *cold* page-cache mode; without
    permission the toggle degrades loudly to the warm mode. Returns
    the mode actually achieved: ``"cold"`` or ``"warm"``."""
    import os

    try:
        os.sync()
    except (AttributeError, OSError):  # pragma: no cover - sync is POSIX
        pass
    if not drop:
        return "warm"
    try:
        with open("/proc/sys/vm/drop_caches", "w") as f:
            f.write("1")
        return "cold"
    except OSError:
        return "warm"


def bench_gb_pull(gb: float = 2.0, runs: int = 3,
                  chunks_per_xorb: int = 512, scale: int = 2,
                  budget_s: float | None = None,
                  drop_caches: bool | None = None) -> dict:
    """``runs`` cold GB-scale pulls; per-stage medians + relative spread.

    ``scale=2`` since ISSUE 8: 2 GB at true-8B dims (scale=1) is a
    DEGENERATE checkpoint — two ~1 GB embedding matrices plus a single
    transformer layer, so the first-layer set is ~half the bytes and
    ``first_layer_ratio`` (the streaming headline) is structurally
    meaningless there. scale=2 keeps the byte total but gives the
    fixture real depth (~14 layers), the shape a 2 GB slice of a
    production pull actually has. The geometry is recorded in the
    artifact (``"geometry"``), so scale-1 and scale-2 artifacts can't
    be silently compared.

    The hub (and the one-time checkpoint + xorb build) is shared across
    runs; each run gets fresh cache/HF dirs so every pull is cold. The
    spread is (max-min)/median of the end-to-end time across runs —
    above 0.20 the result is flagged ``"stable": false`` so an unstable
    number can't masquerade as a measurement (the fail-loudly rule the
    blake3 bench established).

    **Page-cache split**: every run is preceded by a ``sync()`` so the
    previous run's writeback never bleeds into the next timed window
    (each *xorb-cache*-cold run used to be page-cache-warm-or-flushing
    depending on timing — the single biggest spread source the r05
    artifact flagged). ``drop_caches`` (env ``ZEST_BENCH_DROP_CACHES=1``,
    needs root) additionally empties the clean page cache for a fully
    cold-IO measurement; the mode actually achieved is recorded under
    ``"page_cache"`` so warm and cold artifacts can't be confused.

    ``budget_s`` bounds the whole bench (fixture build + warmup +
    timed runs): once at least ONE timed run has landed, the loop stops
    rather than blow the driver's bench window on a slow chip tunnel —
    losing repeat runs (reported via ``"runs"``) beats losing the
    entire recorded benchmark. The checkpoint size is never reduced.
    """
    import os
    import sys

    if drop_caches is None:
        drop_caches = os.environ.get("ZEST_BENCH_DROP_CACHES") == "1"

    # The loopback hub lives in tests/ (it is a test double, not
    # product code). Scope the path injection to the import so an
    # installed package without the checkout fails with a clean
    # ImportError here — and nothing named "fixtures" stays shadowed
    # in the host process.
    tests_dir = str(pathlib.Path(__file__).resolve().parent.parent / "tests")
    sys.path.insert(0, tests_dir)
    try:
        from fixtures import FixtureHub, FixtureRepo
    finally:
        try:
            sys.path.remove(tests_dir)
        except ValueError:
            pass

    from zest_tpu.config import Config
    from zest_tpu.transfer.pull import pull_model

    t_bench0 = t0 = time.perf_counter()
    files = llama_checkpoint_files(gb, scale=scale)
    total = sum(len(b) for b in files.values())
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    repo = FixtureRepo("bench/llama-geometry", files,
                       chunks_per_xorb=chunks_per_xorb)
    t_encode = time.perf_counter() - t0
    n_xorbs = len(repo.xorbs)
    gc.collect()  # drop encode-time garbage before any timed run

    def over_budget(frac: float = 1.0) -> bool:
        """One definition of "the budget is spent" for all three
        decision sites (pre-skip, loop break, warmup promotion)."""
        return (budget_s is not None
                and time.perf_counter() - t_bench0 > budget_s * frac)

    # If the fixture build already ate most of the budget, the untimed
    # warmup pull is a luxury: skip it (flagged below) so the budget
    # overshoot is at most ONE pull — the single timed run that must
    # happen for anything to be recorded at all.
    warmup_runs = 0 if over_budget(0.5) else 1
    results = []
    page_cache_modes: list[str] = []
    with FixtureHub(repo) as hub:
        for run_i in range(runs + warmup_runs):
            if results and over_budget():
                break  # keep what's measured; see docstring
            page_cache_modes.append(_settle_page_cache(drop_caches))
            with tempfile.TemporaryDirectory() as root:
                rootp = pathlib.Path(root)
                cfg = Config(hf_home=rootp / "hf",
                             cache_dir=rootp / "zest",
                             hf_token="hf_test", endpoint=hub.url)
                t0 = time.perf_counter()
                res = pull_model(cfg, "bench/llama-geometry",
                                 device="tpu", no_p2p=True,
                                 log=lambda *a, **k: None)
                wall = time.perf_counter() - t0
                hbm = res.stats.get("hbm") or {}
                if "error" in hbm:
                    raise RuntimeError(f"HBM commit failed: {hbm['error']}")
                is_warmup = run_i < warmup_runs
                if is_warmup and over_budget():
                    # The budget died DURING the warmup (fast build,
                    # slow pulls): promote it to the one recorded run
                    # instead of also paying a mandatory timed pull —
                    # the overshoot stays bounded at one pull. Its
                    # cold-process costs are disclosed by
                    # warmup_skipped below.
                    warmup_runs = 0
                    is_warmup = False
                if not is_warmup:
                    # Run 0 is an untimed warmup (when the budget
                    # affords one): the first pull of a process pays
                    # one-off costs (native lib load, allocator arena
                    # growth, page-cache state) measured at 2-3x the
                    # steady state — a cold-CACHE number should not
                    # smuggle in cold-PROCESS costs.
                    results.append({
                        "wall_s": wall,
                        "stages": res.stats.get("stages", {}),
                        "stages_busy": res.stats.get("stages_busy", {}),
                        "time_to_hbm_s": res.stats.get("time_to_hbm_s"),
                        "time_to_first_layer_s": res.stats.get(
                            "time_to_first_layer_s"),
                        "ring": (res.stats.get("hbm") or {}).get("ring"),
                        "files_hbm_span_s": res.stats.get(
                            "files_hbm_span_s"),
                        "files_after_hbm_s": res.stats.get(
                            "files_after_hbm_s"),
                        "lane_bytes": (res.stats.get("files_pipeline")
                                       or {}).get("lane_bytes"),
                        "hbm_gbps": hbm.get("gbps"),
                        "direct": hbm.get("direct"),
                    })
                res.params = None  # release HBM before the next run
                del res
                gc.collect()

    # time-to-HBM is the BASELINE metric: params resident in device
    # memory. The pull keeps going afterwards (finishing the HF-cache
    # file writes), so the honest time_to_hbm is the pull's own
    # wall-clock up to the commit — stats["time_to_hbm_s"]. (The old
    # stage-sum definition would double-count the pipelined pull:
    # `files` work overlapping `hbm_commit` is not time-to-HBM.) The
    # stage-sum remains the fallback for a pull that never landed.
    hbm_stages = ("resolve", "cas_metadata", "fetch", "hbm_commit")
    hbm_times = [
        r["time_to_hbm_s"] if r.get("time_to_hbm_s") is not None
        else sum(r["stages"].get(s, 0.0) for s in hbm_stages)
        for r in results
    ]
    walls = [r["wall_s"] for r in results]
    med_hbm = statistics.median(hbm_times)
    spread = ((max(hbm_times) - min(hbm_times)) / med_hbm
              if med_hbm else 0.0)
    stage_names = sorted({k for r in results for k in r["stages"]})
    stages = {}
    for name in stage_names:
        vals = [r["stages"].get(name, 0.0) for r in results]
        busies = [r["stages_busy"].get(name, 0.0) for r in results]
        med = statistics.median(vals)
        # Throughput is the median of PER-RUN rates, not total over the
        # median time — medians of ratios and ratios of medians diverge
        # exactly when runs are unstable, which is when the bench's
        # numbers are scrutinized hardest.
        rates = [total / v / 1e9 for v in vals if v > 0.05]
        stages[name] = {
            "s": round(med, 3),
            "busy_s": round(statistics.median(busies), 3),
            "gbps": round(statistics.median(rates), 3) if rates else None,
            "spread": round((max(vals) - min(vals)) / med, 3)
            if med > 0.05 else None,
        }
    # Overlap attribution (the pipelined pull's acceptance metric):
    # busy(files) + busy(hbm_commit) > span(files ∪ hbm_commit) iff the
    # two stages genuinely ran concurrently; overlap_s is the saving.
    busy_sums, span_vals = [], []
    for r in results:
        fb = r["stages_busy"].get("files", 0.0)
        hb = r["stages_busy"].get("hbm_commit", 0.0)
        span = r.get("files_hbm_span_s")
        if span is None:
            span = (r["stages"].get("files", 0.0)
                    + r["stages"].get("hbm_commit", 0.0))
        busy_sums.append(fb + hb)
        span_vals.append(span)
    med_busy = statistics.median(busy_sums)
    med_span = statistics.median(span_vals)
    geom = ("llama-8B-shapes" if scale == 1
            else f"llama-8B-shapes/{scale}")
    after_vals = [r["files_after_hbm_s"] for r in results
                  if r.get("files_after_hbm_s") is not None]
    # Streaming-landing headline (ISSUE 8): how soon the first-token-
    # capable set was resident, next to time_to_hbm — plus per-run
    # values and the last run's ring counters (occupancy/stall
    # evidence). Absent entirely for knob-off runs.
    fl_vals = [r["time_to_first_layer_s"] for r in results
               if r.get("time_to_first_layer_s") is not None]
    rings = [r["ring"] for r in results if r.get("ring")]
    timed_modes = page_cache_modes[-len(results):]
    return {
        "checkpoint_gb": round(total / 1e9, 3),
        "geometry": f"{geom} bf16",
        "runs": len(results),
        "time_to_hbm_s": round(med_hbm, 3),
        "time_to_hbm_runs_s": [round(t, 3) for t in hbm_times],
        **({"time_to_first_layer_s": round(statistics.median(fl_vals), 3),
            "time_to_first_layer_runs_s": [round(t, 3) for t in fl_vals],
            "first_layer_ratio": round(
                statistics.median(fl_vals) / med_hbm, 3)
            if med_hbm else None,
            "ring": rings[-1]} if fl_vals else {}),
        "total_pull_s": round(statistics.median(walls), 3),
        # Background materialization evidence (ISSUE 5): files-stage
        # wall that ran after the params were already resident — work
        # total_pull_s pays but time_to_hbm_s no longer does.
        "files_after_hbm_s": round(statistics.median(after_vals), 3)
        if after_vals else None,
        # Page-cache discipline of the timed runs: "cold" only when
        # every run really dropped caches; a failed drop reports the
        # warm truth instead of a cold label.
        "page_cache": ("cold" if timed_modes
                       and all(m == "cold" for m in timed_modes)
                       else "warm"),
        "pull_gbps": round(total / med_hbm / 1e9, 3),
        "spread": round(spread, 3),
        "stable": spread <= 0.20 and len(results) >= 2,
        "stages": stages,
        "overlap": {
            "files_hbm_busy_s": round(med_busy, 3),
            "files_hbm_span_s": round(med_span, 3),
            "overlap_s": round(max(0.0, med_busy - med_span), 3),
            "overlapped": med_busy > med_span + 0.05,
        },
        "hbm_gbps": statistics.median(
            [r["hbm_gbps"] for r in results if r["hbm_gbps"]] or [0]
        ),
        "direct": all(r["direct"] for r in results),
        "xorbs": n_xorbs,
        "warmup_skipped": warmup_runs == 0,
        "fixture_gen_s": round(t_gen, 1),
        "fixture_encode_s": round(t_encode, 1),
    }


def bench_fleet(fleet_sizes: tuple[int, ...] = (256, 512, 1024),
                pod_size: int = 64, model_gb: float = 8.0,
                n_units: int = 4096,
                ici_bps: float = 12.5e9, dcn_bps: float = 3.1e9,
                pod_wan_bps: float = 625e6, cdn_bps: float = 1.25e9,
                ici_rtt_s: float = 0.0001, dcn_rtt_s: float = 0.001,
                wan_rtt_s: float = 0.05, cdn_rtt_s: float = 0.08,
                hbm_bps: float = 50e9,
                gossip_keys: int = 64,
                out_path: str | None = None) -> dict:
    """Fleet-scale topology sim (ISSUE 16 tentpole d): 256/512/1024
    hosts in ``pod_size``-host pods over a 3-level link matrix
    (ICI < DCN < WAN < CDN), driving the REAL components — CoopPlan
    over synthetic units, CollectiveSchedule (flat hypercube vs the
    federated 3-stage schedule), and live GossipNodes over a
    LoopbackMesh — through an analytic timing model. Nothing at this
    scale fits real sockets in a bench window; what's real here is
    every *decision* (ownership, phase schedules, gateway election,
    gossip spread, cost-ordered routing), and what's modeled is only
    the clock.

    Timing model (extends PR-13's DcnServer shaping to a matrix):
    synchronous-round relaxation — a pull phase on host ``h`` from
    partner ``p`` completes at ``max(t[h], t[p]) + rtt(link) +
    bytes/bw(link)``, with link class derived from the PHYSICAL
    (topology, pods) placement for flat AND federated alike (the flat
    schedule doesn't know about pods; its bytes still cross them — that
    is exactly the comparison). WAN capacity is a per-pod uplink shared
    by the pod's hosts, applied as an aggregate congestion floor
    (inbound WAN bytes / uplink rate) — the WAN-bottlenecked regime the
    ≥1.3× federated gate is judged in. The CDN is one shared origin:
    the 1/N plan-share fetch walls at ``model_bytes / cdn_bps``.

    Per fleet size the artifact records: peer_served_ratio (exchange +
    cold-pod bytes over everything incl. CDN), CDN egress per host (the
    cost axis — total/N, decreasing by construction *because* the plan
    fetches each unit from origin exactly once fleet-wide), p99
    time-to-HBM for the flat and federated schedules and their ratio,
    per-pod WAN bytes for both, gossip convergence (sweeps to full
    who-has coverage vs the 2·ceil(log2 N) bound, digest memory vs its
    configured cap), and the cold-pod join (a fresh ``pod_size``-host
    pod routing every warm-held xorb to the nearest warm pod over WAN —
    zero CDN bytes). Gates live in-artifact under ``gates`` so
    scripts/bench_trend.py locks the result in."""
    import math

    from zest_tpu.cas.reconstruction import ChunkRange, FetchInfo
    from zest_tpu.transfer.collective import (CollectiveSchedule,
                                              elect_gateways)
    from zest_tpu.transfer.coop import CoopPlan
    from zest_tpu.transfer.gossip import (DEFAULT_MAX_ENTRIES,
                                          GossipNode, LoopbackMesh)

    model_bytes = int(model_gb * 1e9)
    unit_bytes = model_bytes // n_units

    def phys(a: int, b: int, topo, pods) -> tuple[str, float, float]:
        """(link class, rtt, per-flow bps) from PHYSICAL placement."""
        if pods[a] != pods[b]:
            return "wan", wan_rtt_s, pod_wan_bps
        if topo[a] != topo[b]:
            return "dcn", dcn_rtt_s, dcn_bps
        return "ici", ici_rtt_s, ici_bps

    def walk(scheds: dict, t0: float, bb: dict, topo, pods,
             gateways=None):
        """Relaxation walk over every host's schedule. Flat hypercube
        and federated stages A/B are mutual-pair lockstep (partner's
        partner is self — both sides agree on the start); federated
        stage C is a binomial tree processed in broadcast order (the
        parent's time is final before any child reads it). Returns
        (per-host completion, wan bytes into each pod, link byte
        totals)."""
        t = {h: t0 for h in scheds}
        wan_in: dict[int, int] = {}
        link_bytes = {"ici": 0, "dcn": 0, "wan": 0}

        def pull(h: int, ph) -> None:
            nbytes = sum(bb[o] for o in ph.owners)
            link, rtt, bps = phys(h, ph.partner, topo, pods)
            link_bytes[link] += nbytes
            if link == "wan":
                wan_in[pods[h]] = wan_in.get(pods[h], 0) + nbytes
            start = max(t[h], t[ph.partner])
            t[h] = start + rtt + nbytes / bps

        kinds = {s.kind for s in scheds.values()}
        if kinds == {"hypercube"}:
            for k in range(len(next(iter(scheds.values())).phases)):
                prev = dict(t)
                for h, s in scheds.items():
                    ph = s.phases[k]
                    nbytes = sum(bb[o] for o in ph.owners)
                    link, rtt, bps = phys(h, ph.partner, topo, pods)
                    link_bytes[link] += nbytes
                    if link == "wan":
                        wan_in[pods[h]] = wan_in.get(pods[h], 0) + nbytes
                    t[h] = (max(prev[h], prev[ph.partner])
                            + rtt + nbytes / bps)
        elif kinds == {"federated"}:
            pod_ids = sorted({pods[h] for h in scheds})
            members = {p: sorted(h for h in scheds if pods[h] == p)
                       for p in pod_ids}
            k_a = max(0, len(members[pod_ids[0]]).bit_length() - 1)
            k_b = max(0, len(pod_ids).bit_length() - 1)
            # Stage A: lockstep within each pod.
            for k in range(k_a):
                prev = dict(t)
                for h, s in scheds.items():
                    ph = s.phases[k]
                    nbytes = sum(bb[o] for o in ph.owners)
                    link, rtt, bps = phys(h, ph.partner, topo, pods)
                    link_bytes[link] += nbytes
                    t[h] = (max(prev[h], prev[ph.partner])
                            + rtt + nbytes / bps)
            # Stage B: lockstep over the gateways only.
            for k in range(k_b):
                prev = dict(t)
                for gw in gateways.values():
                    ph = scheds[gw].phases[k_a + k]
                    nbytes = sum(bb[o] for o in ph.owners)
                    link, rtt, bps = phys(gw, ph.partner, topo, pods)
                    link_bytes[link] += nbytes
                    if link == "wan":
                        wan_in[pods[gw]] = (wan_in.get(pods[gw], 0)
                                            + nbytes)
                    t[gw] = (max(prev[gw], prev[ph.partner])
                             + rtt + nbytes / bps)
            # Stage C: binomial broadcast, parents before children —
            # the gateway-first member order IS the broadcast order.
            for p in pod_ids:
                gw = gateways[p]
                for h in [m for m in members[p] if m != gw]:
                    pull(h, scheds[h].phases[k_a])
        else:  # pragma: no cover - the sim only builds these two
            raise ValueError(f"unexpected schedule kinds {kinds}")
        return t, wan_in, link_bytes

    out: dict = {
        "bench": "fleet",
        "pod_size": pod_size,
        "model_bytes": model_bytes,
        "units": n_units,
        "links": {
            "ici": {"bps": ici_bps, "rtt_s": ici_rtt_s},
            "dcn": {"bps": dcn_bps, "rtt_s": dcn_rtt_s},
            "wan": {"bps": pod_wan_bps, "rtt_s": wan_rtt_s,
                    "shared": "per-pod uplink"},
            "cdn": {"bps": cdn_bps, "rtt_s": cdn_rtt_s,
                    "shared": "one origin"},
        },
        "fleets": {},
    }
    fleets = out["fleets"]

    for n in fleet_sizes:
        n_pods = n // pod_size
        pods = tuple(h // pod_size for h in range(n))
        # Two ICI slices per pod — the full 3-level matrix.
        topo = tuple(2 * (h // pod_size)
                     + (h % pod_size >= pod_size // 2)
                     for h in range(n))
        units = [(f"{i:08x}",
                  FetchInfo(url=f"sim://u{i}", url_range_start=0,
                            url_range_end=unit_bytes,
                            range=ChunkRange(0, 1)))
                 for i in range(n_units)]
        plan = CoopPlan.build([], n, units=units)
        bb = plan.bytes_per_host()
        gateways = elect_gateways(plan, pods)

        # ── Stage 1: the 1/N CDN fetch (shared origin). ──
        fetch_wall = model_bytes / cdn_bps + cdn_rtt_s

        # ── Stage 2: flat (pod-blind hypercube) vs federated. ──
        flat = {h: CollectiveSchedule.build(plan, h, (0,) * n)
                for h in plan.alive}
        fed = {h: CollectiveSchedule.build(plan, h, topo, pods=pods)
               for h in plan.alive}
        results = {}
        for tag, scheds in (("flat", flat), ("federated", fed)):
            t, wan_in, link_bytes = walk(
                scheds, fetch_wall, bb, topo, pods,
                gateways=gateways if tag == "federated" else None)
            floor = {p: fetch_wall + b / pod_wan_bps
                     for p, b in wan_in.items()}
            done = sorted(max(t[h], floor.get(pods[h], 0.0))
                          + model_bytes / hbm_bps
                          for h in plan.alive)
            results[tag] = {
                "schedule": next(iter(scheds.values())).kind,
                "phases_max": max(len(s.phases)
                                  for s in scheds.values()),
                "p50_time_to_hbm_s": round(done[len(done) // 2], 3),
                "p99_time_to_hbm_s": round(
                    done[min(n - 1, int(0.99 * (n - 1)))], 3),
                "wan_bytes_total": sum(wan_in.values()),
                "wan_bytes_per_pod_max": max(wan_in.values(), default=0),
                "link_bytes": link_bytes,
            }
        speedup = (results["flat"]["p99_time_to_hbm_s"]
                   / results["federated"]["p99_time_to_hbm_s"])

        # ── Stage 3: gossip spread + content-aware cold-pod routing
        # (REAL GossipNodes; the clock here is sweeps, not seconds). ──
        book = {h: ("sim", 7000 + h) for h in range(n)}
        mesh = LoopbackMesh()
        nodes = [GossipNode(h, n, book, topology=topo, pods=pods)
                 for h in range(n)]
        for node in nodes:
            mesh.register(node)
        # Warm holders: key j announced by ONE host, pods round-robin —
        # the sparse index shape (most xorbs live in few places) whose
        # fleet-wide spread the sweep count measures.
        keys = [bytes.fromhex(f"{j:064x}") for j in range(gossip_keys)]
        for j in range(gossip_keys):
            holder = ((j % n_pods) * pod_size
                      + (j // n_pods) % pod_size)
            nodes[holder].announce(keys[j], 6881)
        bound = 2 * math.ceil(math.log2(n))
        sweeps = 0
        while sweeps < bound:
            sweeps += 1
            for node in nodes:
                node.tick(mesh)
            if all(node.who_has(k)
                   for node in nodes for k in keys):
                break
        converged = all(
            node.who_has(k) for node in nodes for k in keys)
        mem_max = max(node.digest.memory_bytes() for node in nodes)
        entries_max = max(len(node.digest) for node in nodes)
        gossip_block = {
            "fanout": nodes[0].fanout(),
            "sweeps_to_converge": sweeps,
            "sweep_bound": bound,
            "converged": converged,
            "entries_max": entries_max,
            "digest_memory_bytes_max": mem_max,
            "digest_max_entries": DEFAULT_MAX_ENTRIES,
            "bytes_out_total": sum(node.bytes_out for node in nodes),
        }

        # Cold pod join: pod_size fresh hosts (a brand-new pod) learn
        # the index via anti-entropy, then route every warm-held xorb
        # to the NEAREST warm holder — WAN beats CDN in the cost table,
        # so origin sees zero bytes for anything the fleet holds.
        n2 = n + pod_size
        pods2 = pods + (n_pods,) * pod_size
        topo2 = topo + tuple(
            2 * n_pods + (i >= pod_size // 2) for i in range(pod_size))
        book2 = dict(book)
        book2.update({n + i: ("sim", 7000 + n + i)
                      for i in range(pod_size)})
        cold = [GossipNode(n + i, n2, book2, topology=topo2,
                           pods=pods2) for i in range(pod_size)]
        for node in cold:
            mesh.register(node)
        cold_sweeps = 0
        while cold_sweeps < bound:
            cold_sweeps += 1
            for node in cold:
                node.tick(mesh)
            if all(node.who_has(k) for node in cold for k in keys):
                break
        key_bytes = model_bytes // gossip_keys
        cold_cdn = cold_peer = 0
        wan_routed = True
        for node in cold:
            for k in keys:
                holders = node.who_has(k)
                if holders:
                    cold_peer += key_bytes
                    _link, _rtt, _bps = phys(
                        node.host_index, holders[0], topo2, pods2)
                    wan_routed &= _link == "wan"
                else:
                    cold_cdn += key_bytes
        cold_block = {
            "hosts": pod_size,
            "sweeps_to_index": cold_sweeps,
            "warm_served_bytes": cold_peer,
            "cdn_bytes_for_warm_held": cold_cdn,
            "nearest_link": "wan" if wan_routed else "mixed",
            "pull_s_est": round(
                model_bytes / pod_wan_bps + wan_rtt_s, 3),
        }

        # ── Byte-flow ledger → peer_served_ratio + CDN egress. ──
        peer_bytes = (sum(results["federated"]["link_bytes"].values())
                      + cold_peer)
        cdn_total = model_bytes + cold_cdn
        ratio = peer_bytes / (peer_bytes + cdn_total)
        fleets[str(n)] = {
            "hosts": n,
            "pods": n_pods,
            "gateways": len(gateways),
            "plan_skew": round(plan.skew(), 4),
            "fetch_wall_s": round(fetch_wall, 3),
            "peer_served_ratio": round(ratio, 4),
            "peer_bytes": peer_bytes,
            "cdn_egress_bytes": cdn_total,
            "cdn_egress_bytes_per_host": cdn_total // n,
            "flat": results["flat"],
            "federated": results["federated"],
            "federated_speedup": round(speedup, 2),
            "gossip": gossip_block,
            "cold_pod": cold_block,
        }

    sizes = [str(s) for s in fleet_sizes]
    ratios = [fleets[s]["peer_served_ratio"] for s in sizes]
    egress = [fleets[s]["cdn_egress_bytes_per_host"] for s in sizes]
    out["gates"] = {
        "peer_served_ratio_min": min(ratios),
        "peer_served_ratio_ge_0.90": min(ratios) >= 0.90,
        "peer_served_flat_pm_0.03": max(ratios) - min(ratios) <= 0.03,
        "cdn_egress_per_host_decreasing": all(
            a > b for a, b in zip(egress, egress[1:])),
        "federated_speedup_min": min(
            fleets[s]["federated_speedup"] for s in sizes),
        "federated_speedup_ge_1.3": all(
            fleets[s]["federated_speedup"] >= 1.3 for s in sizes),
        "gossip_converged_within_bound": all(
            fleets[s]["gossip"]["converged"]
            and (fleets[s]["gossip"]["sweeps_to_converge"]
                 <= fleets[s]["gossip"]["sweep_bound"])
            for s in sizes),
        "digest_memory_bounded": all(
            fleets[s]["gossip"]["entries_max"]
            <= fleets[s]["gossip"]["digest_max_entries"]
            for s in sizes),
        "cold_pod_zero_cdn_for_warm": all(
            fleets[s]["cold_pod"]["cdn_bytes_for_warm_held"] == 0
            for s in sizes),
    }
    out["gates"]["all_ok"] = all(
        v for k, v in out["gates"].items()
        if isinstance(v, bool))
    if out_path:
        pathlib.Path(out_path).write_text(json.dumps(out, indent=2)
                                          + "\n")
    return out


def bench_tenants(gb: float = 0.064, k_tenants: int = 6,
                  n_models: int = 2, max_pulls: int = 4,
                  fault_spec: str | None = "cdn_503:0.15,peer_timeout:0.1",
                  fault_seed: int = 1337,
                  shaped_bps: int | None = 24_000_000,
                  disk_pressure: bool = True,
                  kill_tenant: bool = True,
                  chunks_per_xorb: int = 16, scale: int = 8,
                  shard_bytes: int = 16 * 1024 * 1024,
                  out_path: str | None = None) -> dict:
    """Multi-tenant saturation bench (ISSUE 13): K tenants x
    overlapping model sets x fault matrix x shaped CDN, all through
    ONE process' shared pools (transfer.tenancy) — the concurrent-
    daemon scenario ROADMAP item 1 is judged on.

    Phases:

    1. **Solo reference** — each revision pulled alone into a fresh
       cache: the digests every concurrent pull must reproduce
       byte-for-byte, and the wall the saturation p99 is compared
       against.
    2. **Saturation** — ``k_tenants`` concurrent pulls over
       ``n_models`` overlapping revisions (revision B chunk-dedups
       against A, so the tenants contend for shared fetch units) into
       ONE shared cache, admission-limited to ``max_pulls``, with the
       fault injector armed, the CDN data plane token-bucketed, and —
       ``kill_tenant`` — one tenant cancelled mid-pull.
    3. **Disk pressure** (``disk_pressure``) — a deterministic
       pin-survival run: revision A is pulled, EVERY cache entry it
       produced is pinned under a synthetic hold (the live-HBM-tree /
       admitted-plan pin pattern), then revision B is pulled with the
       high watermark set below the combined working set — so the
       admission-time eviction pass and a final explicit pass both run
       against live pins. The evictor must meet the pinned entries and
       skip every one (verified ON DISK, not by counters alone), churn
       must stay bounded, and B's bytes must still land
       digest-identical. Separate from phase 2 so eviction-forced
       refetches don't pollute the duplicate-fetch gate.

    Headline gates (recorded in-artifact under ``gates``):

    - ``duplicate_fetch_ratio`` <= 0.02: fetch units requested from the
      CDN more than once, over distinct units (singleflight + shared
      cache make it ~0; the allowance covers eviction-forced refetches
      under the induced disk pressure);
    - ``zero_corrupt``: every surviving tenant's snapshot is
      byte-identical to its solo reference (nothing the fault matrix,
      the eviction churn, or the mid-pull kill did admitted a bad
      byte);
    - ``killed_isolated``: the cancelled tenant is the ONLY failed
      session and finished ``cancelled`` (not ``error``);
    - ``pinned_never_evicted``: the evictor skipped every pinned entry
      it met under pressure (``pinned_survivals`` > 0 proves pressure
      actually met pins), with eviction churn itself bounded in
      ``eviction``.
    """
    import shutil as _shutil
    import tempfile as _tempfile
    import threading

    from zest_tpu import faults, telemetry
    from zest_tpu.config import Config
    from zest_tpu.telemetry import session as session_mod
    from zest_tpu.transfer import tenancy
    from zest_tpu.transfer.pull import PullCancelled, pull_model
    from zest_tpu.transfer.tenancy import CancelToken

    fixtures = _import_fixtures()
    repo_id = "bench/tenants-llama"
    t_gen = time.perf_counter()
    base = llama_checkpoint_files(gb, scale=scale,
                                  shard_bytes=shard_bytes)
    repo = fixtures.FixtureRepo(repo_id, base,
                                chunks_per_xorb=chunks_per_xorb)
    revs = [repo.latest_sha]
    for m in range(1, n_models):
        rev_files = llama_checkpoint_files(
            gb, scale=scale, shard_bytes=shard_bytes,
            mutate_fraction=0.02, mutate_seed=m)
        revs.append(repo.add_revision(rev_files))
    total = sum(len(b) for b in base.values())
    t_gen = time.perf_counter() - t_gen

    def digests(snapshot_dir) -> dict:
        import hashlib

        out = {}
        for f in sorted(pathlib.Path(snapshot_dir).rglob("*")):
            if f.is_file():
                out[str(f.relative_to(snapshot_dir))] = hashlib.sha256(
                    f.read_bytes()).hexdigest()
        return out

    out: dict = {
        "bench": "tenants",
        "model_bytes": total,
        "k_tenants": k_tenants,
        "n_models": n_models,
        "max_pulls": max_pulls,
        "cdn_bps": shaped_bps,
        "faults": fault_spec,
        "chunks_per_xorb": chunks_per_xorb,
        "fixture_gen_s": round(t_gen, 1),
    }
    faults.install(None)  # solo phase runs clean
    tenancy.reset()
    with fixtures.FixtureHub(repo, throttle_bps=shaped_bps) as hub, \
            _tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)

        # ── Phase 1: solo references ──
        solo_digests: dict[str, dict] = {}
        solo_walls: list[float] = []
        for i, rev in enumerate(revs):
            cfg = Config(hf_home=rootp / f"solo{i}/hf",
                         cache_dir=rootp / f"solo{i}/zest",
                         hf_token="hf_test", endpoint=hub.url)
            t0 = time.perf_counter()
            res = pull_model(cfg, repo_id, revision=rev, no_p2p=True,
                             log=lambda *a, **k: None)
            solo_walls.append(time.perf_counter() - t0)
            solo_digests[rev] = digests(res.snapshot_dir)
            _shutil.rmtree(rootp / f"solo{i}", ignore_errors=True)
        out["solo"] = {"wall_s": [round(w, 3) for w in solo_walls]}

        # ── Phase 2: saturation ──
        tenancy.reset()
        hub.requests_seen.clear()
        hub.xorb_fetches.clear()
        cfg = Config(hf_home=rootp / "shared/hf",
                     cache_dir=rootp / "shared/zest",
                     hf_token="hf_test", endpoint=hub.url,
                     tenant_max_pulls=max_pulls,
                     tenant_queue=max(4, 2 * k_tenants))
        if fault_spec:
            faults.install(fault_spec, fault_seed)

        def cdn_xorbs_total() -> int:
            """Successful CDN fetches recorded by the bridges (the
            process counter) — the duplicate-fetch numerator. Hub-side
            request ARRIVALS over-count: a transport-level failure
            (timeout/truncation under the shaped link, an injected
            fault) arrives at the hub, fails client-side, and retries
            — one successful fetch, two arrivals."""
            for m in telemetry.REGISTRY.metrics():
                if m.name == "zest_fetch_xorbs_total":
                    return int(sum(
                        v for labels, v in m.samples()
                        if labels.get("source") == "cdn"))
            return 0

        cdn_before = cdn_xorbs_total()
        walls: dict[int, float] = {}
        statuses: dict[int, str] = {}
        kill_idx = k_tenants - 1 if kill_tenant else None
        kill_token = CancelToken()
        barrier = threading.Barrier(k_tenants + (1 if kill_tenant else 0))

        def tenant_run(i: int) -> None:
            rev = revs[i % len(revs)]
            barrier.wait()
            t0 = time.perf_counter()
            try:
                pull_model(cfg, repo_id, revision=rev, no_p2p=True,
                           tenant=f"tenant-{i}",
                           cancel=(kill_token if i == kill_idx
                                   else None),
                           log=lambda *a, **k: None)
                statuses[i] = "ok"
            except PullCancelled:
                statuses[i] = "cancelled"
            except Exception as exc:  # noqa: BLE001 - reported in artifact
                statuses[i] = f"error: {exc}"
            walls[i] = time.perf_counter() - t0

        def killer() -> None:
            barrier.wait()
            # Mid-pull by construction: ~40% of the solo median under
            # saturation (the concurrent pull can only be slower).
            time.sleep(max(0.3,
                           0.4 * sorted(solo_walls)[len(solo_walls) // 2]))
            kill_token.cancel("bench tenant kill")

        threads = [threading.Thread(target=tenant_run, args=(i,))
                   for i in range(k_tenants)]
        if kill_tenant:
            threads.append(threading.Thread(target=killer))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sat_wall = time.perf_counter() - t0
        faults.install(None)

        # Evidence: successful CDN fetches vs the distinct
        # unit-granularity (xorb, byte-range) set the hub served —
        # anything above 1 successful fetch per distinct unit is a
        # duplicate the dedupe failed to collapse.
        fetches = list(hub.xorb_fetches)
        distinct = len(set(fetches))
        cdn_ok = cdn_xorbs_total() - cdn_before
        dup_ratio = (max(0, cdn_ok - distinct) / distinct
                     if distinct else 0.0)

        ok_idx = [i for i, s in statuses.items() if s == "ok"]
        survivor_digests_ok = all(
            digests(cfg.model_snapshot_dir(repo_id, revs[i % len(revs)]))
            == solo_digests[revs[i % len(revs)]]
            for i in ok_idx)
        ok_walls = sorted(walls[i] for i in ok_idx)

        def pctl(p: float) -> float | None:
            if not ok_walls:
                return None
            k = min(len(ok_walls) - 1, int(round(p * (len(ok_walls) - 1))))
            return round(ok_walls[k], 3)

        st = tenancy.state(cfg)
        summary = st.summary()
        sessions = [s.snapshot() for s in session_mod.SESSIONS.recent()]
        killed_status = statuses.get(kill_idx) if kill_idx is not None \
            else None
        out["saturation"] = {
            "wall_s": round(sat_wall, 3),
            "per_tenant_wall_s": {str(i): round(w, 3)
                                  for i, w in sorted(walls.items())},
            "statuses": {str(i): s for i, s in sorted(statuses.items())},
            "p50_pull_s": pctl(0.50),
            "p99_pull_s": pctl(0.99),
            "aggregate_gbps": round(
                total * len(ok_idx) / sat_wall / 1e9, 4)
            if sat_wall else None,
            "cdn_fetches": cdn_ok,
            "cdn_request_arrivals": len(fetches),
            "distinct_units": distinct,
            "dedupe": summary["dedupe"],
            "admission": {k: summary[k] for k in
                          ("max_pulls", "admitted_total",
                           "rejected_total")},
            "eviction": summary["eviction"],
            "terminal_statuses": sorted(
                {s["id"]: s["status"] for s in sessions}.values()),
        }
        # ── Phase 3: eviction under induced disk pressure ──
        # Deterministic shape: pull revision A, pin EVERY cache entry
        # it produced under a synthetic hold (the live-HBM-tree /
        # admitted-plan pin pattern), then pull revision B with the
        # high watermark set BELOW the combined working set and run an
        # eviction pass with the pins live. The evictor must meet the
        # pinned entries and skip every one — verified ON DISK, not by
        # counters alone — while B's bytes still land digest-identical
        # (eviction mid-pull degrades to a refetch, never a corrupt
        # read).
        pressure: dict | None = None
        if disk_pressure and len(revs) >= 2:
            tenancy.reset()
            rev_a, rev_b = revs[0], revs[1]
            press_status: dict[str, str] = {}
            pcfg = Config(hf_home=rootp / "press/hf",
                          cache_dir=rootp / "press/zest",
                          hf_token="hf_test", endpoint=hub.url,
                          tenant_max_pulls=max_pulls)
            t0 = time.perf_counter()
            pull_model(pcfg, repo_id, revision=rev_a, no_p2p=True,
                       tenant="press-a", log=lambda *a, **k: None)
            cache_root = pcfg.xorb_cache_dir()
            pinned_entries = [p for sub in cache_root.iterdir()
                             for p in sub.iterdir()
                             if not p.name.startswith(".tmp-")]
            pinned_hashes = {p.name.split(".", 1)[0]
                             for p in pinned_entries}
            a_usage = sum(p.stat().st_size for p in pinned_entries)

            tenancy.reset()
            pcfg2 = Config(hf_home=pcfg.hf_home, cache_dir=pcfg.cache_dir,
                           hf_token="hf_test", endpoint=hub.url,
                           tenant_max_pulls=max_pulls,
                           tenant_disk_high=int(a_usage * 0.9),
                           tenant_disk_low=int(a_usage * 0.5))
            pst = tenancy.state(pcfg2)
            pst.pins.pin("bench-hold", pinned_hashes)
            try:
                pull_model(pcfg2, repo_id, revision=rev_b, no_p2p=True,
                           tenant="press-b", log=lambda *a, **k: None)
                press_status["b"] = "ok"
            except Exception as exc:  # noqa: BLE001
                press_status["b"] = f"error: {exc}"
            # The daemon's watermark pass, run with the hold still
            # live: usage (A + B's delta) is over the mark, only B's
            # now-unpinned entries are fair game. force= bypasses the
            # admission-pass rate limit (B's admission just ran one).
            pst.evictor.maybe_evict(force=True)
            pev = pst.evictor.summary()
            survived = [p for p in pinned_entries if p.exists()]
            press_digests_ok = (
                press_status.get("b") == "ok"
                and digests(pcfg2.model_snapshot_dir(repo_id, rev_b))
                == solo_digests[rev_b])
            pst.pins.release("bench-hold")
            pressure = {
                "wall_s": round(time.perf_counter() - t0, 3),
                "statuses": press_status,
                "pinned_entries": len(pinned_entries),
                "pinned_survived_on_disk": len(survived),
                "eviction": pev,
                "digests_identical": press_digests_ok,
            }
            out["pressure"] = pressure

        out["gates"] = {
            "duplicate_fetch_ratio": round(dup_ratio, 4),
            "duplicate_fetch_ratio_ok": dup_ratio <= 0.02,
            "zero_corrupt": survivor_digests_ok
            and (pressure is None or pressure["digests_identical"]),
            "killed_isolated": (
                kill_idx is None
                or (killed_status == "cancelled"
                    and all(statuses[i] == "ok"
                            for i in statuses if i != kill_idx))),
            "pinned_never_evicted": (
                pressure is None
                or (pressure["pinned_survived_on_disk"]
                    == pressure["pinned_entries"]
                    and pressure["eviction"]["pinned_survivals"] > 0
                    and pressure["digests_identical"])),
        }
        out["gates"]["all_ok"] = all(
            v for k, v in out["gates"].items()
            if k.endswith("_ok") or k in ("zero_corrupt",
                                          "killed_isolated",
                                          "pinned_never_evicted"))
    tenancy.reset()
    telemetry.record("bench_tenants_done", gates_ok=out["gates"]["all_ok"])
    if out_path:
        pathlib.Path(out_path).write_text(json.dumps(out, indent=2)
                                          + "\n")
    return out


def bench_serve_pool(gb: float = 0.02, runs: int = 3, scale: int = 8,
                     throttle_mbps: float = 200.0,
                     chunks_per_xorb: int = 64, steps: int = 8,
                     budget_s: float | None = None) -> dict:
    """HBM serving-pool bench (ISSUE 18 acceptance).

    The scale-to-zero story, measured: model A was served once, got
    evicted under pressure, and a request arrives for it again. The
    baseline arm is what serving A costs with no pool — a full cold
    pull over a throttled loopback network plus the family generator's
    first token (``full_cold_serve_s``, timed from request to first
    token). The pool arm re-lands A from its local snapshot with the
    decode parked on per-layer gates, so the first token overlaps the
    landing tail (``ttft_cold_s`` — the pool's own request-to-first-
    token clock). The ``ttft_cold_ratio`` gate is <= 0.5.

    Each run also proves the safety half of the contract in-band:
    while A is *pinned* (an active decode), B's admission under a
    one-byte-slack budget must NOT evict A (``pinned_never_evicted``),
    and the re-landed tree's ``params_digest`` must be byte-identical
    to the original landing (``digest_identical``). One MoE serve per
    bench records the lazy expert pager's residency — the dense core
    lands, experts page on demand, bounded under 50%.

    Honesty notes: baseline and pool arms share one process, so jit
    traces built by earlier runs are warm for later ones on the pool
    side (its builders cache by config) while the family path
    re-traces per snapshot — exactly the asymmetry a long-lived server
    has, since a re-served model's compiled fns are resident while a
    never-served model pays its build. ``pull_s`` is reported so the
    network share of the baseline is visible."""
    fixtures = _import_fixtures()
    FixtureHub, FixtureRepo = fixtures.FixtureHub, fixtures.FixtureRepo

    from zest_tpu.config import Config
    from zest_tpu.models import hbm_pool
    from zest_tpu.models.generate import load_generator
    from zest_tpu.transfer.pull import pull_model

    t_bench0 = time.perf_counter()
    files_a = llama_checkpoint_files(gb, seed=0, scale=scale,
                                     shard_bytes=8 << 20)
    files_b = llama_checkpoint_files(gb, seed=1, scale=scale,
                                     shard_bytes=8 << 20)
    total = sum(len(b) for b in files_a.values())
    repo_a = FixtureRepo("bench/serve-a", files_a,
                         chunks_per_xorb=chunks_per_xorb)
    repo_b = FixtureRepo("bench/serve-b", files_b,
                         chunks_per_xorb=chunks_per_xorb)
    repo_moe = FixtureRepo("bench/serve-moe",
                           fixtures.mixtral_checkpoint_files(),
                           chunks_per_xorb=chunks_per_xorb)
    gc.collect()

    quiet = {"log": lambda *a, **k: None}
    prompt = [1, 2, 3]
    full_cold: list[float] = []
    pull_s: list[float] = []
    ttft_cold: list[float] = []
    ttft_hot: list[float] = []
    stalls: list[float] = []
    overlap: list[bool] = []
    digest_ok: bool | None = None
    pinned_ok: bool | None = None
    moe: dict | None = None
    with FixtureHub(repo_a, repo_b, repo_moe,
                    throttle_bps=int(throttle_mbps * 1e6 / 8)) as hub:
        for run_i in range(runs):
            if run_i and budget_s is not None \
                    and time.perf_counter() - t_bench0 > budget_s:
                break  # keep what's measured (bench_gb_pull's rule)
            _settle_page_cache(False)
            with tempfile.TemporaryDirectory() as root:
                rootp = pathlib.Path(root)
                cfg = Config(hf_home=rootp / "hf",
                             cache_dir=rootp / "zest",
                             hf_token="hf_test", endpoint=hub.url)

                # Baseline arm: classic cold serve, request → token 1.
                t0 = time.perf_counter()
                res_a = pull_model(cfg, "bench/serve-a", no_p2p=True,
                                   **quiet)
                pull_s.append(time.perf_counter() - t0)
                snap_a = res_a.snapshot_dir
                first: dict = {}
                _mt, family = load_generator(snap_a)
                family(prompt, steps,
                       on_token=lambda _p, _t: first.setdefault(
                           "t", time.perf_counter()))
                full_cold.append(first["t"] - t0)

                pool = hbm_pool.HbmPool(cfg)
                try:
                    # Establish residency (untimed), then prove the
                    # pinned tree survives B's admission pressure.
                    pool.generate_for(snap_a, "bench/serve-a",
                                      prompt, steps)
                    d0 = pool.digest(snap_a)
                    res_b = pull_model(cfg, "bench/serve-b",
                                       no_p2p=True, **quiet)
                    entry_a, _hot = pool.acquire(snap_a,
                                                 "bench/serve-a")
                    pool.budget = entry_a.reserved + 1
                    pool.generate_for(res_b.snapshot_dir,
                                      "bench/serve-b", prompt, 2)
                    ok = (entry_a.state == "resident"
                          and pool.pinned_survivals > 0)
                    pinned_ok = ok if pinned_ok is None \
                        else (pinned_ok and ok)
                    pool.release(entry_a)

                    # Scale A to zero; the measured re-land serve.
                    pool.budget = cfg.hbm_pool_bytes
                    pool.evict(snap_a, "scale_to_zero")
                    _o, info_c = pool.generate_for(
                        snap_a, "bench/serve-a", prompt, steps)
                    ttft_cold.append(info_c["ttft_s"])
                    stalls.append(info_c["gate_stall_s"])
                    overlap.append(
                        info_c["decode_start_before_land_end"])
                    ok = bool(d0) and pool.digest(snap_a) == d0
                    digest_ok = ok if digest_ok is None \
                        else (digest_ok and ok)
                    _o, info_h = pool.generate_for(
                        snap_a, "bench/serve-a", prompt, steps)
                    ttft_hot.append(info_h["ttft_s"])
                    if moe is None:
                        res_m = pull_model(cfg, "bench/serve-moe",
                                           no_p2p=True, **quiet)
                        _o, info_m = pool.generate_for(
                            res_m.snapshot_dir, "bench/serve-moe",
                            prompt, 4)
                        moe = info_m["experts"]
                finally:
                    pool.close()
                del res_a
                gc.collect()

    med_full = statistics.median(full_cold)
    med_cold = statistics.median(ttft_cold)
    ratio = (med_cold / med_full) if med_full else None
    expert_res = (moe or {}).get("residency")
    gates = {
        "ttft_cold_ratio_max": 0.5,
        "ttft_cold_ratio": round(ratio, 4) if ratio is not None
        else None,
        "ttft_ok": bool(ratio is not None and ratio <= 0.5),
        "digest_identical": bool(digest_ok),
        "pinned_never_evicted": bool(pinned_ok),
        "expert_residency_max": 0.5,
        "expert_residency": expert_res,
        "experts_ok": bool(expert_res is not None
                           and expert_res < 0.5
                           and (moe or {}).get("verified", 0) > 0),
    }
    gates["all_ok"] = (gates["ttft_ok"] and gates["digest_identical"]
                       and gates["pinned_never_evicted"]
                       and gates["experts_ok"])
    return {
        "bench": "serve_pool",
        "checkpoint_gb": round(total / 1e9, 3),
        "throttle_mbps": throttle_mbps,
        "runs": len(ttft_cold),
        "steps": steps,
        "full_cold_serve_s": round(med_full, 3),
        "full_cold_serve_runs_s": [round(t, 3) for t in full_cold],
        "pull_s": round(statistics.median(pull_s), 3),
        "ttft_cold_s": round(med_cold, 3),
        "ttft_cold_runs_s": [round(t, 3) for t in ttft_cold],
        "ttft_hot_s": round(statistics.median(ttft_hot), 4),
        "gate_stall_s": round(statistics.median(stalls), 3),
        "decode_start_before_land_end": all(overlap),
        "moe_experts": moe,
        "gates": gates,
    }
