"""Epidemic metadata spread for the beyond-one-pod tier (ROADMAP
item 4, ISSUE 16).

Every discovery path so far funnels through a coordinator: tracker/KV
announce is one round trip per host per swarm, and the pod metrics
scrape is one coordinator asking everyone. Neither survives the
paper's fleet shape — announce cost must not grow with fleet size.
This module is the DHT-heritage answer scoped to a trusted fleet: each
host keeps a **bounded digest** of ``(xorb-key → holder set, seeder
state, revision manifests)`` entries with per-origin version vectors,
and an **anti-entropy push-pull** round runs each tick against
``fanout = O(log N)`` random peers. One round trip both pushes what
the peer is missing and pulls what we are missing (the request carries
our version vector + a delta; the response carries the peer's), so
rumor spread needs O(log N) rounds fleet-wide and announce traffic is
O(N·log N) per tick instead of every-host-to-tracker.

Three rules the implementation pins:

- **Bounded, eviction-safe**: the digest never exceeds
  ``max_entries`` (``ZEST_GOSSIP_MAX``); overflow evicts the
  least-recently-updated FOREIGN entry first (a host is authoritative
  for its own announcements — evicting them would re-rumor stale
  absence). Version vectors survive eviction, so an evicted entry is
  not re-merged from peers that still hold it unless its origin bumps
  the sequence — re-announce refreshes, exactly the tracker TTL model.
- **Deterministic merge**: entry identity is ``(kind, key, origin)``
  and merge keeps the highest origin sequence — commutative,
  idempotent, order-free (the CRDT property that makes push-pull rounds
  composable with any peer sampling).
- **Transport-agnostic, DCN-piggybacked**: a round is one
  ``request → response`` payload pair. In-process fleets wire nodes
  through :class:`LoopbackMesh`; real hosts piggyback on the existing
  :class:`~zest_tpu.transfer.dcn.DcnPool` channels (``MSG_GOSSIP`` —
  no new listener, no new port, the chunk-RPC hello/trace machinery
  comes for free).

``ZEST_GOSSIP=0`` keeps this module entirely out of the wiring:
tracker/KV announce behaves bit-for-bit as before and no gossip key
appears in any stats schema. With gossip ON, the tracker demotes to
the bootstrap seed — first announce per swarm still registers there
(new hosts need a rendezvous), every refresh rides the digest.

The digest doubles as the fleet-wide **"who has which xorb" index**
for content-aware routing (ISSUE 16 tentpole c): ``find_peers``
answers from the local digest ordered by the link-cost table
ICI(0) < DCN(1) < WAN(2) — CDN is the implicit cost-3 tier the
waterfall falls to when the index is empty — so a cold pod's fetch
routes to the nearest warm pod instead of origin.
"""

from __future__ import annotations

import json
import math
import random
import threading
from dataclasses import dataclass

from zest_tpu import telemetry

_M_GOSSIP_ROUNDS = telemetry.counter(
    "zest_gossip_rounds_total", "Anti-entropy push-pull rounds run")
_M_GOSSIP_ENTRIES = telemetry.gauge(
    "zest_gossip_entries", "Live entries in this host's gossip digest")
_M_GOSSIP_BYTES = telemetry.counter(
    "zest_gossip_bytes_total", "Gossip payload bytes by direction",
    ("direction",))
_M_GOSSIP_EVICTED = telemetry.counter(
    "zest_gossip_evicted_total",
    "Digest entries evicted under the size bound")

# Entry kinds the digest carries (ISSUE 16 tentpole a).
KIND_XORB = "xorb"          # key = info_hash hex, payload: listen port
KIND_SEEDER = "seeder"      # key = host index,   payload: seeder state
KIND_MANIFEST = "manifest"  # key = repo@rev,     payload: manifest meta

# A single push-pull payload never carries more than this many entries:
# anti-entropy converges over rounds, it must not turn one round into
# an unbounded state dump on a cold join.
MAX_DELTA_ENTRIES = 512

DEFAULT_MAX_ENTRIES = 65536

# Link-cost table (tentpole c): lower = nearer. CDN is the implicit
# final tier (cost 3) — it is not a peer, so it never appears here.
COST_ICI = 0   # same slice
COST_DCN = 1   # same pod, different slice
COST_WAN = 2   # different pod
COST_CDN = 3   # documented for the routing table; never returned


def link_cost(a: int, b: int, topology=None, pods=None) -> int:
    """Cost class of the a↔b link from the slice/pod maps (missing maps
    degrade conservatively: unknown pod ⇒ same pod, unknown slice ⇒
    cross-slice — mirroring dcn.DcnServer's anonymous-peer rule)."""
    if pods is not None and len(pods) > max(a, b) \
            and pods[a] != pods[b]:
        return COST_WAN
    if topology is not None and len(topology) > max(a, b) \
            and topology[a] == topology[b]:
        return COST_ICI
    return COST_DCN


@dataclass
class _Entry:
    seq: int        # origin's monotonic sequence (version-vector term)
    payload: dict   # small JSON-safe metadata (port, state, manifest)
    stamp: int      # local logical clock, for LRU eviction only


class GossipDigest:
    """The bounded CRDT store: ``(kind, key, origin) → _Entry`` plus
    the per-origin version vector. Thread-safe (merges arrive from the
    DCN serve plane while ticks run on the round's thread)."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES,
                 own_origin: int | None = None):
        self.max_entries = max(1, int(max_entries))
        # The hosting node's own origin: authoritative entries —
        # evicting them would rumor stale absence, so eviction sheds
        # foreign entries first.
        self.own_origin = own_origin
        self._lock = threading.Lock()
        self._entries: dict[tuple[str, str, int], _Entry] = {}
        self.vv: dict[int, int] = {}
        self._clock = 0
        self.evicted = 0
        self.merged_in = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _tick_clock(self) -> int:
        self._clock += 1
        return self._clock

    def update(self, kind: str, key: str, origin: int, seq: int,
               payload: dict) -> bool:
        """Merge one entry; True when it was news (higher seq than the
        stored one for the same identity). Keeps the bound."""
        with self._lock:
            ident = (kind, key, origin)
            cur = self._entries.get(ident)
            if cur is not None and cur.seq >= seq:
                # Still advance the vector: a duplicate proves origin
                # reached seq even when the payload is old news.
                if seq > self.vv.get(origin, -1):
                    self.vv[origin] = seq
                return False
            if cur is None and origin != self.own_origin \
                    and seq <= self.vv.get(origin, -1):
                # Seen-and-evicted: the vector remembers the origin
                # reached this seq, so the entry stays forgotten until
                # the origin re-announces past it (the tracker TTL
                # model — eviction must not thrash against re-merge).
                return False
            self._entries[ident] = _Entry(seq, payload,
                                          self._tick_clock())
            if seq > self.vv.get(origin, -1):
                self.vv[origin] = seq
            self.merged_in += 1
            self._evict_locked(keep_origin=origin)
            _M_GOSSIP_ENTRIES.set(float(len(self._entries)))
            return True

    def _evict_locked(self, keep_origin: int | None = None) -> None:
        while len(self._entries) > self.max_entries:
            # Oldest-updated foreign entry first; own (authoritative)
            # entries and the one just merged only when nothing
            # foreign is left to shed.
            protect = {keep_origin, self.own_origin} - {None}
            victims = sorted(
                ((e.stamp, ident) for ident, e in self._entries.items()
                 if ident[2] not in protect),
                key=lambda t: t[0])
            if not victims:
                victims = sorted(
                    ((e.stamp, ident)
                     for ident, e in self._entries.items()),
                    key=lambda t: t[0])
            self._entries.pop(victims[0][1], None)
            self.evicted += 1
            _M_GOSSIP_EVICTED.inc()

    def delta_since(self, peer_vv: dict[int, int],
                    cap: int = MAX_DELTA_ENTRIES) -> list[list]:
        """Entries whose origin sequence is past ``peer_vv`` —
        oldest-sequence first so repeated capped rounds still drain
        monotonically — as JSON-safe rows
        ``[kind, key, origin, seq, payload]``."""
        with self._lock:
            rows = [
                [k, key, origin, e.seq, e.payload]
                for (k, key, origin), e in self._entries.items()
                if e.seq > int(peer_vv.get(origin,
                                           peer_vv.get(str(origin), -1)))
            ]
        rows.sort(key=lambda r: (r[3], r[0], r[1], r[2]))
        return rows[:cap]

    def merge_rows(self, rows) -> int:
        """Merge a peer's delta rows; returns how many were news."""
        fresh = 0
        for kind, key, origin, seq, payload in rows:
            if self.update(str(kind), str(key), int(origin), int(seq),
                           dict(payload)):
                fresh += 1
        return fresh

    def holders(self, kind: str, key: str) -> dict[int, dict]:
        """``{origin: payload}`` for every live entry of ``key``."""
        with self._lock:
            return {origin: e.payload
                    for (k, kk, origin), e in self._entries.items()
                    if k == kind and kk == key}

    def memory_bytes(self) -> int:
        """Conservative digest footprint estimate — what the 1024-host
        bound gate measures (identity strings + payload JSON + fixed
        per-entry overhead; an exact RSS would measure the allocator,
        not the digest)."""
        with self._lock:
            total = 0
            for (kind, key, _origin), e in self._entries.items():
                total += 64 + len(kind) + len(key)
                total += len(json.dumps(e.payload, separators=(",", ":")))
            return total

    def snapshot_vv(self) -> dict[int, int]:
        with self._lock:
            return dict(self.vv)


class LoopbackMesh:
    """In-process transport: host index → node registry. The sim/test
    fabric — ``exchange`` is a direct method call, zero wire."""

    def __init__(self) -> None:
        self.nodes: dict[int, "GossipNode"] = {}
        self.exchanges = 0

    def register(self, node: "GossipNode") -> None:
        self.nodes[node.host_index] = node

    def exchange(self, peer: int, payload: dict) -> dict | None:
        node = self.nodes.get(peer)
        if node is None:
            return None
        self.exchanges += 1
        return node.handle_exchange(payload)


class DcnGossipTransport:
    """Piggyback on the fleet's existing DCN chunk-RPC channels: one
    ``MSG_GOSSIP`` request/response per push-pull round, multiplexed on
    the same pooled sockets the exchange uses (dcn.DcnPool). A peer
    whose server predates the message type answers with a protocol
    error — treated as "gossip unavailable there", never a failure."""

    def __init__(self, pool, addrs: dict[int, tuple[str, int]]):
        self.pool = pool
        self.addrs = dict(addrs)

    def exchange(self, peer: int, payload: dict) -> dict | None:
        addr = self.addrs.get(peer)
        if addr is None:
            return None
        try:
            return self.pool.gossip_exchange(addr[0], addr[1], payload)
        except Exception:  # noqa: BLE001 - gossip is best-effort
            return None


class GossipNode:
    """One host's epidemic-metadata agent.

    Implements the swarm's ``PeerSource`` protocol (``find_peers`` /
    ``announce``) so it drops into the discovery waterfall as the
    nearest-first source; ``tick()`` runs one anti-entropy round
    against ``fanout`` seeded-random peers. The node is passive
    otherwise — callers (the daemon's serve loop, the fleet sim) own
    the tick cadence (``ZEST_GOSSIP_INTERVAL_S``)."""

    def __init__(self, host_index: int, n_hosts: int,
                 addr_book: dict[int, tuple[str, int]] | None = None,
                 *, topology=None, pods=None, fanout: int = 0,
                 max_entries: int = DEFAULT_MAX_ENTRIES,
                 seed: int | None = None):
        self.host_index = int(host_index)
        self.n_hosts = int(n_hosts)
        self.addr_book = dict(addr_book or {})
        self.topology = tuple(topology) if topology else None
        self.pods = tuple(pods) if pods else None
        self.digest = GossipDigest(max_entries, own_origin=host_index)
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._fanout = int(fanout)
        self._rng = random.Random(
            seed if seed is not None else 0x2E57 ^ self.host_index)
        self._peer_vv: dict[int, dict[int, int]] = {}
        self.rounds = 0
        self.announces = 0
        self.bytes_out = 0
        self.bytes_in = 0

    # ── Local authorship ──

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def announce(self, info_hash: bytes, port: int) -> None:
        """PeerSource announce: record "I hold this xorb" locally; the
        next push-pull rounds rumor it fleet-wide."""
        self.announces += 1
        self.digest.update(KIND_XORB, info_hash.hex(), self.host_index,
                           self._next_seq(), {"port": int(port)})

    def set_seeder_state(self, state: str, **extra) -> None:
        self.digest.update(KIND_SEEDER, str(self.host_index),
                           self.host_index, self._next_seq(),
                           {"state": state, **extra})

    def announce_manifest(self, key: str, payload: dict) -> None:
        self.digest.update(KIND_MANIFEST, key, self.host_index,
                           self._next_seq(), dict(payload))

    # ── Fleet index / content-aware routing (tentpole c) ──

    def cost_to(self, other: int) -> int:
        return link_cost(self.host_index, other,
                         topology=self.topology, pods=self.pods)

    def who_has(self, info_hash: bytes) -> list[int]:
        """Holder host indices, nearest link class first (ICI < DCN <
        WAN), ties by host index for determinism."""
        holders = self.digest.holders(KIND_XORB, info_hash.hex())
        return sorted(holders, key=lambda h: (self.cost_to(h), h))

    def find_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        """PeerSource lookup, answered from the LOCAL digest — zero
        round trips (the tracker needs one per query). Cost-ordered so
        the swarm's candidate list tries the nearest warm host first."""
        holders = self.digest.holders(KIND_XORB, info_hash.hex())
        out: list[tuple[str, int]] = []
        for h in sorted(holders, key=lambda h: (self.cost_to(h), h)):
            if h == self.host_index:
                continue
            addr = self.addr_book.get(h)
            host = addr[0] if addr else None
            port = holders[h].get("port") or (addr[1] if addr else None)
            if host and port:
                out.append((host, int(port)))
        return out

    # ── Anti-entropy rounds ──

    def peers(self) -> list[int]:
        return sorted(h for h in self.addr_book if h != self.host_index)

    def fanout(self) -> int:
        if self._fanout > 0:
            return self._fanout
        n = max(2, len(self.peers()) + 1)
        return max(1, math.ceil(math.log2(n)))

    def request_payload(self, peer: int) -> dict:
        """The push half: our version vector + what we believe ``peer``
        is missing (sized from the vv its last response carried; a
        never-seen peer gets a capped cold delta)."""
        known = self._peer_vv.get(peer, {})
        return {"host": self.host_index,
                "vv": {str(k): v
                       for k, v in self.digest.snapshot_vv().items()},
                "delta": self.digest.delta_since(known)}

    def handle_exchange(self, payload: dict) -> dict:
        """Serve one push-pull round (the responder half — runs on the
        DCN serve plane or a LoopbackMesh call): merge the caller's
        delta, answer with our vector + their missing entries."""
        sender = payload.get("host")
        their_vv = {int(k): int(v)
                    for k, v in (payload.get("vv") or {}).items()}
        self.digest.merge_rows(payload.get("delta") or ())
        if sender is not None:
            self._peer_vv[int(sender)] = their_vv
        return {"host": self.host_index,
                "vv": {str(k): v
                       for k, v in self.digest.snapshot_vv().items()},
                "delta": self.digest.delta_since(their_vv)}

    def merge_response(self, peer: int, resp: dict) -> int:
        their_vv = {int(k): int(v)
                    for k, v in (resp.get("vv") or {}).items()}
        self._peer_vv[peer] = their_vv
        return self.digest.merge_rows(resp.get("delta") or ())

    def tick(self, transport) -> int:
        """One gossip round: push-pull with ``fanout`` random peers.
        Returns how many fresh entries arrived. Peer sampling is seeded
        per node — a fleet sim replays identically."""
        fresh = 0
        peers = self.peers()
        if not peers:
            return 0
        picks = self._rng.sample(peers, min(self.fanout(), len(peers)))
        for peer in picks:
            req = self.request_payload(peer)
            out_n = len(json.dumps(req, separators=(",", ":")))
            self.bytes_out += out_n
            _M_GOSSIP_BYTES.inc(out_n, direction="out")
            resp = transport.exchange(peer, req)
            if not resp:
                continue
            in_n = len(json.dumps(resp, separators=(",", ":")))
            self.bytes_in += in_n
            _M_GOSSIP_BYTES.inc(in_n, direction="in")
            fresh += self.merge_response(peer, resp)
        self.rounds += 1
        _M_GOSSIP_ROUNDS.inc()
        return fresh

    def summary(self) -> dict:
        return {
            "entries": len(self.digest),
            "rounds": self.rounds,
            "announces": self.announces,
            "fanout": self.fanout(),
            "evicted": self.digest.evicted,
            "bytes_out": self.bytes_out,
            "bytes_in": self.bytes_in,
            "memory_bytes": self.digest.memory_bytes(),
            "max_entries": self.digest.max_entries,
        }


def node_from_config(cfg, host_index: int, n_hosts: int,
                     addr_book=None) -> GossipNode | None:
    """Build this host's GossipNode from Config, or None when
    ``ZEST_GOSSIP=0`` — the single wiring gate: with gossip off no node
    exists anywhere, so announce paths and stats schemas are
    bit-for-bit the tracker-only build."""
    if not getattr(cfg, "gossip_enabled", True):
        return None
    return GossipNode(
        host_index, n_hosts, addr_book,
        topology=getattr(cfg, "coop_topology", None),
        pods=getattr(cfg, "coop_pods", None),
        fanout=getattr(cfg, "gossip_fanout", 0),
        max_entries=getattr(cfg, "gossip_max_entries",
                            DEFAULT_MAX_ENTRIES),
    )
