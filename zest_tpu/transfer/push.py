"""``zest push`` — the write path: CDC-dedup checkpoint publishing and
continuous weight fan-out (ISSUE 19).

"The package IS the seeder" has been half true since PR 1: every pulled
byte seeds, but training output could not *enter* the swarm. This
module closes the loop. A push takes a checkpoint directory (safetensors
+ sidecars — a trainer's save, or a live mesh's tree written through the
loader), encodes it with the production CDC-dedup encoder
(:mod:`zest_tpu.cas.publish` — the same implementation the test
fixtures serve from) against the *cached base revision's* xorb set, and
lands the result exactly where a pull would have:

- new xorbs → the local :class:`~zest_tpu.storage.XorbCache`
  (immediately seedable: BtServer serves from this cache, the daemon
  notify below registers + gossips them),
- a revision manifest → :mod:`transfer.delta`'s manifest store, with
  ``parent`` lineage so :func:`delta.find_base_manifest` prefers the
  closest ancestor on the next publish,
- a snapshot + refs update → the normal HF cache layout, so the local
  daemon can serve (and decode) the new revision like any pulled one.

Every minted xorb is re-verified chunk-by-chunk through the existing
``ops/blake3`` hasher path before its bytes are written — published
bytes carry the same provenance guarantee pulls enforce — and the
xorb-blob BLAKE3 digests ride in the :class:`PushResult`.

**Continuous fan-out**: a push POSTs ``/v1/push`` to the local daemon,
which registers the new xorbs, gossip-announces the revision bump
(``KIND_MANIFEST``), and broadcasts to every ``POST /v1/watch``
subscriber. :func:`watch_and_swap` is the subscriber engine serving
pods run: on each revision event it delta-pulls rev B against the
resident rev-A evidence and hot-swaps — the PR-9 in-place swap for a
caller-held param tree, the PR-18 :meth:`HbmPool.swap_to` re-land for
pool-served models — posting trainer→resident propagation latency as a
live timeline series (``push.propagation_s``).

:class:`PublisherIndex` is the read side of the publisher: it answers
the exact Hub/CAS API shapes (``revision`` / ``paths-info`` /
``xet-read-token`` / ``reconstructions`` / ``xorbs`` / ``resolve``)
from local manifests, snapshots, and the xorb cache — so a *normal*
``zest pull`` on a second node, pointed at this daemon as its endpoint,
reassembles the pushed revision byte-identically.
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from pathlib import Path
from types import SimpleNamespace

from zest_tpu import storage, telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas import reconstruction as recon
from zest_tpu.cas.publish import Publisher, is_xet_path
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.config import Config
from zest_tpu.transfer import delta

# Bearer token the publisher daemon accepts/issues for its CAS routes.
# Loopback/DCN trust domain (same as the BT wire): the token exists for
# API-shape parity with the real hub, not as a secret.
PUBLISHER_TOKEN = "zest-publisher-token"

# Timeline series posted by the subscriber on every completed swap —
# the PR-14 live chart of trainer-to-fleet propagation.
SERIES_PROPAGATION = "push.propagation_s"


@dataclass
class PushResult:
    """What one publish did (also the ``--json`` CLI payload)."""

    repo_id: str
    revision: str
    parent: str | None
    preview: bool
    files: int = 0
    xet_files: int = 0
    total_bytes: int = 0
    xet_bytes: int = 0
    reused_bytes: int = 0
    new_xorbs: int = 0
    new_xorb_bytes: int = 0
    elapsed_s: float = 0.0
    manifest_written: bool = False
    seeded_base_xorbs: int = 0
    xorb_digests: dict[str, str] = field(default_factory=dict)
    notified: dict | None = None

    @property
    def dedup_ratio(self) -> float:
        """Fraction of xet bytes that did NOT become new xorb payload —
        the headline: 1%-changed weights should land ≥ 0.90 here."""
        if not self.xet_bytes:
            return 1.0
        return max(0.0, 1.0 - (self.new_xorb_bytes / self.xet_bytes))

    def summary(self) -> dict:
        return {
            "repo": self.repo_id,
            "revision": self.revision,
            "parent": self.parent,
            "preview": self.preview,
            "files": self.files,
            "xet_files": self.xet_files,
            "total_bytes": self.total_bytes,
            "xet_bytes": self.xet_bytes,
            "reused_bytes": self.reused_bytes,
            "new_xorbs": self.new_xorbs,
            "new_xorb_bytes": self.new_xorb_bytes,
            "dedup_ratio": round(self.dedup_ratio, 4),
            "manifest_written": self.manifest_written,
            "seeded_base_xorbs": self.seeded_base_xorbs,
            "elapsed_s": round(self.elapsed_s, 3),
            "notified": self.notified,
        }


def read_checkpoint_dir(checkpoint_dir: str | Path) -> dict[str, bytes]:
    """A checkpoint directory as {relative posix path: bytes}, sorted —
    deterministic walk order keeps the revision sha content-defined."""
    root = Path(checkpoint_dir)
    if not root.is_dir():
        raise ValueError(f"not a checkpoint directory: {root}")
    files: dict[str, bytes] = {}
    for p in sorted(root.rglob("*")):
        if p.is_file():
            files[p.relative_to(root).as_posix()] = p.read_bytes()
    if not files:
        raise ValueError(f"checkpoint directory is empty: {root}")
    return files


def _resolve_base_sha(cfg: Config, repo_id: str,
                      base_revision: str | None) -> str | None:
    """The revision-A sha a push dedups against: explicit sha/ref, else
    whatever ``refs/main`` points at (the fine-tune-loop common case)."""
    if base_revision:
        if delta.manifest_path(cfg, repo_id, base_revision).exists():
            return base_revision
        return storage.read_ref(cfg, repo_id, base_revision) or base_revision
    return storage.read_ref(cfg, repo_id, "main")


def _seed_from_base(cfg: Config, pub: Publisher, base_man: dict,
                    cache: storage.XorbCache) -> int:
    """Feed the base revision's locally-cached xorbs into the dedup
    index. Only FULL cache entries qualify (a partial entry's chunk
    indices are rebased — offsets would lie); a missing xorb just means
    its chunks can't dedup, never a failed push."""
    seeded = 0
    seen: set[str] = set()
    for rec in (base_man.get("files") or {}).values():
        for term in rec.get("terms") or []:
            xh_hex = term[0]
            if xh_hex in seen:
                continue
            seen.add(xh_hex)
            blob = cache.get(xh_hex)
            if blob is None:
                continue
            try:
                reader = XorbReader(blob)
                pub.seed_xorb(xh_hex, reader.frame_offsets(),
                              reader.chunk_hashes())
                seeded += 1
            except Exception:  # noqa: BLE001 - a bad cache entry only costs dedup
                continue
    return seeded


def _revision_identities(cfg: Config, repo_id: str, sha: str,
                         man: dict | None) -> dict[str, str] | None:
    """Per-file identity map of an already-published revision (xet hash
    from its manifest, BLAKE3 for sidecars) — None when local state is
    too incomplete to compare. Feeds the no-op-push check."""
    try:
        snap = cfg.model_snapshot_dir(repo_id, sha)
    except ValueError:
        return None
    if not snap.is_dir():
        return None
    out: dict[str, str] = {}
    for p in sorted(snap.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(snap).as_posix()
        if is_xet_path(rel):
            rec = ((man or {}).get("files") or {}).get(rel)
            if rec is None:
                return None
            out[rel] = rec["xet_hash"]
        else:
            out[rel] = hashing.blake3_hash(p.read_bytes()).hex()
    return out


def _content_sha(parent: str | None, identities: dict[str, str]) -> str:
    """Content-defined revision id: BLAKE3 over (parent, per-file
    identity), 40 hex chars like a git sha. Re-pushing identical
    content over the same parent is the same revision — idempotent."""
    doc = json.dumps({"parent": parent or "", "files": identities},
                     sort_keys=True, separators=(",", ":"))
    return hashing.blake3_hash(doc.encode()).hex()[:40]


def _verify_minted(pub_xorbs) -> dict[str, str]:
    """Provenance gate (tentpole): re-hash every minted xorb's chunks
    through the ops/blake3 hasher path and compare against the chunk
    hashes the encoder packed — published bytes get the same BLAKE3
    verification pulls enforce on fetched bytes. Returns {xorb_hex:
    blob blake3 hex} digests. Raises on any mismatch: corrupt bytes
    must never enter the seedable cache."""
    from zest_tpu import ops

    hasher = ops.unit_verify_hasher(hashing.CHUNK_KEY)
    digests: dict[str, str] = {}
    for px in pub_xorbs:
        reader = XorbReader(px.blob)
        chunks = [reader.extract_chunk(i, verify=False)
                  for i in range(len(reader))]
        got = hasher.hash_batch(chunks)
        want = [h for h, _len in reader.chunk_hashes()]
        if got != want:
            raise RuntimeError(
                f"minted xorb {px.hash_hex[:12]} failed BLAKE3 "
                "verification — refusing to publish corrupt bytes")
        digests[px.hash_hex] = hashing.blake3_hash(px.blob).hex()
    return digests


def notify_daemon(cfg: Config, payload: dict,
                  timeout_s: float = 5.0) -> dict | None:
    """POST the push notification to the local daemon's ``/v1/push``.
    Best-effort: no daemon (or watch off, 404) returns None — the push
    itself has already durably landed; only the live fan-out is lost."""
    port = cfg.effective_http_port()
    url = f"http://127.0.0.1:{port}/v1/push"
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode() or "{}")
    except (urllib.error.URLError, OSError, ValueError):
        return None


def push_checkpoint(cfg: Config, repo_id: str,
                    checkpoint_dir: str | Path | None = None,
                    files: dict[str, bytes] | None = None,
                    base_revision: str | None = None,
                    preview: bool = False,
                    notify: bool = True,
                    log=print) -> PushResult:
    """Publish a checkpoint as a new revision of ``repo_id`` (tentpole).

    ``files`` may be passed directly (a live mesh's serialized tree);
    otherwise ``checkpoint_dir`` is read. With ``preview=True`` the full
    CDC-dedup encode runs but NOTHING is written, announced, or
    notified — the ``zest diff --push-preview`` dry-run reporting the
    would-be dedup ratio and new-xorb byte count.
    """
    t0 = time.monotonic()
    cfg.model_cache_dir(repo_id)  # repo-id validation (raises ValueError)
    if files is None:
        if checkpoint_dir is None:
            raise ValueError("push needs a checkpoint_dir or files dict")
        files = read_checkpoint_dir(checkpoint_dir)

    cache = storage.XorbCache(cfg)
    base_sha = _resolve_base_sha(cfg, repo_id, base_revision)
    base_man = (delta.load_manifest(cfg, repo_id, base_sha)
                if base_sha else None)
    if base_sha and base_man is None and base_revision:
        # An explicit base the caller believes exists but has no local
        # evidence: proceed cold, but loudly — dedup against nothing is
        # a full upload, probably not what a trainer loop intended.
        telemetry.record("push_degraded", repo=repo_id,
                         reason="missing base manifest")
        log(f"push: no manifest for base {base_sha[:12]} — publishing "
            "without dedup evidence")

    pub = Publisher(chunks_per_xorb=getattr(cfg, "push_chunks_per_xorb", 0))
    seeded = _seed_from_base(cfg, pub, base_man, cache) if base_man else 0

    with telemetry.span("push", repo=repo_id):
        published: dict[str, object] = {}
        identities: dict[str, str] = {}
        result = PushResult(repo_id=repo_id, revision="",
                            parent=base_sha if base_man else None,
                            preview=preview, files=len(files),
                            seeded_base_xorbs=seeded)
        for path, data in files.items():
            result.total_bytes += len(data)
            if is_xet_path(path):
                pf = pub.publish_file(path, data, dedup=True)
                published[path] = pf
                identities[path] = pf.xet_hash
                result.xet_files += 1
                result.xet_bytes += pf.size
                result.reused_bytes += pf.reused_bytes
            else:
                identities[path] = hashing.blake3_hash(data).hex()

        minted = pub.drain_new_xorbs()
        result.new_xorbs = len(minted)
        result.new_xorb_bytes = sum(len(px.blob) for px in minted)
        result.revision = _content_sha(result.parent, identities)

        # No-op push (trainer retry safety): bytes identical to the
        # resolved base ARE the base revision — report it, write and
        # notify nothing, so a re-push after a crashed ack can't mint a
        # spurious self-parented revision.
        if result.parent and identities == _revision_identities(
                cfg, repo_id, result.parent, base_man):
            result.revision = result.parent
            result.parent = (base_man or {}).get("parent")
            result.elapsed_s = time.monotonic() - t0
            telemetry.record("push_noop", repo=repo_id,
                             revision=result.revision)
            return result

        if preview:
            result.elapsed_s = time.monotonic() - t0
            return result

        # ── Provenance, then durable writes (xorbs → snapshot → manifest
        # → refs): a crash mid-push leaves extra cache bytes, never a
        # ref pointing at an unserveable revision. ──
        result.xorb_digests = _verify_minted(minted)
        for px in minted:
            if not cache.has(px.hash_hex):
                cache.put(px.hash_hex, px.blob)

        snap = cfg.model_snapshot_dir(repo_id, result.revision)
        snap.mkdir(parents=True, exist_ok=True)
        for path, data in files.items():
            target = snap / path
            target.parent.mkdir(parents=True, exist_ok=True)
            storage.atomic_write(target, data)

        entries = [SimpleNamespace(is_xet=True, path=p, size=pf.size,
                                   xet_hash=pf.xet_hash)
                   for p, pf in published.items()]
        result.manifest_written = delta.save_manifest(
            cfg, repo_id, result.revision, entries,
            lambda e: published[e.path].reconstruction,
            parent=result.parent)
        storage.write_ref(cfg, repo_id, "main", result.revision)

        telemetry.record("push_published", repo=repo_id,
                         revision=result.revision,
                         new_xorbs=result.new_xorbs,
                         dedup_ratio=round(result.dedup_ratio, 4))
        if notify:
            result.notified = notify_daemon(cfg, {
                "repo": repo_id,
                "revision": result.revision,
                "parent": result.parent,
                "pushed_at": time.time(),
                "dedup_ratio": round(result.dedup_ratio, 4),
                "new_xorb_bytes": result.new_xorb_bytes,
                "xorbs": [[px.hash_hex, len(px.blob)] for px in minted],
            })
    result.elapsed_s = time.monotonic() - t0
    return result


def preview_push(cfg: Config, repo_id: str,
                 checkpoint_dir: str | Path,
                 base_revision: str | None = None) -> dict:
    """``zest diff --push-preview``: the would-be dedup outcome of
    pushing ``checkpoint_dir``, without writing anything."""
    res = push_checkpoint(cfg, repo_id, checkpoint_dir,
                          base_revision=base_revision, preview=True,
                          notify=False, log=lambda *a, **k: None)
    return res.summary()


# ── The watch client: continuous fan-out, subscriber side ──


def watch_events(base_url: str, repos: list[str] | None = None,
                 timeout_s: float | None = None):
    """Generator over a daemon's ``POST /v1/watch`` SSE stream.

    Yields event dicts (``hello`` once, then ``revision`` bumps;
    ``ping`` keepalives are swallowed). ``timeout_s`` bounds the
    per-read socket wait — expiry ends the stream, it is not an error.
    """
    req = urllib.request.Request(
        base_url.rstrip("/") + "/v1/watch",
        data=json.dumps({"repos": repos or []}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    resp = urllib.request.urlopen(req, timeout=timeout_s)
    try:
        for raw in resp:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data: "):
                continue
            try:
                ev = json.loads(line[len("data: "):])
            except ValueError:
                continue
            if ev.get("event") == "ping":
                continue
            yield ev
    finally:
        resp.close()


def watch_and_swap(cfg: Config, repo_id: str,
                   publisher_url: str | None = None,
                   device: str | None = None,
                   base_params: dict | None = None,
                   base_revision: str | None = None,
                   max_events: int = 1,  # 0/None = until stream ends
                   timeout_s: float | None = 120.0,
                   swarm=None, no_p2p: bool = False,
                   log=print) -> list[dict]:
    """Subscriber engine (tentpole): auto-delta-pull + hot-swap on
    every pushed revision.

    Connects to the publisher daemon's ``/v1/watch``; each ``revision``
    event triggers a delta pull of the new sha with the resident rev-A
    evidence (``base_params``/``base_revision`` — the PR-9 in-place
    swap; the updated tree becomes the base for the NEXT event, so a
    long-running subscriber tracks the trainer at one-tree HBM peak).
    When the HBM serving pool holds the repo (PR 18), the swap also
    re-lands the new snapshot through :meth:`HbmPool.swap_to` — pinned
    in flight, old revision evicted after.

    Per event, posts ``push.propagation_s`` (trainer ``pushed_at`` →
    swap complete) to the live timeline and returns a record list:
    ``{revision, parent, propagation_s, time_to_swap_s, dedup_ratio}``.
    """
    from zest_tpu.transfer.pull import pull_model

    if publisher_url is None:
        publisher_url = f"http://127.0.0.1:{cfg.effective_http_port()}"
    # Pull FROM the daemon being watched: the publisher serves the full
    # hub/CAS read surface (PublisherIndex), so the subscriber's pulls
    # must target it — not whatever cfg.endpoint defaults to.
    if cfg.endpoint.rstrip("/") != publisher_url.rstrip("/"):
        cfg = dataclasses.replace(cfg, endpoint=publisher_url.rstrip("/"))
    telemetry.timeline.ensure_started()
    records: list[dict] = []
    for ev in watch_events(publisher_url, repos=[repo_id],
                           timeout_s=timeout_s):
        if ev.get("event") != "revision" or ev.get("repo") != repo_id:
            continue
        sha = ev.get("revision")
        if not sha or sha == base_revision:
            continue
        log(f"watch: {repo_id} bumped to {sha[:12]} "
            f"(parent {str(ev.get('parent'))[:12]}) — delta pulling")
        old_snap = None
        if base_revision:
            try:
                old_snap = cfg.model_snapshot_dir(repo_id, base_revision)
            except ValueError:
                old_snap = None
        result = pull_model(
            cfg, repo_id, revision=sha, device=device, swarm=swarm,
            no_p2p=no_p2p, base_params=base_params,
            base_revision=base_revision if base_params else None,
            log=log)
        record = {
            "revision": sha,
            "parent": ev.get("parent"),
            "dedup_ratio": ev.get("dedup_ratio"),
            "time_to_swap_s": result.stats.get(
                "time_to_swap_s", result.stats.get("elapsed_s")),
        }
        # PR-18 re-land path: pool-served models swap inside the pool
        # (pinned land → evict old), not via caller-held params.
        from zest_tpu.models import hbm_pool as pool_mod

        pool = pool_mod.pool(cfg)
        if pool is not None and old_snap is not None:
            try:
                # Only re-land when the OLD snapshot is actually pool-
                # resident — digest() is the residency probe.
                if pool.digest(old_snap) is not None:
                    new_snap = cfg.model_snapshot_dir(repo_id, sha)
                    entry, swap_s = pool.swap_to(
                        old_snap, new_snap, repo=repo_id)
                    pool.release(entry)
                    record["pool_swap_s"] = round(swap_s, 4)
            except Exception as exc:  # noqa: BLE001 - pool swap advisory
                record["pool_swap_error"] = type(exc).__name__
        pushed_at = ev.get("pushed_at")
        if isinstance(pushed_at, (int, float)):
            propagation = max(0.0, time.time() - pushed_at)
            record["propagation_s"] = round(propagation, 4)
            telemetry.timeline.post(SERIES_PROPAGATION, propagation)
        records.append(record)
        base_params = result.params if result.params else base_params
        base_revision = sha
        if max_events and len(records) >= max_events:
            break
    return records


# ── The publisher's read side: hub-shaped serving index ──


class PublisherIndex:
    """Answers the Hub/CAS API shapes from local state (manifests,
    snapshots, xorb cache) so the daemon can serve pushed revisions to
    a second node's *unmodified* ``zest pull``.

    Used by ``api.http_api``: ``GET /api/models/{repo}/revision/{rev}``,
    ``POST .../paths-info/{rev}``, ``GET .../xet-read-token/{rev}``,
    ``GET /v1/reconstructions/{hex}`` (with Range pagination +
    ``offset_into_first_range``, 416 past EOF), ``GET /xorbs/{hex}``
    (ranged), ``GET /{org}/{name}/resolve/{rev}/{file}`` — the same
    shapes (and pagination semantics) the loopback FixtureHub speaks,
    which are the shapes the production client speaks.
    """

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.cache = storage.XorbCache(cfg)
        self._offsets: dict[str, list[int]] = {}

    # ── revision / file metadata ──

    def resolve_sha(self, repo_id: str, rev: str | None) -> str | None:
        rev = rev or "main"
        if delta.manifest_path(self.cfg, repo_id, rev).exists() \
                or self._snapshot_dir(repo_id, rev) is not None:
            return rev
        return storage.read_ref(self.cfg, repo_id, rev)

    def _snapshot_dir(self, repo_id: str, sha: str) -> Path | None:
        try:
            snap = self.cfg.model_snapshot_dir(repo_id, sha)
        except ValueError:
            return None
        return snap if snap.is_dir() else None

    def files_for(self, repo_id: str,
                  sha: str) -> dict[str, dict] | None:
        """{path: {size, xetHash?}} for a revision — snapshot listing
        for sizes/sidecars, manifest for xet identities. None when the
        revision is unknown locally."""
        man = delta.load_manifest(self.cfg, repo_id, sha)
        snap = self._snapshot_dir(repo_id, sha)
        if man is None and snap is None:
            return None
        out: dict[str, dict] = {}
        if snap is not None:
            for p in sorted(snap.rglob("*")):
                if p.is_file():
                    rel = p.relative_to(snap).as_posix()
                    out[rel] = {"size": p.stat().st_size}
        for path, rec in ((man or {}).get("files") or {}).items():
            entry = out.setdefault(path, {"size": int(rec["size"])})
            entry["size"] = int(rec["size"])
            entry["xetHash"] = rec["xet_hash"]
        return out

    def revision_doc(self, repo_id: str, rev: str | None) -> dict | None:
        sha = self.resolve_sha(repo_id, rev)
        if sha is None:
            return None
        files = self.files_for(repo_id, sha)
        if files is None:
            return None
        return {"sha": sha,
                "siblings": [{"rfilename": p} for p in sorted(files)]}

    def paths_info(self, repo_id: str, rev: str | None,
                   paths: list[str]) -> list[dict] | None:
        sha = self.resolve_sha(repo_id, rev)
        files = self.files_for(repo_id, sha) if sha else None
        if files is None:
            return None
        out = []
        for p in paths:
            meta = files.get(p)
            if meta is None:
                continue
            item = {"path": p, "size": meta["size"], "type": "file"}
            if meta.get("xetHash"):
                item["xetHash"] = meta["xetHash"]
            out.append(item)
        return out

    def resolve_file(self, repo_id: str, rev: str,
                     filename: str) -> bytes | None:
        sha = self.resolve_sha(repo_id, rev)
        snap = self._snapshot_dir(repo_id, sha) if sha else None
        if snap is None:
            return None
        target = (snap / filename)
        try:
            target = target.resolve()
            target.relative_to(snap.resolve())  # no traversal
            return target.read_bytes()
        except (OSError, ValueError):
            return None

    # ── CAS data plane ──

    def xorb_blob(self, xorb_hex: str) -> bytes | None:
        return self.cache.get(xorb_hex)

    def _frame_offsets(self, xorb_hex: str) -> list[int] | None:
        offs = self._offsets.get(xorb_hex)
        if offs is not None:
            return offs
        blob = self.cache.get(xorb_hex)
        if blob is None:
            return None
        try:
            offs = XorbReader(blob).frame_offsets()
        except Exception:  # noqa: BLE001 - corrupt entry = unserveable
            return None
        self._offsets[xorb_hex] = offs
        return offs

    def _find_file_record(self, file_hex: str) -> dict | None:
        """Locate ``file_hex``'s term list in any local manifest."""
        root = delta.manifest_dir(self.cfg)
        try:
            paths = sorted(root.iterdir(),
                           key=lambda p: p.stat().st_mtime, reverse=True)
        except OSError:
            return None
        for p in paths:
            try:
                doc = json.loads(p.read_text())
            except (OSError, ValueError):
                continue
            for rec in (doc.get("files") or {}).values():
                if rec.get("xet_hash") == file_hex:
                    return rec
        return None

    def reconstruction_doc(self, file_hex: str,
                           range_header: str | None,
                           base_url: str):
        """The reconstruction JSON for ``file_hex`` — or the string
        ``"range"`` for a 416 window, or None when unknown/unserveable
        (a term's xorb missing from the local cache)."""
        rec_doc = self._find_file_record(file_hex)
        if rec_doc is None:
            return None
        terms: list[recon.Term] = []
        fetch_info: dict[str, list[recon.FetchInfo]] = {}
        for t in rec_doc.get("terms") or []:
            xh_hex, start, end, nbytes = t[0], int(t[1]), int(t[2]), int(t[3])
            offs = self._frame_offsets(xh_hex)
            if offs is None or end > len(offs) - 1:
                return None
            terms.append(recon.Term(
                xorb_hash=hashing.hex_to_hash(xh_hex),
                range=recon.ChunkRange(start, end),
                unpacked_length=nbytes))
            fi = recon.FetchInfo(
                url=f"/xorbs/{xh_hex}",
                url_range_start=offs[start], url_range_end=offs[end],
                range=recon.ChunkRange(start, end))
            entries = fetch_info.setdefault(xh_hex, [])
            if fi not in entries:
                entries.append(fi)
        rec_obj = recon.Reconstruction(
            file_hash=hashing.hex_to_hash(file_hex), terms=terms,
            fetch_info=fetch_info)

        total = sum(t.unpacked_length for t in rec_obj.terms)
        lo, hi = 0, total
        if range_header:
            spec = range_header.split("=", 1)[-1]
            start_s, _, end_s = spec.partition("-")
            try:
                lo = int(start_s or 0)
                hi = min(int(end_s) + 1 if end_s else total, total)
            except ValueError:
                lo, hi = 0, total
            if lo >= total and total > 0:
                return "range"
        doc = recon.to_json(rec_obj)
        if lo > 0 or hi < total:
            kept, off, offset_into_first = [], 0, 0
            for t, tj in zip(rec_obj.terms, doc["terms"]):
                t_lo, t_hi = off, off + t.unpacked_length
                if t_hi > lo and t_lo < hi:
                    if not kept:
                        offset_into_first = lo - t_lo
                    kept.append(tj)
                off = t_hi
            doc["terms"] = kept
            doc["offset_into_first_range"] = offset_into_first
            keep = {t["hash"] for t in kept}
            doc["fetch_info"] = {h: v for h, v in doc["fetch_info"].items()
                                 if h in keep}
        for entries in doc["fetch_info"].values():
            for fi in entries:
                if fi["url"].startswith("/"):
                    fi["url"] = base_url + fi["url"]
        return doc
