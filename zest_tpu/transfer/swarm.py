"""Swarm download orchestrator (the src/swarm.zig equivalent).

Decides *which peers* to ask for a xorb range and manages the connection
pool, discovery cache, and per-session stats. Discovery is pluggable
(``PeerSource``): direct ``--peer`` addresses are tried first, then
discovered peers — DHT and tracker sources on the interop plane
(zest_tpu.p2p.dht / .tracker), the JAX-coordinator registry on the pod
plane (zest_tpu.parallel.coordinator). Discovery results are cached for
30 s per swarm under a lock (reference: swarm.zig:320-355); an
*all-sources-failed* round caches for only ~2 s, so one DHT blip can't
blank peer discovery for a whole TTL.

Failure semantics improve on the reference (swarm.zig:398-437), which
forgot failures between calls: every candidate carries per-peer health
(zest_tpu.p2p.health) — a latency EWMA orders candidates fast-first and
drives adaptive connect/IO timeouts, while connect failures, IO
timeouts, and corrupt-chunk attributions from the bridge each count a
strike toward a quarantine circuit breaker. CHUNK_NOT_FOUND still keeps
the connection (the peer is healthy, it just lacks this xorb), and an
IO failure on a *reused* pooled socket gets one fresh-reconnect retry
before the peer is blamed — the pool's eviction race and server-side
idle closes both look exactly like that.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Protocol

from zest_tpu import faults, telemetry
from zest_tpu.config import Config
from zest_tpu.p2p import peer_id as peer_id_mod
from zest_tpu.p2p.health import HealthRegistry
from zest_tpu.p2p.peer import (
    ChunkNotFoundError,
    ContentRefusedError,
    PeerChokedError,
    PeerError,
)
from zest_tpu.p2p.pool import PeerPool

DISCOVERY_TTL_S = 30.0
# An empty discovery round (all sources failed or no peers yet) is
# renegotiated quickly: caching the blank list for the full TTL would
# silence the peer tier for 30 s after one transient DHT/tracker blip.
NEGATIVE_DISCOVERY_TTL_S = 2.0
# Re-announce dedup window (ISSUE 16 satellite): one health-transition
# sweep re-registers a swarm at most once per window — a quarantine
# storm at fleet scale (hundreds of transitions in seconds) must not
# emit O(swarms × transitions) tracker round trips when each swarm's
# registration is already fresh.
REANNOUNCE_WINDOW_S = 30.0

_M_SWARM = telemetry.counter(
    "zest_swarm_events_total", "Swarm events (attempts, failures, ...)",
    ("event",))
_M_PEER_BYTES = telemetry.counter(
    "zest_swarm_bytes_total", "Payload bytes served by peers")


class PeerSource(Protocol):
    """Anything that can map an info_hash to peer addresses."""

    def find_peers(self, info_hash: bytes) -> list[tuple[str, int]]: ...

    def announce(self, info_hash: bytes, port: int) -> None: ...


@dataclass
class SwarmStats:
    """(reference: swarm.zig:150-163)"""

    peers_discovered: int = 0
    peer_attempts: int = 0
    peer_failures: int = 0
    peer_retries: int = 0          # stale-pooled-socket reconnect retries
    peer_choked: int = 0           # upload-policy denials (no strike)
    peer_refusals: int = 0         # quarantined-source refusals (no strike)
    peers_quarantined: int = 0     # circuit-breaker trips
    peers_demoted: int = 0         # proactive remediation demotions
    corrupt_from_peer: int = 0     # corruption attributions from the bridge
    chunks_from_peers: int = 0
    bytes_from_peers: int = 0
    announces: int = 0
    reannounces: int = 0           # quarantine/probation-driven re-announces
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def bump(self, name: str, amount: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + amount)
        if name == "bytes_from_peers":
            _M_PEER_BYTES.inc(amount)
        else:
            _M_SWARM.inc(amount, event=name)

    def summary(self) -> dict:
        return {
            "peers_discovered": self.peers_discovered,
            "peer_attempts": self.peer_attempts,
            "peer_failures": self.peer_failures,
            "peer_retries": self.peer_retries,
            "peer_choked": self.peer_choked,
            "peer_refusals": self.peer_refusals,
            "peers_quarantined": self.peers_quarantined,
            "peers_demoted": self.peers_demoted,
            "corrupt_from_peer": self.corrupt_from_peer,
            "chunks_from_peers": self.chunks_from_peers,
            "bytes_from_peers": self.bytes_from_peers,
            "announces": self.announces,
            "reannounces": self.reannounces,
        }


@dataclass(frozen=True)
class PeerResult:
    data: bytes
    chunk_offset: int
    # Which peer served the bytes — the bridge's corruption-attribution
    # handle (a BLAKE3 mismatch at extraction strikes this address).
    addr: tuple[str, int] | None = None


class SwarmDownloader:
    def __init__(
        self,
        cfg: Config,
        peer_sources: list[PeerSource] | None = None,
        pool: PeerPool | None = None,
        health: HealthRegistry | None = None,
    ):
        self.cfg = cfg
        self.peer_id = peer_id_mod.generate()
        self.pool = pool or PeerPool(cfg.max_peers)
        self.peer_sources = peer_sources or []
        self.direct_peers: list[tuple[str, int]] = []
        self.stats = SwarmStats()
        self.health = health or HealthRegistry()
        self._discovery_cache: dict[
            bytes, tuple[float, list[tuple[str, int]], float]
        ] = {}
        self._discovery_lock = threading.Lock()
        # Quarantine-aware announce (ISSUE 12): circuit-breaker
        # transitions change what this host effectively offers/uses, so
        # every swarm it has announced to gets a refresh — the tracker's
        # peer list must not keep routing leechers through a hole.
        self._announced: set[bytes] = set()
        self._reannounce_lock = threading.Lock()
        self._reannounce_pending = False
        self._last_reannounce: dict[bytes, float] = {}
        # Fleet gossip (transfer.gossip; ISSUE 16): when attached, the
        # node is the FIRST discovery source (cost-ordered, zero round
        # trips) and the tracker/DHT sources demote to bootstrap-only
        # announce. None (ZEST_GOSSIP=0) = tracker-only, bit-for-bit.
        self.gossip = None
        self.health.subscribe(self._on_health_transition)
        # Self-healing targets (ISSUE 17): the remediation engine's
        # seeder scan reads the health book through ``peer_health`` and
        # demotes collapsing seeders through ``demote`` — injected here
        # because telemetry must not import transfer. Replace semantics
        # (latest swarm wins), identity-checked unregister in close();
        # with ZEST_REMEDIATE=0 both calls are one flag check.
        self._remediate_monitor = lambda: {
            "rows": self.health.detail(),
            "strike_budget": self.health.strikes_to_quarantine,
        }
        telemetry.remediate.register_target("peer_health",
                                            self._remediate_monitor)
        # Bound once: unregister_target is identity-checked, and each
        # ``self._demote_peer`` access makes a fresh bound method.
        self._demote_fn = self._demote_peer
        telemetry.remediate.register_target("demote", self._demote_fn)

    def attach_gossip(self, node) -> None:
        """Adopt ``node`` (transfer.gossip.GossipNode) as the primary
        discovery source: its local digest answers ``find_peers``
        nearest-warm-host first (ICI < DCN < WAN), and every announce
        rumors through anti-entropy instead of a tracker round trip —
        the non-gossip sources only see the FIRST announce per swarm
        (the bootstrap seed)."""
        self.gossip = node
        self.peer_sources = [node] + [
            s for s in self.peer_sources if s is not node]

    def add_direct_peer(self, host: str, port: int) -> None:
        """--peer flag path: tried before discovered peers (swarm.zig:279-314)."""
        addr = (host, port)
        if addr not in self.direct_peers:
            self.direct_peers.append(addr)

    def _demote_peer(self, addr: tuple[str, int]) -> dict:
        """The engine's proactive demote (ISSUE 17): a strike-FREE
        re-rank window through :meth:`HealthRegistry.demote` — the
        "demoted" transition event drives the same re-announce sweep a
        breaker trip does, so the tracker's view shifts traffic off the
        collapsing seeder before its strike budget exhausts."""
        window = self.health.demote(addr)
        self.stats.bump("peers_demoted")
        return {"window_s": round(window, 2)}

    def close(self) -> None:
        # Detach from the (possibly shared, longer-lived) health
        # registry first: a closed swarm must not keep re-announcing on
        # its transitions or be pinned in memory by the listener ref.
        self.health.unsubscribe(self._on_health_transition)
        telemetry.remediate.unregister_target("peer_health",
                                              self._remediate_monitor)
        telemetry.remediate.unregister_target("demote", self._demote_fn)
        self.pool.close_all()

    def summary(self) -> dict:
        """Session stats plus the health registry's live view. The
        ``gossip`` block exists only when a node is attached — with
        ZEST_GOSSIP=0 the schema is bit-for-bit the tracker-only
        build's."""
        out = self.stats.summary()
        out["health"] = self.health.summary()
        if self.gossip is not None:
            out["gossip"] = self.gossip.summary()
        return out

    # ── Discovery (reference: swarm.zig:320-355) ──

    def discover_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        now = time.monotonic()
        with self._discovery_lock:
            cached = self._discovery_cache.get(info_hash)
            if cached is not None and now - cached[0] < cached[2]:
                return cached[1]

        found: list[tuple[str, int]] = []
        for source in self.peer_sources:
            try:
                for addr in source.find_peers(info_hash):
                    if addr not in found:
                        found.append(addr)
            except Exception:
                continue  # a dead source must not break the waterfall
        self.stats.bump("peers_discovered", len(found))

        ttl = DISCOVERY_TTL_S if found else NEGATIVE_DISCOVERY_TTL_S
        with self._discovery_lock:
            self._discovery_cache[info_hash] = (now, found, ttl)
        return found

    # ── Download (reference: swarm.zig:363-437) ──

    def try_peer_download(
        self,
        xorb_hash: bytes,
        hash_hex: str,
        range_start: int,
        range_end: int,
        deadline=None,  # zest_tpu.resilience.Deadline | None
    ) -> PeerResult | None:
        """Fetch chunk range [range_start, range_end) of a xorb from the
        swarm; None when no peer could serve it (bridge falls to CDN).

        Candidates are health-ordered (fast, clean peers first);
        quarantined peers are skipped outright, so a peer that kept
        timing out or serving corrupt bytes stops taxing every xorb.
        ``deadline`` caps each attempt's connect/IO timeouts — when the
        budget runs dry the remaining candidates are abandoned and the
        caller's CDN tier takes over."""
        with telemetry.span("swarm.fetch", xorb=hash_hex) as sp:
            info_hash = peer_id_mod.compute_info_hash(xorb_hash)
            candidates = list(self.direct_peers)
            for addr in self.discover_peers(info_hash):
                if addr not in candidates:
                    candidates.append(addr)
            if not candidates:
                sp.set("outcome", "no_candidates")
                return None
            ready, _shunned = self.health.partition(candidates)
            sp.set("candidates", len(ready))

            for host, port in ready:
                if deadline is not None and deadline.expired():
                    sp.set("outcome", "deadline")
                    return None
                self.stats.bump("peer_attempts")
                result = self._attempt(
                    host, port, info_hash, xorb_hash, range_start, range_end,
                    deadline,
                )
                if result is None:
                    continue
                self.stats.bump("chunks_from_peers")
                self.stats.bump("bytes_from_peers", len(result.data))
                self.announce_available(xorb_hash, hash_hex)
                sp.set("outcome", "served")
                sp.set("peer", f"{host}:{port}")
                sp.add_bytes(len(result.data))
                return result
            sp.set("outcome", "exhausted")
            return None

    def _attempt(
        self,
        host: str,
        port: int,
        info_hash: bytes,
        xorb_hash: bytes,
        range_start: int,
        range_end: int,
        deadline,
    ) -> PeerResult | None:
        """One candidate, at most two tries: an IO failure on a REUSED
        pooled connection earns a single fresh-reconnect retry (the
        eviction race / server idle-close case — the socket was stale,
        not the peer), then failures strike the peer's health."""
        addr = (host, port)
        for attempt in (0, 1):
            reused = False
            connect_s = None
            starved = False
            leased = False
            t_req = t0 = time.monotonic()
            try:
                connect_t = self.health.connect_timeout(addr)
                io_t = self.health.io_timeout(addr)
                if deadline is not None:
                    capped_c, capped_io = (deadline.cap(connect_t),
                                           deadline.cap(io_t))
                    # A timeout the deadline squeezed below the health-
                    # derived budget can fail for budget reasons alone.
                    starved = capped_c < connect_t or capped_io < io_t
                    connect_t, io_t = capped_c, capped_io
                peer, reused = self.pool.lease(
                    host, port, info_hash, self.peer_id,
                    listen_port=self.cfg.listen_port,
                    connect_timeout=connect_t, io_timeout=io_t,
                )
                leased = True
                t_req = time.monotonic()
                if not reused:
                    connect_s = t_req - t0
                result = peer.request_chunk(xorb_hash, range_start, range_end,
                                            io_timeout=io_t)
            except ContentRefusedError:
                # Deliberate refusal (quarantined-source content): the
                # peer is healthy and honest about it — no strike, the
                # next candidate/tier serves. Distinct stat so triage
                # sees refusals, not phantom cache misses.
                self.stats.bump("peer_refusals")
                self.health.record_success(
                    addr, rtt_s=time.monotonic() - t_req,
                    connect_s=connect_s)
                return None
            except ChunkNotFoundError:
                # Peer healthy, xorb absent: keep the connection
                # (swarm.zig:406-413); counts toward the latency EWMA.
                self.stats.bump("peer_failures")
                self.health.record_success(
                    addr, rtt_s=time.monotonic() - t_req,
                    connect_s=connect_s)
                return None
            except PeerChokedError:
                # Upload policy denied us a slot: healthy peer enforcing
                # fairness. Keep the pooled connection (it answered
                # promptly), no strike — striking seeders under load
                # would quarantine the whole peer tier exactly when it
                # matters.
                self.stats.bump("peer_choked")
                self.health.record_success(
                    addr, rtt_s=time.monotonic() - t_req,
                    connect_s=connect_s)
                return None
            except (PeerError, OSError) as _exc:
                self.stats.bump("peer_failures")
                self.pool.remove(host, port)
                if reused and attempt == 0:
                    # Stale pooled socket, not a peer verdict: exactly
                    # one reconnect retry, no strike yet.
                    self.stats.bump("peer_retries")
                    continue
                if starved:
                    # The pull budget, not the peer, set this timeout:
                    # quarantining a healthy peer over the deadline's
                    # tail would poison the NEXT pull's candidate list.
                    return None
                # Serving-side attribution (ISSUE 12): a peer that
                # timed out AFTER a successful lease stalled *as a
                # seeder* mid-request — struck with the distinct
                # ``seed_stall`` kind so health.detail() separates "it
                # serves, slowly-to-death" from "it is unreachable".
                kind = ("seed_stall"
                        if leased and isinstance(_exc, TimeoutError)
                        else "error")
                if self.health.record_failure(addr, kind=kind):
                    self.stats.bump("peers_quarantined")
                return None
            # nbytes feeds the reciprocity book: the seeding tier
            # unchokes the peers that served US the most bytes recently.
            self.health.record_success(
                addr, rtt_s=time.monotonic() - t_req, connect_s=connect_s,
                nbytes=len(result.data))
            data = result.data
            if faults.fire("chunk_corrupt", key=f"{host}:{port}"):
                data = faults.corrupt(data)
            return PeerResult(data, result.chunk_offset, addr=addr)
        return None

    def report_corrupt(self, addr: tuple[str, int]) -> None:
        """Corruption attribution from the bridge: the blob this peer
        served failed structural or BLAKE3 verification. Drop the
        connection and strike the peer — K strikes quarantine it, so a
        corrupting peer's traffic shifts to healthy tiers instead of
        poisoning every retry."""
        self.stats.bump("corrupt_from_peer")
        self.pool.remove(*addr)
        if self.health.record_failure(addr, kind="corrupt"):
            self.stats.bump("peers_quarantined")

    # ── Seeding announcements (reference: swarm.zig:458-470) ──

    def announce_available(self, xorb_hash: bytes, hash_hex: str) -> None:
        info_hash = peer_id_mod.compute_info_hash(xorb_hash)
        first = info_hash not in self._announced
        self._announced.add(info_hash)
        for source in self.peer_sources:
            # With gossip attached the tracker/DHT tier is bootstrap
            # only: it sees the FIRST announce per swarm (seeding the
            # epidemic), and every refresh is a local digest update the
            # anti-entropy rounds spread — announce traffic drops from
            # every-host-to-tracker to O(N·log N) gossip payloads.
            if self.gossip is not None and source is not self.gossip \
                    and not first:
                continue
            try:
                source.announce(info_hash, self.cfg.listen_port)
            except Exception:
                continue
        if self.peer_sources:
            self.stats.bump("announces")

    def _on_health_transition(self, event: str, addr: tuple[str, int]) -> None:
        """Quarantine-aware announce: a breaker trip or probation
        re-admit refreshes every swarm this host has announced to (the
        tracker tier of :mod:`zest_tpu.p2p.tracker` treats each announce
        as a registration, so a refresh both re-registers us and pulls a
        peer list that routes around the transition). The sweep runs on
        a background thread — N announced swarms × blocking tracker
        HTTP calls must never stall the observing thread (a pull worker
        or a serve loop) — and concurrent transitions coalesce into the
        one in-flight sweep. Best-effort, like every announce."""
        if not self.peer_sources or not self._announced:
            return
        telemetry.record("swarm_reannounce", reason=event,
                         peer=f"{addr[0]}:{addr[1]}",
                         swarms=len(self._announced))
        with self._reannounce_lock:
            if self._reannounce_pending:
                return  # the in-flight sweep re-registers everything
            self._reannounce_pending = True
        threading.Thread(target=self._reannounce_sweep,
                         name="zest-reannounce", daemon=True).start()

    def _reannounce_sweep(self) -> None:
        try:
            now = self.health.now()
            swept = False
            for info_hash in list(self._announced):
                # Per-swarm dedup: a swarm whose registration was
                # refreshed within the window is skipped — back-to-back
                # transitions (a quarantine storm) re-register each
                # swarm once, not once per transition.
                if now - self._last_reannounce.get(info_hash, -1e9) \
                        < REANNOUNCE_WINDOW_S:
                    continue
                self._last_reannounce[info_hash] = now
                swept = True
                for source in self.peer_sources:
                    try:
                        source.announce(info_hash, self.cfg.listen_port)
                    except Exception:
                        continue
            if swept:
                self.stats.bump("reannounces")
        finally:
            with self._reannounce_lock:
                self._reannounce_pending = False

    def announce_xorbs(self, hash_hexes: list[str]) -> int:
        """``zest seed`` path: announce every cached xorb (main.zig:307-369)."""
        from zest_tpu.cas import hashing

        for hex_key in hash_hexes:
            self.announce_available(hashing.hex_to_hash(hex_key), hex_key)
        return len(hash_hexes)
