"""Swarm download orchestrator (the src/swarm.zig equivalent).

Decides *which peers* to ask for a xorb range and manages the connection
pool, discovery cache, and per-session stats. Discovery is pluggable
(``PeerSource``): direct ``--peer`` addresses are tried first, then
discovered peers — DHT and tracker sources on the interop plane
(zest_tpu.p2p.dht / .tracker), the JAX-coordinator registry on the pod
plane (zest_tpu.parallel.coordinator). Discovery results are cached for
30 s per swarm under a lock (reference: swarm.zig:320-355).

Failure semantics match the reference (swarm.zig:398-437): a connection
error evicts the peer from the pool; CHUNK_NOT_FOUND keeps the connection
(the peer is healthy, it just lacks this xorb).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Protocol

from zest_tpu.config import Config
from zest_tpu.p2p import peer_id as peer_id_mod
from zest_tpu.p2p.peer import ChunkNotFoundError, PeerError
from zest_tpu.p2p.pool import PeerPool

DISCOVERY_TTL_S = 30.0


class PeerSource(Protocol):
    """Anything that can map an info_hash to peer addresses."""

    def find_peers(self, info_hash: bytes) -> list[tuple[str, int]]: ...

    def announce(self, info_hash: bytes, port: int) -> None: ...


@dataclass
class SwarmStats:
    """(reference: swarm.zig:150-163)"""

    peers_discovered: int = 0
    peer_attempts: int = 0
    peer_failures: int = 0
    chunks_from_peers: int = 0
    bytes_from_peers: int = 0
    announces: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def bump(self, name: str, amount: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + amount)

    def summary(self) -> dict:
        return {
            "peers_discovered": self.peers_discovered,
            "peer_attempts": self.peer_attempts,
            "peer_failures": self.peer_failures,
            "chunks_from_peers": self.chunks_from_peers,
            "bytes_from_peers": self.bytes_from_peers,
            "announces": self.announces,
        }


@dataclass(frozen=True)
class PeerResult:
    data: bytes
    chunk_offset: int


class SwarmDownloader:
    def __init__(
        self,
        cfg: Config,
        peer_sources: list[PeerSource] | None = None,
        pool: PeerPool | None = None,
    ):
        self.cfg = cfg
        self.peer_id = peer_id_mod.generate()
        self.pool = pool or PeerPool(cfg.max_peers)
        self.peer_sources = peer_sources or []
        self.direct_peers: list[tuple[str, int]] = []
        self.stats = SwarmStats()
        self._discovery_cache: dict[bytes, tuple[float, list[tuple[str, int]]]] = {}
        self._discovery_lock = threading.Lock()

    def add_direct_peer(self, host: str, port: int) -> None:
        """--peer flag path: tried before discovered peers (swarm.zig:279-314)."""
        addr = (host, port)
        if addr not in self.direct_peers:
            self.direct_peers.append(addr)

    def close(self) -> None:
        self.pool.close_all()

    # ── Discovery (reference: swarm.zig:320-355) ──

    def discover_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        now = time.monotonic()
        with self._discovery_lock:
            cached = self._discovery_cache.get(info_hash)
            if cached is not None and now - cached[0] < DISCOVERY_TTL_S:
                return cached[1]

        found: list[tuple[str, int]] = []
        for source in self.peer_sources:
            try:
                for addr in source.find_peers(info_hash):
                    if addr not in found:
                        found.append(addr)
            except Exception:
                continue  # a dead source must not break the waterfall
        self.stats.bump("peers_discovered", len(found))

        with self._discovery_lock:
            self._discovery_cache[info_hash] = (now, found)
        return found

    # ── Download (reference: swarm.zig:363-437) ──

    def try_peer_download(
        self,
        xorb_hash: bytes,
        hash_hex: str,
        range_start: int,
        range_end: int,
    ) -> PeerResult | None:
        """Fetch chunk range [range_start, range_end) of a xorb from the
        swarm; None when no peer could serve it (bridge falls to CDN)."""
        info_hash = peer_id_mod.compute_info_hash(xorb_hash)
        candidates = list(self.direct_peers)
        for addr in self.discover_peers(info_hash):
            if addr not in candidates:
                candidates.append(addr)
        if not candidates:
            return None

        for host, port in candidates:
            self.stats.bump("peer_attempts")
            try:
                peer = self.pool.get_or_connect(
                    host, port, info_hash, self.peer_id,
                    listen_port=self.cfg.listen_port,
                )
                result = peer.request_chunk(xorb_hash, range_start, range_end)
            except ChunkNotFoundError:
                # Peer healthy, xorb absent: keep the connection
                # (swarm.zig:406-413).
                self.stats.bump("peer_failures")
                continue
            except (PeerError, OSError) as _exc:
                self.stats.bump("peer_failures")
                self.pool.remove(host, port)
                continue
            self.stats.bump("chunks_from_peers")
            self.stats.bump("bytes_from_peers", len(result.data))
            self.announce_available(xorb_hash, hash_hex)
            return PeerResult(result.data, result.chunk_offset)
        return None

    # ── Seeding announcements (reference: swarm.zig:458-470) ──

    def announce_available(self, xorb_hash: bytes, hash_hex: str) -> None:
        info_hash = peer_id_mod.compute_info_hash(xorb_hash)
        for source in self.peer_sources:
            try:
                source.announce(info_hash, self.cfg.listen_port)
            except Exception:
                continue
        if self.peer_sources:
            self.stats.bump("announces")

    def announce_xorbs(self, hash_hexes: list[str]) -> int:
        """``zest seed`` path: announce every cached xorb (main.zig:307-369)."""
        from zest_tpu.cas import hashing

        for hex_key in hash_hexes:
            self.announce_available(hashing.hex_to_hash(hex_key), hex_key)
        return len(hash_hexes)
