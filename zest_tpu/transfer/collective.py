"""Collective-native coop exchange: plan-derived all-to-all over
ICI/DCN with compressed-in-flight payloads (ROADMAP item 3).

The PR-6 exchange (transfer.coop) is point-to-point: each host pulls
every foreign unit from its owner through per-window
``DcnPool.request_many`` calls, serially re-negotiating per window what
the deterministic ownership plan already determines, with a per-unit
NOT_FOUND retry loop against owners that are still fetching. This
module replaces that with a **collective**: every host derives the full
N×N send/recv byte matrix purely from :class:`~zest_tpu.transfer.coop.
CoopPlan` (the plan is fingerprint-identical on every host, so there is
no negotiation round), and the redistribution executes as a schedule of
synchronized phases — a recursive-doubling **hypercube** all-gather
when the alive-host count is a power of two (log2 N phases), a
pipelined **ring** otherwise (N-1 phases, one constant neighbor) — with
ONE pre-sized request window per phase instead of per-unit
request/reply round trips. Per-host connection count drops from
O(N·units) round trips to O(log N) phases.

Three properties carried through from the papers this leans on:

- **Compressed through the collective** (EQuARX, PAPERS.md): phase
  windows move xorb frame streams — BG4/LZ4 payloads still in their
  planar compressed form — and the receiving host expands+verifies
  with the fused Pallas pass (``ops.decode_pallas.FusedBg4Verifier``
  via ``transfer.pod.make_unit_verifier``) before anything reaches the
  cache. The interconnect never carries expanded bytes. On top of the
  byte-exact tier, ``ZEST_COLLECTIVE_LOSSY=dcn|wan`` arms the
  EQuARX-style *lossy* tier (transfer.lossy): BG4 float payloads on
  the named bandwidth-starved link classes quantize to int8 + one
  fp32 scale per 256-value block before the wire and dequantize on
  receipt, with bounded error (≤ absmax/127 per block). Lossy units
  land in the HBM staging overlay ONLY — the merkle-verified xorb
  cache, and every admission path into it, is untouched — and the
  exchange stats report ``lossy_bytes``/``bits_saved_ratio``. The
  default (``0``) keeps the exchange byte-exact, wire- and
  schema-identical.
- **Topology awareness**: hosts are ranked slice-major (slice topology
  from ``ZEST_COOP_TOPOLOGY`` — the sim override — or the JAX
  runtime's ``slice_index``, transfer.pod.local_slice_groups), so the
  many small early hypercube phases ride intra-slice (ICI-class)
  links and only the few large top-bit phases cross slices on DCN.
  Phase bytes are attributed per link class
  (``zest_coop_collective_bytes_total{link=ici|dcn}``).
- **Transport-agnostic** (ISSUE 20): the planner executes against the
  :class:`~zest_tpu.transfer.transport.ExchangeTransport` protocol —
  the pooled ``DcnChannel`` wire path (``ZEST_COLLECTIVE_BACKEND=dcn``,
  the default, argument-identical to the pre-split code), the jax ICI
  backend (intra-slice phases as device-to-device uint8 lane permutes,
  DCN/WAN phases on the wire), or the in-process loopback fabric the
  big simulations ride.
- **Degradation, never a stall**: the schedule is pull-based over the
  existing :class:`~zest_tpu.transfer.dcn.DcnChannel` transport, so a
  lagging partner is a bounded barrier wait (NOT_FOUND → whole-window
  retry with backoff, blamed to ``coop.collective.barrier`` spans),
  and a dead/straggling partner ABORTS the collective: every
  undelivered unit degrades to the PR-6 point-to-point exchange —
  which itself degrades per-unit to the quarantine + re-shard + CDN
  fallback ladder — and the pull always completes byte-identically.
  ``ZEST_COOP_COLLECTIVE=0`` restores the PR-6 exchange bit-for-bit.

The deterministic-schedule trick that removes the negotiation round:
in a pull-based all-gather, host ``r`` can compute exactly which units
its phase-``k`` partner holds (the partner's phase-``k`` subcube of
owners in the hypercube; the ``(r-1-k) mod N``-th ownership block in
the ring), because every host runs the same schedule over the same
plan. A request window therefore never asks for anything the partner
is not *scheduled* to have — NOT_FOUND means "partner behind", never
"wrong host", which is what makes the whole-window barrier retry
correct.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from zest_tpu import faults, telemetry
from zest_tpu.cas import hashing
from zest_tpu.config import parse_topology
from zest_tpu.transfer.dcn import FLAG_LOSSY, DcnResponse
from zest_tpu.transfer.transport import (
    TransportUnavailable, make_transport,
)

_M_PHASE_SECONDS = telemetry.histogram(
    "zest_coop_collective_phase_seconds",
    "Wall seconds per collective exchange phase",
    buckets=(0.005, 0.02, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0))
_M_COLLECTIVE_BYTES = telemetry.counter(
    "zest_coop_collective_bytes_total",
    "Collective exchange wire bytes by link class",
    ("link",))
_M_COLLECTIVE_PHASES = telemetry.gauge(
    "zest_coop_collective_phases",
    "Phase count of this host's last collective exchange")
_M_COLLECTIVE_WALL = telemetry.gauge(
    "zest_coop_collective_wall_seconds",
    "This host's last collective exchange wall time")
_M_COLLECTIVE_ABORTS = telemetry.counter(
    "zest_coop_collective_aborts_total",
    "Collective exchanges aborted to the point-to-point ladder")

# Phase sub-window sizing: a phase window is pre-sized from the plan,
# but its in-flight replies still stage under the round's ByteBudget —
# sub-windows bound how many pipelined replies are outstanding at
# once. Larger than the P2P exchange's 32 MiB/64-unit windows because
# a phase is ONE partner at a predictable rate, not N racing owners.
_PHASE_WINDOW_BYTES = 64 * 1024 * 1024
_PHASE_WINDOW_UNITS = 512
# Barrier pacing: a NOT_FOUND window means the partner has not reached
# this phase yet (it is still fetching its share, or in an earlier
# phase) — back off and re-request the WHOLE missing set as one window.
_BARRIER_SLEEP_S = 0.05
_BARRIER_SLEEP_CAP_S = 1.0

LINK_ICI = "ici"
LINK_DCN = "dcn"
LINK_WAN = "wan"


class CollectiveUnavailable(RuntimeError):
    """The collective cannot run for this round (unaddressable partner,
    degenerate topology): the caller falls back to the point-to-point
    exchange — same bytes, more round trips, never a failure."""


def slice_topology(n_hosts: int, cfg=None,
                   env: dict | None = None) -> tuple[int, ...]:
    """Slice id per host index, length ``n_hosts``.

    Resolution order: an explicit ``env`` dict's ``ZEST_COOP_TOPOLOGY``
    (callers that carry their own env — bare sims/tests; the process
    environment is NOT re-read here: ``Config.load`` already parses
    that knob once, strictly, into ``coop_topology``) >
    ``Config.coop_topology`` > the JAX runtime's per-process
    ``slice_index`` (transfer.pod.local_slice_groups — real
    multi-slice jobs) > one flat slice (every link ICI-class; the
    single-slice pod the north star quotes). A spec whose length
    disagrees with the round is a config error and raises ValueError
    (the coop round degrades it to the point-to-point exchange and
    records why)."""
    spec = (env or {}).get("ZEST_COOP_TOPOLOGY")
    topo = None
    if spec:
        topo = parse_topology(spec)
    elif cfg is not None and getattr(cfg, "coop_topology", None):
        topo = tuple(cfg.coop_topology)
    if topo is not None:
        if len(topo) != n_hosts:
            raise ValueError(
                f"ZEST_COOP_TOPOLOGY names {len(topo)} hosts for an "
                f"{n_hosts}-host round")
        return topo
    from zest_tpu.transfer.pod import local_slice_groups

    topo = local_slice_groups(n_hosts)
    return topo if topo is not None else (0,) * n_hosts


def pod_topology(n_hosts: int, cfg=None,
                 env: dict | None = None) -> tuple[int, ...] | None:
    """Pod id per host index, or ``None`` (no pod map — every host in
    one pod; the flat/hierarchical schedules, bit-for-bit today's
    behavior). Resolution mirrors :func:`slice_topology`: an explicit
    ``env`` dict's ``ZEST_COOP_PODS`` > ``Config.coop_pods`` > None.
    A spec whose length disagrees with the round raises ValueError."""
    spec = (env or {}).get("ZEST_COOP_PODS")
    pods = None
    if spec:
        pods = parse_topology(spec)
    elif cfg is not None and getattr(cfg, "coop_pods", None):
        pods = tuple(cfg.coop_pods)
    if pods is None:
        return None
    if len(pods) != n_hosts:
        raise ValueError(
            f"ZEST_COOP_PODS names {len(pods)} hosts for an "
            f"{n_hosts}-host round")
    return pods


def elect_gateways(plan, pods: tuple[int, ...]) -> dict[int, int]:
    """Deterministic gateway election: pod id → its lowest *alive*
    host index from the shared plan. Every host computes the same
    mapping from the same fingerprinted plan, so the election needs no
    round trips; a quarantined gateway is simply absent from
    ``plan.alive`` and the next-lowest member inherits the role."""
    out: dict[int, int] = {}
    for h in sorted(plan.alive):
        p = pods[h]
        if p not in out:
            out[p] = h
    return dict(sorted(out.items()))


@dataclass(frozen=True)
class Phase:
    """One step of this host's schedule: request from ``partner`` every
    plan unit owned by the hosts in ``owners`` (the set the partner is
    scheduled to hold by now)."""

    index: int
    partner: int                 # host index (not rank)
    owners: tuple[int, ...]      # host indices whose units to request
    link: str                    # "ici" | "dcn" | "wan"


def _is_pow2(n: int) -> bool:
    return n > 0 and n & (n - 1) == 0


@dataclass(frozen=True)
class CollectiveSchedule:
    """This host's deterministic phase schedule over ``plan.alive``.

    Three shapes, picked from the topology:

    - **hierarchical** (S ≥ 2 equal power-of-two slices of power-of-two
      size m ≥ 2): first a cross-slice all-gather among *counterpart
      groups* (the hosts at the same intra-slice position — each
      imports only its counterparts' OWN blocks, so the aggregate DCN
      traffic is ONE copy of each slice's data instead of one per
      receiving host), then an intra-slice all-gather spreads the
      imported blocks over ICI-class links. Cross-slice bytes per host
      drop to (S−1)/N of the total — vs (N−m)/N for the flat schedules
      and the point-to-point exchange — which is the "prefer
      intra-slice links" rule in byte form.
    - **hypercube** (flat, power-of-two hosts): recursive doubling,
      log2 N phases; ranks are slice-major so low-order-bit partners
      land intra-slice when a topology exists but the hierarchical
      conditions don't hold.
    - **ring** (anything else): N−1 phases pulling from the constant
      left neighbor.

    With a pod map (``pods``, from ZEST_COOP_PODS) naming ≥ 2 alive
    pods, the schedule becomes **federated** — the 3-level ICI < DCN <
    WAN generalization of the hierarchical shape:

    - **Stage A** — intra-pod all-gather of the pod's OWN blocks
      (hypercube when the pod's alive-member count is a power of two,
      ring otherwise; links classed by the slice topology as usual).
    - **Stage B** — WAN, gateways only: each pod's deterministically
      elected gateway (:func:`elect_gateways` — lowest alive host
      index) all-gathers the per-pod aggregates with the other
      gateways (recursive doubling over pods when the pod count is a
      power of two, ring over gateways otherwise). Aggregate WAN
      traffic is ONE copy of each pod's data per receiving pod —
      (P−1)/P of the total per gateway — instead of one per receiving
      host.
    - **Stage C** — intra-pod binomial-tree broadcast of the imported
      foreign blocks, gateway-first member order: the member at
      broadcast position p pulls everything foreign from position
      p − 2^⌊log2 p⌋ (its binomial parent). Pull + NOT_FOUND barrier
      makes the ordering self-synchronizing — a parent that has not
      finished its own pull yet is "behind", never "wrong".

    Every host computes every other host's schedule from the same plan
    + topology, which is what lets a request window name exactly the
    units its partner holds."""

    kind: str   # "hierarchical" | "hypercube" | "ring" | "federated"
    host: int
    alive: tuple[int, ...]       # rank order (pod- then slice-major)
    phases: tuple[Phase, ...]

    @staticmethod
    def build(plan, host_index: int,
              topology: tuple[int, ...],
              pods: tuple[int, ...] | None = None,
              ) -> "CollectiveSchedule":
        if host_index not in plan.alive:
            raise CollectiveUnavailable(
                f"host {host_index} is not in the plan's alive set")
        if max(plan.alive) >= len(topology):
            raise ValueError(
                f"topology names {len(topology)} hosts but the plan "
                f"includes host {max(plan.alive)}")
        if pods is not None and max(plan.alive) >= len(pods):
            raise ValueError(
                f"pod map names {len(pods)} hosts but the plan "
                f"includes host {max(plan.alive)}")

        def link(a: int, b: int) -> str:
            if pods is not None and pods[a] != pods[b]:
                return LINK_WAN
            return LINK_ICI if topology[a] == topology[b] else LINK_DCN

        if pods is not None \
                and len({pods[h] for h in plan.alive}) >= 2:
            return CollectiveSchedule._build_federated(
                plan, host_index, topology, pods, link)

        order = tuple(sorted(plan.alive, key=lambda h: (topology[h], h)))
        n = len(order)
        if n < 2:
            raise CollectiveUnavailable("nothing to exchange with")
        rank = {h: i for i, h in enumerate(order)}
        r = rank[host_index]

        # Slice groups in rank order (slice-major ⇒ contiguous).
        slices: list[list[int]] = []
        for h in order:
            if slices and topology[slices[-1][0]] == topology[h]:
                slices[-1].append(h)
            else:
                slices.append([h])
        s_count = len(slices)
        m = len(slices[0])
        hier = (s_count >= 2 and m >= 2 and _is_pow2(s_count)
                and _is_pow2(m)
                and all(len(sl) == m for sl in slices))

        phases: list[Phase] = []
        if hier:
            kind = "hierarchical"
            gidx, pos = next(
                (gi, sl.index(host_index))
                for gi, sl in enumerate(slices) if host_index in sl)
            members = slices[gidx]
            group = [sl[pos] for sl in slices]  # my counterpart group
            # Stage A — cross-slice all-gather of the counterparts'
            # OWN blocks (recursive doubling over the group).
            for k in range(s_count.bit_length() - 1):
                pg = gidx ^ (1 << k)
                owners = tuple(group[pg ^ q] for q in range(1 << k))
                phases.append(Phase(len(phases), group[pg], owners,
                                    link(host_index, group[pg])))
            # Stage B — intra-slice all-gather where each member
            # contributes its whole counterpart group (own block +
            # everything stage A imported).
            for k in range(m.bit_length() - 1):
                pp = pos ^ (1 << k)
                owners = tuple(
                    sl[pp ^ q]
                    for q in range(1 << k) for sl in slices)
                phases.append(Phase(len(phases), members[pp], owners,
                                    link(host_index, members[pp])))
        elif _is_pow2(n):
            kind = "hypercube"
            for k in range(n.bit_length() - 1):
                p = r ^ (1 << k)
                owners = tuple(order[p ^ q] for q in range(1 << k))
                phases.append(Phase(k, order[p], owners,
                                    link(host_index, order[p])))
        else:
            kind = "ring"
            left = order[(r - 1) % n]
            for k in range(n - 1):
                owner = order[(r - 1 - k) % n]
                phases.append(Phase(k, left, (owner,),
                                    link(host_index, left)))
        return CollectiveSchedule(kind, host_index, order, tuple(phases))

    @staticmethod
    def _build_federated(plan, host_index: int,
                         topology: tuple[int, ...],
                         pods: tuple[int, ...],
                         link) -> "CollectiveSchedule":
        pod_ids = sorted({pods[h] for h in plan.alive})
        members_by_pod = {
            p: sorted((h for h in plan.alive if pods[h] == p),
                      key=lambda h: (topology[h], h))
            for p in pod_ids
        }
        gateways = elect_gateways(plan, pods)
        my_pod = pods[host_index]
        members = members_by_pod[my_pod]
        gw = gateways[my_pod]
        phases: list[Phase] = []

        # Stage A — intra-pod all-gather of this pod's OWN blocks.
        m = len(members)
        r = members.index(host_index)
        if m >= 2:
            if _is_pow2(m):
                for k in range(m.bit_length() - 1):
                    p = r ^ (1 << k)
                    owners = tuple(members[p ^ q] for q in range(1 << k))
                    phases.append(Phase(len(phases), members[p], owners,
                                        link(host_index, members[p])))
            else:
                left = members[(r - 1) % m]
                for k in range(m - 1):
                    owner = members[(r - 1 - k) % m]
                    phases.append(Phase(len(phases), left, (owner,),
                                        link(host_index, left)))

        if host_index == gw:
            # Stage B — WAN, gateways only: all-gather the per-pod
            # aggregates (a phase's owners are EVERY alive host of the
            # pods in the partner gateway's subcube/ring block).
            pcount = len(pod_ids)
            pr = pod_ids.index(my_pod)
            if _is_pow2(pcount):
                for k in range(pcount.bit_length() - 1):
                    pp = pr ^ (1 << k)
                    owners = tuple(
                        h for q in range(1 << k)
                        for h in members_by_pod[pod_ids[pp ^ q]])
                    partner = gateways[pod_ids[pp]]
                    phases.append(Phase(len(phases), partner, owners,
                                        link(host_index, partner)))
            else:
                left_gw = gateways[pod_ids[(pr - 1) % pcount]]
                for k in range(pcount - 1):
                    op = pod_ids[(pr - 1 - k) % pcount]
                    owners = tuple(members_by_pod[op])
                    phases.append(Phase(len(phases), left_gw, owners,
                                        link(host_index, left_gw)))
        else:
            # Stage C — intra-pod binomial broadcast of the foreign
            # blocks, gateway-first order: position p pulls from its
            # binomial parent p − 2^⌊log2 p⌋. One phase per member;
            # the NOT_FOUND barrier sequences the tree.
            foreign = tuple(
                h for p in pod_ids if p != my_pod
                for h in members_by_pod[p])
            bcast = [gw] + [h for h in members if h != gw]
            bpos = bcast.index(host_index)
            src = bcast[bpos - (1 << (bpos.bit_length() - 1))]
            phases.append(Phase(len(phases), src, foreign,
                                link(host_index, src)))

        order = tuple(sorted(
            plan.alive, key=lambda h: (pods[h], topology[h], h)))
        return CollectiveSchedule("federated", host_index, order,
                                  tuple(phases))


def units_by_owner(plan) -> dict[int, list]:
    """``{owner_host: [(hash_hex, FetchInfo), ...]}`` over the plan —
    the blocks the schedule's phases are expressed in."""
    out: dict[int, list] = {h: [] for h in plan.alive}
    for (hh, _start), fi in plan.units:
        out[plan.owners[(hh, _start)]].append((hh, fi))
    return out


def transfer_matrix(plan, topology: tuple[int, ...],
                    pods: tuple[int, ...] | None = None,
                    ) -> list[list[int]]:
    """The full N×N wire-byte matrix the schedule implies:
    ``matrix[src][dst]`` = bytes host ``dst`` requests from host ``src``
    across every phase of its schedule (indexed by host, zeros for
    quarantined hosts). Derived purely from the plan + topology — the
    no-negotiation proof the determinism tests pin: every byte a host
    receives is requested exactly once, and per-owner received bytes
    equal the plan's ownership row."""
    n = plan.n_hosts
    blocks = units_by_owner(plan)
    block_bytes = {
        h: sum(fi.url_range_end - fi.url_range_start for _hh, fi in us)
        for h, us in blocks.items()
    }
    matrix = [[0] * n for _ in range(n)]
    for dst in plan.alive:
        sched = CollectiveSchedule.build(plan, dst, topology, pods)
        for ph in sched.phases:
            matrix[ph.partner][dst] += sum(
                block_bytes[o] for o in ph.owners)
    return matrix


def matrix_skew(matrix: list[list[int]]) -> float:
    """max per-host sent bytes over mean sent bytes (1.0 = perfectly
    balanced links)."""
    sent = [sum(row) for row in matrix if sum(row)]
    if not sent:
        return 1.0
    return max(sent) / (sum(sent) / len(sent))


def run_collective(bridge, plan, host_index: int,
                   peers: dict[int, tuple[str, int]], pool, budget,
                   ex, verify, deadline: float,
                   topology: tuple[int, ...],
                   priorities: dict | None = None,
                   entries_map: dict | None = None,
                   health=None,
                   pods: tuple[int, ...] | None = None,
                   transport=None,
                   ) -> tuple[dict, dict[int, list]]:
    """Execute this host's phase schedule. Returns
    ``(stats, leftover_by_owner)`` — ``leftover_by_owner`` is empty on
    success; after an abort it maps TRUE owner host → undelivered
    units, ready for the point-to-point exchange ladder.

    ``transport`` overrides the configured exchange backend
    (``ZEST_COLLECTIVE_BACKEND`` → ``Config.collective_backend`` →
    :func:`~zest_tpu.transfer.transport.make_transport` over ``pool``).

    Raises :class:`CollectiveUnavailable` (before any wire traffic)
    when a scheduled partner has no address or the configured backend
    cannot be built — the caller runs the full P2P exchange instead.
    """
    from zest_tpu.transfer.coop import (
        _admit, _admit_lossy, _already_cached, _fallback, _layer_order,
    )

    sched = CollectiveSchedule.build(plan, host_index, topology, pods)
    for ph in sched.phases:
        if ph.partner not in peers:
            raise CollectiveUnavailable(
                f"phase {ph.index} partner host {ph.partner} has no "
                "DCN address")
    if transport is None:
        backend = getattr(bridge.cfg, "collective_backend", "dcn")
        try:
            transport = make_transport(backend, pool, plan=plan)
        except TransportUnavailable as exc:
            raise CollectiveUnavailable(str(exc)) from exc
    # Lossy arming (ZEST_COLLECTIVE_LOSSY): which link classes may
    # carry quantized payloads. Once ANY link is armed, every window
    # also advertises "lossy acceptable" (FLAG_LOSSY_OK) so a partner
    # can forward a staged container it received over an armed link —
    # store-and-forward schedules re-serve imported blocks on links
    # that would not quantize FRESH data themselves.
    mode = str(getattr(bridge.cfg, "collective_lossy", "0") or "0")
    lossy_links = {"dcn": {LINK_DCN, LINK_WAN},
                   "wan": {LINK_WAN}}.get(mode, set())
    blocks = units_by_owner(plan)
    mtx = transfer_matrix(plan, topology, pods)

    t0 = time.monotonic()
    phase_walls: list[float] = []
    link_bytes = {LINK_ICI: 0, LINK_DCN: 0}
    if pods is not None:
        # The wan key exists only under a pod map — without
        # ZEST_COOP_PODS the stats schema is bit-for-bit PR-13's.
        link_bytes[LINK_WAN] = 0
    windows = requests = retry_windows = 0
    barrier_s = 0.0
    window_cap = min(_PHASE_WINDOW_BYTES, budget.budget_bytes)

    stats: dict = {
        "schedule": sched.kind,
        "phases": len(sched.phases),
        "phase_wall_s": phase_walls,
        "matrix_skew": round(matrix_skew(mtx), 4),
        "link_bytes": link_bytes,
        "windows": 0,
        "requests": 0,
        "retry_windows": 0,
        # Per-unit request/reply round trips outside a phase window —
        # structurally zero: the collective only ever issues whole
        # (sub-)window batches. The smoke gate asserts it via the
        # pool's wire-tag counters.
        "unit_round_trips": 0,
        "barrier_wait_s": 0.0,
    }
    if transport.name != "dcn":
        # Present only off the default backend — with
        # ZEST_COLLECTIVE_BACKEND=dcn the stats schema stays
        # bit-for-bit PR-13's (the restore-pre-split pin).
        stats["backend"] = transport.name
    if lossy_links:
        stats["lossy"] = mode

    def finish(aborted: str | None = None,
               dead_host: int | None = None) -> dict:
        telemetry.timeline.clear("collective.")
        stats["windows"] = windows
        stats["requests"] = requests
        stats["retry_windows"] = retry_windows
        stats["barrier_wait_s"] = round(barrier_s, 3)
        stats["elapsed_s"] = round(time.monotonic() - t0, 3)
        if aborted:
            stats["aborted"] = aborted
            if dead_host is not None:
                stats["dead_host"] = dead_host
        _M_COLLECTIVE_PHASES.set(float(len(sched.phases)))
        _M_COLLECTIVE_WALL.set(time.monotonic() - t0)
        return stats

    def leftovers(from_phase: int, pending: list) -> dict[int, list]:
        """Undelivered foreign units by TRUE owner: the current phase's
        remainder plus every later phase's blocks (minus anything a
        whole-xorb sibling admit already covered)."""
        out: dict[int, list] = {}
        for hh, fi in pending:
            if not _already_cached(bridge, hh, fi):
                out.setdefault(plan.owners[(hh, fi.range.start)],
                               []).append((hh, fi))
        for ph in sched.phases[from_phase + 1:]:
            for o in ph.owners:
                for hh, fi in blocks[o]:
                    if not _already_cached(bridge, hh, fi):
                        out.setdefault(o, []).append((hh, fi))
        return out

    # Remediation action target (ISSUE 17): the policy engine's
    # collective_straggler handler. "strike" feeds the blamed partner
    # into peer health — quarantine re-shard then re-plans ownership
    # around it on the next round; past the patience budget "abort"
    # requests a mid-round abort, which the barrier-retry loop honors
    # by returning the leftovers down the point-to-point ladder
    # instead of waiting the deadline out.
    abort_req: dict = {}

    def _remediate_cmd(cmd: str, partner: int) -> dict:
        if cmd == "strike":
            if health is not None and partner in peers:
                try:
                    health.record_failure(peers[partner],
                                          kind="straggler")
                except Exception:  # noqa: BLE001 - health is advisory
                    pass
            return {"cmd": "strike", "partner": partner}
        if cmd == "abort":
            abort_req["partner"] = partner
            return {"cmd": "abort", "partner": partner}
        return {"cmd": cmd, "partner": partner, "ignored": True}

    telemetry.remediate.register_target("collective", _remediate_cmd)

    try:
        for ph in sched.phases:
            host, port = peers[ph.partner]
            wants = [(hh, fi) for o in ph.owners for hh, fi in blocks[o]
                     if not _already_cached(bridge, hh, fi)]
            wants = _layer_order(wants, priorities)
            t_phase = time.monotonic()
            # Live cells for the timeline sampler (ISSUE 15): the current
            # phase index + partner and the cumulative barrier wait — what
            # the per-phase straggler rule attributes from. Cleared by
            # finish() so a finished exchange stops reporting a phase.
            telemetry.timeline.post("collective.phase", ph.index)
            telemetry.timeline.post("collective.partner", ph.partner)
            telemetry.timeline.post("collective.barrier_s", barrier_s)
            sleep_s = _BARRIER_SLEEP_S
            # Distinguishes a barrier RE-request (the missing set after a
            # NOT_FOUND round — partner lag) from plain pagination (a phase
            # larger than one sub-window): only the former is a retry.
            retry_pass = False
            with telemetry.span(f"coop.collective.phase{ph.index}",
                                partner=ph.partner, link=ph.link,
                                units=len(wants)):
                pending = list(wants)
                while pending:
                    window, wire_est = [], 0
                    while pending and len(window) < _PHASE_WINDOW_UNITS:
                        nbytes = (pending[0][1].url_range_end
                                  - pending[0][1].url_range_start)
                        if window and wire_est + nbytes > window_cap:
                            break
                        window.append(pending.pop(0))
                        wire_est += nbytes
                    budget.acquire(wire_est)
                    try:
                        if faults.fire("peer_timeout", key=f"{host}:{port}"):
                            raise TimeoutError("injected peer_timeout")
                        replies = transport.request_window(
                            ph.partner, (host, port),
                            [(hashing.hex_to_hash(hh), fi.range.start,
                              fi.range.end) for hh, fi in window],
                            timeout=max(1.0, deadline - time.monotonic()),
                            tag=transport.window_tag(),
                            link=ph.link,
                            lossy_ok=bool(lossy_links),
                            quant_ok=ph.link in lossy_links,
                        )
                        windows += 1
                        requests += len(window)
                        if retry_pass:
                            retry_windows += 1
                            retry_pass = False
                    except (ConnectionError, TimeoutError, OSError) as exc:
                        budget.release(wire_est)
                        with ex.lock:
                            ex.dead_hosts.add(ph.partner)
                        _M_COLLECTIVE_ABORTS.inc()
                        telemetry.record(
                            "collective_abort", phase=ph.index,
                            partner=ph.partner, link=ph.link,
                            error=type(exc).__name__)
                        if health is not None:
                            try:
                                health.record_failure((host, port),
                                                      kind="io_timeout")
                            except Exception:  # noqa: BLE001 - advisory
                                pass
                        return (finish(aborted=type(exc).__name__,
                                       dead_host=ph.partner),
                                leftovers(ph.index, window + pending))
                    missing = []
                    try:
                        for (hh, fi), reply in zip(window, replies):
                            if isinstance(reply, DcnResponse) \
                                    and reply.flags & FLAG_LOSSY:
                                # Quantized container: admissible to
                                # the HBM staging overlay only — never
                                # the merkle-verified cache. A partner
                                # can only send this after we opted in
                                # (FLAG_LOSSY_OK on the request).
                                admitted, wire, unpacked, exact = \
                                    _admit_lossy(bridge, hh, fi, reply)
                                if admitted:
                                    bridge.stats.record("peer", wire)
                                    ex.book_exchange(
                                        (hh, fi.range.start), wire,
                                        unpacked, link=ph.link,
                                        lossy_exact=exact)
                                    link_bytes[ph.link] += wire
                                    _M_COLLECTIVE_BYTES.inc(
                                        wire, link=ph.link)
                                else:
                                    with ex.lock:
                                        ex.verify_rejected += 1
                                    telemetry.record(
                                        "verify_rejected", unit=hh[:16],
                                        owner=ph.partner,
                                        tier="collective")
                                    _fallback(bridge, entries_map,
                                              [(hh, fi)], ex,
                                              owner=ph.partner)
                                continue
                            admitted, wire, unpacked = _admit(
                                bridge, entries_map, hh, fi, reply, verify)
                            if admitted:
                                bridge.stats.record("peer", wire)
                                ex.book_exchange((hh, fi.range.start),
                                                 wire, unpacked,
                                                 link=ph.link)
                                link_bytes[ph.link] += wire
                                _M_COLLECTIVE_BYTES.inc(wire, link=ph.link)
                            elif isinstance(reply, DcnResponse):
                                # Structurally or content-bad bytes from a
                                # live partner: never retried (the same
                                # bytes would come back) — the unit heals
                                # through the full waterfall, exactly the
                                # P2P exchange's trust-boundary rule.
                                with ex.lock:
                                    ex.verify_rejected += 1
                                telemetry.record("verify_rejected",
                                                 unit=hh[:16],
                                                 owner=ph.partner,
                                                 tier="collective")
                                _fallback(bridge, entries_map, [(hh, fi)],
                                          ex, owner=ph.partner)
                            else:
                                missing.append((hh, fi))  # partner behind
                    finally:
                        budget.release(wire_est)
                    if missing:
                        if abort_req:
                            # The remediation engine's patience ran out
                            # on this straggler: abort NOW instead of
                            # burning barrier backoff up to the
                            # deadline — the leftovers go down the
                            # point-to-point ladder, which re-plans
                            # ownership around the quarantined partner.
                            with ex.lock:
                                ex.dead_hosts.add(ph.partner)
                            _M_COLLECTIVE_ABORTS.inc()
                            telemetry.record(
                                "collective_abort", phase=ph.index,
                                partner=ph.partner, link=ph.link,
                                error="remediation")
                            return (finish(aborted="remediation",
                                           dead_host=ph.partner),
                                    leftovers(ph.index, missing + pending))
                        if time.monotonic() + sleep_s > deadline:
                            _M_COLLECTIVE_ABORTS.inc()
                            telemetry.record(
                                "collective_abort", phase=ph.index,
                                partner=ph.partner, link=ph.link,
                                error="deadline")
                            return (finish(aborted="deadline",
                                           dead_host=ph.partner),
                                    leftovers(ph.index, missing + pending))
                        # Phase barrier: the partner has not finished the
                        # prior phase (or its fetch share). Its own span so
                        # the critical-path analyzer blames lag as
                        # barrier idle, not exchange work.
                        with telemetry.span("coop.collective.barrier",
                                            phase=ph.index,
                                            partner=ph.partner,
                                            units=len(missing)):
                            time.sleep(sleep_s)
                        barrier_s += sleep_s
                        telemetry.timeline.post("collective.barrier_s",
                                                barrier_s)
                        sleep_s = min(sleep_s * 2, _BARRIER_SLEEP_CAP_S)
                        retry_pass = True
                        pending = missing + pending
            wall = time.monotonic() - t_phase
            phase_walls.append(round(wall, 4))
            _M_PHASE_SECONDS.observe(wall)
            if health is not None:
                try:
                    health.record_success((host, port))
                except Exception:  # noqa: BLE001 - health is advisory
                    pass
        return finish(), {}
    finally:
        telemetry.remediate.unregister_target("collective",
                                              _remediate_cmd)
