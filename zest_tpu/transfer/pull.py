"""``pull``: download a model repo through the swarm into the HF cache.

The reference's ``cmdPull`` (src/main.zig:83-305): resolve revision, list
files, then per file run the 3-deep fallback chain — parallel reconstruct →
sequential bridge reconstruct → plain CDN download — and finish by writing
the refs file so ``from_pretrained()`` resolves offline. Already-cached
files are skipped (idempotent resume; SURVEY.md §5 "checkpoint/resume").

With ``device="tpu"`` the pulled checkpoint is additionally staged into
TPU HBM via zest_tpu.parallel (the north-star path; no reference
counterpart).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait
from pathlib import Path

from zest_tpu import faults, storage, telemetry
from zest_tpu.cas.hub import HubClient
from zest_tpu.config import Config
from zest_tpu.transfer import tenancy
from zest_tpu.transfer.bridge import XetBridge
from zest_tpu.transfer.parallel import ParallelDownloader
from zest_tpu.transfer.tenancy import (  # noqa: F401 - ByteBudget re-export
    ByteBudget,
    CancelToken,
    PullCancelled,
)

_M_PULLS = telemetry.counter(
    "zest_pulls_total", "Pulls finished, by outcome", ("outcome",))
_M_PULL_SECONDS = telemetry.histogram(
    "zest_pull_seconds", "End-to-end pull wall time")
_M_TTH_SECONDS = telemetry.histogram(
    "zest_time_to_hbm_seconds", "Pull start → weights resident in HBM")
_M_TTFL_SECONDS = telemetry.histogram(
    "zest_time_to_first_layer_seconds",
    "Pull start → first-token-capable set (embedding + layer 0) "
    "resident in HBM (streaming landing)")
# Last-pull wall gauges: the live first-layer-vs-HBM line the
# dashboard / `zest stats --watch` renders (histograms aggregate; the
# operator's question is "how did the LAST landing do").
_M_LAST_TTFL = telemetry.gauge(
    "zest_last_pull_first_layer_seconds",
    "time_to_first_layer_s of the most recent streaming landing")
_M_LAST_TTH = telemetry.gauge(
    "zest_last_pull_hbm_seconds",
    "time_to_hbm_s of the most recent --device pull")
_M_LAST_RING_STALLS = telemetry.gauge(
    "zest_last_pull_ring_stalls",
    "Ring producer stalls during the most recent streaming landing "
    "(the cumulative zest_land_ring_stalls_total would misattribute "
    "earlier pulls' stalls to the last one)")
_M_LAST_DELTA_RATIO = telemetry.gauge(
    "zest_last_pull_delta_ratio",
    "Network-fetched fraction of the most recent pull's checkpoint "
    "bytes when a delta plan ran (0.0 = fully reused from the local "
    "cache); -1 when the last pull was not a delta")
_M_LAST_SWAP = telemetry.gauge(
    "zest_last_pull_swap_seconds",
    "time_to_swap_s of the most recent in-place hot-swap delta pull "
    "(0 when the last pull was not a hot-swap)")
_M_STAGE_SECONDS = telemetry.histogram(
    "zest_stage_seconds", "Per-entry stage wall time", ("stage",))
_M_STAGE_BYTES = telemetry.counter(
    "zest_stage_bytes_total", "Payload bytes attributed per stage",
    ("stage",))
_M_FILES_BYTES = telemetry.counter(
    "zest_files_bytes_total",
    "HF-cache bytes materialized by the background files lane, by lane",
    ("lane",))
_M_SLO_BREACHES = telemetry.counter(
    "zest_slo_breaches_total",
    "Pulls that breached an armed SLO budget (ZEST_SLO_TTHBM_S / "
    "ZEST_SLO_TTFL_S)", ("slo",))


class PullResult:
    """What a pull produced: the snapshot path, stats, and — for
    ``device="tpu"`` — the staged param tree. The result *owns* the HBM
    buffers: drop it (or set ``params = None``) to release them."""

    def __init__(self, snapshot_dir: Path, stats: dict, params=None):
        self.snapshot_dir = snapshot_dir
        self.stats = stats
        self.params = params  # name → jax.Array, or None

    def __fspath__(self) -> str:
        return str(self.snapshot_dir)

    def __str__(self) -> str:
        return str(self.snapshot_dir)


class StageClock:
    """Per-stage timing for one pull — the tracing story SURVEY.md §5
    asks for (the reference only prints end-of-pull totals,
    swarm.zig:472-485).

    The pipelined pull broke the old "stages are additive and
    non-overlapping" invariant on purpose: several worker threads can sit
    inside ``files`` at once, and ``files`` runs concurrently with
    ``hbm_commit``. The clock therefore records raw ``(start, end)``
    intervals (thread-safe) and reports two views:

    - :meth:`summary` — per-stage *wall* time: union coverage of the
      stage's intervals. Concurrent entries into the same stage count
      once, so a stage's wall never exceeds the pull's elapsed time.
    - :meth:`busy_summary` — per-stage *busy* time: summed thread-seconds.
      ``busy > wall`` is the direct evidence of intra-stage parallelism;
      ``busy(a) + busy(b) > span(a, b)`` is the evidence two stages
      overlapped (the bench's attribution for pipelining wins).

    ``note_bytes`` attributes payload bytes to a stage so
    :meth:`gbps_summary` can report per-stage effective throughput.

    Since the telemetry subsystem landed, the clock is a thin adapter
    over :func:`zest_tpu.telemetry.span`: every stage entry opens a
    ``stage.<name>`` span (so a ``ZEST_TRACE`` trace shows the exact
    same intervals the stats report) and mirrors its duration/bytes
    into the process metrics registry. The interval bookkeeping — and
    with it the ``stats["stages*"]`` schema and the bench's overlap
    evidence — is unchanged bit-for-bit: the summaries are computed
    from the same ``(start, end)`` pairs as before, whether telemetry
    is on or off.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._intervals: dict[str, list[tuple[float, float]]] = {}
        self._bytes: dict[str, int] = {}
        # Coarse stage-entry/exit observer (the pull session's live
        # ``phase``, ISSUE 11): a handful of calls per pull, never per
        # chunk — and never allowed to break the pull itself.
        self.observer = None

    def _notify(self, stage: str, entered: bool) -> None:
        obs = self.observer
        if obs is not None:
            try:
                obs(stage, entered)
            except Exception:  # noqa: BLE001 - observers are advisory
                pass

    @contextlib.contextmanager
    def __call__(self, stage: str):
        t0 = time.monotonic()
        self._notify(stage, True)
        try:
            with telemetry.span(f"stage.{stage}"):
                yield
        finally:
            t1 = time.monotonic()
            with self._lock:
                self._intervals.setdefault(stage, []).append((t0, t1))
            _M_STAGE_SECONDS.observe(t1 - t0, stage=stage)
            self._notify(stage, False)

    def ensure(self, stage: str) -> None:
        """Materialize a stage key even when nothing entered it (an
        all-skipped ``files`` stage must still report 0.0, not vanish)."""
        with self._lock:
            self._intervals.setdefault(stage, [])

    def note_interval(self, stage: str, t0: float, t1: float) -> None:
        """Record an interval measured elsewhere (monotonic seconds) —
        the streaming landing's ``first_layer`` span is anchored at the
        pull's own t0, which no ``with clock(...)`` block brackets."""
        if t1 < t0:
            t0, t1 = t1, t0
        with self._lock:
            self._intervals.setdefault(stage, []).append((t0, t1))
        _M_STAGE_SECONDS.observe(t1 - t0, stage=stage)

    def note_bytes(self, stage: str, nbytes: int) -> None:
        with self._lock:
            self._bytes[stage] = self._bytes.get(stage, 0) + int(nbytes)
        _M_STAGE_BYTES.inc(int(nbytes), stage=stage)

    @staticmethod
    def _coverage(intervals: list[tuple[float, float]]) -> float:
        total = 0.0
        end = float("-inf")
        for s, e in sorted(intervals):
            if s > end:
                total += e - s
                end = e
            elif e > end:
                total += e - end
                end = e
        return total

    def span(self, *stages: str) -> float:
        """Union wall-clock coverage across several stages combined —
        the denominator of the overlap attribution."""
        with self._lock:
            ivs = [iv for s in stages for iv in self._intervals.get(s, [])]
        return self._coverage(ivs)

    def coverage_after(self, stage: str, t: float) -> float:
        """Union coverage of ``stage`` clipped to after monotonic time
        ``t`` — the background-lane evidence: ``files`` coverage after
        the HBM landing finished is exactly the materialization work
        that ran off the time-to-HBM span."""
        with self._lock:
            ivs = [(max(s, t), e)
                   for s, e in self._intervals.get(stage, []) if e > t]
        return self._coverage(ivs)

    def summary(self) -> dict[str, float]:
        with self._lock:
            items = {k: list(v) for k, v in self._intervals.items()}
        return {k: round(self._coverage(v), 4) for k, v in items.items()}

    def busy_summary(self) -> dict[str, float]:
        with self._lock:
            return {
                k: round(sum(e - s for s, e in v), 4)
                for k, v in self._intervals.items()
            }

    def gbps_summary(self) -> dict[str, float]:
        """Effective GB/s for stages with noted bytes (wall-based)."""
        walls = self.summary()
        with self._lock:
            noted = dict(self._bytes)
        return {
            k: round(n / walls[k] / 1e9, 3)
            for k, n in noted.items()
            if walls.get(k, 0.0) > 1e-3
        }


def _resolve_files_workers(n: int | None) -> int:
    """Materialization pool width: explicit value, else auto (2–4 by
    core count — the lane is disk-bound, so even a 1-core host gets two
    writers to overlap write submission with fsync/allocation waits)."""
    if n and n > 0:
        return int(n)
    return max(2, min(4, os.cpu_count() or 1))


def _hdr_fan(fn, items):
    """Map ``fn`` over independent KB-scale metadata fetches
    (reconstructions, safetensors headers) with one bounded pool —
    serialized they put shards × RTT on the time_to_first_layer
    critical path; the single definition keeps every fan-out site
    (coop priorities, the landing's rec+header resolve) on the same
    width and thread naming."""
    items = list(items)
    if len(items) <= 1:
        return [fn(x) for x in items]
    with ThreadPoolExecutor(min(8, len(items)),
                            thread_name_prefix="zest-hdr") as pool:
        return list(pool.map(fn, items))


def _is_complete(snapshot_dir: Path, entry) -> bool:
    """One definition of "this file is already pulled" — shared by the
    pod pre-pass, the download loop's skip, and the direct-landing
    eligibility check, so the three never disagree about resume state."""
    dest = snapshot_dir / entry.path
    return dest.exists() and dest.stat().st_size == entry.size


# ByteBudget moved to transfer.tenancy (re-exported above): with
# tenancy on, ONE instance is shared by every admitted session — the
# aggregate in-flight byte budget — so the class lives with the other
# shared-pool machinery. Semantics unchanged.


class _FilePipeline:
    """Bounded worker pool writing the HF-cache files concurrently.

    Files are independent by construction (per-file work is offset-
    addressed into a private tmp file, committed by atomic rename), so
    the old per-file serial loop becomes ``width`` workers fed by
    ``submit``; a :class:`ByteBudget` bounds in-flight blob bytes so a
    wide pipeline cannot hold every shard's working set at once (the
    bounded-memory producer/consumer argument from "Bounded-Memory
    Parallel Image Pulling", PAPERS.md). ``submit`` dedups by path —
    the direct landing hands each shard over via ``submit_prepared``
    the moment its host tensors are decoded (write-behind), and the
    tail submit-everything pass catches the rest.

    **The materialization lane is a background stage** (ISSUE 5): with
    ``async_handoff`` (``ZEST_FILES_ASYNC``, default on) the write-
    behind handoff never blocks the landing — a full byte budget makes
    ``submit_prepared`` *decline* (the shard falls to the post-commit
    cache lane) instead of parking the decode thread, and the prepared
    pool is ``materialize_workers`` wide (``ZEST_FILES_WORKERS``) so
    shards materialize concurrently, during and after the landing.
    Prepared writes land under temp names and register with
    :meth:`defer_commit`; the durability barrier (fsync + atomic
    rename, :func:`zest_tpu.storage.durable_replace`) runs only in
    :meth:`join` at pull exit — a pull killed any time before that
    leaves *no* complete-named partial file, and the re-pull converges
    from the idempotent xorb cache.

    First error wins: it cancels queued work, ``join`` drains in-flight
    workers (each file is atomic, so a cancelled pull leaves only
    complete files — the ``_is_complete`` resume contract), then
    re-raises."""

    def __init__(self, width: int, budget_bytes: int, clock: StageClock,
                 work, term_executor: ThreadPoolExecutor | None = None,
                 skip_check=None, materialize_workers: int = 1,
                 async_handoff: bool = True, budget: ByteBudget | None = None,
                 cancel: CancelToken | None = None):
        self.width = max(1, int(width))
        # ``budget``: the tenancy-shared aggregate ByteBudget — the
        # per-pull budget then STACKS under it (both bounds hold: the
        # session's own ZEST_PULL_INFLIGHT peak and the process-wide
        # ZEST_TENANT_INFLIGHT cap). Absent, the per-pull budget alone,
        # as before. ``cancel``: the session's token, checked per file
        # so an aborted pull stops submitting work at the next boundary.
        local_budget = ByteBudget(budget_bytes)
        self.budget = (local_budget if budget is None
                       else tenancy.StackedBudget(local_budget, budget))
        self.cancel_token = cancel
        self.clock = clock
        self.work = work  # work(entry) -> "downloaded" | "skipped"
        # Cheap completeness probe run BEFORE the budget acquire: a
        # resume pull of already-complete multi-GiB shards must not
        # serialize its no-op skips through the byte budget.
        self.skip_check = skip_check
        # The shared term-fetch pool the per-file ParallelDownloader
        # rides (bounds total fetch streams across concurrent files);
        # owned here, torn down by join().
        self.term_executor = term_executor
        self.async_handoff = async_handoff
        self.materialize_workers = max(1, int(materialize_workers))
        self.downloaded = 0
        self.skipped = 0
        self.declined = 0
        # Bytes materialized per lane: "tensors" (write-behind from the
        # landing's decoded buffers), "copy" (copy_file_range from
        # cached entries), "decode" (cache-decode), "waterfall"
        # (refetched through the 3-deep chain + regular files).
        self.lane_bytes: dict[str, int] = {}
        self._pending_commits: list[tuple[str, Path]] = []
        # Session attribution for worker threads (ISSUE 11): pool
        # threads outlive any one task, so each task re-binds the
        # session id the pipeline was built under — recorder events
        # from file workers (budget declines, fault sites downstream)
        # then attribute to the right pull even with several running.
        self._session_id = telemetry.session.current_id()
        self._lock = threading.Lock()
        self._cancel = threading.Event()
        self._futures: dict[str, object] = {}
        self._pool = ThreadPoolExecutor(
            self.width, thread_name_prefix="zest-pull-file")
        # Prepared (write-behind) jobs hold budget bytes from enqueue
        # time, so they must NEVER queue behind budget-waiting plain
        # workers: a dedicated writer pool guarantees the oldest
        # budget holder can always run — the holder always progresses,
        # releases, and unblocks any workers parked in acquire().
        # (Sharing self._pool would deadlock: all workers blocked in
        # acquire while the only releaser sits queued behind them.)
        self._prepared_pool = ThreadPoolExecutor(
            self.materialize_workers,
            thread_name_prefix="zest-pull-writeback")

    def note_lane(self, lane: str, nbytes: int) -> None:
        """Attribute materialized bytes to a lane (pull stats + the
        process-wide ``zest_files_bytes_total{lane}`` counter)."""
        with self._lock:
            self.lane_bytes[lane] = self.lane_bytes.get(lane, 0) + int(nbytes)
        _M_FILES_BYTES.inc(int(nbytes), lane=lane)

    def defer_commit(self, tmp: str, dest: Path) -> None:
        """Register a fully written temp file for the exit barrier."""
        with self._lock:
            self._pending_commits.append((tmp, dest))

    def submit(self, entry) -> None:
        with self._lock:
            if entry.path in self._futures:
                return
            self._futures[entry.path] = self._pool.submit(self._run, entry)

    def submit_prepared(self, entry, prepared) -> bool:
        """Submit a file whose payload the caller already holds in
        memory (the landing's write-behind: decoded host tensors).

        The byte budget is acquired HERE, in the caller's thread, before
        the job is queued — bounding the in-memory payload closures the
        lane may retain. With ``async_handoff`` the acquire is
        non-blocking: a full budget returns ``False`` (the caller's
        shard will be materialized from the cache after the landing)
        instead of stalling the decode thread — file writes must never
        re-enter the time-to-HBM critical path. Without it, the acquire
        blocks (the PR-1 backpressure contract). ``prepared(entry)``
        returns a status or None/raises to decline, in which case the
        worker falls back to the normal waterfall ``work``."""
        with self._lock:
            if entry.path in self._futures:
                return True
        if not self.acquire_for(entry):
            return False
        with self._lock:
            if entry.path in self._futures:  # raced with a plain submit
                self.budget.release(entry.size)
                return True
            self._queue_prepared(entry, prepared)
        return True

    def acquire_for(self, entry) -> bool:
        """Acquire ``entry.size`` budget bytes in the caller's thread —
        the one decline/backpressure protocol every prepared lane uses
        (submit_prepared, the streaming sink). Non-blocking with
        ``async_handoff``: a full budget bumps ``declined``, records a
        ``budget_decline`` event, and returns False (the shard then
        materializes from the cache lane); without it, blocks (the
        PR-1 backpressure contract)."""
        if self.async_handoff:
            if not self.budget.try_acquire(entry.size):
                with self._lock:
                    self.declined += 1
                telemetry.record("budget_decline", path=entry.path,
                                 bytes=entry.size)
                return False
        else:
            self.budget.acquire(entry.size)
        return True

    def _queue_prepared(self, entry, prepared) -> None:
        """Queue a prepared job whose budget bytes are already held.
        Caller MUST hold ``self._lock``. A queued future cancelled by
        join()/abort() never runs _run_prepared's finally — its
        pre-acquired bytes must be released by the done-callback or
        the budget leaks and acquire()-parked workers hang the
        shutdown itself."""
        fut = self._prepared_pool.submit(
            self._run_prepared, entry, prepared)
        fut.add_done_callback(
            lambda f, n=entry.size:
            self.budget.release(n) if f.cancelled() else None)
        self._futures[entry.path] = fut

    def submit_held(self, entry, prepared) -> bool:
        """Queue a prepared job whose ``entry.size`` budget bytes the
        caller ALREADY holds (the streaming sink acquires them at
        slot-retain time, before any byte is kept). Dedup by path like
        :meth:`submit_prepared`; on a duplicate the held bytes are
        released here and ``False`` is returned — the caller must then
        drop its retained payload. Release on completion/cancel follows
        the submit_prepared contract unchanged."""
        with self._lock:
            if entry.path in self._futures:
                self.budget.release(entry.size)
                return False
            self._queue_prepared(entry, prepared)
        return True

    def _run_prepared(self, entry, prepared) -> None:
        telemetry.session.use(self._session_id)
        try:
            if self._cancel.is_set():
                return
            with self.clock("files"):
                status = None
                try:
                    status = prepared(entry)
                except Exception:  # noqa: BLE001 - fast lane is optional
                    status = None
                if status is None:
                    status = self.work(entry)
        finally:
            self.budget.release(entry.size)
        with self._lock:
            if status == "skipped":
                self.skipped += 1
            else:
                self.downloaded += 1

    def _run(self, entry) -> None:
        telemetry.session.use(self._session_id)
        if self._cancel.is_set():
            return
        if self.cancel_token is not None:
            # Session abort (ISSUE 13): raising here makes join() treat
            # the cancellation as the first error — queued files drop,
            # in-flight ones drain atomically, temps are discarded.
            self.cancel_token.check()
        if self.skip_check is not None and self.skip_check(entry):
            with self._lock:
                self.skipped += 1
            return
        # The budget wait is queueing, not work: acquired OUTSIDE the
        # stage clock so a starved worker doesn't inflate `files` busy.
        self.budget.acquire(entry.size)
        try:
            if self._cancel.is_set():
                return
            if self.cancel_token is not None:
                self.cancel_token.check()
            with self.clock("files"):
                status = self.work(entry)
        finally:
            self.budget.release(entry.size)
        with self._lock:
            if status == "skipped":
                self.skipped += 1
            else:
                self.downloaded += 1

    def _commit_barrier(self) -> int:
        """The durability barrier: fsync + atomic rename every deferred
        temp file (under the ``files`` stage clock — this IS files-lane
        work, it just runs after the landing by construction). The
        per-file ``durable_replace`` calls are independent, so they fan
        over the materialize pool — serial fsyncs would sum each file's
        writeback drain into the pull's tail instead of overlapping it.
        Returns the number of files committed; failed files' temps are
        discarded (crash-safe either way) and the first error
        re-raises."""
        with self._lock:
            pending, self._pending_commits = self._pending_commits, []
        if not pending:
            return 0
        with self.clock("files"), telemetry.span("files.commit",
                                                 files=len(pending)):
            futures = [
                self._prepared_pool.submit(storage.durable_replace,
                                           tmp, dest)
                for tmp, dest in pending
            ]
            first_error: BaseException | None = None
            for fut, (tmp, _dest) in zip(futures, pending):
                try:
                    fut.result()
                except BaseException as exc:  # noqa: BLE001 - re-raised
                    if first_error is None:
                        first_error = exc
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
            if first_error is not None:
                raise first_error
        return len(pending)

    def _discard_commits(self, pending=None) -> None:
        if pending is None:
            with self._lock:
                pending, self._pending_commits = self._pending_commits, []
        for tmp, _dest in pending:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def join(self) -> tuple[int, int]:
        """Wait for every submitted file, then run the durability
        barrier; (downloaded, skipped) counts. Raises the first worker
        error after cancelling queued work and draining in-flight
        workers (discarding their uncommitted temp files)."""
        with self._lock:
            futures = list(self._futures.values())
        try:
            done, not_done = wait(futures, return_when=FIRST_EXCEPTION)
            first_error = next(
                (f.exception() for f in done if f.exception()), None)
            if first_error is not None:
                self._cancel.set()
                for f in not_done:
                    f.cancel()
                wait(not_done)
                raise first_error
        except BaseException:
            # KeyboardInterrupt (or any waiter-side failure) must not
            # leave the whole queued repo downloading: cancel first so
            # the shutdown below only drains in-flight files, not the
            # full submission backlog.
            self._cancel.set()
            for f in futures:
                f.cancel()
            raise
        else:
            self._commit_barrier()
        finally:
            self._pool.shutdown(wait=True)
            self._prepared_pool.shutdown(wait=True)
            self._discard_commits()  # error paths only; no-op on success
            if self.term_executor is not None:
                self.term_executor.shutdown(wait=True)
        return self.downloaded, self.skipped

    def abort(self) -> None:
        """Cancel queued work and tear the pools down without raising —
        the cleanup path for exceptions that bypass :meth:`join` (e.g. a
        bad mesh config before the tail pass). Idempotent; in-flight
        files drain (each is atomic), queued ones are dropped, and
        uncommitted temp files are discarded (never renamed — the
        partial-file contract)."""
        self._cancel.set()
        with self._lock:
            futures = list(self._futures.values())
        for f in futures:
            f.cancel()
        self._pool.shutdown(wait=True)
        self._prepared_pool.shutdown(wait=True)
        self._discard_commits()
        if self.term_executor is not None:
            self.term_executor.shutdown(wait=True)

    def summary(self) -> dict:
        with self._lock:
            lanes = dict(sorted(self.lane_bytes.items()))
        out = {
            "width": self.width,
            "budget_bytes": self.budget.budget_bytes,
            "inflight_peak_bytes": self.budget.peak_bytes,
            "async": self.async_handoff,
            "materialize_workers": self.materialize_workers,
            "lane_bytes": lanes,
        }
        if self.declined:
            out["handoffs_declined"] = self.declined
        return out


def _tensors_tile(header, size: int) -> bool:
    """True iff the header's tensor ranges tile the data section
    exactly — the provability precondition the write-behind fast lane
    (``_write_file_from_tensors``) requires. Checked up front by the
    streaming sink so it never retains ring slots for a shard it would
    decline at assembly time."""
    spans = sorted(info.file_range(header.data_start)
                   for info in header.tensors.values())
    pos = header.data_start
    for lo, hi in spans:
        if lo != pos:
            return False
        pos = hi
    return pos == size


class _StreamFileSink:
    """Write-behind consumer for one shard of the STREAMING landing
    (ISSUE 8): keeps addref'd ring-slot references per tensor as they
    decode (``offer`` — never blocks; the slot is ``detach``ed so the
    retained bytes count against the file pipeline's ByteBudget, not
    the landing's ring), then submits ONE prepared write assembling the
    HF-cache file from those buffers — the decoded bytes are written
    without a second decode, exactly like the shard-level write-behind
    the non-streaming path keeps.

    Bounded memory: the whole shard's ``entry.size`` is acquired from
    the pipeline's ByteBudget at construction (non-blocking in async
    mode, mirroring ``submit_prepared``); a full budget — or a shard
    whose tensors don't provably tile its data section — makes the
    sink INERT: every ``offer`` is a no-op, slots recycle into the
    ring untouched, and the shard materializes through the existing
    post-landing cache lane instead ("slot recycled first" in the
    ISSUE's terms)."""

    def __init__(self, pipeline: _FilePipeline, bridge, entry, rec,
                 header, snapshot_dir: Path, clock: StageClock):
        self.pipeline = pipeline
        self.bridge = bridge
        self.entry = entry
        self.rec = rec
        self.header = header
        self.snapshot_dir = snapshot_dir
        self.clock = clock
        self.held: dict[str, tuple] = {}
        self.active = False
        if _is_complete(snapshot_dir, entry):
            return  # resume: nothing to write
        if not _tensors_tile(header, rec.total_bytes):
            return
        if not pipeline.acquire_for(entry):
            return
        self.active = True

    def offer(self, name: str, info, arr, slot) -> None:
        """Producer thread, right after tensor ``name`` decoded into
        ``slot``. Retains the slot (addref + detach) so the buffer
        survives the ring recycle until the file write drains it."""
        if not self.active:
            return
        slot.addref()
        slot.detach()
        self.held[name] = (arr, slot)

    def done_decoding(self) -> None:
        """Producer thread, after the shard's last tensor (or on the
        landing's error path — the retained budget/slots must be
        surrendered either way). Hands the write job to the pipeline's
        prepared pool; an incomplete retain set (producer error
        mid-shard) assembles to None inside the worker and falls back
        to the waterfall there."""
        if not self.active:
            return
        self.active = False
        host = {n: a for n, (a, _s) in self.held.items()}
        slots = [s for _a, s in self.held.values()]
        self.held = {}
        pipeline, bridge, clock = self.pipeline, self.bridge, self.clock
        rec, header, snapshot_dir = self.rec, self.header, self.snapshot_dir

        def write(entry, _host=host):
            try:
                dest = snapshot_dir / entry.path
                if _is_complete(snapshot_dir, entry):
                    return "skipped"
                tmp = _write_file_from_tensors(bridge, rec, header,
                                               _host, dest)
                if tmp is None:
                    return None  # decline → worker runs the waterfall
                pipeline.defer_commit(tmp, dest)
                pipeline.note_lane("tensors", entry.size)
                clock.note_bytes("files", entry.size)
                return "downloaded"
            finally:
                for s in slots:
                    s.release()

        if not pipeline.submit_held(self.entry, write):
            for s in slots:  # raced with a plain submit: drop retains
                s.release()


def pull_model(
    cfg: Config,
    repo_id: str,
    revision: str = "main",
    device: str | None = None,
    swarm=None,
    no_p2p: bool = False,
    pod: bool | None = None,
    pods: int | None = None,
    pod_index: int | None = None,
    pod_addrs: dict[int, tuple[str, int]] | None = None,
    coop: bool | None = None,
    coop_hosts: int | None = None,
    coop_index: int | None = None,
    coop_addrs: dict[int, tuple[str, int]] | None = None,
    base_params: dict | None = None,
    base_revision: str | None = None,
    tenant: str | None = None,
    cancel: CancelToken | None = None,
    log=print,
) -> PullResult:
    """Pull ``repo_id@revision`` (see module docstring).

    **Multi-tenant service** (ISSUE 13): with tenancy on (the default;
    ``ZEST_TENANCY=0`` restores fully independent pulls) the pull is
    admitted through the process-global controller — it may park in
    the fair per-tenant queue (session phase ``queued``) or be
    rejected with a typed :class:`~zest_tpu.transfer.tenancy.
    AdmissionRejected` when the queue is full — and then runs over the
    shared pools: the singleflight fetch table (one network fetch per
    xorb range process-wide), the aggregate in-flight byte budget, and
    the pinned xorb-cache eviction.

    **Cancellation**: ``cancel`` (a :class:`CancelToken`; one is
    created and attached to the session when absent, so ``DELETE
    /v1/pulls/<id>`` always works) aborts the pull at the next stage
    boundary. A cancelled pull finishes with the ``cancelled``
    terminal session status — distinct from ``error`` — releases its
    admission slot, byte shares and pins, and detaches from shared
    flights without poisoning them (a cancelled flight LEADER hands
    the fetch to a live waiter).

    **Session** (ISSUE 11): every pull registers in the process-global
    session table (:mod:`zest_tpu.telemetry.session`) — live phase,
    byte progress and ETA while running, terminal status + the stats
    dict after — behind ``GET /v1/pulls`` / ``zest ps``. ``tenant``
    labels the session (falls back to ``cfg.tenant`` /
    ``ZEST_TENANT``); with ``ZEST_TELEMETRY=0`` no session is
    registered and the pull is bit-for-bit the pre-session pull.

    **Delta hot-swap** (ISSUE 10): ``base_params``, when given with
    ``device="tpu"``, is an already-resident param tree of a previously
    pulled revision of the same repo (``base_revision`` — a ref or sha
    — names which one, and is required with it). The landing then
    short-circuits every tensor whose chunk cover is unchanged (reusing
    the resident array) and lands only changed tensors; the base dict
    is CONSUMED — superseded arrays are popped as replacements commit,
    so a live mesh swaps revisions at ~one-tree HBM peak. The returned
    ``PullResult.params`` is the complete revision-``revision`` tree,
    byte-identical to a cold pull (``params_digest``), and stats gain
    ``time_to_swap_s`` next to ``time_to_hbm_s``. With ``ZEST_DELTA=0``
    (or missing base evidence — recorded as a ``delta_degraded``
    flight event) the pull degrades to a full pull and ``base_params``
    is left untouched. ``base_revision`` is REQUIRED with
    ``base_params``: tensor reuse is judged against that revision's
    manifest, and guessing (e.g. newest manifest) could diff against a
    revision the resident tree does not hold — reusing wrong bytes
    silently."""
    if base_params is not None and not base_revision:
        raise ValueError(
            "base_params requires base_revision: tensor reuse is only "
            "sound against the manifest of the revision the resident "
            "tree actually holds")
    t0 = time.monotonic()
    tenant_label = tenant or getattr(cfg, "tenant", None)
    if cancel is None:
        cancel = CancelToken()
    # Session registration (ISSUE 11): identity + live progress for the
    # whole pull; `bind` stamps this thread's recorder events with the
    # session id (worker pools re-bind from a captured id). None with
    # telemetry off — every session call below no-ops on None.
    sess = telemetry.session.begin(
        repo_id, revision, tenant=tenant_label, device=device)
    if sess is not None:
        sess.cancel_token = cancel
    # Live timelines (ISSUE 15): make sure the process sampler is
    # running for the life of this pull — one idempotent flag check;
    # with ZEST_TIMELINE=0 nothing starts and the store stays empty.
    telemetry.timeline.ensure_started()
    # Self-healing control plane (ISSUE 17): subscribe the remediation
    # engine to the anomaly stream + sampler tick for the life of the
    # process. Idempotent; with ZEST_REMEDIATE=0 (or timeline off) the
    # engine never subscribes and the process is a pure observer.
    telemetry.remediate.ensure_started()
    # The coop stage installs this pull's fleet trace context (host +
    # trace_id); restore the previous one at exit so a long-lived
    # daemon's NEXT pull never records under a stale identity (spans
    # are context-stamped at record time, so this pull's spans keep
    # theirs regardless).
    _prev_ctx = telemetry.trace.base_context()
    # Root span: every subsystem span (stage.*, swarm.*, cdn.*, hbm.*)
    # nests under this one, which is also what makes the acceptance
    # criterion trivial to check — the trace's union coverage must be
    # ~the pull's wall time, because this span IS the pull's wall time.
    with telemetry.session.bind(sess.id if sess else None), \
            telemetry.span("pull", repo=repo_id, revision=revision,
                           device=device or "") as _root:
        try:
            # Global admission (ISSUE 13): the ticket is held for the
            # pull's whole run — slot + queue fairness on entry (and a
            # disk-watermark eviction pass), slot/pin release on exit
            # however the pull ends. Knob-off, admit() is a no-op
            # passthrough and the pull is the pre-tenancy pull.
            with tenancy.admit(cfg, tenant_label, cancel=cancel,
                               session=sess) as ticket:
                result = _pull_model(cfg, repo_id, revision, device, swarm,
                                     no_p2p, pod, pods, pod_index,
                                     pod_addrs,
                                     (coop, coop_hosts, coop_index,
                                      coop_addrs),
                                     base_params, base_revision,
                                     log, t0, session=sess,
                                     cancel=cancel, ticket=ticket)
        except BaseException as exc:
            # The finally guarantees the session reaches its terminal
            # state even when the crash-report bookkeeping below raises
            # (e.g. a caller-supplied log whose stream is gone) — a
            # skipped finish would strand a phantom "running" session
            # in /v1/pulls forever, same hazard the success path guards.
            cancelled = isinstance(exc, PullCancelled)
            rejected = isinstance(exc, tenancy.AdmissionRejected)
            # Deliberate aborts and typed backpressure are NOT errors:
            # a load-shedding daemon must not fill dashboards/alerts
            # with "failed" pulls that are the 429 contract working.
            status = ("cancelled" if cancelled
                      else "rejected" if rejected else "error")
            try:
                _M_PULLS.inc(outcome=status)
                if cancelled or rejected:
                    # Neither is a crash: no flight-recorder dump — a
                    # deliberate abort (or typed backpressure) must not
                    # bury real crash reports in noise.
                    telemetry.record(
                        "pull_cancelled" if cancelled
                        else "pull_rejected",
                        repo=repo_id, reason=str(exc))
                else:
                    # Flight-recorder crash report (ISSUE 7): the last N
                    # notable events — strikes, fallbacks, faults,
                    # declines — dumped as one artifact next to the
                    # cache, so a failed pull's triage starts from the
                    # ordered event tail instead of log archaeology.
                    # Best-effort; never masks the real failure.
                    telemetry.record("pull_failed", repo=repo_id,
                                     error=type(exc).__name__)
                    path = telemetry.recorder.dump_crash_report(
                        cfg.cache_dir, f"pull {repo_id} failed: "
                        f"{type(exc).__name__}")
                    if path:
                        try:
                            log(f"flight-recorder crash report: {path}",
                                file=sys.stderr)
                        except TypeError:
                            pass  # log doubles without file= keep the dump
            finally:
                telemetry.session.finish(
                    sess, status, error=f"{type(exc).__name__}: {exc}")
            raise
        finally:
            telemetry.trace.replace_context(_prev_ctx)
    # Critical-path attribution (ISSUE 11): a traced pull's stats carry
    # the analyzer's blame report — computed AFTER the root span closed
    # (the analyzer needs the complete window), pinned to THIS pull's
    # own root span so a daemon's accumulated tracer can never hand
    # this pull a concurrent pull's root/window. The finally guarantees
    # the session reaches its terminal state even when this post-span
    # bookkeeping is interrupted (Ctrl-C here would otherwise leave a
    # phantom "running" session in /v1/pulls forever) — the pull itself
    # HAS succeeded by this point.
    try:
        tracer = telemetry.trace.active()
        if tracer is not None and telemetry.enabled():
            try:
                cp = telemetry.critpath.analyze_tracer(tracer,
                                                       root_span=_root)
            except Exception:  # noqa: BLE001 - attribution is advisory
                cp = None
            if cp is not None:
                result.stats["critical_path"] = cp
        _check_slos(cfg, repo_id, result.stats, sess)
        _M_PULLS.inc(outcome="ok")
        _M_PULL_SECONDS.observe(time.monotonic() - t0)
        tth = result.stats.get("time_to_hbm_s")
        if tth is not None:
            _M_TTH_SECONDS.observe(tth)
    finally:
        telemetry.session.finish(sess, "ok", stats=result.stats)
    return result


def _check_slos(cfg: Config, repo_id: str, stats: dict, sess) -> None:
    """Per-session SLO breach detection (ISSUE 11): compare the pull's
    headline walls against the armed budgets (``ZEST_SLO_TTHBM_S`` /
    ``ZEST_SLO_TTFL_S``); a breach bumps
    ``zest_slo_breaches_total{slo}`` and records a flight-recorder
    event carrying the session id and the critical-path analyzer's top
    blamed stage (when the pull ran traced). Burn bookkeeping lives on
    the session table (``/v1/pulls``'s ``slo`` block)."""
    budgets = (
        ("tthbm", getattr(cfg, "slo_tthbm_s", None),
         stats.get("time_to_hbm_s")),
        ("ttfl", getattr(cfg, "slo_ttfl_s", None),
         stats.get("time_to_first_layer_s")),
    )
    cp_stages = (stats.get("critical_path") or {}).get("stages") or {}
    blamed = max(cp_stages, key=cp_stages.get) if cp_stages else None
    for slo, budget, actual in budgets:
        if not budget or actual is None:
            continue
        breached = actual > budget
        if sess is not None:
            telemetry.session.SESSIONS.note_slo(slo, breached)
            sess.note_slo(slo, {"budget_s": budget, "actual_s": actual,
                                "breached": breached})
        if breached:
            _M_SLO_BREACHES.inc(slo=slo)
            telemetry.record(
                "slo_breach", slo=slo, repo=repo_id,
                budget_s=budget, actual_s=actual,
                session=sess.id if sess is not None else None,
                blamed_stage=blamed)


def _pull_model(
    cfg: Config,
    repo_id: str,
    revision: str,
    device: str | None,
    swarm,
    no_p2p: bool,
    pod: bool | None,
    pods: int | None,
    pod_index: int | None,
    pod_addrs: dict[int, tuple[str, int]] | None,
    coop_args: tuple,
    base_params: dict | None,
    base_revision: str | None,
    log,
    t0: float,
    session=None,
    cancel: CancelToken | None = None,
    ticket=None,
) -> PullResult:
    # Validate the landing dtype BEFORE any network work: a config typo
    # (ZEST_TPU_DTYPE=fp16) must fail fast here, not be swallowed by the
    # staging try/excepts after a multi-GB warm fetch. Only the TPU
    # device path consumes it — a plain pull ignores a bad value.
    land_dtype = None
    if device == "tpu":
        from zest_tpu.models.loader import resolve_dtype

        land_dtype = resolve_dtype(cfg.land_dtype)
        # First-touch backend init (jax.devices()) costs ~80 ms on CPU
        # and far more on a real TPU runtime, and the landing path hits
        # it strictly AFTER the metadata round trips. Warm it on a
        # daemon thread so it overlaps the resolve/metadata network
        # I/O instead of extending time_to_first_layer serially.
        def _warm_backend():
            try:
                import jax

                jax.devices()
            except Exception:  # noqa: BLE001 - landing reports its own error
                pass

        threading.Thread(target=_warm_backend, daemon=True,
                         name="zest-jax-warm").start()
    hub = HubClient(cfg)
    clock = StageClock()
    if session is not None:
        # The session watches the pull's existing instrumentation: the
        # clock's stage observer drives the live phase, and snapshot
        # reads pull byte counters lazily — no new hot-path work.
        session.attach(clock=clock)

    def _cancel_point() -> None:
        """Stage-boundary cancellation check (ISSUE 13 satellite):
        raises PullCancelled the moment the session's token fired."""
        if cancel is not None:
            cancel.check()

    _cancel_point()
    with clock("resolve"):
        commit_sha = hub.resolve_revision(repo_id, revision)
        files = hub.list_files(repo_id, revision)
    _cancel_point()
    snapshot_dir = cfg.model_snapshot_dir(repo_id, commit_sha)
    if session is not None:
        session.set_revision(commit_sha)
        session.set_total_bytes(sum(
            e.size for e in files if not _is_complete(snapshot_dir, e)))

    if swarm is None and not no_p2p:
        swarm = _default_swarm(cfg)
    bridge = XetBridge(cfg, swarm=swarm)
    # Shared-pool wiring (ISSUE 13): the process singleflight table
    # (one network fetch per xorb range across every session), this
    # session's cancel token (waiters detach, a cancelled leader hands
    # off), the eviction pins (every resolved plan's xorbs stay
    # unevictable while this session is admitted), and the aggregate
    # in-flight byte budget the file pipeline draws from. All absent
    # with ZEST_TENANCY=0 — the bridge then behaves bit-for-bit as
    # before.
    shared_budget = None
    if tenancy.enabled(cfg):
        _tstate = tenancy.state(cfg)
        bridge.flights = _tstate.flights
        shared_budget = _tstate.byte_budget
        if ticket is not None:
            bridge.on_reconstruction = (
                lambda rec: ticket.pin(rec.fetch_info.keys()))
    bridge.cancel = cancel
    if session is not None:
        session.attach(fetch_stats=bridge.stats)
    # Per-pull wall-clock budget (ZEST_PULL_DEADLINE_S; off by default).
    # Armed BEFORE authenticate() so the CAS client inherits it; the
    # swarm receives it per call from the bridge.
    from zest_tpu.resilience import Deadline

    deadline = Deadline.after(getattr(cfg, "pull_deadline_s", None))
    bridge.deadline = deadline
    # Remediation action target (ISSUE 17): a stall/throughput-collapse
    # anomaly on THIS session arms a mid-flight hedge on its bridge —
    # the evidence-armed path in XetBridge._peer_tier races the peer
    # tier against the CDN with a fixed head start, no
    # ZEST_PULL_DEADLINE_S required. Unregistered when the bridge
    # closes; no-op (and no trace) with ZEST_REMEDIATE=0.
    _hedge_target = None
    _hedge_fn = bridge.arm_hedge  # bound once: unregister is identity-checked
    if session is not None:
        _hedge_target = f"hedge:{session.id}"
        telemetry.remediate.register_target(_hedge_target, _hedge_fn)
    width = max(1, getattr(cfg, "pull_pipeline_width", 1))
    # ONE term-fetch pool shared by every concurrent file reassembly:
    # total in-flight fetch streams stay at the configured budget no
    # matter how wide the file pipeline runs (width x per-file pools
    # would oversubscribe it). Owned by the file pipeline below.
    term_pool = ThreadPoolExecutor(
        max(1, cfg.max_concurrent_downloads),
        thread_name_prefix="zest-term-fetch")
    par = ParallelDownloader(bridge, executor=term_pool)
    authenticated = False
    auth_lock = threading.Lock()

    def ensure_auth() -> None:
        """Idempotent, thread-safe CAS auth — file workers and the
        landing thread can both demand it; exactly one authenticates."""
        nonlocal authenticated
        with auth_lock:
            if not authenticated and bridge.cas is None:
                bridge.authenticate(repo_id, revision, hub=hub)
            authenticated = True

    def file_work(entry) -> str:
        dest = snapshot_dir / entry.path
        try:
            if _is_complete(snapshot_dir, entry):
                return "skipped"
            if entry.is_xet:
                ensure_auth()
                _pull_xet_file(bridge, par, hub, cfg, repo_id, revision,
                               entry, dest, log,
                               lane_note=file_pipeline.note_lane)
            else:
                dest.parent.mkdir(parents=True, exist_ok=True)
                hub.download_regular_file(repo_id, revision, entry.path,
                                          dest)
                file_pipeline.note_lane("waterfall", entry.size)
        except OSError as exc:
            # ENOSPC on an HF-cache write surfaces TYPED (ISSUE 13
            # satellite): the writers above have already cleaned their
            # temps; this fires the disk_pressure event + the tenancy
            # eviction pass and re-raises as CacheFullError instead of
            # a raw mid-pull OSError.
            import errno as _errno

            if (getattr(exc, "errno", None) == _errno.ENOSPC
                    and not isinstance(exc, storage.CacheFullError)):
                storage.note_disk_full(dest)
                raise storage.CacheFullError(
                    f"HF-cache write of {entry.path} hit ENOSPC",
                    dest) from exc
            raise
        clock.note_bytes("files", entry.size)
        return "downloaded"

    file_pipeline = _FilePipeline(
        width, getattr(cfg, "pull_inflight_bytes", 2 << 30), clock,
        file_work, term_executor=term_pool,
        skip_check=lambda e: _is_complete(snapshot_dir, e),
        materialize_workers=_resolve_files_workers(
            getattr(cfg, "files_workers", 0)),
        async_handoff=bool(getattr(cfg, "files_async", True)),
        budget=shared_budget, cancel=cancel)

    try:
        # config.json feeds family dispatch twice (pod pre-pass, landing
        # rules) before the file loop would fetch it — prefetch it on
        # the shared pool so neither consumer pays its round trip
        # serially on the landing's critical path.
        early_cfg = None
        if device == "tpu":
            early_cfg = term_pool.submit(
                _early_config, hub, repo_id, revision, files,
                snapshot_dir)

            # Likewise the per-shard safetensors headers: the landing
            # blocks on all of them before its first fetch, and none
            # need jax — resolving them here rides under the pod
            # round's backend-init wall, so the landing's own header
            # pass becomes a warm cache read. Best-effort: a miss just
            # leaves the landing to fetch them itself.
            def _prefetch_headers():
                try:
                    from zest_tpu.transfer.pod import fetch_file_header

                    ensure_auth()
                    _hdr_fan(
                        lambda e: fetch_file_header(
                            bridge,
                            bridge.get_reconstruction(e.xet_hash)),
                        [e for e in files
                         if e.is_xet
                         and e.path.endswith(".safetensors")])
                except Exception:  # noqa: BLE001 - advisory warmup
                    pass

            term_pool.submit(_prefetch_headers)
        # ── Delta plan (ISSUE 10) ──
        # A pull of revision B over a locally-evidenced revision A diffs
        # the two term lists BEFORE any byte moves: content-unchanged
        # units serve from the cache with zero network, the cooperative
        # plan (below) shards only the changed set, and the landing's
        # per-tensor short-circuit rides the same evidence. Knob-off
        # (ZEST_DELTA=0) restores the pre-delta pull bit-for-bit —
        # no plan, no manifest, no new stats keys.
        from zest_tpu.transfer import delta as delta_mod

        delta_plan = None
        delta_base = None
        delta_net_before = None
        if getattr(cfg, "delta_pull", True):
            delta_base = delta_mod.find_base_manifest(
                cfg, repo_id, commit_sha, base_revision)
            if delta_base is None:
                if base_params is not None or base_revision:
                    # The caller expected a delta (a resident tree / an
                    # explicit base) but the rev-A evidence is gone:
                    # degrade to a full pull, loudly and on the flight
                    # recorder — never guess at what changed.
                    telemetry.record("delta_degraded", repo=repo_id,
                                     revision=commit_sha,
                                     reason="missing rev-A manifest")
                    log("delta: no base-revision manifest — running a "
                        "full pull", file=sys.stderr)
                    base_params = None
            else:
                pending_xet = [e for e in files if e.is_xet
                               and not _is_complete(snapshot_dir, e)]
                if not pending_xet and base_params is not None:
                    # Snapshot already fully materialized: the direct
                    # landing (and with it the per-tensor short-circuit)
                    # won't run — it defers to the disk path for
                    # complete files. Say so instead of silently
                    # returning a SECOND full tree next to the caller's
                    # resident one.
                    telemetry.record("delta_degraded", repo=repo_id,
                                     revision=commit_sha,
                                     reason="snapshot already complete")
                    log("delta: snapshot already materialized — "
                        "hot-swap skipped (disk staging), base params "
                        "left untouched", file=sys.stderr)
                if pending_xet:
                    try:
                        from zest_tpu.parallel.plan import collect_units

                        with clock("cas_metadata"):
                            ensure_auth()
                            delta_recs = _hdr_fan(
                                lambda e: bridge.get_reconstruction(
                                    e.xet_hash), pending_xet)
                        units = [(hh, fi) for (hh, _s), fi
                                 in collect_units(delta_recs)]
                        delta_plan = delta_mod.build_plan(
                            delta_base,
                            [(e.path, delta_mod.terms_of(r))
                             for e, r in zip(pending_xet, delta_recs)],
                            units=units, cache=bridge.cache)
                        # Network baseline for the measured
                        # fetched-bytes evidence (tests/smoke assert
                        # changed-bytes-only from FetchStats).
                        delta_net_before = (bridge.stats.bytes_from_peer
                                            + bridge.stats.bytes_from_cdn)
                    except Exception as exc:  # noqa: BLE001 - plan is advisory
                        telemetry.record(
                            "delta_degraded", repo=repo_id,
                            reason=f"plan: {type(exc).__name__}")
                        log(f"delta plan unavailable ({exc}); running "
                            "a full pull", file=sys.stderr)
                        delta_plan = None
                    if delta_plan is not None and session is not None:
                        # Progress denominator = the bytes this pull
                        # will actually move: content-unchanged reused
                        # units never touch FetchStats, so against the
                        # full incomplete-file total a 5%-changed delta
                        # pull would sit at ~5% "progress" until the
                        # instant it finished.
                        session.set_total_bytes(
                            delta_plan.changed_bytes
                            + delta_plan.stale_bytes)
        elif base_params is not None:
            log("delta disabled (ZEST_DELTA=0); base params ignored, "
                "running a full pull", file=sys.stderr)
            base_params = None
        if delta_plan is None:
            base_params = None

        # Pod pre-pass (BASELINE config #3): one collective round fills the
        # cache so the per-file loop below hits tier 1 for planned bytes.
        # Defaults on for --device=tpu; force with ZEST_TPU_POD=1/0.
        if pod is None:
            env = os.environ.get("ZEST_TPU_POD")
            pod = env == "1" if env in ("0", "1") else device == "tpu"
        fed = pods is not None and pods > 1 and pod_index is not None
        coop_cfg = _resolve_coop(cfg, *coop_args, log=log)
        pod_stats = fed_stats = coop_stats = None
        _cancel_point()
        if pod or fed or coop_cfg:
            pending = [
                e for e in files
                if e.is_xet and not _is_complete(snapshot_dir, e)
            ]
            if pending:
                try:
                    with clock("cas_metadata"):
                        bridge.authenticate(repo_id, revision, hub=hub)
                        authenticated = True
                        recs = [bridge.get_reconstruction(e.xet_hash)
                                for e in pending]
                except Exception as exc:  # noqa: BLE001 - round is an accelerator
                    log(f"distribution rounds unavailable ({exc}); "
                        "continuing with the per-host waterfall",
                        file=sys.stderr)
                    recs = None
                # Cooperative host tier FIRST (transfer.coop): each host
                # fetches ~1/N and the exchange completes the cache, so
                # the federated/pod stages (and the landing) run
                # peer-fed. Failure degrades to the full waterfall.
                if recs and coop_cfg:
                    # Streaming interop (ISSUE 8): hand the round the
                    # deterministic layer-priority key so its fetch and
                    # exchange phases ship embedding + layer-0 bytes
                    # first — the ownership plan (and its fingerprint)
                    # is untouched, only iteration order is. Header
                    # fetches are KB-scale and idempotent (the landing
                    # refetches them from cache moments later).
                    prio = None
                    if (device == "tpu"
                            and getattr(cfg, "land_stream", True)):
                        try:
                            from zest_tpu.models.direct import (
                                unit_layer_priorities,
                            )
                            from zest_tpu.transfer.pod import (
                                fetch_file_header,
                            )

                            shard_recs = [
                                r for e, r in zip(pending, recs)
                                if e.path.endswith(".safetensors")
                            ]
                            headers = _hdr_fan(
                                lambda r: fetch_file_header(bridge, r),
                                shard_recs)
                            prio = unit_layer_priorities(
                                list(zip(shard_recs, headers)))
                        except Exception:  # noqa: BLE001 - order is advisory
                            prio = None
                    try:
                        # Delta interop: the ownership plan shards ONLY
                        # the content-changed unit set — a pure function
                        # of the two revisions, so hosts with
                        # differently-warm caches still fingerprint-
                        # agree (transfer.delta). Landing order comes
                        # from ``priorities``: coop_round routes BOTH
                        # phases through the shared
                        # unit_priority_sort_key, changed subset or not.
                        coop_stats = _coop_stage(
                            bridge, recs, cfg, coop_cfg, repo_id,
                            commit_sha, log, priorities=prio,
                            units=(delta_plan.changed_units
                                   if delta_plan is not None
                                   and delta_plan.changed_units
                                   else None))
                    except Exception as exc:  # noqa: BLE001
                        log(f"cooperative pull unavailable ({exc}); "
                            "continuing with the per-host waterfall",
                            file=sys.stderr)
                # Cross-pod stage first (pods that are separate processes —
                # DCN chunk RPC), so the in-pod collective spreads a warm
                # cache. Either round failing degrades to the waterfall.
                if recs and fed:
                    try:
                        from zest_tpu.transfer.federated import federated_round

                        fed_stats = federated_round(
                            bridge, recs, pod_index, pods, pod_addrs or {},
                            log=lambda m: log(m),
                        )
                    except Exception as exc:  # noqa: BLE001
                        log(f"federated round unavailable ({exc}); "
                            "continuing with the per-host waterfall",
                            file=sys.stderr)
                if recs and pod:
                    try:
                        pod_stats = _pod_stage(
                            bridge, pending, recs, hub, repo_id, revision,
                            files, snapshot_dir, log,
                            early_cfg=early_cfg)
                    except Exception as exc:  # noqa: BLE001
                        log(f"pod round unavailable ({exc}); "
                            "continuing with the per-host waterfall",
                            file=sys.stderr)

        # Direct-to-HBM landing (SURVEY.md §7 hard part #2, the north star):
        # land tensors straight from cached units BEFORE any file is written,
        # so the landing path never reads a reassembled file. The HF-cache
        # files are still written by the loop below — served from the
        # now-warm cache, not refetched.
        hbm_params = hbm_stats = None
        mesh = None
        time_to_hbm = hbm_done_at = None
        time_to_first_layer = None
        _cancel_point()
        if device == "tpu":
            if cfg.mesh.mesh_axes:
                from zest_tpu.parallel.mesh import mesh_from_config

                mesh = mesh_from_config(cfg.mesh)
            # Aux files (config/tokenizer/regular files) don't depend on the
            # landing's warm fetch — submit them now so they ride the
            # pipeline UNDER the landing's metadata + warm phase. The
            # safetensors shards are submitted by the landing itself, each
            # the moment its host tensors are decoded (write-behind, see
            # _try_direct_stage), so file writes overlap decode + HBM commit
            # without decoding any byte twice.
            for entry in files:
                if not entry.path.endswith(".safetensors"):
                    file_pipeline.submit(entry)
            hbm_params, hbm_stats = _try_direct_stage(
                bridge, hub, repo_id, revision, files, snapshot_dir, mesh,
                land_dtype, log, clock,
                file_pipeline=file_pipeline, ensure_auth=ensure_auth,
                early_cfg=early_cfg,
                delta_state=((delta_base, base_params, delta_plan)
                             if delta_plan is not None else None),
                exchange_landed=bool(((coop_stats or {}).get("exchange")
                                      or {}).get("units")),
            )
            authenticated = authenticated or bridge.cas is not None
            if hbm_stats is not None:
                hbm_done_at = time.monotonic()
                time_to_hbm = hbm_done_at - t0
                clock.note_bytes("hbm_commit", hbm_stats.get("bytes", 0))
                fl_at = hbm_stats.pop("first_layer_at", None)
                if fl_at is not None:
                    time_to_first_layer = fl_at - t0
                    # Anchored at the pull's own t0 so the stage view
                    # and the headline stat agree by construction.
                    clock.note_interval("first_layer", t0, fl_at)

        # Tail pass: everything not already riding the pipeline (the whole
        # repo, for a plain pull) — submit is path-deduped, then the join is
        # the stage barrier. Workers time themselves under clock("files").
        _cancel_point()
        for entry in files:
            file_pipeline.submit(entry)
        clock.ensure("files")
        downloaded, skipped = file_pipeline.join()
    except BaseException:
        # Any failure escaping this window (bad mesh config, Ctrl-C
        # inside the pre-pass or landing) must not leak the pools or
        # leave queued downloads running unsupervised.
        file_pipeline.abort()
        if _hedge_target is not None:
            telemetry.remediate.unregister_target(_hedge_target,
                                                  _hedge_fn)
        bridge.close()
        raise
    if _hedge_target is not None:
        # The session's fetch work is over: a late-firing anomaly must
        # not arm a hedge on a closed bridge.
        telemetry.remediate.unregister_target(_hedge_target, _hedge_fn)
    bridge.close()  # release hedge threads (no-op unless a deadline hedged)

    storage.write_ref(cfg, repo_id, revision, commit_sha)

    if getattr(cfg, "delta_pull", True):
        # Persist this revision's manifest — the rev-A evidence a later
        # delta pull diffs against. Best-effort and complete-or-nothing:
        # a fully-skipped resume pull has no reconstructions memoized
        # (and its original pull already wrote one), and a partial
        # manifest would poison future plans (transfer.delta).
        from zest_tpu.transfer import delta as delta_mod

        def _rec_of(entry):
            rec = bridge.known_reconstruction(entry.xet_hash)
            if rec is not None or bridge.cas is None:
                # Unauthenticated (fully-skipped resume): decline — the
                # original pull already wrote this manifest.
                return rec
            try:
                # Partially-resumed pull: the completed files' recs were
                # never needed for bytes — one KB-scale metadata round
                # trip each, at pull exit, keeps the manifest complete.
                return bridge.get_reconstruction(entry.xet_hash)
            except Exception:  # noqa: BLE001 - complete-or-nothing
                return None

        try:
            # Lineage (ISSUE 19): record which revision this pull
            # actually diffed against, so find_base_manifest can prefer
            # the closest ancestor (and refuse descendants) next time.
            delta_mod.save_manifest(
                cfg, repo_id, commit_sha, files, _rec_of,
                parent=(delta_base or {}).get("revision"))
        except Exception as exc:  # noqa: BLE001 - evidence is advisory
            log(f"delta manifest not saved ({exc})", file=sys.stderr)

    elapsed = time.monotonic() - t0
    stats = {
        "repo": repo_id,
        "revision": commit_sha,
        "files_downloaded": downloaded,
        "files_skipped": skipped,
        "elapsed_s": round(elapsed, 3),
        "stages": clock.summary(),
        "stages_busy": clock.busy_summary(),
        "stages_gbps": clock.gbps_summary(),
        "files_pipeline": file_pipeline.summary(),
        "files_hbm_span_s": round(clock.span("files", "hbm_commit"), 4),
        "fetch": bridge.stats.summary(),
    }
    if time_to_hbm is not None:
        stats["time_to_hbm_s"] = round(time_to_hbm, 3)
        _M_LAST_TTH.set(time_to_hbm)
        # Background-lane evidence: files-stage wall that ran AFTER the
        # params were resident — materialization work the restructure
        # moved off the time-to-HBM span (CI smoke asserts it's > 0 and
        # that time_to_hbm_s < elapsed_s, schema-level).
        stats["files_after_hbm_s"] = round(
            clock.coverage_after("files", hbm_done_at), 4)
    if time_to_first_layer is not None:
        # Headline next to time_to_hbm_s (ISSUE 8): the instant the
        # first-token-capable set (embedding + layer 0) was resident —
        # what a serving mesh needs to start generating while layer N
        # is still on the wire. Only present when the streaming landing
        # ran (knob-off pulls keep the pre-streaming stats schema).
        stats["time_to_first_layer_s"] = round(time_to_first_layer, 3)
        _M_TTFL_SECONDS.observe(time_to_first_layer)
        _M_LAST_TTFL.set(time_to_first_layer)
        _M_LAST_RING_STALLS.set(float(
            ((hbm_stats or {}).get("ring") or {}).get("stalls", 0)))
    elif time_to_hbm is not None:
        # A landing ran but did NOT stream: zero the first-layer gauge
        # so the status/dashboard "last pull" block never pairs a STALE
        # first_layer_s from an earlier streamed pull with THIS pull's
        # hbm wall (the renderers treat <= 0 as absent) — and the stall
        # gauge with it, for the same staleness reason.
        _M_LAST_TTFL.set(0.0)
        _M_LAST_RING_STALLS.set(0.0)
    if delta_plan is not None:
        dsum = delta_plan.summary()
        if delta_net_before is not None:
            # Measured, not planned: the bytes that actually crossed
            # the network (FetchStats peer+CDN delta, plus the coop
            # exchange's DCN wire bytes) — the changed-bytes-only
            # evidence the smoke gate asserts.
            fetched = (bridge.stats.bytes_from_peer
                       + bridge.stats.bytes_from_cdn) - delta_net_before
            if coop_stats is not None:
                fetched += (coop_stats.get("exchange") or {}).get(
                    "wire_bytes", 0)
            dsum["fetched_bytes"] = fetched
            if delta_plan.total_bytes:
                dsum["fetched_ratio"] = round(
                    fetched / delta_plan.total_bytes, 4)
        swap = (hbm_stats or {}).get("swap")
        if swap:
            dsum["tensors"] = {"reused": swap["reused_tensors"],
                               "landed": swap["landed_tensors"]}
        stats["delta"] = dsum
        _M_LAST_DELTA_RATIO.set(
            dsum.get("fetched_ratio", dsum["delta_bytes_ratio"]))
        if swap and time_to_hbm is not None:
            # In-place hot-swap headline (ISSUE 10): the instant the
            # mesh held the COMPLETE new revision — reused tensors
            # resident throughout, changed ones landed at tensor
            # granularity into the existing tree's footprint.
            stats["time_to_swap_s"] = round(time_to_hbm, 3)
            _M_LAST_SWAP.set(time_to_hbm)
        else:
            _M_LAST_SWAP.set(0.0)
        if swap and base_params:
            # Tensors the new revision dropped entirely: release them
            # so the consumed-base contract holds ("the base dict is
            # empty when the swap returns").
            base_params.clear()
    else:
        _M_LAST_DELTA_RATIO.set(-1.0)
        _M_LAST_SWAP.set(0.0)
    if coop_stats is not None:
        stats["coop"] = coop_stats
        # Headline stat (README schema note): the fraction of this
        # round's network bytes served by peers instead of CDN — the
        # number the ≥90% north-star target is judged on.
        stats["peer_served_ratio"] = coop_stats.get("peer_served_ratio")
    if fed_stats is not None:
        stats["federated"] = fed_stats
    if pod_stats is not None:
        stats["pod"] = pod_stats
    if deadline is not None:
        stats["deadline"] = {
            "budget_s": deadline.total_s,
            "remaining_s": round(max(0.0, deadline.remaining()), 3),
        }
    if swarm is not None:
        # SwarmDownloader.summary() folds in the health registry's view;
        # injected test doubles may only carry bare stats.
        stats["swarm"] = (swarm.summary() if hasattr(swarm, "summary")
                          else swarm.stats.summary())

    if device == "tpu" and hbm_stats is None:
        # Disk fallback: direct landing was ineligible or failed; the
        # files are on disk now, stage them the reference's way. A
        # staging failure (e.g. a repo whose .safetensors doesn't parse)
        # must not lose the completed download — report it and return.
        from zest_tpu.models.loader import stage_snapshot_to_hbm

        from zest_tpu.models.registry import shard_rules_for_snapshot

        try:
            with clock("hbm_commit"):
                hbm_params, hbm_stats = stage_snapshot_to_hbm(
                    snapshot_dir, mesh=mesh,
                    rules=shard_rules_for_snapshot(snapshot_dir),
                    dtype=land_dtype,
                )
            # The late stage must keep every timing view coherent:
            # refresh the stage summaries AND the wall clocks together.
            clock.note_bytes("hbm_commit", hbm_stats.get("bytes", 0))
            stats["stages"] = clock.summary()
            stats["stages_busy"] = clock.busy_summary()
            stats["stages_gbps"] = clock.gbps_summary()
            stats["files_hbm_span_s"] = round(
                clock.span("files", "hbm_commit"), 4)
            stats["elapsed_s"] = round(time.monotonic() - t0, 3)
            stats["time_to_hbm_s"] = stats["elapsed_s"]
            # Disk fallback stages after the file barrier: there is no
            # post-commit files window by construction.
            stats["files_after_hbm_s"] = 0.0
        except Exception as exc:  # noqa: BLE001
            log(f"HBM staging failed ({exc}); files remain in "
                f"{snapshot_dir}", file=sys.stderr)
            hbm_stats = {"error": str(exc), "direct": False}
    if hbm_stats is not None:
        stats["hbm"] = hbm_stats
    if ticket is not None and hbm_params is not None:
        # Live-HBM-tree pin (ISSUE 13): the manifest evidence a later
        # delta/hot-swap of this repo will diff against must survive
        # this session's own pins releasing — replaced when a newer
        # revision of the same repo lands.
        ticket.pin_tree(repo_id, bridge.resolved_xorb_hashes())

    # Chaos-run evidence (ISSUE 4 satellite): per-fault fired counts, so
    # a chaos test asserts "the fault actually fired" directly instead
    # of inferring it from retry counters downstream. Process-cumulative
    # (the injector outlives a pull); absent entirely when injection is
    # off, so ordinary pulls keep the pre-telemetry stats schema.
    fired = faults.counters()
    if fired:
        stats["faults"] = dict(sorted(fired.items()))

    return PullResult(snapshot_dir, stats, params=hbm_params)


def _try_direct_stage(
    bridge, hub, repo_id, revision, files, snapshot_dir, mesh, dtype, log,
    clock: StageClock | None = None,
    file_pipeline: _FilePipeline | None = None,
    ensure_auth=None,
    early_cfg=None,
    delta_state=None,
    exchange_landed: bool = False,
):
    """Direct cache→HBM landing for every safetensors file, before any
    file write. Returns ``(None, None)`` when ineligible — non-xet
    safetensors (no reconstruction to land from) or files already on
    disk (the resume case: reading local disk beats refetching) — or on
    any failure, in which case the disk fallback runs after the file
    loop. With a ``file_pipeline``, each shard's HF-cache file write is
    submitted the moment its host tensors are decoded (write-behind
    from the landing's own buffers — no second decode), so file writes
    run concurrently with the decode + HBM commit of the same (and
    later) shards — the pull's tentpole overlap."""
    st = [e for e in files if e.path.endswith(".safetensors")]
    if not st or not all(e.is_xet for e in st):
        return None, None
    if any(_is_complete(snapshot_dir, e) for e in st):
        return None, None
    if clock is None:
        clock = StageClock()
    pipeline = None
    try:
        from zest_tpu.models.loader import stage_cached_to_hbm
        from zest_tpu.transfer.pod import fetch_file_header

        with clock("cas_metadata"):
            if ensure_auth is not None:
                ensure_auth()
            elif bridge.cas is None:
                bridge.authenticate(repo_id, revision, hub=hub)
            # One reconstruction + header round trip per shard; every
            # landing stage waits on ALL of them — _hdr_fan keeps them
            # off the serial critical path.
            def _rec_with_header(e):
                rec = bridge.get_reconstruction(e.xet_hash)
                return rec, fetch_file_header(bridge, rec)

            recs_with_headers = _hdr_fan(_rec_with_header, st)
            # Resolve every OTHER xet file's reconstruction too (KB-scale
            # metadata, memoized for the file loop moments later): the
            # full-vs-partial cache-key evidence must see ALL references
            # to a xorb — a tokenizer packed into the tail of a shard's
            # xorb would otherwise get that xorb full-keyed truncated.
            # Best-effort: a miss here costs evidence (partial keys),
            # never the landing — but the gap must be RECORDED: with a
            # file's references unresolved, "every known reference sees
            # the whole xorb" is no longer provable for ANY xorb, so the
            # bridge is flagged to force partial cache keys for the rest
            # of the pull (ADVICE r5: an evidence gap could otherwise
            # cache a truncated blob under the full key that seeding
            # then advertises as the complete xorb).
            evidence_recs = [r for r, _h in recs_with_headers]
            for e in files:
                if e.is_xet and not e.path.endswith(".safetensors"):
                    try:
                        evidence_recs.append(
                            bridge.get_reconstruction(e.xet_hash))
                    except Exception:  # noqa: BLE001
                        bridge.mark_evidence_incomplete()
        # Whatever the distribution rounds didn't cache (single chip:
        # everything) arrives max_concurrent-wide, not term-by-term —
        # pipelined per shard: shard 0's fetch is the visible "fetch"
        # stage, every later shard's network time hides under the
        # previous shard's decode+commit inside "hbm_commit".
        cfg = getattr(bridge, "cfg", None)
        stream_on = (bool(getattr(cfg, "land_stream", True))
                     and bool(getattr(cfg, "land_decode_ahead", 1)))
        rules = _landing_rules(hub, repo_id, revision, files, snapshot_dir,
                               early_cfg=early_cfg)
        recs_only = [r for r, _h in recs_with_headers]

        # ── Per-tensor delta short-circuit + in-place hot-swap ──
        # With a resident base tree, every tensor whose canonical chunk
        # cover is unchanged between the base manifest and this
        # revision (transfer.delta — content-addressed, so equal covers
        # mean byte-identical data) is REUSED as-is: no fetch gate, no
        # decode, no verify, no device_put. The base dict is consumed
        # in place as changed tensors' replacements commit.
        preloaded: dict = {}
        swap_from = None
        skip_keys: frozenset = frozenset()
        if delta_state is not None:
            from zest_tpu.transfer import delta as delta_mod

            import numpy as _np

            d_base, d_params, d_plan = delta_state
            skip_keys = d_plan.reused_local_keys
            if d_params:
                swap_from = d_params
                base_files = d_base.get("files") or {}

                def _landed_dtype(info):
                    """The dtype this landing would commit the tensor
                    at — commit_tensors' cast rule (non-integer tensors
                    cast to the landing dtype; int/bool keep theirs)."""
                    src = info.np_dtype
                    if dtype is None or _np.issubdtype(src, _np.integer) \
                            or src == _np.bool_:
                        return src
                    return _np.dtype(dtype)

                for entry, (rec, header) in zip(st, recs_with_headers):
                    bf = base_files.get(entry.path)
                    if not bf:
                        continue
                    for name in delta_mod.unchanged_tensor_names(
                            bf["terms"], rec, header):
                        arr = d_params.get(name)
                        if arr is None:
                            continue
                        info = header.tensors[name]
                        if tuple(getattr(arr, "shape", ())) \
                                != tuple(info.shape) \
                                or _np.dtype(getattr(arr, "dtype", None)) \
                                != _landed_dtype(info):
                            # Base tree disagrees (re-sharded shape, or
                            # it landed under a different --dtype than
                            # this pull would): re-land — a mixed-dtype
                            # tree would break the cold-pull identity.
                            continue
                        preloaded[name] = arr
                for name in preloaded:
                    d_params.pop(name, None)  # moved into the new tree

        if stream_on:
            # ── Streaming landing (ISSUE 8) ──
            # Tensor-granularity flow through the loader's HostRing:
            # the warm fetch runs layer-ordered with per-unit
            # completion events, the tensor gate lets decode chase the
            # fetch inside a shard, and the write-behind sink keeps
            # the decoded ring slots so the HF-cache file assembles
            # with zero re-decode.
            from zest_tpu.models.direct import (
                tensor_unit_keys, unit_layer_priorities,
            )

            priorities = unit_layer_priorities(recs_with_headers)
            required = [tensor_unit_keys(rec, header)
                        for rec, header in recs_with_headers]
            pipeline = _PipelinedWarm(bridge, recs_only,
                                      evidence_recs=evidence_recs,
                                      unit_priorities=priorities,
                                      streaming=True, clock=clock,
                                      skip_keys=skip_keys)

            def tensor_gate(i, name, cancel=None, _req=required,
                            _p=pipeline):
                keys = _req[i].get(name)
                if keys:
                    _p.wait_units(i, keys, cancel=cancel)

            first_layer_at: list[float] = []

            def on_first_layer():
                first_layer_at.append(time.monotonic())

            stream_file_sink = None
            if file_pipeline is not None:
                def stream_file_sink(i, _reader, _st=st,
                                     _rwh=recs_with_headers):
                    rec, header = _rwh[i]
                    if preloaded and any(n in preloaded
                                         for n in header.tensors):
                        # A delta shard decodes only its changed
                        # tensors — the sink could never assemble the
                        # whole file from ring slots; the cache lane
                        # materializes it instead (all units local).
                        return None
                    return _StreamFileSink(file_pipeline, bridge,
                                           _st[i], rec, header,
                                           snapshot_dir, clock)

            clock.ensure("fetch")  # warm threads clock it; key must exist
            pipeline.poke(0)
            with clock("hbm_commit"), \
                    (telemetry.span("delta.swap",
                                    reused=len(preloaded))
                     if preloaded else contextlib.nullcontext()):
                params, hbm_stats = stage_cached_to_hbm(
                    bridge, recs_with_headers, mesh=mesh, rules=rules,
                    dtype=dtype,
                    prefetch_next=pipeline.poke,
                    clock=clock,
                    stream=True,
                    tensor_gate=tensor_gate,
                    on_first_layer=on_first_layer,
                    stream_file_sink=stream_file_sink,
                    preloaded=preloaded or None,
                    swap_from=swap_from,
                    exchange_landed=exchange_landed,
                )
            if first_layer_at:
                # Monotonic instant the first-token-capable set became
                # resident; _pull_model anchors it to the pull's t0.
                hbm_stats["first_layer_at"] = first_layer_at[0]
        else:
            on_host_ready = None
            if file_pipeline is not None:
                # Write-behind: the moment shard i's host tensors are
                # decoded, hand them to the file pipeline — the HF-cache
                # file is assembled from the decoded bytes (no second
                # decode) while the same shard's commit and the next
                # shard's decode proceed. The handoff is non-blocking by
                # default (ZEST_FILES_ASYNC): a full byte budget declines
                # — the shard then materializes from the cache after the
                # landing — instead of parking the decode thread and
                # dragging file writes back onto the time-to-HBM span.
                def on_host_ready(i, host, _st=st, _rwh=recs_with_headers):
                    rec, header = _rwh[i]
                    entry = _st[i]
                    if preloaded and any(n in preloaded
                                         for n in header.tensors):
                        return  # delta shard: host dict is partial —
                        # the cache lane materializes the file instead

                    def write(entry, _rec=rec, _h=header, _host=host):
                        dest = snapshot_dir / entry.path
                        if _is_complete(snapshot_dir, entry):
                            return "skipped"
                        tmp = _write_file_from_tensors(
                            bridge, _rec, _h, _host, dest)
                        if tmp is None:
                            return None  # decline → waterfall
                        # Fully written under a temp name; fsync + rename
                        # happen at the pull-exit durability barrier.
                        file_pipeline.defer_commit(tmp, dest)
                        file_pipeline.note_lane("tensors", entry.size)
                        clock.note_bytes("files", entry.size)
                        return "downloaded"

                    file_pipeline.submit_prepared(entry, write)

            pipeline = _PipelinedWarm(bridge, recs_only,
                                      evidence_recs=evidence_recs,
                                      skip_keys=skip_keys)
            with clock("fetch"):
                pipeline.ensure(0)
            with clock("hbm_commit"), \
                    (telemetry.span("delta.swap",
                                    reused=len(preloaded))
                     if preloaded else contextlib.nullcontext()):
                params, hbm_stats = stage_cached_to_hbm(
                    bridge, recs_with_headers, mesh=mesh, rules=rules,
                    dtype=dtype,
                    prefetch_next=pipeline.ensure,
                    on_host_ready=on_host_ready,
                    clock=clock,
                    stream=False,
                    preloaded=preloaded or None,
                    swap_from=swap_from,
                )
        # Join the warm threads before reading their stats: the
        # streaming tensor gate releases the moment a unit resolves —
        # the last shard's warm thread may still be in its retry pass /
        # stats append when the landing returns, and an unjoined thread
        # could keep writing cache entries after the pull itself
        # returns. (The non-streaming path joined every shard in
        # ensure(); this makes both paths uniform.)
        pipeline.drain()
        warm = pipeline.summary()
        if warm["failed"] or warm.get("prefetch_errors"):
            log(f"warm fetch: {warm['failed']} unit(s) + "
                f"{warm.get('prefetch_errors', 0)} whole-shard "
                "prefetch(es) failed; landing fell back per-term",
                file=sys.stderr)
        hbm_stats["warm"] = warm
        return params, hbm_stats
    except Exception as exc:  # noqa: BLE001 - landing is an accelerator
        if pipeline is not None:
            pipeline.drain()
        log(f"direct HBM landing unavailable ({exc}); "
            "will stage from disk after download", file=sys.stderr)
        return None, None


class _PipelinedWarm:
    """One-shard-lookahead warm fetch for the direct landing.

    ``ensure(i)`` joins shard ``i``'s warm fetch (spawning it if no one
    has) and kicks off shard ``i+1``'s in a background thread — so while
    shard ``i`` decodes and commits, shard ``i+1``'s bytes stream into
    the cache. Exactly one fetch runs concurrently with the landing
    (lookahead 1): deeper lookahead would pile cache writes onto the
    landing's reads on hosts where both share a disk. A failed prefetch
    is absorbed — the landing's per-term waterfall self-serves the
    missing units — and reported in :meth:`summary`.
    """

    def __init__(self, bridge, recs, evidence_recs=None,
                 unit_priorities=None, streaming: bool = False,
                 clock: StageClock | None = None,
                 skip_keys: frozenset | None = None):
        import threading

        from zest_tpu.transfer.federated import _entries_by_hash

        self._threading = threading
        self.bridge = bridge
        self.recs = recs
        # Full-vs-partial evidence, built ONCE over every known xet
        # reconstruction (``evidence_recs`` ⊇ the shards being warmed —
        # aux xet files can share xorbs with shards): the map is
        # invariant across shards, and per-shard rebuilds are
        # O(shards^2) CPU stolen from the decode+commit the lookahead
        # is trying to overlap.
        self.entries_map = _entries_by_hash(
            evidence_recs if evidence_recs is not None else recs)
        self.threads: dict[int, object] = {}
        self.stats: list[dict] = []
        self.cancelled = False
        # Streaming mode (ISSUE 8): the warm publishes per-unit
        # completion so the landing's tensor gate can decode a tensor
        # while the REST of its shard is still on the wire, and units
        # submit in layer-priority order (models.direct.
        # unit_layer_priorities) so embedding + layer 0 bytes arrive
        # first. Fetch wall is clocked per shard here — with the
        # landing no longer blocking on a whole-shard warm there is no
        # foreground ensure() left to attribute "fetch" to.
        self.streaming = streaming
        self.unit_priorities = unit_priorities
        self.clock = clock
        # Delta fast path (ISSUE 10): unit keys the plan proved
        # content-unchanged AND locally present are excluded from the
        # warm entirely — `_already_cached`'s per-unit full-entry read
        # + frame parse would otherwise re-read the whole checkpoint's
        # cache on a 1%-changed pull. Skipped units resolve (gates
        # release) immediately; a stale skip self-serves through the
        # landing's per-term waterfall, the same terminal fallback a
        # failed warm already uses.
        self.skip_keys = frozenset(skip_keys or ())
        # Warm threads are spawned per shard; re-bind the owning pull's
        # session id so their recorder events (fallbacks, strikes
        # downstream) attribute correctly under concurrent pulls.
        self._session_id = telemetry.session.current_id()
        self._cv = threading.Condition()
        self._units_done: set[tuple[str, int]] = set()
        self._shards_done: set[int] = set()

    def _spawn(self, i: int) -> None:
        # Under the condition's lock: streaming mode calls this from
        # the decode thread (poke) AND each warm thread's chained
        # finally concurrently — an unlocked check-then-insert would
        # let two threads fetch the same shard (racing cache writes)
        # with one of them lost to drain()'s join.
        with self._cv:
            if (self.cancelled or not 0 <= i < len(self.recs)
                    or i in self.threads):
                return
            t = self._threading.Thread(target=self._run, args=(i,),
                                       daemon=True)
            self.threads[i] = t
            t.start()

    def _shard_units(self, i: int):
        """Shard ``i``'s fetch units in landing-priority order (file
        order when no priorities were given), minus the delta skip set
        (those are marked resolved by the caller). Unknown units sort
        last."""
        from zest_tpu.models.direct import unit_priority_sort_key
        from zest_tpu.parallel.plan import collect_units

        units = [(key[0], fi) for key, fi in collect_units([self.recs[i]])
                 if key not in self.skip_keys]
        if self.unit_priorities:
            units.sort(key=unit_priority_sort_key(self.unit_priorities))
        return units

    def _mark_skipped(self, i: int) -> None:
        """Resolve shard ``i``'s delta-skipped units without touching
        their cache entries (gates on them release immediately)."""
        from zest_tpu.parallel.plan import collect_units

        for key, _fi in collect_units([self.recs[i]]):
            if key in self.skip_keys:
                self._mark_unit(key)

    def _mark_unit(self, key) -> None:
        with self._cv:
            self._units_done.add(key)
            self._cv.notify_all()

    def _mark_shard(self, i: int) -> None:
        with self._cv:
            self._shards_done.add(i)
            self._cv.notify_all()

    def _run(self, i: int) -> None:
        from zest_tpu.transfer.federated import warm_units_parallel

        telemetry.session.use(self._session_id)
        try:
            # entries_map = ALL shards: the full-vs-partial cache-key
            # decision must see cross-shard dedup, or a xorb shared
            # between shards gets a truncated blob under its full key.
            if self.streaming:
                import contextlib as _ctx

                if self.skip_keys:
                    self._mark_skipped(i)
                with (self.clock("fetch") if self.clock is not None
                      else _ctx.nullcontext()):
                    self.stats.append(warm_units_parallel(
                        self.bridge, [self.recs[i]],
                        entries_map=self.entries_map,
                        units=self._shard_units(i),
                        on_unit=self._mark_unit))
            else:
                self.stats.append(warm_units_parallel(
                    self.bridge, [self.recs[i]],
                    entries_map=self.entries_map,
                    units=(self._shard_units(i) if self.skip_keys
                           else None)))
        except Exception:  # noqa: BLE001 - landing self-serves misses
            self.stats.append({"units": 0, "bytes": 0, "failed": 0,
                               "prefetch_error": True})
        finally:
            # Shard-done ALWAYS fires (success, failure, cancel): gates
            # blocked on this shard release and the landing's per-term
            # waterfall self-serves whatever the warm didn't cache.
            self._mark_shard(i)
            if self.streaming:
                # Chained lookahead: the moment shard i's fetch drains,
                # shard i+1's starts — still at most ONE shard fetching
                # (the dedup race rule below), but now fully decoupled
                # from the landing's decode position.
                self._spawn(i + 1)

    def drain(self) -> None:
        """Stop spawning and wait out any in-flight prefetch (at most
        one shard). Both landing exits call this — the failure path
        before the disk fallback runs (an orphaned prefetch racing the
        fallback's waterfall would double-fetch units) and the success
        path before summary() (an unjoined warm thread could still be
        appending stats or writing cache entries after the pull
        returns). Idempotent."""
        # cancelled is set under the same lock _spawn checks it under,
        # so the snapshot below is complete: no thread can register
        # after it (a chained spawn racing this used to escape the
        # join and keep writing cache entries post-return).
        with self._cv:
            self.cancelled = True
            threads = list(self.threads.values())
        for t in threads:
            t.join()
        with self._cv:  # release any gate still parked on us
            self._shards_done.update(range(len(self.recs)))
            self._cv.notify_all()

    def ensure(self, i: int) -> None:
        """Block until shard ``i`` is warmed; then start shard ``i+1``.

        The lookahead spawns only after the join so two shards never
        fetch concurrently — units shared across shards (dedup) would
        otherwise be double-fetched by racing `_already_cached` checks.
        """
        self._spawn(i)
        t = self.threads.get(i)
        if t is not None:
            t.join()
        self._spawn(i + 1)

    def poke(self, i: int) -> None:
        """Non-blocking ensure — the streaming landing's
        ``prefetch_next``: start shard ``i``'s warm (no-op if running or
        done) and return; the tensor gate below is what actually waits,
        per tensor, not per shard."""
        self._spawn(i)

    def wait_units(self, i: int, keys: frozenset,
                   cancel=None) -> None:
        """Block until every unit in ``keys`` is resolved OR shard
        ``i``'s whole warm finished (covers failed/unknown units — the
        landing's waterfall self-serves those) OR ``cancel`` (the
        landing's abort event) is set — without it, a consumer error
        couldn't unblock a producer parked here until the in-flight
        shard fetch resolved on its own, stalling the disk fallback by
        the full fetch duration. The timeout re-check guards against a
        lost wakeup ever deadlocking the landing."""
        with self._cv:
            while not (keys <= self._units_done
                       or i in self._shards_done
                       or (cancel is not None and cancel.is_set())):
                self._cv.wait(0.05)

    # The per-shard counters summary() may sum. warm_units_parallel
    # counters are ADDITIVE by contract; anything it reports outside
    # this allowlist (a future rate, width, or timestamp) is surfaced
    # under ``unsummed_keys`` instead of being silently added up as if
    # it were a counter (ADVICE r5 — a summed timestamp would corrupt
    # the pull telemetry without ever failing a test).
    _COUNTER_KEYS = frozenset({"units", "bytes", "failed", "retried"})

    def summary(self) -> dict:
        """Aggregate of the per-shard warm stats: the allowlisted
        additive counters are summed; unknown numeric keys are listed,
        not summed. The merge runs through the telemetry registry's
        shared helper (ISSUE 4 satellite), which emits a ONE-TIME
        RuntimeWarning + a ``zest_unsummed_counter_keys_total`` bump for
        each dropped key — a newly added counter nobody allowlisted now
        fails loudly in CI output instead of silently vanishing."""
        sums, unsummed = telemetry.sum_allowlisted(
            self.stats, allow=self._COUNTER_KEYS,
            skip=("prefetch_error",), context="warm.summary")
        out = {"units": 0, "bytes": 0, "failed": 0,
               "pipelined_shards": len(self.threads)}
        out.update(sums)
        prefetch_errors = sum(
            1 for s in self.stats if s.get("prefetch_error"))
        if prefetch_errors:
            out["prefetch_errors"] = prefetch_errors
        if unsummed:
            out["unsummed_keys"] = unsummed
        return out


def _resolve_coop(cfg, coop, coop_hosts, coop_index, coop_addrs, log):
    """Resolve the cooperative-pull topology: explicit args > config
    (ZEST_COOP*) > auto. Auto turns coop ON when a multi-host topology
    is actually known (addr map / host count / multi-process mesh) —
    the ISSUE's "auto when a multi-host mesh is present" — and quietly
    OFF otherwise; an explicit ``coop=True`` with an unusable topology
    logs why it degraded. Returns (index, n_hosts, addrs) or None."""
    enabled = coop if coop is not None else cfg.coop_pull
    if enabled is False:
        return None
    n = coop_hosts if coop_hosts is not None else cfg.coop_hosts
    i = coop_index if coop_index is not None else cfg.coop_index
    addrs = dict(coop_addrs) if coop_addrs else dict(cfg.coop_addrs)
    if n is None and addrs:
        n = max(addrs) + 1
    if cfg.mesh.is_distributed:
        if n is None:
            n = cfg.mesh.num_processes
        if i is None:
            i = cfg.mesh.process_id
    if enabled is None:
        enabled = bool(n and n > 1)
    if not enabled:
        return None
    if not n or n <= 1 or i is None or not 0 <= i < n:
        log("cooperative pull disabled: need coop hosts > 1 and a "
            f"host index in range (hosts={n}, index={i})",
            file=sys.stderr)
        return None
    return i, n, addrs


def _coop_stage(bridge, recs, cfg, coop_cfg, repo_id, commit_sha, log,
                priorities=None, units=None):
    """Run the cooperative round, discovering peer DCN endpoints over
    the jax.distributed KV store when no explicit addr map was given
    (the zero-config multi-host TPU job path). The DCN listener binds
    BEFORE the announce so peers learn the truly bound port; it stays
    up under the bridge until pull exit (peers behind us still read).

    Also mints the pull's fleet ``trace_id`` (ISSUE 7): derived from
    ``repo@sha`` plus a KV-shared nonce when the coordinator store is
    reachable (host 0 announces it next to the addr exchange), so every
    host of the pod stamps the SAME id on its spans and carries it to
    peers in the DCN hello — the key ``zest trace --coop`` merges on.
    The id is installed as the process trace context (one host = one
    process in production) and repeated per-thread by coop_round for
    the in-process simulations."""
    from zest_tpu.telemetry.fleet import mint_trace_id
    from zest_tpu.transfer.coop import (
        CoopUnavailable, coop_round, exchange_addrs_via_kv,
        share_nonce_via_kv,
    )
    from zest_tpu.transfer.dcn import DcnServer

    host_index, n_hosts, addrs = coop_cfg
    pull_key = f"{repo_id}@{commit_sha}"
    nonce = ""
    server = None
    if not addrs:
        server = DcnServer(cfg, bridge.cache)
        try:
            port = server.start()
        except OSError:
            server, port = None, cfg.dcn_port
        else:
            bridge.adopt_coop_server(server)
        # Nonce ordering vs the addr exchange: host 0 WRITES its nonce
        # before announcing its addr, and peers poll for it only AFTER
        # the addr exchange — so "host 0's addr appeared" implies the
        # nonce is already readable, and a host-0 start lag inside the
        # addr window can never fork the pod onto two trace_ids (a
        # peer-side pre-poll with its own shorter window could).
        if host_index == 0:
            nonce = share_nonce_via_kv(pull_key, host_index)
        addrs = exchange_addrs_via_kv(
            pull_key, host_index, n_hosts, port)
        if not addrs:
            raise CoopUnavailable(
                "no coop peer addresses: set ZEST_COOP_ADDRS or run "
                "under jax.distributed for KV discovery")
        if host_index != 0:
            nonce = share_nonce_via_kv(pull_key, host_index,
                                       timeout_s=5.0)
    trace_id = mint_trace_id(pull_key, nonce)
    if telemetry.enabled():
        telemetry.trace.set_context(host=host_index, trace_id=trace_id)
        tracer = telemetry.trace.active()
        if tracer is not None:
            # Persist the identity at the DOC level too: pull_model
            # restores the previous context at exit, so the export's
            # otherData.context (what --merge keys host docs by) must
            # not depend on the context still being installed then.
            tracer.add_metadata(
                context={"host": host_index, "trace_id": trace_id})
    return coop_round(bridge, recs, host_index, n_hosts, addrs,
                      server=server,
                      budget_bytes=cfg.coop_inflight_bytes,
                      trace_id=trace_id,
                      priorities=priorities,
                      units=units,
                      log=lambda m: log(m))


def _early_config(hub, repo_id, revision, files, snapshot_dir) -> dict | None:
    """config.json parsed before the file loop runs.

    The pod pre-pass and direct landing both dispatch on the model
    family, and both run before any file is written — so config.json is
    downloaded early here (the file loop later skips it via
    ``_is_complete``). Returns None on any miss: callers degrade to the
    family-agnostic path."""
    import json

    dest = snapshot_dir / "config.json"
    if not dest.exists():
        entry = next((e for e in files if e.path == "config.json"), None)
        if entry is None:
            return None
        try:
            dest.parent.mkdir(parents=True, exist_ok=True)
            hub.download_regular_file(repo_id, revision, entry.path, dest)
        except Exception:  # noqa: BLE001 - family dispatch is optional
            return None
    try:
        cfg = json.loads(dest.read_text())
    except (OSError, ValueError):
        return None
    return cfg if isinstance(cfg, dict) else None


def _pod_stage(bridge, pending, recs, hub, repo_id, revision, files,
               snapshot_dir, log, early_cfg=None):
    """Collective byte distribution, family-dispatched.

    Expert-sharded families (models.registry.is_expert_sharded — Mixtral)
    route each expert's private xorbs to the one host whose shard
    consumes them (BASELINE config #4); everything else — and any
    failure inside the routing pre-pass — takes the plain all-gather
    round (config #3). Byte distribution always runs over the 1-D pod
    mesh (pod_round's default) — the N-D model mesh from config is for
    checkpoint *landing*, not bytes.

    **Multi-process safety**: the expert-vs-plain choice changes the
    collective's plan (shapes and count of all-gather rows), so every
    process MUST take the same branch — but the dispatch inputs
    (config.json download, header fetches) can fail per-host. All
    fallible pre-pass work therefore happens BEFORE any collective,
    folded into one local ``ready`` bit, and multi-process runs agree
    on ``all(ready)`` via a host-level allgather; a host with a
    transient HTTP failure downgrades the whole pod to the plain round
    instead of hanging it on mismatched collectives. The routing inputs
    themselves are content-addressed (pinned revision), so successful
    prep is identical everywhere by construction."""
    from zest_tpu.models.registry import is_expert_sharded
    from zest_tpu.parallel.mesh import num_slots, pod_mesh
    from zest_tpu.transfer.pod import pod_round

    import jax

    cfg_json = (early_cfg.result() if early_cfg is not None
                else _early_config(hub, repo_id, revision, files,
                                   snapshot_dir))
    n_experts = int((cfg_json or {}).get("num_local_experts") or 0)
    mesh = pod_mesh()
    prepped = None
    if (cfg_json and is_expert_sharded(cfg_json.get("model_type"))
            and n_experts > 0 and num_slots(mesh) > 1):
        try:
            prepped = _expert_prep(bridge, pending, recs, n_experts, mesh)
        except Exception as exc:  # noqa: BLE001 - routing is an accelerator
            log(f"expert routing unavailable ({exc}); "
                "falling back to the plain pod round", file=sys.stderr)
    if jax.process_count() > 1:
        # Unconditional when multi-process (a host that failed even the
        # config download must still rendezvous here): one tiny
        # host-level allgather of the local ready bit.
        import numpy as _np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            _np.asarray([prepped is not None]))
        if not bool(flags.all()):
            if prepped is not None:
                log("expert routing disabled: another host's pre-pass "
                    "failed; taking the plain pod round", file=sys.stderr)
            prepped = None
    if prepped is not None:
        return _expert_stage(bridge, prepped, mesh, log)
    return pod_round(bridge, recs, mesh=mesh, log=lambda m: log(m))


def _expert_prep(bridge, pending, recs, n_experts, mesh):
    """All fallible expert-routing inputs, fetched before any collective:
    safetensors headers (through the waterfall) → per-file tensor→expert
    maps + the placement. Returns (file_maps, other_recs, placement)."""
    from zest_tpu.models import moe
    from zest_tpu.parallel.expert import ExpertPlacement, classify_file
    from zest_tpu.parallel.mesh import num_slots
    from zest_tpu.transfer.pod import fetch_file_header

    placement = ExpertPlacement(n_experts, num_hosts=num_slots(mesh))
    file_maps, other = [], []
    for entry, rec in zip(pending, recs):
        if entry.path.endswith(".safetensors"):
            header = fetch_file_header(bridge, rec)
            file_maps.append(
                classify_file(rec, header, moe.expert_of_tensor))
        else:
            other.append(rec)
    if not file_maps:
        raise ValueError("no safetensors files to expert-route")
    return file_maps, other, placement


def _expert_stage(bridge, prepped, mesh, log):
    """Expert-routed distribution (transfer.pod.expert_pod_round) for
    the safetensors files; any other xet files (tokenizers etc.) still
    ride the plain round, reported under ``"other"``."""
    from zest_tpu.transfer.pod import expert_pod_round, pod_round

    file_maps, other, placement = prepped
    stats = expert_pod_round(bridge, file_maps, placement, mesh=mesh,
                             log=lambda m: log(m))
    stats["expert_routed"] = True
    stats["n_experts"] = placement.n_experts
    if other:
        stats["other"] = pod_round(bridge, other, mesh=mesh,
                                   log=lambda m: log(m))
    return stats


def _landing_rules(hub, repo_id, revision, files, snapshot_dir,
                   early_cfg=None):
    """Family shard rules for direct landing (models.registry dispatch).
    Returns None on any miss: the loader's infer_spec fallback still
    lands the bytes balanced."""
    from zest_tpu.models.registry import shard_rules_for_model_type

    cfg_json = (early_cfg.result() if early_cfg is not None
                else _early_config(hub, repo_id, revision, files,
                                   snapshot_dir))
    return shard_rules_for_model_type((cfg_json or {}).get("model_type"))


# pwritev batching bounds: iovec count per call (conservatively below
# every Linux IOV_MAX) and a byte ceiling per call (single write(2)/
# pwritev(2) transfers cap near 2 GiB — a larger batch would silently
# short-write and force the resume loop anyway).
_IOV_BATCH = 512
_IOV_BATCH_BYTES = 1 << 30


def _preallocate(fd: int, size: int) -> None:
    """Best-effort ``posix_fallocate``: reserves the extent map up
    front so the worker-pool writes below don't serialize on block
    allocation (and ENOSPC surfaces here, before any byte moves).
    Advisory — filesystems without extent support still work."""
    if size <= 0:
        return
    try:
        os.posix_fallocate(fd, 0, size)
    except (AttributeError, OSError):
        pass


def _pwritev_all(fd: int, buffers: list, offset: int) -> int:
    """Positional vectored write of ``buffers`` at ``offset``, resuming
    short writes (one pwritev(2) caps near 2 GiB; an unchecked short
    write would be COMMITTED by the atomic rename later). Returns the
    byte count. Falls back to plain ``os.pwrite`` loops when pwritev is
    unavailable."""
    views = [memoryview(b).cast("B") for b in buffers]
    total = sum(v.nbytes for v in views)
    pos = 0
    if hasattr(os, "pwritev"):
        while views:
            n = os.pwritev(fd, views, offset + pos)
            if n <= 0:
                raise OSError(f"pwritev wrote {n} bytes")
            pos += n
            while views and n >= views[0].nbytes:
                n -= views[0].nbytes
                views.pop(0)
            if views and n:
                views[0] = views[0][n:]
    else:  # pragma: no cover - every supported platform has pwritev
        for v in views:
            while v.nbytes:
                n = os.pwrite(fd, v, offset + pos)
                pos += n
                v = v[n:]
    if pos != total:
        raise OSError(f"pwritev wrote {pos} of {total} bytes")
    return pos


def _write_file_from_tensors(bridge, rec, header, host,
                             dest: Path) -> tuple[int, str] | None:
    """Write-behind fast lane: assemble a safetensors file from the
    landing's already-decoded host tensors — zero re-decode of the data
    section (the ``files`` stage used to decode every byte a second
    time, right after ``hbm_commit`` decoded it the first).

    Byte-exactness is guaranteed by construction, and only attempted
    when provable: the tensors' file ranges must tile the data section
    exactly (no gaps, no overlap — true for every writer we know of,
    but a file with padding would assemble wrong, so it falls back).
    The header prefix ([0, data_start)) is decoded from the cache (the
    warm fetch has those terms).

    The destination is preallocated (``posix_fallocate``) and written
    with batched ``pwritev`` — one syscall per ~hundreds of tensors
    instead of one ``write`` each. Returns the temp path — a fully
    written (and closed: a many-shard pull must not hold an fd per
    pending commit) file whose fsync + atomic rename belong to the
    caller's durability barrier — or ``None`` to decline, in which case
    the caller runs the normal cache-decode/waterfall path."""
    import tempfile

    import numpy as np

    from zest_tpu.models.direct import CachedFileReader

    data_start = header.data_start
    size = rec.total_bytes
    spans = sorted(
        (info.file_range(data_start) + (name,)
         for name, info in header.tensors.items()),
        key=lambda s: s[0],
    )
    pos = data_start
    for lo, hi, name in spans:
        if lo != pos or name not in host:
            return None
        pos = hi
    if pos != size:
        return None

    reader = CachedFileReader(bridge.cache, rec, bridge=bridge, workers=1)
    head = reader.read(0, data_start) if data_start else b""

    dest.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dest.parent, prefix=f".tmp-{dest.name}.")
    try:
        _preallocate(fd, size)
        offset = _pwritev_all(fd, [head], 0) if head else 0
        batch: list = []
        batch_bytes = 0
        batch_off = offset
        for _lo, _hi, name in spans:
            view = memoryview(
                np.ascontiguousarray(host[name]).reshape(-1)
                .view(np.uint8)).cast("B")
            # Zero-size tensors contribute no iovec (an all-empty batch
            # would make pwritev legitimately return 0, which the short-
            # write guard reads as an error); >1 GiB tensors split so no
            # single iovec nears the 2 GiB per-call transfer cap.
            while view.nbytes:
                piece = view[:_IOV_BATCH_BYTES]
                view = view[_IOV_BATCH_BYTES:]
                batch.append(piece)
                batch_bytes += piece.nbytes
                if (len(batch) >= _IOV_BATCH
                        or batch_bytes >= _IOV_BATCH_BYTES):
                    batch_off += _pwritev_all(fd, batch, batch_off)
                    batch, batch_bytes = [], 0
        if batch:
            _pwritev_all(fd, batch, batch_off)
    except BaseException:
        os.close(fd)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    os.close(fd)
    # Same per-source accounting as the cache-decode lane: the bytes
    # were served from cached units (decoded once, by the landing).
    for term in rec.terms:
        bridge.stats.record("cache", term.unpacked_length)
    return tmp


# One-shot downgrade for kernels/filesystems without a usable
# copy_file_range (ENOSYS pre-4.5, EXDEV across filesystems pre-5.3):
# after the first refusal every run uses the pread/pwrite fallback —
# still no decode, one user-space bounce instead of zero.
_CFR_DISABLED = not hasattr(os, "copy_file_range")


def _copy_run(src_fd: int, dst_fd: int, src_off: int, dst_off: int,
              length: int) -> None:
    """Move one contiguous payload run cache-entry → destination,
    kernel-side when the platform allows. Short transfers resume; a
    source that ends early (truncated entry) raises ValueError so the
    caller declines to the self-healing waterfall."""
    import errno

    global _CFR_DISABLED
    remaining = length
    while remaining:
        if not _CFR_DISABLED:
            try:
                n = os.copy_file_range(src_fd, dst_fd, remaining,
                                       src_off, dst_off)
            except OSError as exc:
                # Downgrade ONLY on platform refusal (pre-4.5 kernels,
                # cross-fs pre-5.3, fs without the op). A real I/O error
                # (EIO, ENOSPC...) must propagate — the caller declines
                # this file to the waterfall — not silently demote every
                # future pull in the process to the bounce path.
                if exc.errno not in (errno.ENOSYS, errno.EXDEV,
                                     errno.EOPNOTSUPP, errno.EINVAL):
                    raise
                _CFR_DISABLED = True
                continue
            if n == 0:
                raise ValueError(
                    f"cache entry ended {remaining} bytes early")
        else:
            data = os.pread(src_fd, min(remaining, 8 << 20), src_off)
            if not data:
                raise ValueError(
                    f"cache entry ended {remaining} bytes early")
            n = os.pwrite(dst_fd, data, dst_off)
        src_off += n
        dst_off += n
        remaining -= n


def _execute_copy_plan(copies, dst_fd: int) -> int:
    """Run a :meth:`CachedFileReader.copy_plan` copy list against the
    destination fd; returns bytes moved. Source fds are opened once per
    distinct entry path (terms of one file overwhelmingly share
    entries)."""
    fds: dict = {}
    moved = 0
    try:
        for path, src_offs, dst_offs, lens in copies:
            fd = fds.get(path)
            if fd is None:
                fd = fds[path] = os.open(path, os.O_RDONLY)
            for s, d, n in zip(src_offs.tolist(), dst_offs.tolist(),
                               lens.tolist()):
                _copy_run(fd, dst_fd, s, d, n)
                moved += n
    finally:
        for fd in fds.values():
            os.close(fd)
    return moved


def _write_file_from_cache(bridge, xet_hash: str, dest: Path,
                           lane_note=None) -> bool:
    """Materialize a file straight from cached units — the fast lane
    for files whose bytes a distribution round, warm fetch, or landing
    already put in the verified cache, i.e. the common state of the
    ``files`` stage.

    Two tiers inside (ISSUE 5): a **zero-copy tier** first —
    ``copy_file_range`` moves stored-scheme payload runs kernel-side
    from the cache entry into the (preallocated) destination, no decode
    and no user-space byte — then an mmap + in-place chunk decode tier
    for whatever the copy plan couldn't take (compressed chunks,
    footer-hashed entries, boundary terms, misses). ``lane_note`` gets
    the per-tier byte attribution. Returns False when any unit is
    missing or fails to decode, so the 3-deep waterfall chain (which
    can reach peers/CDN and self-heals corrupt cache keys) runs
    instead."""
    import mmap
    import tempfile

    from zest_tpu.models.direct import CachedFileReader, DirectLandingError

    rec = bridge.get_reconstruction(xet_hash)
    # cache-only (no bridge), and SERIAL term decode (workers=1): the
    # decode lands in an mmap view, and a worker exception's traceback
    # cycle can pin a view export past gc's reach — mm.close() would
    # then raise BufferError on a healthy fallback path. Concurrency
    # for the files stage comes from the file-level pipeline instead;
    # the parallel term decode serves the np-buffer landing path.
    reader = CachedFileReader(bridge.cache, rec, workers=1)
    size = reader.size
    dest.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dest.parent, prefix=f".tmp-{dest.name}.")
    try:
        ok = True
        err: BaseException | None = None
        copied = decoded = 0
        if size:
            _preallocate(fd, size)
            os.ftruncate(fd, size)
            try:
                copies, leftovers = reader.copy_plan(0, size)
            except DirectLandingError:
                copies, leftovers = [], [(0, size)]
            try:
                copied = _execute_copy_plan(copies, fd)
            except (OSError, ValueError):
                # A source entry vanished/truncated mid-copy: the
                # waterfall refetches and self-heals the cache key.
                ok = False
            if ok and leftovers:
                mm = mmap.mmap(fd, size)
                try:
                    view = memoryview(mm)
                    try:
                        for d_lo, d_hi in leftovers:
                            decoded += reader.read_into(
                                d_lo, d_hi, view[d_lo:d_hi])
                    except (DirectLandingError, ValueError):
                        # Handled HERE, inside the view's lifetime: a
                        # propagating traceback would pin read_into's
                        # frame (and its cast of this view), making
                        # mm.close() raise BufferError("exported
                        # pointers exist"). Covers cache misses and
                        # corrupt-entry decode errors alike — both mean
                        # "let the waterfall do it" (it self-heals bad
                        # cache keys).
                        ok = False
                    except BaseException as exc:
                        # Anything else (OSError, KeyboardInterrupt...)
                        # must survive as ITSELF, not as the masking
                        # BufferError — so detach its traceback (freeing
                        # the pinned view) and re-raise once the mmap is
                        # closed.
                        err = exc.with_traceback(None)
                    finally:
                        view.release()
                finally:
                    mm.close()
        if err is not None:
            raise err
        if not ok:
            os.unlink(tmp)
            return False
        os.replace(tmp, dest)
        if lane_note is not None:
            if copied:
                lane_note("copy", copied)
            if decoded:
                lane_note("decode", decoded)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    finally:
        os.close(fd)
    # Per-source accounting: one cache-tier event per term, like the
    # waterfall. Byte counts are the terms' UNPACKED lengths (sum =
    # file size); the waterfall records packed cached-blob lengths, so
    # the two lanes agree on counts and agree on bytes only up to
    # compression (bf16 checkpoints are mostly stored uncompressed).
    for term in rec.terms:
        bridge.stats.record("cache", term.unpacked_length)
    return True


def _pull_xet_file(bridge, par, hub, cfg, repo_id, revision, entry, dest, log,
                   lane_note=None):
    """Cache-direct fast lane, then the 3-deep fallback chain
    (reference: main.zig:232-256). A session cancellation
    (PullCancelled, ISSUE 13) is NOT a tier failure — it re-raises
    instead of falling to the next tier, or a cancelled pull would
    grind through every fallback (ending at a plain CDN download of
    the very file it was told to stop fetching)."""
    try:
        if _write_file_from_cache(bridge, entry.xet_hash, dest,
                                  lane_note=lane_note):
            return
    except PullCancelled:
        raise
    except Exception as exc:  # noqa: BLE001 - fast lane is optional
        log(f"cache-direct write of {entry.path} failed ({exc}); "
            "taking the waterfall chain", file=sys.stderr)
    if lane_note is not None:
        lane_note("waterfall", entry.size)
    try:
        par.reconstruct_to_file(entry.xet_hash, dest)
        return
    except PullCancelled:
        raise
    except Exception as exc:  # noqa: BLE001 - any failure falls through
        log(f"parallel fetch of {entry.path} failed ({exc}); "
            "retrying sequentially", file=sys.stderr)
    try:
        bridge.reconstruct_to_file(entry.xet_hash, dest)
        return
    except PullCancelled:
        raise
    except Exception as exc:  # noqa: BLE001
        log(f"sequential fetch of {entry.path} failed ({exc}); "
            "falling back to plain download", file=sys.stderr)
    hub.download_regular_file(repo_id, revision, entry.path, dest)


def _default_swarm(cfg: Config):
    """Construct the default swarm downloader; None when P2P can't start."""
    try:
        from zest_tpu.transfer.swarm import SwarmDownloader

        return SwarmDownloader(cfg)
    except Exception:
        return None
