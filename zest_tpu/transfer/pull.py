"""``pull``: download a model repo through the swarm into the HF cache.

The reference's ``cmdPull`` (src/main.zig:83-305): resolve revision, list
files, then per file run the 3-deep fallback chain — parallel reconstruct →
sequential bridge reconstruct → plain CDN download — and finish by writing
the refs file so ``from_pretrained()`` resolves offline. Already-cached
files are skipped (idempotent resume; SURVEY.md §5 "checkpoint/resume").

With ``device="tpu"`` the pulled checkpoint is additionally staged into
TPU HBM via zest_tpu.parallel (the north-star path; no reference
counterpart).
"""

from __future__ import annotations

import contextlib
import sys
import time
from pathlib import Path

from zest_tpu import storage
from zest_tpu.cas.hub import HubClient
from zest_tpu.config import Config
from zest_tpu.transfer.bridge import XetBridge
from zest_tpu.transfer.parallel import ParallelDownloader


class PullResult:
    """What a pull produced: the snapshot path, stats, and — for
    ``device="tpu"`` — the staged param tree. The result *owns* the HBM
    buffers: drop it (or set ``params = None``) to release them."""

    def __init__(self, snapshot_dir: Path, stats: dict, params=None):
        self.snapshot_dir = snapshot_dir
        self.stats = stats
        self.params = params  # name → jax.Array, or None

    def __fspath__(self) -> str:
        return str(self.snapshot_dir)

    def __str__(self) -> str:
        return str(self.snapshot_dir)


class StageClock:
    """Accumulating per-stage wall-clock for one pull — the tracing story
    SURVEY.md §5 asks for (the reference only prints end-of-pull totals,
    swarm.zig:472-485). ``with clock("fetch"):`` adds elapsed seconds to
    that stage; totals land in ``stats["stages"]``. Stages are additive
    and non-overlapping by construction (only the pull thread enters
    them), so they decompose ``elapsed_s`` minus untimed glue."""

    def __init__(self):
        self.seconds: dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, stage: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.seconds[stage] = (
                self.seconds.get(stage, 0.0) + time.monotonic() - t0
            )

    def summary(self) -> dict[str, float]:
        return {k: round(v, 4) for k, v in self.seconds.items()}


def _is_complete(snapshot_dir: Path, entry) -> bool:
    """One definition of "this file is already pulled" — shared by the
    pod pre-pass, the download loop's skip, and the direct-landing
    eligibility check, so the three never disagree about resume state."""
    dest = snapshot_dir / entry.path
    return dest.exists() and dest.stat().st_size == entry.size


def pull_model(
    cfg: Config,
    repo_id: str,
    revision: str = "main",
    device: str | None = None,
    swarm=None,
    no_p2p: bool = False,
    pod: bool | None = None,
    pods: int | None = None,
    pod_index: int | None = None,
    pod_addrs: dict[int, tuple[str, int]] | None = None,
    log=print,
) -> PullResult:
    t0 = time.monotonic()
    # Validate the landing dtype BEFORE any network work: a config typo
    # (ZEST_TPU_DTYPE=fp16) must fail fast here, not be swallowed by the
    # staging try/excepts after a multi-GB warm fetch. Only the TPU
    # device path consumes it — a plain pull ignores a bad value.
    land_dtype = None
    if device == "tpu":
        from zest_tpu.models.loader import resolve_dtype

        land_dtype = resolve_dtype(cfg.land_dtype)
    hub = HubClient(cfg)
    clock = StageClock()

    with clock("resolve"):
        commit_sha = hub.resolve_revision(repo_id, revision)
        files = hub.list_files(repo_id, revision)
    snapshot_dir = cfg.model_snapshot_dir(repo_id, commit_sha)

    if swarm is None and not no_p2p:
        swarm = _default_swarm(cfg)
    bridge = XetBridge(cfg, swarm=swarm)
    par = ParallelDownloader(bridge)
    authenticated = False

    # Pod pre-pass (BASELINE config #3): one collective round fills the
    # cache so the per-file loop below hits tier 1 for planned bytes.
    # Defaults on for --device=tpu; force with ZEST_TPU_POD=1/0.
    if pod is None:
        import os

        env = os.environ.get("ZEST_TPU_POD")
        pod = env == "1" if env in ("0", "1") else device == "tpu"
    fed = pods is not None and pods > 1 and pod_index is not None
    pod_stats = fed_stats = None
    if pod or fed:
        pending = [
            e for e in files
            if e.is_xet and not _is_complete(snapshot_dir, e)
        ]
        if pending:
            try:
                with clock("cas_metadata"):
                    bridge.authenticate(repo_id, revision, hub=hub)
                    authenticated = True
                    recs = [bridge.get_reconstruction(e.xet_hash)
                            for e in pending]
            except Exception as exc:  # noqa: BLE001 - round is an accelerator
                log(f"distribution rounds unavailable ({exc}); "
                    "continuing with the per-host waterfall",
                    file=sys.stderr)
                recs = None
            # Cross-pod stage first (pods that are separate processes —
            # DCN chunk RPC), so the in-pod collective spreads a warm
            # cache. Either round failing degrades to the waterfall.
            if recs and fed:
                try:
                    from zest_tpu.transfer.federated import federated_round

                    fed_stats = federated_round(
                        bridge, recs, pod_index, pods, pod_addrs or {},
                        log=lambda m: log(m),
                    )
                except Exception as exc:  # noqa: BLE001
                    log(f"federated round unavailable ({exc}); "
                        "continuing with the per-host waterfall",
                        file=sys.stderr)
            if recs and pod:
                try:
                    pod_stats = _pod_stage(
                        bridge, pending, recs, hub, repo_id, revision,
                        files, snapshot_dir, log)
                except Exception as exc:  # noqa: BLE001
                    log(f"pod round unavailable ({exc}); "
                        "continuing with the per-host waterfall",
                        file=sys.stderr)

    # Direct-to-HBM landing (SURVEY.md §7 hard part #2, the north star):
    # land tensors straight from cached units BEFORE any file is written,
    # so the landing path never reads a reassembled file. The HF-cache
    # files are still written by the loop below — served from the
    # now-warm cache, not refetched.
    hbm_params = hbm_stats = None
    mesh = None
    if device == "tpu":
        if cfg.mesh.mesh_axes:
            from zest_tpu.parallel.mesh import mesh_from_config

            mesh = mesh_from_config(cfg.mesh)
        hbm_params, hbm_stats = _try_direct_stage(
            bridge, hub, repo_id, revision, files, snapshot_dir, mesh,
            land_dtype, log, clock,
        )
        authenticated = authenticated or bridge.cas is not None

    downloaded = skipped = 0
    with clock("files"):
        for entry in files:
            dest = snapshot_dir / entry.path
            if _is_complete(snapshot_dir, entry):
                skipped += 1
                continue
            if entry.is_xet:
                if not authenticated:
                    bridge.authenticate(repo_id, revision, hub=hub)
                    authenticated = True
                _pull_xet_file(bridge, par, hub, cfg, repo_id, revision,
                               entry, dest, log)
            else:
                dest.parent.mkdir(parents=True, exist_ok=True)
                hub.download_regular_file(repo_id, revision, entry.path,
                                          dest)
            downloaded += 1

    storage.write_ref(cfg, repo_id, revision, commit_sha)

    elapsed = time.monotonic() - t0
    stats = {
        "repo": repo_id,
        "revision": commit_sha,
        "files_downloaded": downloaded,
        "files_skipped": skipped,
        "elapsed_s": round(elapsed, 3),
        "stages": clock.summary(),
        "fetch": bridge.stats.summary(),
    }
    if fed_stats is not None:
        stats["federated"] = fed_stats
    if pod_stats is not None:
        stats["pod"] = pod_stats
    if swarm is not None:
        stats["swarm"] = swarm.stats.summary()

    if device == "tpu" and hbm_stats is None:
        # Disk fallback: direct landing was ineligible or failed; the
        # files are on disk now, stage them the reference's way. A
        # staging failure (e.g. a repo whose .safetensors doesn't parse)
        # must not lose the completed download — report it and return.
        from zest_tpu.models.loader import stage_snapshot_to_hbm

        from zest_tpu.models.registry import shard_rules_for_snapshot

        try:
            with clock("hbm_commit"):
                hbm_params, hbm_stats = stage_snapshot_to_hbm(
                    snapshot_dir, mesh=mesh,
                    rules=shard_rules_for_snapshot(snapshot_dir),
                    dtype=land_dtype,
                )
            # The late stage must keep the decomposition invariant
            # (sum(stages) <= elapsed_s): refresh BOTH.
            stats["stages"] = clock.summary()
            stats["elapsed_s"] = round(time.monotonic() - t0, 3)
        except Exception as exc:  # noqa: BLE001
            log(f"HBM staging failed ({exc}); files remain in "
                f"{snapshot_dir}", file=sys.stderr)
            hbm_stats = {"error": str(exc), "direct": False}
    if hbm_stats is not None:
        stats["hbm"] = hbm_stats

    return PullResult(snapshot_dir, stats, params=hbm_params)


def _try_direct_stage(
    bridge, hub, repo_id, revision, files, snapshot_dir, mesh, dtype, log,
    clock: StageClock | None = None,
):
    """Direct cache→HBM landing for every safetensors file, before any
    file write. Returns ``(None, None)`` when ineligible — non-xet
    safetensors (no reconstruction to land from) or files already on
    disk (the resume case: reading local disk beats refetching) — or on
    any failure, in which case the disk fallback runs after the file
    loop."""
    st = [e for e in files if e.path.endswith(".safetensors")]
    if not st or not all(e.is_xet for e in st):
        return None, None
    if any(_is_complete(snapshot_dir, e) for e in st):
        return None, None
    if clock is None:
        clock = StageClock()
    pipeline = None
    try:
        from zest_tpu.models.loader import stage_cached_to_hbm
        from zest_tpu.transfer.pod import fetch_file_header

        with clock("cas_metadata"):
            if bridge.cas is None:
                bridge.authenticate(repo_id, revision, hub=hub)
            recs_with_headers = []
            for e in st:
                rec = bridge.get_reconstruction(e.xet_hash)
                recs_with_headers.append(
                    (rec, fetch_file_header(bridge, rec))
                )
            # Resolve every OTHER xet file's reconstruction too (KB-scale
            # metadata, memoized for the file loop moments later): the
            # full-vs-partial cache-key evidence must see ALL references
            # to a xorb — a tokenizer packed into the tail of a shard's
            # xorb would otherwise get that xorb full-keyed truncated.
            # Best-effort: a miss here costs evidence (partial keys),
            # never the landing.
            evidence_recs = [r for r, _h in recs_with_headers]
            for e in files:
                if e.is_xet and not e.path.endswith(".safetensors"):
                    try:
                        evidence_recs.append(
                            bridge.get_reconstruction(e.xet_hash))
                    except Exception:  # noqa: BLE001
                        pass
        # Whatever the distribution rounds didn't cache (single chip:
        # everything) arrives max_concurrent-wide, not term-by-term —
        # pipelined per shard: shard 0's fetch is the visible "fetch"
        # stage, every later shard's network time hides under the
        # previous shard's decode+commit inside "hbm_commit".
        pipeline = _PipelinedWarm(bridge, [r for r, _h in recs_with_headers],
                                  evidence_recs=evidence_recs)
        with clock("fetch"):
            pipeline.ensure(0)
        with clock("hbm_commit"):
            params, hbm_stats = stage_cached_to_hbm(
                bridge, recs_with_headers, mesh=mesh,
                rules=_landing_rules(hub, repo_id, revision, files,
                                     snapshot_dir),
                dtype=dtype,
                prefetch_next=pipeline.ensure,
            )
        warm = pipeline.summary()
        if warm["failed"] or warm.get("prefetch_errors"):
            log(f"warm fetch: {warm['failed']} unit(s) + "
                f"{warm.get('prefetch_errors', 0)} whole-shard "
                "prefetch(es) failed; landing fell back per-term",
                file=sys.stderr)
        hbm_stats["warm"] = warm
        return params, hbm_stats
    except Exception as exc:  # noqa: BLE001 - landing is an accelerator
        if pipeline is not None:
            pipeline.drain()
        log(f"direct HBM landing unavailable ({exc}); "
            "will stage from disk after download", file=sys.stderr)
        return None, None


class _PipelinedWarm:
    """One-shard-lookahead warm fetch for the direct landing.

    ``ensure(i)`` joins shard ``i``'s warm fetch (spawning it if no one
    has) and kicks off shard ``i+1``'s in a background thread — so while
    shard ``i`` decodes and commits, shard ``i+1``'s bytes stream into
    the cache. Exactly one fetch runs concurrently with the landing
    (lookahead 1): deeper lookahead would pile cache writes onto the
    landing's reads on hosts where both share a disk. A failed prefetch
    is absorbed — the landing's per-term waterfall self-serves the
    missing units — and reported in :meth:`summary`.
    """

    def __init__(self, bridge, recs, evidence_recs=None):
        import threading

        from zest_tpu.transfer.federated import _entries_by_hash

        self._threading = threading
        self.bridge = bridge
        self.recs = recs
        # Full-vs-partial evidence, built ONCE over every known xet
        # reconstruction (``evidence_recs`` ⊇ the shards being warmed —
        # aux xet files can share xorbs with shards): the map is
        # invariant across shards, and per-shard rebuilds are
        # O(shards^2) CPU stolen from the decode+commit the lookahead
        # is trying to overlap.
        self.entries_map = _entries_by_hash(
            evidence_recs if evidence_recs is not None else recs)
        self.threads: dict[int, object] = {}
        self.stats: list[dict] = []
        self.cancelled = False

    def _spawn(self, i: int) -> None:
        if (not self.cancelled and 0 <= i < len(self.recs)
                and i not in self.threads):
            t = self._threading.Thread(target=self._run, args=(i,),
                                       daemon=True)
            self.threads[i] = t
            t.start()

    def _run(self, i: int) -> None:
        from zest_tpu.transfer.federated import warm_units_parallel

        try:
            # entries_map = ALL shards: the full-vs-partial cache-key
            # decision must see cross-shard dedup, or a xorb shared
            # between shards gets a truncated blob under its full key.
            self.stats.append(warm_units_parallel(
                self.bridge, [self.recs[i]], entries_map=self.entries_map))
        except Exception:  # noqa: BLE001 - landing self-serves misses
            self.stats.append({"units": 0, "bytes": 0, "failed": 0,
                               "prefetch_error": True})

    def drain(self) -> None:
        """Stop spawning and wait out any in-flight prefetch (at most
        one shard). The landing's failure path calls this before the
        disk fallback runs — an orphaned prefetch racing the fallback's
        waterfall would double-fetch units and could still be writing
        cache entries after the pull returns."""
        self.cancelled = True
        for t in self.threads.values():
            t.join()

    def ensure(self, i: int) -> None:
        """Block until shard ``i`` is warmed; then start shard ``i+1``.

        The lookahead spawns only after the join so two shards never
        fetch concurrently — units shared across shards (dedup) would
        otherwise be double-fetched by racing `_already_cached` checks.
        """
        self._spawn(i)
        t = self.threads.get(i)
        if t is not None:
            t.join()
        self._spawn(i + 1)

    def summary(self) -> dict:
        """Aggregate of the per-shard warm stats. Sums EVERY numeric
        counter the fetcher reports (units/bytes/failed/retried/...), so
        a new counter in warm_units_parallel can't silently vanish from
        the pull's telemetry here."""
        out = {"units": 0, "bytes": 0, "failed": 0,
               "pipelined_shards": len(self.threads)}
        for s in self.stats:
            if s.get("prefetch_error"):
                out["prefetch_errors"] = out.get("prefetch_errors", 0) + 1
            for k, v in s.items():
                if k != "prefetch_error" and isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    out[k] = out.get(k, 0) + v
        return out


def _early_config(hub, repo_id, revision, files, snapshot_dir) -> dict | None:
    """config.json parsed before the file loop runs.

    The pod pre-pass and direct landing both dispatch on the model
    family, and both run before any file is written — so config.json is
    downloaded early here (the file loop later skips it via
    ``_is_complete``). Returns None on any miss: callers degrade to the
    family-agnostic path."""
    import json

    dest = snapshot_dir / "config.json"
    if not dest.exists():
        entry = next((e for e in files if e.path == "config.json"), None)
        if entry is None:
            return None
        try:
            dest.parent.mkdir(parents=True, exist_ok=True)
            hub.download_regular_file(repo_id, revision, entry.path, dest)
        except Exception:  # noqa: BLE001 - family dispatch is optional
            return None
    try:
        cfg = json.loads(dest.read_text())
    except (OSError, ValueError):
        return None
    return cfg if isinstance(cfg, dict) else None


def _pod_stage(bridge, pending, recs, hub, repo_id, revision, files,
               snapshot_dir, log):
    """Collective byte distribution, family-dispatched.

    Expert-sharded families (models.registry.is_expert_sharded — Mixtral)
    route each expert's private xorbs to the one host whose shard
    consumes them (BASELINE config #4); everything else — and any
    failure inside the routing pre-pass — takes the plain all-gather
    round (config #3). Byte distribution always runs over the 1-D pod
    mesh (pod_round's default) — the N-D model mesh from config is for
    checkpoint *landing*, not bytes.

    **Multi-process safety**: the expert-vs-plain choice changes the
    collective's plan (shapes and count of all-gather rows), so every
    process MUST take the same branch — but the dispatch inputs
    (config.json download, header fetches) can fail per-host. All
    fallible pre-pass work therefore happens BEFORE any collective,
    folded into one local ``ready`` bit, and multi-process runs agree
    on ``all(ready)`` via a host-level allgather; a host with a
    transient HTTP failure downgrades the whole pod to the plain round
    instead of hanging it on mismatched collectives. The routing inputs
    themselves are content-addressed (pinned revision), so successful
    prep is identical everywhere by construction."""
    from zest_tpu.models.registry import is_expert_sharded
    from zest_tpu.parallel.mesh import num_slots, pod_mesh
    from zest_tpu.transfer.pod import pod_round

    import jax

    cfg_json = _early_config(hub, repo_id, revision, files, snapshot_dir)
    n_experts = int((cfg_json or {}).get("num_local_experts") or 0)
    mesh = pod_mesh()
    prepped = None
    if (cfg_json and is_expert_sharded(cfg_json.get("model_type"))
            and n_experts > 0 and num_slots(mesh) > 1):
        try:
            prepped = _expert_prep(bridge, pending, recs, n_experts, mesh)
        except Exception as exc:  # noqa: BLE001 - routing is an accelerator
            log(f"expert routing unavailable ({exc}); "
                "falling back to the plain pod round", file=sys.stderr)
    if jax.process_count() > 1:
        # Unconditional when multi-process (a host that failed even the
        # config download must still rendezvous here): one tiny
        # host-level allgather of the local ready bit.
        import numpy as _np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            _np.asarray([prepped is not None]))
        if not bool(flags.all()):
            if prepped is not None:
                log("expert routing disabled: another host's pre-pass "
                    "failed; taking the plain pod round", file=sys.stderr)
            prepped = None
    if prepped is not None:
        return _expert_stage(bridge, prepped, mesh, log)
    return pod_round(bridge, recs, mesh=mesh, log=lambda m: log(m))


def _expert_prep(bridge, pending, recs, n_experts, mesh):
    """All fallible expert-routing inputs, fetched before any collective:
    safetensors headers (through the waterfall) → per-file tensor→expert
    maps + the placement. Returns (file_maps, other_recs, placement)."""
    from zest_tpu.models import moe
    from zest_tpu.parallel.expert import ExpertPlacement, classify_file
    from zest_tpu.parallel.mesh import num_slots
    from zest_tpu.transfer.pod import fetch_file_header

    placement = ExpertPlacement(n_experts, num_hosts=num_slots(mesh))
    file_maps, other = [], []
    for entry, rec in zip(pending, recs):
        if entry.path.endswith(".safetensors"):
            header = fetch_file_header(bridge, rec)
            file_maps.append(
                classify_file(rec, header, moe.expert_of_tensor))
        else:
            other.append(rec)
    if not file_maps:
        raise ValueError("no safetensors files to expert-route")
    return file_maps, other, placement


def _expert_stage(bridge, prepped, mesh, log):
    """Expert-routed distribution (transfer.pod.expert_pod_round) for
    the safetensors files; any other xet files (tokenizers etc.) still
    ride the plain round, reported under ``"other"``."""
    from zest_tpu.transfer.pod import expert_pod_round, pod_round

    file_maps, other, placement = prepped
    stats = expert_pod_round(bridge, file_maps, placement, mesh=mesh,
                             log=lambda m: log(m))
    stats["expert_routed"] = True
    stats["n_experts"] = placement.n_experts
    if other:
        stats["other"] = pod_round(bridge, other, mesh=mesh,
                                   log=lambda m: log(m))
    return stats


def _landing_rules(hub, repo_id, revision, files, snapshot_dir):
    """Family shard rules for direct landing (models.registry dispatch).
    Returns None on any miss: the loader's infer_spec fallback still
    lands the bytes balanced."""
    from zest_tpu.models.registry import shard_rules_for_model_type

    cfg_json = _early_config(hub, repo_id, revision, files, snapshot_dir)
    return shard_rules_for_model_type((cfg_json or {}).get("model_type"))


def _write_file_from_cache(bridge, xet_hash: str, dest: Path) -> bool:
    """Decode cached units straight into the destination file (mmap +
    in-place chunk decode, no per-term refetch loop, no join) — the fast
    lane for files whose bytes a distribution round or warm fetch
    already landed in the cache, i.e. the common state of the ``files``
    stage. Returns False when any unit is missing or fails to decode,
    so the 3-deep waterfall chain (which can reach peers/CDN and
    self-heals corrupt cache keys) runs instead."""
    import mmap
    import os
    import tempfile

    from zest_tpu.models.direct import CachedFileReader, DirectLandingError

    rec = bridge.get_reconstruction(xet_hash)
    reader = CachedFileReader(bridge.cache, rec)  # cache-only: no bridge
    size = reader.size
    dest.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dest.parent, prefix=f".tmp-{dest.name}.")
    try:
        ok = True
        err: BaseException | None = None
        if size:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
            try:
                view = memoryview(mm)
                try:
                    reader.read_into(0, size, view)
                except (DirectLandingError, ValueError):
                    # Handled HERE, inside the view's lifetime: a
                    # propagating traceback would pin read_into's frame
                    # (and its cast of this view), making mm.close()
                    # raise BufferError("exported pointers exist").
                    # Covers cache misses and corrupt-entry decode
                    # errors alike — both mean "let the waterfall do
                    # it" (it self-heals bad cache keys).
                    ok = False
                except BaseException as exc:
                    # Anything else (OSError, KeyboardInterrupt...) must
                    # survive as ITSELF, not as the masking BufferError —
                    # so detach its traceback (freeing the pinned view)
                    # and re-raise once the mmap is closed.
                    err = exc.with_traceback(None)
                finally:
                    view.release()
            finally:
                mm.close()
        if err is not None:
            raise err
        if not ok:
            os.unlink(tmp)
            return False
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    finally:
        os.close(fd)
    # Per-source accounting: one cache-tier event per term, like the
    # waterfall. Byte counts are the terms' UNPACKED lengths (sum =
    # file size); the waterfall records packed cached-blob lengths, so
    # the two lanes agree on counts and agree on bytes only up to
    # compression (bf16 checkpoints are mostly stored uncompressed).
    for term in rec.terms:
        bridge.stats.record("cache", term.unpacked_length)
    return True


def _pull_xet_file(bridge, par, hub, cfg, repo_id, revision, entry, dest, log):
    """Cache-direct fast lane, then the 3-deep fallback chain
    (reference: main.zig:232-256)."""
    try:
        if _write_file_from_cache(bridge, entry.xet_hash, dest):
            return
    except Exception as exc:  # noqa: BLE001 - fast lane is optional
        log(f"cache-direct write of {entry.path} failed ({exc}); "
            "taking the waterfall chain", file=sys.stderr)
    try:
        par.reconstruct_to_file(entry.xet_hash, dest)
        return
    except Exception as exc:  # noqa: BLE001 - any failure falls through
        log(f"parallel fetch of {entry.path} failed ({exc}); "
            "retrying sequentially", file=sys.stderr)
    try:
        bridge.reconstruct_to_file(entry.xet_hash, dest)
        return
    except Exception as exc:  # noqa: BLE001
        log(f"sequential fetch of {entry.path} failed ({exc}); "
            "falling back to plain download", file=sys.stderr)
    hub.download_regular_file(repo_id, revision, entry.path, dest)


def _default_swarm(cfg: Config):
    """Construct the default swarm downloader; None when P2P can't start."""
    try:
        from zest_tpu.transfer.swarm import SwarmDownloader

        return SwarmDownloader(cfg)
    except Exception:
        return None
