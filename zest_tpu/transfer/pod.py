"""Pod distribution round: one collective fetch for the whole mesh.

This is what makes ``pull`` pod-native (BASELINE config #3): instead of
every host running the per-term waterfall independently (N× CDN
ingress), the round computes the deterministic ownership plan, each
owner sources only its units through the waterfall
(``XetBridge.fetch_unit``), one jitted resharding all-gathers the staged
pool over ICI, gathered blobs are BLAKE3-verified *on device* (full
xorbs: chunk hashes on the accelerator, Merkle fold on host), and every
verified blob lands in the local cache — so the per-file reconstruction
that follows hits tier 1 for everything and the P2P byte ratio goes to
(n-1)/n of planned bytes.

The round is strictly an accelerator for the unchanged waterfall
contract: anything it misses (failed fetch → zero row, failed verify →
not cached) falls through to peers/CDN during reconstruction, preserving
the reference's degradation semantics (SURVEY.md §5 "failure detection").
"""

from __future__ import annotations

import time

from zest_tpu import telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.parallel.collectives import PodDistributor
from zest_tpu.parallel.mesh import num_slots, pod_mesh
from zest_tpu.parallel.plan import DistributionPlan


def _device_verify_full_xorb(data: bytes, hash_hex: str, hasher,
                             fused=None) -> bool:
    """Full-xorb integrity on the accelerator: decode frames, hash every
    chunk payload on device (keyed, chunk domain), Merkle-fold on host,
    compare to the content address.

    With a ``fused`` verifier (ops.FusedBg4Verifier, TPU landings), BG4
    chunks skip the host byte-regroup entirely: only the LZ4 entropy
    stage runs host-side, the planar bytes ride PCIe, and the regroup +
    BLAKE3 happen in one fused device pass — the host never
    materializes the interleaved bytes of the dominant tensor-data
    scheme."""
    from zest_tpu.cas.compression import Scheme

    try:
        reader = XorbReader(data)
        n = len(reader)
        digests: list[bytes | None] = [None] * n
        # Columnar views, not reader.entries: verification runs per
        # filled unit, and materializing a ChunkEntry per frame here
        # would re-pay the per-chunk object cost the decode engine
        # removed.
        sizes = reader.chunk_sizes.tolist()
        bg4 = [i for i, s in enumerate(reader.chunk_schemes.tolist())
               if s == int(Scheme.BG4_LZ4)] if fused is not None else []
        if bg4:
            planar = [reader.extract_chunk_planar(i) for i in bg4]
            for i, d in zip(bg4, fused.hash_planar_batch(
                    planar, [sizes[i] for i in bg4])):
                digests[i] = d
        rest = [i for i in range(n) if digests[i] is None]
        if rest:
            chunks = [reader.extract_chunk(i, verify=False) for i in rest]
            for i, d in zip(rest, hasher.hash_batch(chunks)):
                digests[i] = d
        leaves = list(zip(digests, sizes))
        return hashing.hash_to_hex(hashing.xorb_hash(leaves)) == hash_hex
    except Exception:
        # Any malformed peer-supplied blob — bad framing (XorbFormatError)
        # or chunks exceeding the hasher's leaf cap (ValueError from
        # hash_batch) — is a verification failure, never a round abort:
        # one bad unit must not kill the fill phase.
        return False


def make_unit_verifier(key: bytes | None = None):
    """``verify(hash_hex, data) -> bool`` for provably-whole xorb blobs
    — one construction shared by every tier that admits peer-served
    whole units into the cache (the pod round's ICI gather fill and the
    cooperative exchange, transfer.coop). On TPU the BG4 chunks of the
    blob expand+verify in one fused Pallas pass
    (ops.decode_pallas.FusedBg4Verifier) so the compressed wire bytes
    are judged where the FLOPs are; elsewhere the host batch hasher
    runs. Built once per round: the hasher/fused-kernel setup is not
    per-unit work."""
    from zest_tpu.ops import fused_verifier_for_backend, unit_verify_hasher

    if key is None:
        key = hashing.CHUNK_KEY
    hasher = unit_verify_hasher(key)
    fused = fused_verifier_for_backend(key)

    def verify(hash_hex: str, data: bytes) -> bool:
        return _device_verify_full_xorb(data, hash_hex, hasher,
                                        fused=fused)

    return verify


def local_slice_groups(n_hosts: int) -> tuple[int, ...] | None:
    """Slice id per coop host index, from the JAX runtime — the
    auto-inferred topology the collective exchange classes its links
    with (``transfer.collective.slice_topology``; the explicit
    ``ZEST_COOP_TOPOLOGY`` override wins for sims).

    Multi-slice TPU jobs expose ``Device.slice_index``; each process's
    devices share one slice, so process index → slice id is the whole
    map. Returns None when the runtime has no slice notion (CPU sims,
    single-controller), when jax is not importable here, or when the
    process count disagrees with ``n_hosts`` (a coop round spanning a
    different host set than the mesh — no honest inference exists)."""
    try:
        import jax

        devs = jax.devices()
        if not devs or getattr(devs[0], "slice_index", None) is None:
            return None
        by_proc: dict[int, int] = {}
        for d in devs:
            by_proc.setdefault(int(d.process_index), int(d.slice_index))
        if sorted(by_proc) != list(range(n_hosts)):
            return None
        return tuple(by_proc[i] for i in range(n_hosts))
    except Exception:  # noqa: BLE001 - topology inference is advisory
        return None


def fetch_file_header(bridge, rec):
    """Parse a safetensors header by fetching only the file's head terms.

    Expert routing must know tensor byte ranges *before* the bulk fetch
    (zest_tpu.parallel.expert); the header lives in the first few KB, so
    this pulls terms through the waterfall until ``8 + header_len`` bytes
    are decoded (each fetched blob lands in the cache and is reused by
    the bulk round). Raises for files that are not safetensors.
    """
    import struct as _struct

    from zest_tpu.models.safetensors_io import parse_header_prefix

    buf = bytearray()
    for term in rec.terms:
        buf += bridge.fetch_term(term, rec)
        if len(buf) >= 8:
            (hlen,) = _struct.unpack_from("<Q", buf, 0)
            if len(buf) >= 8 + hlen:
                break
    return parse_header_prefix(bytes(buf))


def expert_pod_round(
    bridge, file_maps, placement, mesh=None, log=None
) -> dict:
    """Expert-sharded distribution round (BASELINE config #4).

    Shared (dense) units go through the normal all-gather round; units
    feeding exactly one expert's tensors are fetched *only* by the
    process that owns that expert's shard — never gathered, saving
    (X-1)/X of expert-weight ICI traffic. Under a single controller that
    means all expert units are fetched locally (it owns every shard);
    multi-process, each process fetches its hosts' expert units.
    """
    from zest_tpu.parallel.expert import ExpertRoutedPlan

    with telemetry.span("pod.expert_round", files=len(file_maps)):
        return _expert_pod_round(bridge, file_maps, placement, mesh, log,
                                 ExpertRoutedPlan)


def _expert_pod_round(bridge, file_maps, placement, mesh, log,
                      ExpertRoutedPlan) -> dict:
    mesh = pod_mesh() if mesh is None else mesh
    routed = ExpertRoutedPlan.build(file_maps, placement)

    t0 = time.monotonic()
    shared_stats = pod_round(bridge, [], mesh=mesh, log=None,
                             _plan=routed.shared)

    import jax

    if jax.process_count() == 1:
        my_hosts = range(placement.num_hosts)
    else:
        # Placement hosts are mesh slots along the pod axis, not process
        # indices: with several local devices per process (the normal TPU
        # topology) one process covers several slots. Derive the slots this
        # process's addressable devices occupy — the same mapping
        # PodDistributor uses for its shard bands.
        my_hosts = [
            s for s in PodDistributor(mesh).local_slots()
            if s < placement.num_hosts
        ]
    # Whole-checkpoint full-vs-partial evidence, built ONCE (the per-unit
    # rebuild would be O(units x files) on the fetch hot loop).
    from zest_tpu.transfer.federated import _entries_by_hash

    entries_map = _entries_by_hash([fm.rec for fm in file_maps])
    fetched = failed = expert_bytes = 0
    for h in my_hosts:
        for a in routed.expert_units.get(h, []):
            try:
                data = bridge.fetch_unit(a.hash_hex, a.fetch_info)
            except Exception:
                failed += 1
                continue
            fi = a.fetch_info
            # The bridge's guarded write: never-narrower under the
            # hash-striped lock, ENOSPC absorbed (bridge.cache_blob).
            bridge.cache_blob(
                a.hash_hex, fi.range.start, data,
                whole=bridge.whole_xorb_provable(
                    entries_map.get(a.hash_hex, []), fi.range.start))
            fetched += 1
            expert_bytes += len(data)

    s = routed.summary()
    return {
        "shared": shared_stats,
        "expert_units_fetched": fetched,
        "expert_units_failed": failed,
        "expert_bytes": expert_bytes,
        "ici_bytes_saved": s["ici_bytes_saved"],
        "elapsed_s": round(time.monotonic() - t0, 3),
    }


def pod_round(
    bridge, recs, mesh=None, log=None, _plan=None, budget_bytes=None,
) -> dict:
    """Run one distribution round for ``recs`` over ``mesh``.

    Single-slot meshes skip the collective entirely — the waterfall alone
    is optimal there. The round is windowed: the plan is split into waves
    whose staged pool fits ``budget_bytes`` (default
    ``Config.hbm_staging_bytes``; the reference's analog is its 128-term
    batches, src/parallel_download.zig:117-131), each wave gathered,
    verified, and drained into the cache before the next is staged —
    per-device HBM cost is bounded by the budget, not the model size.
    Returns the stats block recorded under ``stats["pod"]`` in PullResult.
    """
    with telemetry.span("pod.round", files=len(recs)):
        return _pod_round(bridge, recs, mesh, log, _plan, budget_bytes)


def _pod_round(
    bridge, recs, mesh=None, log=None, _plan=None, budget_bytes=None,
) -> dict:
    mesh = pod_mesh() if mesh is None else mesh
    n = num_slots(mesh)
    plan = _plan if _plan is not None else DistributionPlan.build(recs, n)
    if not plan.assignments or n <= 1:
        return {"slots": n, "units": len(plan.assignments), "skipped": True}

    from zest_tpu.parallel.collectives import split_waves

    if budget_bytes is None:
        budget_bytes = bridge.cfg.hbm_staging_bytes
    waves = split_waves(plan, budget_bytes)

    dist = PodDistributor(mesh)
    # Full xorbs are device-verified before caching; partial-range blobs
    # carry per-chunk hashes in their frames, checked at extraction
    # (XorbReader) — same trust boundary as the reference's cache writes
    # (swarm.zig:416-420). On TPU the verifier's BG4 chunks
    # expand+verify in one fused device pass (ops.decode_pallas).
    verifier = make_unit_verifier()
    filled = rejected = 0
    gather_s = fill_s = 0.0
    peak_pool = 0
    for wave in waves:
        tw = time.monotonic()
        pool = dist.distribute(
            wave,
            lambda a: bridge.fetch_unit(a.hash_hex, a.fetch_info),
        )
        t_gather = time.monotonic()
        f, r = pool.fill_cache(bridge.cache, verify=verifier)
        filled += f
        rejected += r
        if r:
            # Flight-recorder breadcrumb: a rejected wave unit is a
            # trust-boundary event worth its position in the timeline.
            telemetry.record("verify_rejected", tier="pod", count=r)
        peak_pool = max(peak_pool, pool.layout.pool_bytes)
        gather_s += t_gather - tw
        fill_s += time.monotonic() - t_gather
        del pool  # drop the gathered buffers before staging the next wave

    stats = {
        "slots": n,
        "units": len(plan.assignments),
        "planned_bytes": plan.total_bytes,
        "waves": len(waves),
        "pool_bytes": peak_pool,
        "budget_bytes": budget_bytes,
        "balance": plan.summary()["balance"],
        "filled": filled,
        "verify_rejected": rejected,
        "gather_s": round(gather_s, 3),
        "fill_s": round(fill_s, 3),
    }
    if log is not None:
        log(f"pod round: {filled}/{stats['units']} units cached over "
            f"{n} slots in {len(waves)} wave(s) "
            f"({stats['planned_bytes']} bytes, gather {stats['gather_s']}s)")
    return stats
