"""Pod distribution round: one collective fetch for the whole mesh.

This is what makes ``pull`` pod-native (BASELINE config #3): instead of
every host running the per-term waterfall independently (N× CDN
ingress), the round computes the deterministic ownership plan, each
owner sources only its units through the waterfall
(``XetBridge.fetch_unit``), one jitted resharding all-gathers the staged
pool over ICI, gathered blobs are BLAKE3-verified *on device* (full
xorbs: chunk hashes on the accelerator, Merkle fold on host), and every
verified blob lands in the local cache — so the per-file reconstruction
that follows hits tier 1 for everything and the P2P byte ratio goes to
(n-1)/n of planned bytes.

The round is strictly an accelerator for the unchanged waterfall
contract: anything it misses (failed fetch → zero row, failed verify →
not cached) falls through to peers/CDN during reconstruction, preserving
the reference's degradation semantics (SURVEY.md §5 "failure detection").
"""

from __future__ import annotations

import time

from zest_tpu.cas import hashing
from zest_tpu.cas.xorb import XorbFormatError, XorbReader
from zest_tpu.parallel.collectives import PodDistributor
from zest_tpu.parallel.mesh import num_slots, pod_mesh
from zest_tpu.parallel.plan import DistributionPlan


def _device_verify_full_xorb(data: bytes, hash_hex: str, hasher) -> bool:
    """Full-xorb integrity on the accelerator: decode frames, hash every
    chunk payload on device (keyed, chunk domain), Merkle-fold on host,
    compare to the content address."""
    try:
        reader = XorbReader(data)
        chunks = [
            reader.extract_chunk(i, verify=False) for i in range(len(reader))
        ]
    except XorbFormatError:
        return False
    digests = hasher.hash_batch(chunks)
    leaves = [(d, len(c)) for d, c in zip(digests, chunks)]
    return hashing.hash_to_hex(hashing.xorb_hash(leaves)) == hash_hex


def pod_round(bridge, recs, mesh=None, log=None) -> dict:
    """Run one distribution round for ``recs`` over ``mesh``.

    Single-slot meshes skip the collective entirely — the waterfall alone
    is optimal there. Returns the stats block recorded under
    ``stats["pod"]`` in PullResult.
    """
    mesh = pod_mesh() if mesh is None else mesh
    n = num_slots(mesh)
    plan = DistributionPlan.build(recs, n)
    if not plan.assignments or n <= 1:
        return {"slots": n, "units": len(plan.assignments), "skipped": True}

    from zest_tpu.ops import best_hasher

    t0 = time.monotonic()
    dist = PodDistributor(mesh)
    pool = dist.distribute(
        plan,
        lambda a: bridge.fetch_unit(a.hash_hex, a.fetch_info),
    )
    t_gather = time.monotonic()
    # Full xorbs are device-verified before caching; partial-range blobs
    # carry per-chunk hashes in their frames, checked at extraction
    # (XorbReader) — same trust boundary as the reference's cache writes
    # (swarm.zig:416-420).
    hasher = best_hasher(hashing.CHUNK_KEY)
    filled, rejected = pool.fill_cache(
        bridge.cache,
        verify=lambda hh, data: _device_verify_full_xorb(data, hh, hasher),
    )
    t_fill = time.monotonic()

    stats = {
        "slots": n,
        "units": len(plan.assignments),
        "planned_bytes": plan.total_bytes,
        "pool_bytes": pool.layout.pool_bytes,
        "balance": plan.summary()["balance"],
        "filled": filled,
        "verify_rejected": rejected,
        "gather_s": round(t_gather - t0, 3),
        "fill_s": round(t_fill - t_gather, 3),
    }
    if log is not None:
        log(f"pod round: {filled}/{stats['units']} units cached over "
            f"{n} slots ({stats['planned_bytes']} bytes, "
            f"gather {stats['gather_s']}s)")
    return stats
