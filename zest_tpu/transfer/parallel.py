"""Parallel term fetcher: the multi-stream download engine.

The reference batches 128 terms, runs 16 concurrent fetch tasks, then
serializes writes after a batch barrier (src/parallel_download.zig:91-204).
This build improves on that per SURVEY.md §2.4: term output offsets are
known up front from the reconstruction plan, so workers ``pwrite`` their
terms straight to the right file offset — full pipelining, no
barrier-then-serialize, bounded memory (at most ``max_concurrent`` blobs
in flight). First error wins and cancels remaining work (the reference's
atomic error flag, parallel_download.zig:152-153).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait
from pathlib import Path

from zest_tpu.cas import reconstruction as recon
from zest_tpu.transfer.bridge import XetBridge


class ParallelDownloader:
    def __init__(self, bridge: XetBridge, max_concurrent: int | None = None,
                 executor: ThreadPoolExecutor | None = None):
        """``executor``, when given, is a SHARED term-fetch pool: the
        pipelined pull reconstructs several files concurrently, and one
        pool across all of them bounds total in-flight fetch threads at
        the pool's size instead of files x max_concurrent. The caller
        owns its lifetime (it is never shut down here). Term tasks never
        block on other term tasks, so sharing cannot deadlock — worst
        case is queueing."""
        self.bridge = bridge
        self.max_concurrent = (
            max_concurrent or bridge.cfg.max_concurrent_downloads
        )
        self._executor = executor

    def reconstruct_to_file(self, file_hash_hex: str, out_path: Path) -> int:
        rec = self.bridge.get_reconstruction(file_hash_hex)
        return self.reconstruct_plan_to_file(rec, out_path)

    def reconstruct_plan_to_file(
        self, rec: recon.Reconstruction, out_path: Path
    ) -> int:
        total = rec.total_bytes
        offsets = []
        pos = 0
        for term in rec.terms:
            offsets.append(pos)
            pos += term.unpacked_length

        out_path = Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        # Unique per call (mkstemp), not a fixed ".tmp-<name>": two
        # concurrent pulls of the same repo (the serving memo allows the
        # race — pull_model is idempotent) must not truncate or replace
        # each other's half-written file; both finish, last rename wins.
        import tempfile

        fd, tmp_name = tempfile.mkstemp(dir=out_path.parent,
                                        prefix=f".tmp-{out_path.name}.")
        tmp_path = Path(tmp_name)
        cancel = threading.Event()
        try:
            os.ftruncate(fd, total)

            def fetch_one(i: int) -> None:
                if cancel.is_set():
                    return
                term = rec.terms[i]
                data = self.bridge.fetch_term(term, rec)
                if cancel.is_set():
                    return
                os.pwrite(fd, data, offsets[i])

            pool = self._executor or ThreadPoolExecutor(self.max_concurrent)
            try:
                futures = [
                    pool.submit(fetch_one, i) for i in range(len(rec.terms))
                ]
                done, not_done = wait(futures, return_when=FIRST_EXCEPTION)
                first_error = next(
                    (f.exception() for f in done if f.exception()), None
                )
                if first_error is not None:
                    cancel.set()
                    for f in not_done:
                        f.cancel()
                    wait(not_done)  # cancelled-or-done before fd closes
                    raise first_error
            finally:
                if pool is not self._executor:
                    pool.shutdown(wait=True)
        except BaseException:
            os.close(fd)
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
            raise
        os.close(fd)
        os.replace(tmp_path, out_path)
        return total
