"""Cooperative pod-scale pull: shard the CDN fetch across hosts,
redistribute compressed chunks host-to-host (ROADMAP item 1).

The single-host pull is done (PRs 1-5); what remains between this build
and the north star (Llama-3.1-70B -> v5p-64 HBM in <60 s, >=90%
peer-served) is that every host still fetches the WHOLE deduped xorb
set from CDN. This module makes the pull pod-native: the pod's N hosts
agree — with zero coordination — on a byte-balanced ownership plan over
the deduped fetch units, each host fetches only its ~1/N share through
the existing resilient waterfall (cache -> peers -> CDN, PR-2
hedging/retries intact), and an **exchange phase** redistributes the
verified chunks host-to-host over the DCN chunk RPC so every host ends
fully cached and lands its own mesh shard. Per-host CDN demand drops
N-fold (16x on v5p-64) and the peer-served ratio rises to ~(N-1)/N by
construction.

Three design rules carried through from the papers this leans on:

- **Compressed on the wire** (EQuARX, PAPERS.md): the exchange moves
  xorb *frame streams* — BG4/LZ4 payloads still in their compressed,
  planar form — and the receiving host expands+verifies with the fused
  Pallas kernel (ops.decode_pallas.FusedBg4Verifier via
  transfer.pod.make_unit_verifier) before anything is decoded for
  ``device_put``. The interconnect never carries expanded bytes.
- **Bounded staging** ("Bounded-Memory Parallel Image Pulling",
  PAPERS.md): exchange windows acquire a :class:`ByteBudget` before
  any reply is in flight and drain into the on-disk cache before the
  next window stages — no host ever holds ~model-size blobs in memory
  on top of the landing's own staging.
- **Degradation, never a stall** (PR-2 failure model): a host that the
  health machinery has quarantined is excluded from the plan up front
  (its share re-shards across the alive hosts, every unit exactly
  once); a host that dies *mid-exchange* (connection reset, injected
  ``dcn_reset``/``peer_timeout``) degrades its units to the per-host
  CDN fallback — the pull always completes, ``fallbacks`` counts the
  cost, and nothing unverified ever reaches the cache.

The in-pod spread (one host's devices) stays with the existing
collective machinery: ``transfer.pod.pod_round`` over ICI after this
round, and ``transfer.federated`` remains the cross-pod (separate-job)
tier. This module is the *host-level* tier between them.

Since ISSUE 14 the exchange phase itself is a ladder: the
**collective-native** path (transfer.collective — a plan-derived
hypercube/ring phase schedule, one pre-sized window per phase,
topology-aware ici/dcn link classes) runs first; a dead or straggling
partner aborts it to the point-to-point exchange below; and the
point-to-point exchange keeps degrading per-unit to the CDN fallback.
``ZEST_COOP_COLLECTIVE=0`` skips straight to the point-to-point
exchange, restoring the PR-6 behavior (and stats schema) bit-for-bit.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from zest_tpu import faults, telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas.compression import CompressionError
from zest_tpu.cas.reconstruction import FetchInfo, Reconstruction
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.parallel.plan import collect_units
from zest_tpu.transfer.dcn import DcnPool, DcnResponse, DcnServer
from zest_tpu.transfer.federated import (
    _already_cached,
    _blob_covers,
    _cache_unit,
    _entries_by_hash,
    warm_units_parallel,
)

_M_COOP_BYTES = telemetry.counter(
    "zest_coop_bytes_total",
    "Cooperative-pull payload bytes by serving tier",
    ("tier",))
_M_COOP_FALLBACKS = telemetry.counter(
    "zest_coop_fallbacks_total",
    "Exchange units degraded to the per-host CDN fallback")
# Pod-aggregation inputs (ISSUE 7): each host exports its exchange wall
# and fetch-phase bytes as gauges; the coordinator's ?scope=pod scrape
# derives zest_coop_straggler_seconds (slowest minus median wall) and
# the fetch-share skew from the per-host-labeled series.
_M_COOP_EXCHANGE_WALL = telemetry.gauge(
    "zest_coop_exchange_wall_seconds",
    "This host's last cooperative exchange-phase wall time")
_M_COOP_FETCH_BYTES = telemetry.gauge(
    "zest_coop_fetch_bytes",
    "This host's last cooperative fetch-phase bytes (its plan share)")
# The exchange had only byte totals; this is the latency distribution.
# Observed per unit as window-wall / units-in-window (units in one
# pipelined window complete together, so the amortized figure is the
# honest per-unit number).
_M_COOP_UNIT_SECONDS = telemetry.histogram(
    "zest_coop_exchange_unit_seconds",
    "Amortized per-unit exchange latency (window wall over window units)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0))

# Exchange pacing: how long a host keeps retrying NOT_FOUND units
# (the owner may simply still be fetching them — hosts run the round
# concurrently) before degrading them to CDN, and the per-pass backoff.
DEFAULT_EXCHANGE_DEADLINE_S = 60.0
_RETRY_SLEEP_S = 0.25
_RETRY_SLEEP_CAP_S = 2.0
# Exchange window target: enough replies in flight to pipeline the
# channel without staging more than this (and never more than the
# ByteBudget admits) per request batch.
_WINDOW_TARGET_BYTES = 32 * 1024 * 1024
_WINDOW_MAX_UNITS = 64


class CoopUnavailable(RuntimeError):
    """Cooperative mode cannot run (no peer addresses, no alive hosts):
    the caller must degrade to the ordinary full-fetch waterfall —
    partially fetching 1/N and then having nobody to exchange with
    would be strictly worse than not cooperating."""


@dataclass(frozen=True)
class CoopPlan:
    """Deterministic, byte-balanced unit->host ownership.

    Every host builds the plan independently from the same
    reconstruction set and MUST get byte-for-byte the same answer (the
    exchange asks owner ``h`` for exactly the units ``h`` believes it
    owns). Determinism comes from sorted inputs + a pure greedy:
    units sorted by (wire bytes desc, key) are assigned to the
    least-loaded alive host, ties broken by host index. LPT keeps the
    per-host byte skew within ``mean + largest_unit`` — far inside the
    1.15x-of-mean bound the tests pin for checkpoint-shaped unit sets —
    where the HRW draw the pod/federated tiers use (uniform, not
    load-aware) can leave a host with 2x the mean at typical unit
    counts.

    ``quarantined`` hosts (the PR-2 health registry's verdict, or an
    operator's) are excluded from the draw entirely: their share
    re-shards across the alive hosts with every unit still assigned
    exactly once — the straggler rule SCALING.md §6 documents.
    """

    n_hosts: int
    alive: tuple[int, ...]
    units: tuple[tuple[tuple[str, int], FetchInfo], ...]
    owners: dict[tuple[str, int], int]

    @staticmethod
    def build(recs: list[Reconstruction], n_hosts: int,
              quarantined=frozenset(), units=None) -> "CoopPlan":
        """``units`` restricts the plan to an explicit
        ``[(hash_hex, FetchInfo)]`` subset — the delta pull's
        content-changed units (transfer.delta). The subset MUST be a
        pure function of content-addressed metadata, never of local
        cache state: the fingerprint below is the cross-host agreement
        proof, and hosts with differently-warm caches still compute the
        identical changed set from the same two revisions."""
        if n_hosts <= 0:
            raise ValueError("n_hosts must be positive")
        alive = tuple(h for h in range(n_hosts) if h not in set(quarantined))
        if not alive:
            raise CoopUnavailable("every host is quarantined")
        if units is not None:
            units = tuple(sorted(
                ((hh, fi.range.start), fi) for hh, fi in units))
        else:
            units = tuple(collect_units(recs))
        order = sorted(
            units,
            key=lambda u: (-(u[1].url_range_end - u[1].url_range_start),
                           u[0]),
        )
        load = {h: 0 for h in alive}
        owners: dict[tuple[str, int], int] = {}
        for key, fi in order:
            best = min(alive, key=lambda h: (load[h], h))
            owners[key] = best
            load[best] += fi.url_range_end - fi.url_range_start
        return CoopPlan(n_hosts, alive, units, owners)

    def for_host(self, host: int) -> list[tuple[str, FetchInfo]]:
        return [(key[0], fi) for key, fi in self.units
                if self.owners[key] == host]

    def bytes_per_host(self) -> dict[int, int]:
        out = {h: 0 for h in self.alive}
        for key, fi in self.units:
            out[self.owners[key]] += fi.url_range_end - fi.url_range_start
        return out

    @property
    def total_bytes(self) -> int:
        return sum(fi.url_range_end - fi.url_range_start
                   for _k, fi in self.units)

    def skew(self) -> float:
        """max bytes/host over mean bytes/host (1.0 = perfect)."""
        per = self.bytes_per_host()
        if not per or self.total_bytes == 0:
            return 1.0
        mean = self.total_bytes / len(per)
        return max(per.values()) / mean if mean else 1.0

    def fingerprint(self) -> str:
        """Content hash of the full assignment — the determinism proof
        hosts could cross-check out of band (tests pin that shuffled
        reconstruction order and repeated builds agree)."""
        acc = hashing.blake3_hash(
            b"|".join(
                f"{hh}:{start}:{self.owners[(hh, start)]}".encode()
                for (hh, start), _fi in self.units
            )
        )
        return acc.hex()

    def summary(self) -> dict:
        per = self.bytes_per_host()
        return {
            "units": len(self.units),
            "hosts": self.n_hosts,
            "alive": len(self.alive),
            "total_bytes": self.total_bytes,
            "bytes_per_host": [per.get(h, 0) for h in range(self.n_hosts)],
            "skew": round(self.skew(), 4),
            "fingerprint": self.fingerprint()[:16],
        }


def quarantined_hosts(health, host_addrs: dict[int, tuple[str, int]]):
    """Hosts whose DCN address the PR-2 health registry currently holds
    in quarantine — excluded from the plan so their share re-shards
    before the round instead of timing out during it."""
    if health is None:
        return frozenset()
    out = set()
    for h, addr in host_addrs.items():
        try:
            if health.is_quarantined(addr):
                out.add(h)
        except Exception:  # noqa: BLE001 - health is advisory
            continue
    return frozenset(out)


def _unpacked_bytes(data: bytes) -> int:
    """Sum of the blob's chunk unpacked sizes — the bytes the wire
    would have carried had the exchange shipped expanded payloads.
    ``wire < unpacked`` on compressible checkpoints is the
    compressed-on-the-wire evidence the bench records."""
    try:
        return int(XorbReader(data).chunk_sizes.sum())
    except Exception:  # noqa: BLE001 - malformed blobs are rejected later
        return len(data)


class _ExchangeStats:
    """Thread-safe per-unit attribution LEDGER for the exchange phase.

    Tier attribution must exactly tile the delivered bytes: every unit
    is booked under exactly one tier — the exchange wire (with its
    ici/dcn link class, collective mode) or the fallback tier that
    actually served it — and the tier totals are derived from the
    ledger, never incremented twice. A unit that is RE-delivered later
    in the round (the mid-round eviction race: an exchanged unit's
    cache entry can be evicted under disk pressure before a fallback
    pass re-lists it, so the refetch books fallback bytes for a unit
    the exchange already counted) REPLACES its earlier booking — the
    aborted delivery's bytes are subtracted, so
    ``wire_bytes + fallback_bytes`` always equals the bytes that ended
    the round attributed, one tier per unit. ``reattributed`` counts
    the replacements (absent when zero, keeping the stats schema
    byte-identical on rounds without the race)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # key (hash_hex, range_start) ->
        #   (kind, tier, bytes, unpacked, lossy_exact)
        self._booked: dict[tuple[str, int], tuple] = {}
        self.units = 0
        self.wire_bytes = 0
        self.unpacked_bytes = 0
        # Lossy-tier slice of the wire bytes (ZEST_COLLECTIVE_LOSSY):
        # quantized container bytes actually shipped, and the
        # byte-exact bytes they replaced — bits_saved_ratio derives
        # from the pair. Both stay 0 (and out of the summary) on
        # byte-exact rounds.
        self.lossy_bytes = 0
        self.lossy_exact_bytes = 0
        self.fallback_units = 0
        self.fallback_bytes = 0
        # Fallback bytes by the tier that ACTUALLY served them (the
        # full waterfall runs, so a "CDN fallback" unit can still come
        # from a swarm peer or the cache): peer_served_ratio must not
        # book peer-served fallback bytes as CDN spend.
        self.fallback_tiers: dict[str, int] = {}
        self.reattributed = 0
        self.verify_rejected = 0
        self.retries = 0
        self.dead_hosts: set[int] = set()

    def book_exchange(self, key: tuple[str, int], wire: int,
                      unpacked: int, link: str = "dcn",
                      lossy_exact: int | None = None) -> None:
        """Attribute one exchange-delivered unit to the wire tier.
        ``lossy_exact`` (the byte-exact length a quantized container
        replaced) marks the unit as lossy-delivered."""
        with self.lock:
            self._unbook(key)
            self._booked[key] = ("x", link, wire, unpacked, lossy_exact)
            self.units += 1
            self.wire_bytes += wire
            self.unpacked_bytes += unpacked
            if lossy_exact is not None:
                self.lossy_bytes += wire
                self.lossy_exact_bytes += lossy_exact

    def book_fallback(self, key: tuple[str, int], source: str,
                      nbytes: int) -> None:
        """Attribute one fallback-delivered unit to its serving tier."""
        with self.lock:
            self._unbook(key)
            self._booked[key] = ("f", source, nbytes, 0, None)
            self.fallback_units += 1
            self.fallback_bytes += nbytes
            self.fallback_tiers[source] = (
                self.fallback_tiers.get(source, 0) + nbytes)

    def _unbook(self, key: tuple[str, int]) -> None:
        prev = self._booked.pop(key, None)
        if prev is None:
            return
        kind, tier, nbytes, unpacked, lossy_exact = prev
        self.reattributed += 1
        if kind == "x":
            self.units -= 1
            self.wire_bytes -= nbytes
            self.unpacked_bytes -= unpacked
            if lossy_exact is not None:
                self.lossy_bytes -= nbytes
                self.lossy_exact_bytes -= lossy_exact
        else:
            self.fallback_units -= 1
            self.fallback_bytes -= nbytes
            left = self.fallback_tiers.get(tier, 0) - nbytes
            if left > 0:
                self.fallback_tiers[tier] = left
            else:
                self.fallback_tiers.pop(tier, None)

    def summary(self) -> dict:
        out = {
            "units": self.units,
            "wire_bytes": self.wire_bytes,
            "unpacked_bytes": self.unpacked_bytes,
            "fallback_units": self.fallback_units,
            "fallback_bytes": self.fallback_bytes,
            "verify_rejected": self.verify_rejected,
            "retries": self.retries,
        }
        if self.lossy_bytes:
            # Present only when lossy traffic actually flowed — the
            # byte-exact default keeps the schema bit-identical.
            out["lossy_bytes"] = self.lossy_bytes
            if self.lossy_exact_bytes:
                out["bits_saved_ratio"] = round(
                    1.0 - self.lossy_bytes / self.lossy_exact_bytes, 4)
        if self.fallback_tiers:
            out["fallback_tiers"] = dict(sorted(self.fallback_tiers.items()))
        if self.reattributed:
            out["reattributed"] = self.reattributed
        if self.dead_hosts:
            out["dead_hosts"] = sorted(self.dead_hosts)
        return out


def coop_round(
    bridge,
    recs: list[Reconstruction],
    host_index: int,
    n_hosts: int,
    host_addrs: dict[int, tuple[str, int]] | None = None,
    *,
    budget_bytes: int | None = None,
    server: DcnServer | None = None,
    quarantined=None,
    entries_map: dict[str, list[FetchInfo]] | None = None,
    deadline_s: float | None = None,
    dcn_pool: DcnPool | None = None,
    trace_id: str | None = None,
    priorities: dict | None = None,
    units=None,
    log=None,
) -> dict:
    """One cooperative round: plan -> fetch (my ~1/N) -> exchange.

    Afterwards every unit of ``recs`` is in the local verified cache,
    so the direct landing (or the in-pod ``pod_round``) runs entirely
    peer-fed. Returns the ``stats["coop"]`` block with
    ``peer_served_ratio`` as the headline.

    ``host_addrs`` maps host index -> (host, dcn_port) for every OTHER
    host (``server``, when given, is this host's already-running DCN
    listener; otherwise one is started on ``cfg.dcn_port`` — or
    ephemeral when that port is taken — and owned by the bridge until
    ``bridge.close()``, so late peers can still read from us while the
    landing proceeds). Raises :class:`CoopUnavailable` when no exchange
    peer is addressable — the caller degrades to the full waterfall.

    ``trace_id`` is the fleet trace identity every host of this pull
    shares (pull_model mints it from ``repo@sha`` + the KV-shared
    nonce); when absent it is derived from the deduped unit-key set —
    identical on every host by construction, so bare ``coop_round``
    callers still correlate. The round runs under a thread-scoped trace
    context (host index + trace_id) so its spans split into per-host
    tracks even when several simulated hosts share one process.

    ``priorities`` (unit key ``(hash_hex, range_start)`` → sortable
    layer-priority tuple, models.direct.unit_layer_priorities) orders
    BOTH phases' iteration — my fetch share and each owner's exchange
    request stream — so a streaming landing receives embedding +
    layer-0 bytes first. Ordering only: the ownership plan, its
    fingerprint, and every stats field are computed exactly as without
    it (tests pin the fingerprint unchanged), so hosts may even
    disagree about priorities (they don't — the key is a pure function
    of content-addressed metadata) without breaking the exchange.

    ``units`` restricts the round to an explicit unit subset — the
    delta pull's content-changed set (transfer.delta): the ownership
    plan (and its fingerprint) is built over ONLY those units, so hosts
    with differently-warm caches still agree, and unchanged bytes never
    cross the exchange wire. Per-host stale units (evicted locally) are
    each host's own waterfall problem, never the plan's.
    """
    if trace_id is None:
        trace_id = _derive_trace_id(recs)
    with telemetry.trace.context(host=host_index, trace_id=trace_id):
        with telemetry.span("coop.round", hosts=n_hosts):
            return _coop_round(bridge, recs, host_index, n_hosts,
                               host_addrs or {}, budget_bytes, server,
                               quarantined, entries_map, deadline_s,
                               dcn_pool, trace_id, priorities, units,
                               log)


def _derive_trace_id(recs) -> str:
    """Trace id from the deduped unit-key set: every host of one pull
    computes the same sorted key list from the same reconstructions
    (quarantine/ownership do NOT enter — health views may differ across
    hosts; the unit set cannot)."""
    from zest_tpu.telemetry.fleet import mint_trace_id

    keys = "|".join(f"{hh}:{start}"
                    for (hh, start), _fi in sorted(collect_units(recs)))
    return mint_trace_id(keys)


def _layer_order(units, priorities):
    """Stable layer-priority ordering of ``[(hash_hex, fi)]`` unit
    lists — units the map doesn't know (non-safetensors files) sort
    last, keyed for determinism. No-op without priorities."""
    if not priorities:
        return units
    from zest_tpu.models.direct import unit_priority_sort_key
    return sorted(units, key=unit_priority_sort_key(priorities))


def _coop_round(bridge, recs, host_index, n_hosts, host_addrs,
                budget_bytes, server, quarantined, entries_map,
                deadline_s, dcn_pool, trace_id, priorities, unit_subset,
                log) -> dict:
    from zest_tpu.transfer.pull import ByteBudget

    t0 = time.monotonic()
    if n_hosts <= 1:
        return {"host": host_index, "hosts": n_hosts, "skipped": True}
    peers = {h: a for h, a in host_addrs.items() if h != host_index}
    if not peers:
        raise CoopUnavailable(
            f"cooperative pull over {n_hosts} hosts has no peer "
            "addresses (host_addrs empty)")

    swarm_health = getattr(getattr(bridge, "swarm", None), "health", None)
    q = set(quarantined or ())
    q |= quarantined_hosts(swarm_health, peers)
    q.discard(host_index)  # we are demonstrably alive
    plan = CoopPlan.build(recs, n_hosts, frozenset(q),
                          units=unit_subset)
    if entries_map is None:
        entries_map = _entries_by_hash(recs)

    # Serve our share while (and after) we pull everyone else's: the
    # listener must outlive this round — peers behind us in the round
    # still read from it — so an owned server is parked on the bridge
    # and closed with it (transfer.pull calls bridge.close() at exit).
    own_server = False
    if server is None:
        server = DcnServer(bridge.cfg, bridge.cache)
        try:
            server.start()
        except OSError:
            # Port taken — normally this host's own daemon already
            # serving the same cache dir over DCN; peers reach that.
            server = None
        else:
            own_server = True
            bridge.adopt_coop_server(server)

    if budget_bytes is None:
        budget_bytes = getattr(bridge.cfg, "coop_inflight_bytes",
                               1 << 30)
    if deadline_s is None:
        deadline_s = DEFAULT_EXCHANGE_DEADLINE_S
        # The default must scale with the work: retry headroom for
        # owners that are legitimately still fetching their share at
        # pod scale (a fixed 60 s would mass-fallback a 9 GB/host
        # checkpoint on a WAN CDN), while explicit callers keep full
        # control. 8 s per plan-GB on top of the floor is ~3x the
        # north-star per-host fetch time.
        deadline_s += 8.0 * plan.total_bytes / 1e9

    # ── Phase 1: fetch my share through the resilient waterfall ──
    # Layer-ordered when the caller is a streaming landing: my share
    # warms early-layer bytes first, and peers asking ME get them
    # servable sooner. The plan itself is untouched.
    mine = _layer_order(plan.for_host(host_index), priorities)
    before = _tier_bytes(bridge.stats)
    with telemetry.span("coop.fetch", units=len(mine)):
        fetch_stats = warm_units_parallel(bridge, recs,
                                          entries_map=entries_map,
                                          units=mine)
    fetch_tiers = _tier_delta(before, _tier_bytes(bridge.stats))
    for tier, nbytes in fetch_tiers.items():
        if nbytes:
            _M_COOP_BYTES.inc(nbytes, tier=tier)
    _M_COOP_FETCH_BYTES.set(sum(fetch_tiers.values()))

    # ── Phase 2: exchange — pull every foreign-owned unit from its
    # owner over DCN, windowed under the byte budget ──
    budget = ByteBudget(budget_bytes)
    ex = _ExchangeStats()
    pool = dcn_pool or DcnPool()
    own_pool = dcn_pool is None
    verify = _make_verifier()
    # Anchored HERE, not at round start: the fetch phase's duration is
    # workload (a slow CDN), and letting it consume the exchange budget
    # would time out healthy owners — striking their health and
    # degrading the whole exchange to CDN exactly when cooperation
    # matters most.
    deadline = time.monotonic() + deadline_s

    foreign = {
        h: _layer_order([(hh, fi) for hh, fi in plan.for_host(h)
                         if not _already_cached(bridge, hh, fi)],
                        priorities)
        for h in plan.alive if h != host_index
    }
    clock_offsets: dict = {}
    collective_stats: dict | None = None
    use_collective = bool(getattr(bridge.cfg, "coop_collective", True))
    t_exchange = time.monotonic()
    try:
        with telemetry.span("coop.exchange",
                            collective=use_collective) as _xsp:
            # Collective tier FIRST (transfer.collective, ROADMAP item
            # 3): the phase schedule redistributes everything in
            # O(log N) pre-sized windows; whatever it could not deliver
            # (abort on a dead/straggling partner) falls to the PR-6
            # point-to-point exchange below, which itself degrades
            # per-unit to the CDN fallback — the full ladder.
            # ZEST_COOP_COLLECTIVE=0 skips straight to point-to-point,
            # restoring the PR-6 exchange bit-for-bit.
            if use_collective and any(foreign.values()):
                from zest_tpu.transfer.collective import (
                    CollectiveUnavailable, pod_topology,
                    run_collective, slice_topology,
                )

                try:
                    topo = slice_topology(n_hosts, cfg=bridge.cfg)
                    pods = pod_topology(n_hosts, cfg=bridge.cfg)
                    collective_stats, foreign = run_collective(
                        bridge, plan, host_index, peers, pool, budget,
                        ex, verify, deadline, topo,
                        priorities=priorities, entries_map=entries_map,
                        health=swarm_health, pods=pods)
                except (CollectiveUnavailable, ValueError) as exc:
                    # ValueError = a topology spec that disagrees with
                    # this round's host count — a config problem, but
                    # the point-to-point exchange needs no topology,
                    # so degrade (recorded) instead of failing the
                    # whole cooperative round over link classing.
                    telemetry.record("collective_unavailable",
                                     error=str(exc))
                if (collective_stats
                        and collective_stats.get("aborted") == "remediation"
                        and collective_stats.get("dead_host") is not None):
                    # The remediation engine condemned this partner
                    # mid-round (ISSUE 17): handing its leftovers a
                    # fresh point-to-point channel would override that
                    # decision and ride NOT_FOUND retries to the shared
                    # deadline. They degrade straight down the landing
                    # waterfall instead (another peer / swarm / CDN).
                    bad = collective_stats["dead_host"]
                    condemned = foreign.pop(bad, None)
                    if condemned:
                        telemetry.record("exchange_condemned",
                                         owner=bad,
                                         units=len(condemned))
                        _fallback(bridge, entries_map, condemned, ex,
                                  owner=bad)
            # Exchange workers are fresh threads: hand them this
            # round's trace context explicitly (thread-locals do not
            # propagate) so their spans land on this host's track in
            # the merged trace.
            ctx = telemetry.trace.current_context()
            workers = [
                threading.Thread(
                    target=_exchange_from,
                    args=(bridge, entries_map, pool, peers, h, units,
                          budget, ex, verify, deadline, swarm_health,
                          ctx),
                    name=f"zest-coop-x{h}", daemon=True,
                )
                for h, units in foreign.items() if units
            ]
            _xsp.set("owners", len(workers))
            for w in workers:
                w.start()
            for w in workers:
                w.join()
        _collect_clock_offsets(pool, peers, clock_offsets)
        # The timeline's pod merge (`/v1/timeline?scope=pod`) normalizes
        # peer series onto this host's clock with the same offsets the
        # trace merge uses (ISSUE 15).
        telemetry.timeline.set_clock_offsets(clock_offsets)
    finally:
        if own_pool:
            pool.close()
    # Units owned by hosts the plan already excluded (quarantined) were
    # re-sharded into `mine`/`foreign` above; nothing is unowned.

    _M_COOP_EXCHANGE_WALL.set(time.monotonic() - t_exchange)
    _M_COOP_BYTES.inc(ex.wire_bytes, tier="dcn")
    if ex.fallback_bytes:
        _M_COOP_BYTES.inc(ex.fallback_bytes, tier="fallback")

    # Headline ratio over *network* bytes (cache hits excluded), with
    # fallback bytes attributed to the tier that actually served them.
    cdn_bytes = (fetch_tiers.get("cdn", 0)
                 + ex.fallback_tiers.get("cdn", 0))
    peer_bytes = (fetch_tiers.get("peer", 0) + ex.wire_bytes
                  + ex.fallback_tiers.get("peer", 0))
    served = peer_bytes + cdn_bytes
    ratio = 1.0 - (cdn_bytes / served) if served else 1.0

    stats = {
        "host": host_index,
        "hosts": n_hosts,
        "trace_id": trace_id,
        "plan": plan.summary(),
        "fetch": {**fetch_stats, "tiers": fetch_tiers},
        "exchange": {
            **ex.summary(),
            "budget_bytes": budget.budget_bytes,
            "inflight_peak_bytes": budget.peak_bytes,
        },
        "fallbacks": ex.fallback_units,
        "own_server": own_server,
        "peer_served_ratio": round(ratio, 4),
        "elapsed_s": round(time.monotonic() - t0, 3),
    }
    if collective_stats is not None:
        # Present only when the collective tier actually ran — with
        # ZEST_COOP_COLLECTIVE=0 (or CollectiveUnavailable) the stats
        # schema stays byte-identical to the point-to-point exchange.
        stats["collective"] = collective_stats
    if clock_offsets:
        stats["clock_offsets"] = clock_offsets
    if log is not None:
        log(f"coop round host {host_index}/{n_hosts}: "
            f"{len(mine)} fetched, {ex.units} over DCN "
            f"({ex.wire_bytes} wire bytes), {ex.fallback_units} "
            f"CDN-fallback, peer_served {stats['peer_served_ratio']:.0%}")
    return stats


# How long the round waits for the clock-offset hello dials before
# moving on: a hung hello must never hold the round's tail.
_CLK_HELLO_TIMEOUT_S = 2.0


def _collect_clock_offsets(pool, peers, out: dict,
                           timeout_s: float = _CLK_HELLO_TIMEOUT_S) -> None:
    """Per-peer hello clock-offset estimates keyed by HOST INDEX (the
    merge's normalization key), copied into the round stats and the
    active tracer's metadata. Best-effort: an offset-less round merges
    on raw epoch anchors (documented fallback).

    Peers the exchange never dialed (a collective round only opens
    channels to its log N partners; a P2P round skips owners with no
    foreign units) get a hello dialed here so the merged trace can
    normalize EVERY host's clock. The dial workers are named
    (``zest-coop-clk-*``) and joined under one bounded deadline — a
    hung hello is abandoned to its daemon thread (its channel, if it
    ever completes, lands in the pool and is closed with it) instead
    of leaking an anonymous unjoined thread per round."""
    try:
        by_addr = pool.clock_offsets()
    except Exception:  # noqa: BLE001 - observability must not fail a round
        return
    missing = [(idx, addr) for idx, addr in sorted(peers.items())
               if addr not in by_addr]
    if missing:
        def dial(addr):
            try:
                pool.channel(*addr)  # hello runs in channel setup
            except Exception:  # noqa: BLE001 - offsets are best-effort
                pass

        workers = [
            threading.Thread(target=dial, args=(addr,),
                             name=f"zest-coop-clk-{idx}", daemon=True)
            for idx, addr in missing
        ]
        for w in workers:
            w.start()
        join_deadline = time.monotonic() + timeout_s
        for w in workers:
            w.join(timeout=max(0.0, join_deadline - time.monotonic()))
        try:
            by_addr = pool.clock_offsets()
        except Exception:  # noqa: BLE001
            pass  # keep the pre-dial snapshot
    addr_to_idx = {addr: idx for idx, addr in peers.items()}
    for addr, row in by_addr.items():
        idx = row.get("host", addr_to_idx.get(addr))
        if idx is None:
            continue
        out[int(idx)] = {"offset_s": row["offset_s"],
                         "rtt_s": row["rtt_s"]}
    tracer = telemetry.trace.active()
    if out and tracer is not None:
        # Merge per-host: several simulated hosts share one tracer.
        existing = tracer.metadata.get("clock_offsets", {})
        tracer.add_metadata(clock_offsets={**existing, **out})


def _tier_bytes(stats) -> dict[str, int]:
    return {"cache": stats.bytes_from_cache,
            "peer": stats.bytes_from_peer,
            "cdn": stats.bytes_from_cdn}


def _tier_delta(before: dict[str, int], after: dict[str, int]) -> dict:
    return {k: after[k] - before[k] for k in before
            if after[k] - before[k] > 0}


def _make_verifier():
    """Whole-xorb verifier for exchange-received blobs: the same fused
    device pass the pod round uses (BG4 expands+verifies on the
    accelerator; the host never materializes the interleaved bytes of
    a blob it is about to reject)."""
    from zest_tpu.transfer.pod import make_unit_verifier

    return make_unit_verifier()


def _exchange_from(bridge, entries_map, pool, peers, owner, units,
                   budget, ex: _ExchangeStats, verify, deadline,
                   health, trace_ctx=None) -> None:
    """Pull ``units`` from ``owner``; NOT_FOUND retries until the
    deadline (the owner may still be fetching), a dead channel or an
    expired deadline degrades the rest to the per-host CDN fallback."""
    if trace_ctx:
        telemetry.trace.use_context(trace_ctx)
    addr = peers.get(owner)
    if addr is None:
        _fallback(bridge, entries_map, units, ex, owner=owner)
        return
    host, port = addr
    pending = list(units)
    sleep_s = _RETRY_SLEEP_S
    # A window never plans past the budget: ByteBudget's oversized-alone
    # admission exists for single items larger than the whole budget —
    # letting a multi-unit window ride it would defeat the bound.
    window_cap = min(_WINDOW_TARGET_BYTES, budget.budget_bytes)
    while pending:
        window, wire_est = [], 0
        while pending and len(window) < _WINDOW_MAX_UNITS:
            nbytes = (pending[0][1].url_range_end
                      - pending[0][1].url_range_start)
            if window and wire_est + nbytes > window_cap:
                break
            window.append(pending.pop(0))
            wire_est += nbytes
        budget.acquire(wire_est)
        t_window = time.monotonic()
        try:
            if faults.fire("peer_timeout", key=f"{host}:{port}"):
                raise TimeoutError("injected peer_timeout")
            # Explicitly tagged like the collective's phase windows:
            # the shaped-DCN hub charges RTT per WINDOW (tag boundary),
            # and an untagged batch would be billed per request —
            # penalizing the point-to-point leg for tagging, not for
            # its actual round-trip structure.
            replies = pool.request_many(
                host, port,
                [(hashing.hex_to_hash(hh), fi.range.start, fi.range.end)
                 for hh, fi in window],
                timeout=max(1.0, deadline - time.monotonic()),
                tag=pool.window_tag(),
            )
        except (ConnectionError, TimeoutError, OSError) as exc:
            budget.release(wire_est)
            with ex.lock:
                ex.dead_hosts.add(owner)
            telemetry.record("exchange_dead_host", owner=owner,
                             peer=f"{host}:{port}",
                             error=type(exc).__name__)
            if health is not None:
                try:
                    health.record_failure(addr, kind="io_timeout")
                except Exception:  # noqa: BLE001 - health is advisory
                    pass
            _fallback(bridge, entries_map, window + pending, ex,
                      owner=owner)
            return
        window_s = time.monotonic() - t_window
        per_unit_s = window_s / max(1, len(window))
        # One observation per unit that actually produced a RESPONSE:
        # NOT_FOUND units re-enter later windows and would otherwise be
        # observed once per retry round, inflating _count past the
        # exchanged-unit total and skewing the distribution toward the
        # fast not-found round trips.
        for reply in replies:
            if isinstance(reply, DcnResponse):
                _M_COOP_UNIT_SECONDS.observe(per_unit_s)
        missing = []
        try:
            for (hh, fi), reply in zip(window, replies):
                admitted, wire, unpacked = _admit(
                    bridge, entries_map, hh, fi, reply, verify)
                if admitted:
                    bridge.stats.record("peer", wire)
                    ex.book_exchange((hh, fi.range.start), wire,
                                     unpacked)
                elif isinstance(reply, DcnResponse):
                    # Structurally or content-bad bytes from a live
                    # owner: do NOT retry (same bytes would come back);
                    # degrade to CDN, which self-heals the cache key.
                    with ex.lock:
                        ex.verify_rejected += 1
                    telemetry.record("verify_rejected", unit=hh[:16],
                                     owner=owner)
                    _fallback(bridge, entries_map, [(hh, fi)], ex,
                              owner=owner)
                else:
                    missing.append((hh, fi))  # NOT_FOUND: owner behind
        finally:
            budget.release(wire_est)
        if health is not None and not missing:
            try:
                health.record_success(addr)
            except Exception:  # noqa: BLE001
                pass
        if missing:
            if time.monotonic() + sleep_s > deadline:
                _fallback(bridge, entries_map, missing + pending, ex,
                          owner=owner)
                return
            with ex.lock:
                ex.retries += 1
            time.sleep(sleep_s)
            sleep_s = min(sleep_s * 2, _RETRY_SLEEP_CAP_S)
            pending = missing + pending


def _admit(bridge, entries_map, hh, fi, reply, verify):
    """Gate one exchange reply into the cache: right coordinate frame,
    structural cover, and — when the evidence proves the blob is the
    whole xorb — a full content verification (fused on TPU) BEFORE the
    cache write. Partial-range blobs keep the extraction-time per-chunk
    hash model, the same trust boundary as every other tier. Returns
    (admitted, wire_bytes, unpacked_bytes)."""
    if not isinstance(reply, DcnResponse):
        return False, 0, 0
    if reply.chunk_offset > fi.range.start:
        return False, 0, 0
    if not _blob_covers(reply.data, fi.range.end - reply.chunk_offset):
        return False, 0, 0
    if bridge.whole_xorb_provable(entries_map.get(hh, []),
                                  reply.chunk_offset):
        if not verify(hh, reply.data):
            return False, 0, 0
    _cache_unit(bridge, entries_map, hh, fi, reply.chunk_offset,
                reply.data)
    return True, len(reply.data), _unpacked_bytes(reply.data)


def _admit_lossy(bridge, hh, fi, reply):
    """Gate one LOSSY exchange reply (a ZQLS container, dcn.FLAG_LOSSY)
    into the HBM staging overlay — NEVER the xorb cache. The container
    must parse, dequantize into frames in the right coordinate frame,
    and structurally cover the unit; content verification is
    impossible by construction (the bytes are not the bytes the merkle
    tree committed to), which is exactly why the landing is staged:
    lossy data reaches HBM through the explicitly opted-in decode
    overlay and nothing else, and any later byte-exact need refetches
    through the verified waterfall. The CONTAINER is what gets staged,
    so re-serving it to a later phase partner forwards the original
    quantization verbatim instead of compounding error. Returns
    (admitted, wire_bytes, unpacked_bytes, exact_bytes)."""
    from zest_tpu.transfer import lossy

    try:
        frames = lossy.dequantize_blob(reply.data)
        exact = lossy.exact_len(reply.data)
    except (ValueError, CompressionError):
        return False, 0, 0, 0
    if reply.chunk_offset > fi.range.start:
        return False, 0, 0, 0
    if not _blob_covers(frames, fi.range.end - reply.chunk_offset):
        return False, 0, 0, 0
    lossy.staging_for(bridge.cfg.cache_dir).put(
        hh, reply.chunk_offset, reply.data)
    return True, len(reply.data), _unpacked_bytes(frames), exact


def _fallback(bridge, entries_map, units, ex: _ExchangeStats,
              owner=None) -> None:
    """Per-host CDN fallback for units the exchange could not deliver.
    Runs through the full waterfall (a *different* peer or the swarm
    tier may still serve them before CDN does)."""
    for hh, fi in units:
        if _already_cached(bridge, hh, fi):
            continue
        try:
            data, source = bridge.fetch_unit_tiered(hh, fi)
        except Exception:  # noqa: BLE001 - landing waterfall retries per term
            continue
        _cache_unit(bridge, entries_map, hh, fi, fi.range.start, data)
        ex.book_fallback((hh, fi.range.start), source, len(data))
        telemetry.record("cdn_fallback", unit=hh[:16], owner=owner,
                         tier=source, bytes=len(data))
        _M_COOP_FALLBACKS.inc()


# ── Address exchange over the jax.distributed KV store ──


def _advertise_host() -> str:
    """The address peer hosts should dial for this host's DCN listener:
    ``ZEST_COOP_ADVERTISE`` when set, else the primary interface's
    routable IP (UDP-connect trick — no packet is sent), else the
    hostname's resolution. Loopback is the LAST resort: on a real
    multi-host job an announced 127.0.0.1 makes every peer dial itself
    and the exchange silently degrade to full CDN."""
    import os
    import socket

    env = os.environ.get("ZEST_COOP_ADVERTISE")
    if env:
        return env
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # route lookup only
            addr = s.getsockname()[0]
        if addr and not addr.startswith("127."):
            return addr
    except OSError:
        pass
    try:
        addr = socket.gethostbyname(socket.gethostname())
        if addr:
            return addr
    except OSError:
        pass
    return "127.0.0.1"


def exchange_addrs_via_kv(pull_key: str, host_index: int, n_hosts: int,
                          dcn_port: int, advertise_host: str | None = None,
                          timeout_s: float = 30.0):
    """Best-effort DCN endpoint exchange through the coordinator KV
    store (the pod-native discovery tier, parallel.coordinator): every
    host announces ``zest/coop/{pull_key}/{index} -> host:port`` and
    polls until all ``n_hosts`` entries exist. Returns the full addr
    map, or None when jax.distributed is not initialized / peers never
    appear — the caller then needs explicit ``host_addrs`` or degrades.
    """
    from zest_tpu.parallel.coordinator import _kv_client

    client = _kv_client()
    if client is None:
        return None
    if advertise_host is None:
        advertise_host = _advertise_host()
    prefix = f"zest/coop/{pull_key}"
    try:
        client.key_value_set(f"{prefix}/{host_index}",
                             f"{advertise_host}:{dcn_port}",
                             allow_overwrite=True)
    except Exception:  # noqa: BLE001 - KV write failure = no coop
        return None
    deadline = time.monotonic() + timeout_s
    addrs: dict[int, tuple[str, int]] = {}
    while time.monotonic() < deadline:
        try:
            entries = client.key_value_dir_get(prefix)
        except Exception:  # noqa: BLE001
            entries = []
        for key, value in entries:
            idx = key.rsplit("/", 1)[-1]
            host, _, port = value.rpartition(":")
            if idx.isdigit() and host and port.isdigit():
                addrs[int(idx)] = (host, int(port))
        if len(addrs) >= n_hosts:
            return addrs
        time.sleep(0.2)
    return addrs if len(addrs) > 1 else None


def share_nonce_via_kv(pull_key: str, host_index: int,
                       timeout_s: float = 10.0) -> str:
    """Best-effort pull nonce through the coordinator KV store: host 0
    announces a fresh nonce under ``zest/coop-nonce/{pull_key}`` (a
    SIBLING prefix of the addr announce — a nested key would collide
    with the addr parser's index extraction), everyone else polls for
    it. Call ordering matters for id agreement (see pull._coop_stage):
    host 0 writes BEFORE announcing its addr; peers poll only AFTER
    the addr exchange, when host 0's participation (and therefore the
    nonce's presence) is already decided — a short ``timeout_s`` then
    suffices. The nonce disambiguates repeated pulls of the same
    revision in the fleet trace id (telemetry.fleet.mint_trace_id);
    every fallback returns ``""`` — hosts then derive the id from
    ``repo@sha`` alone, which still correlates, just without
    cross-pull uniqueness."""
    import os

    from zest_tpu.parallel.coordinator import _kv_client

    client = _kv_client()
    if client is None:
        return ""
    prefix = f"zest/coop-nonce/{pull_key}"
    if host_index == 0:
        nonce = os.urandom(8).hex()
        try:
            client.key_value_set(f"{prefix}/0", nonce,
                                 allow_overwrite=True)
        except Exception:  # noqa: BLE001 - no nonce = still correlated
            return ""
        return nonce
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            entries = client.key_value_dir_get(prefix)
        except Exception:  # noqa: BLE001
            entries = []
        for _key, value in entries:
            if value:
                return str(value)
        time.sleep(0.2)
    return ""
