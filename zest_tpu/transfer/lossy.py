"""EQuARX-style lossy payload tier for the collective exchange.

Cross-slice DCN/WAN links are the scarce plane in every shaped run, and
most checkpoint bytes are float tensors whose BG4+LZ4 frames still ship
close to raw size. EQuARX (PAPERS.md) shows all-reduce-style exchanges
tolerate a bounded-error quantized wire format on exactly those links;
this module is the codec half of that tier: BG4 float chunks quantize
to int8 with one fp32 scale per 256-value block (~26% of raw, error
bounded by absmax/127 per block) and everything else rides verbatim.

The TRUST BOUNDARY is deliberately brutal: a quantized payload can
never reproduce the chunk bytes the merkle tree committed to, so lossy
containers are admissible to the HBM staging overlay ONLY — they never
enter the xorb cache, are never re-served to peers, and any later
byte-exact need (file materialization, re-serving) refetches through
the verified waterfall. The container self-describes (magic "ZQLS") so
a receiver can never confuse it with frame bytes; the wire marks it
redundantly via the RESPONSE flag byte (dcn.FLAG_LOSSY).

Container layout (little-endian)::

    header:    "ZQLS" u8 version(1) u8 rsvd u16 nchunks
               u32 block_values u64 exact_len
    per chunk: u8 kind  u32 payload_len  u32 raw_len
      kind 0 (VERBATIM): payload = the chunk's original frame bytes
      kind 1 (QUANT):    payload = u8 phase  u8 tail_len
                                   + phase head bytes + tail_len tail bytes
                                   + nblocks x f32 scales
                                   + nvals x i8 values
        where nvals = (raw_len - phase - tail_len) / 4: CDC chunk
        boundaries fall on arbitrary BYTES of the float stream, so the
        codec detects the float grid's byte phase (the fully-finite
        reinterpretation with the best blockwise-int8 SNR) and carries
        the sub-float head/tail bytes verbatim — without this, three
        out of four chunks of a real checkpoint would decline.

``exact_len`` records the byte-exact blob length the container
replaced, which is what ``bits_saved_ratio`` in the exchange stats is
computed against.
"""

from __future__ import annotations

import struct
import threading

import numpy as np

from zest_tpu.cas import compression
from zest_tpu.cas.xorb import XorbFormatError, XorbReader, encode_frame

MAGIC = b"ZQLS"
VERSION = 1
# fp32 values per quantization block (one f32 scale each): 256 keeps
# the scale overhead at ~1.6% of raw while staying planar-friendly
# (a block never straddles more than one cache line of scales).
BLOCK_VALUES = 256

_HDR = struct.Struct("<4sBBHIQ")
_CHDR = struct.Struct("<BII")

KIND_VERBATIM = 0
KIND_QUANT = 1


class LossyFormatError(ValueError):
    pass


def is_lossy_container(blob: bytes) -> bool:
    return len(blob) >= _HDR.size and blob[:4] == MAGIC


# Minimum blockwise-int8 SNR (signal power / quantization error power)
# for a phase candidate to count as "this IS a float stream". True-phase
# normal/uniform float data reconstructs well above 30 dB; a misphased
# reinterpretation (exponent bytes drawn from mantissa noise) or
# non-float content lands near 0 dB — so one threshold both picks the
# grid phase and declines unquantizable chunks.
_MIN_SNR = 100.0


def _quantize_chunk(raw: bytes) -> bytes | None:
    """int8 + per-block scale payload for one raw float chunk, or None
    when no byte phase of the chunk reads as a quantizable float
    stream (non-finite values, or below the SNR floor)."""
    if len(raw) < 8:
        return None
    best = None
    for phase in range(4):
        nvals = (len(raw) - phase) // 4
        if nvals <= 0:
            continue
        vals = np.frombuffer(raw, dtype="<f4", offset=phase,
                             count=nvals)
        if not np.isfinite(vals).all():
            continue
        nblocks = -(-nvals // BLOCK_VALUES)
        padded = np.zeros(nblocks * BLOCK_VALUES, dtype=np.float32)
        padded[:nvals] = vals
        blocks = padded.reshape(nblocks, BLOCK_VALUES)
        absmax = np.abs(blocks).max(axis=1)
        scales = (absmax / 127.0).astype("<f4")
        safe = np.where(scales > 0.0, scales, 1.0)
        q = np.rint(blocks / safe[:, None]).clip(-127, 127) \
            .astype(np.int8)
        err = float(np.square(q.astype(np.float32) * safe[:, None]
                              - blocks).sum())
        power = float(np.square(blocks).sum())
        snr = power / err if err > 0.0 else float("inf")
        if best is None or snr > best[0]:
            tail = raw[phase + nvals * 4:]
            best = (snr, bytes([phase, len(tail)]) + raw[:phase]
                    + tail + scales.tobytes()
                    + q.reshape(-1)[:nvals].tobytes())
    if best is None or best[0] < _MIN_SNR:
        return None
    return best[1]


def _dequantize_chunk(payload: bytes, raw_len: int) -> bytes:
    if len(payload) < 2:
        raise LossyFormatError("quant payload too short")
    phase, tail_len = payload[0], payload[1]
    body = raw_len - phase - tail_len
    if phase > 3 or body < 0 or body % 4:
        raise LossyFormatError("bad quant phase/tail")
    nvals = body // 4
    nblocks = -(-nvals // BLOCK_VALUES)
    pos = 2
    head = payload[pos:pos + phase]
    pos += phase
    tail = payload[pos:pos + tail_len]
    pos += tail_len
    want = pos + nblocks * 4 + nvals
    if len(payload) != want:
        raise LossyFormatError(
            f"quant payload {len(payload)}B, expected {want}B")
    scales = np.frombuffer(payload, dtype="<f4", offset=pos,
                           count=nblocks)
    q = np.frombuffer(payload, dtype=np.int8, offset=pos + nblocks * 4)
    vals = q.astype(np.float32) * np.repeat(scales, BLOCK_VALUES)[:nvals]
    return bytes(head) + vals.astype("<f4").tobytes() + bytes(tail)


def quantize_blob(blob: bytes) -> bytes | None:
    """Quantize a response blob (concatenated xorb frames) into a ZQLS
    container. Returns None when the blob isn't parseable frames, has
    no BG4 float chunk worth quantizing, or wouldn't shrink — the
    caller then ships the byte-exact blob with flags 0."""
    try:
        reader = XorbReader(blob)
    except XorbFormatError:
        return None
    n = len(reader)
    if n == 0 or n > 0xFFFF:
        return None
    schemes = reader.chunk_schemes
    if not (schemes == int(compression.Scheme.BG4_LZ4)).any():
        return None
    parts = [b""] * (n + 1)
    parts[0] = _HDR.pack(MAGIC, VERSION, 0, n, BLOCK_VALUES, len(blob))
    gained = False
    for i in range(n):
        frame = reader.slice_range(i, i + 1)
        payload = None
        if int(schemes[i]) == int(compression.Scheme.BG4_LZ4):
            try:
                raw = reader.extract_chunk(i, verify=False)
            except (XorbFormatError, compression.CompressionError):
                return None
            payload = _quantize_chunk(raw)
            if payload is not None \
                    and _CHDR.size + len(payload) < len(frame):
                parts[i + 1] = _CHDR.pack(KIND_QUANT, len(payload),
                                          len(raw)) + payload
                gained = True
                continue
        parts[i + 1] = _CHDR.pack(KIND_VERBATIM, len(frame),
                                  len(frame)) + frame
    if not gained:
        return None
    return b"".join(parts)


def dequantize_blob(container: bytes) -> bytes:
    """Rebuild a frames blob from a ZQLS container. Quantized chunks
    re-frame their DEQUANTIZED bytes (``encode_frame`` of the lossy
    raw), so the result parses exactly like a normal response blob —
    but its chunk hashes no longer match the merkle tree, which is why
    callers must route it to staging, never the cache."""
    if not is_lossy_container(container):
        raise LossyFormatError("not a ZQLS container")
    magic, version, _rsvd, n, block, exact_len = \
        _HDR.unpack_from(container)
    if version != VERSION:
        raise LossyFormatError(f"unsupported ZQLS version {version}")
    if block != BLOCK_VALUES:
        raise LossyFormatError(f"unsupported block size {block}")
    pos = _HDR.size
    frames = []
    for _ in range(n):
        if pos + _CHDR.size > len(container):
            raise LossyFormatError("truncated chunk header")
        kind, plen, raw_len = _CHDR.unpack_from(container, pos)
        pos += _CHDR.size
        payload = container[pos:pos + plen]
        if len(payload) != plen:
            raise LossyFormatError("truncated chunk payload")
        pos += plen
        if kind == KIND_VERBATIM:
            frames.append(payload)
        elif kind == KIND_QUANT:
            frame, _h = encode_frame(_dequantize_chunk(payload, raw_len))
            frames.append(frame)
        else:
            raise LossyFormatError(f"unknown chunk kind {kind}")
    if pos != len(container):
        raise LossyFormatError("trailing bytes after last chunk")
    return b"".join(frames)


def exact_len(container: bytes) -> int:
    """The byte-exact blob length this container replaced (for
    ``bits_saved_ratio`` accounting)."""
    if not is_lossy_container(container):
        raise LossyFormatError("not a ZQLS container")
    return _HDR.unpack_from(container)[5]


class LossyStaging:
    """HBM-only landing zone for lossy-admitted exchange units.

    Holds dequantized (re-framed) blobs keyed by xorb hash, mirroring
    the xorb cache's ``get_with_range`` lookup shape so the decode
    engine can overlay it transparently — without ever writing a byte
    to the merkle-verified cache. Entries live for one load: the
    loader drains the staging once tensors are committed to HBM, and
    any later byte-exact need refetches through the verified waterfall.
    """

    def __init__(self) -> None:
        self._blobs: dict[tuple[str, int], bytes] = {}
        self._lock = threading.Lock()

    def put(self, hash_hex: str, chunk_offset: int, blob: bytes) -> None:
        with self._lock:
            self._blobs[(hash_hex, int(chunk_offset))] = blob

    def get_with_range(self, hash_hex: str,
                       range_start: int) -> tuple[bytes, int] | None:
        """``(blob, chunk_offset)`` for the staged entry of ``hash_hex``
        whose chunk range starts at or before ``range_start`` (the same
        rebasing contract as ``XorbCache.get_with_range``)."""
        with self._lock:
            best = None
            for (hh, off), blob in self._blobs.items():
                if hh != hash_hex or off > range_start:
                    continue
                if best is None or off > best[1]:
                    best = (blob, off)
            return best

    def units(self) -> int:
        with self._lock:
            return len(self._blobs)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._blobs.values())

    def clear(self) -> None:
        with self._lock:
            self._blobs.clear()


# Staging registry keyed by cache dir: one LossyStaging per host
# identity, reachable from anything that knows the host's cache
# location (the bridge's admit path, the DcnServer's serve path, the
# decode engine's overlay) with zero constructor plumbing — and still
# correctly per-host in the in-process multi-host simulations, where
# every simulated host has its own cache dir.
_STAGINGS: dict[str, LossyStaging] = {}
_STAGINGS_LOCK = threading.Lock()


def staging_for(cache_dir) -> LossyStaging:
    key = str(cache_dir)
    with _STAGINGS_LOCK:
        st = _STAGINGS.get(key)
        if st is None:
            st = _STAGINGS[key] = LossyStaging()
        return st


def reset_stagings() -> None:
    """Drop every registered staging (tests/bench isolation)."""
    with _STAGINGS_LOCK:
        _STAGINGS.clear()
