"""Multi-tenant pull service: shared, globally-budgeted pools (ISSUE 13).

Before this module the daemon's concurrency story was "two concurrent
``POST /v1/pull`` requests run as fully independent ``pull_model``
calls": duplicate in-flight xorb fetches for overlapping models, no
shared admission of disk/byte budgets, no eviction when the xorb cache
fills, and no way to cancel or isolate a tenant mid-pull. This module
is the shared substrate every pull session runs over:

- **Singleflight fetch dedupe** (:class:`Singleflight`): a
  process-wide in-flight table keyed by ``(xorb hash, chunk range)``.
  The first session to miss the cache leads the fetch; every other
  session *subscribes* and, when the leader resolves, reads the
  winner's cache entry instead of refetching ("many consumers, one
  artifact" — IOTA, PAPERS.md). A failed flight propagates the
  leader's typed error to every waiter (the fetch is struck/retried
  ONCE, by the leader's own waterfall, never once per waiter); a
  *cancelled* leader hands leadership to a live waiter instead of
  failing the flight.

- **Global admission control** (:class:`AdmissionController`): one
  ``ZEST_TENANT_*`` budget set — concurrent pulls, aggregate in-flight
  reassembly bytes (a single :class:`ByteBudget` every session's file
  pipeline draws from), disk high/low watermarks — admitting sessions
  through a fair per-tenant queue (deficit round-robin, so one
  tenant's queue depth cannot starve another tenant's single pull).
  Queued sessions surface as a ``queued`` phase in ``/v1/pulls``;
  when the queue itself is full the request is REJECTED with a typed
  retry-after error (:class:`AdmissionRejected` → HTTP 429) — bounded
  backpressure, never unbounded parking ("Bounded-Memory Parallel
  Image Pulling", PAPERS.md).

- **Xorb-cache eviction** (:class:`CacheEvictor`): LRU over cache
  entries with pinning (:class:`PinBook`) — entries referenced by any
  admitted session's resolved plan, or by the manifest a live HBM
  tree depends on for delta/hot-swap, are unevictable. Triggered by
  the disk high-watermark (at admission) and by ENOSPC (via
  :func:`zest_tpu.storage.set_disk_full_hook`). Eviction mid-pull
  degrades to a refetch — the waterfall treats a vanished entry as a
  plain cache miss — never a corrupt read (entries are whole files
  written by atomic rename).

- **Tenant fault isolation** (:class:`CancelToken`): a session abort
  (client disconnect, ``DELETE /v1/pulls/<id>``) releases its
  admission slot and byte shares, unpins its cache entries, and
  detaches from shared flights without poisoning them (a cancelled
  waiter just leaves; a cancelled leader abdicates).

``ZEST_TENANCY=0`` disables all of it: pulls run exactly as before —
per-pull byte budgets, no flights table, no admission queue, no
eviction (the knob-off identity tests pin this).

Process-global state lives behind :func:`state` (configured lazily
from the first caller's Config) so the daemon, the CLI, and embedders
share one controller per process; :func:`reset` rebuilds for tests.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from zest_tpu import storage, telemetry

_M_DEDUPE_HITS = telemetry.counter(
    "zest_inflight_dedupe_hits_total",
    "Fetches served from another session's in-flight fetch "
    "(the waiter read the winner's cache entry)")
_M_FLIGHTS = telemetry.counter(
    "zest_inflight_flights_total",
    "Singleflight fetches led (one per deduped network fetch)")
_M_REJECTS = telemetry.counter(
    "zest_admission_rejects_total",
    "Pull sessions rejected because the admission queue was full")
_M_EVICTIONS = telemetry.counter(
    "zest_cache_evictions_total",
    "Xorb-cache entries evicted under disk pressure, by trigger",
    ("reason",))
_M_QUEUE_DEPTH = telemetry.gauge(
    "zest_tenant_queue_depth",
    "Pull sessions currently parked in the admission queue")
_M_ADMITTED = telemetry.gauge(
    "zest_tenant_active_pulls",
    "Pull sessions currently holding an admission slot")
# Tenancy metrics gaps (ISSUE 15 satellite): how singleflight resolved
# each participant (leader fetched, waiter read the winner's entry,
# handoff = a waiter inherited a cancelled leader's fetch), and how
# long admission actually made sessions wait — the queue-health signal
# the queue-depth gauge alone can't give (depth 3 for 10 ms and depth 3
# for 10 min look identical on a gauge).
_M_SINGLEFLIGHT = telemetry.counter(
    "zest_singleflight_total",
    "Singleflight participations by outcome",
    ("outcome",))
_M_ADMISSION_WAIT = telemetry.histogram(
    "zest_admission_wait_seconds",
    "Wall seconds a pull session waited for an admission slot",
    buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             300.0))


class PullCancelled(RuntimeError):
    """A session abort: the pull stops at the next stage boundary and
    finishes with the ``cancelled`` terminal status (distinct from
    ``error`` — nothing went wrong, somebody asked it to stop)."""


class AdmissionRejected(RuntimeError):
    """Typed backpressure: the admission queue is full. Carries
    ``retry_after_s`` so the HTTP layer can answer 429 + Retry-After
    instead of parking the request unboundedly."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class CancelToken:
    """Cooperative cancellation for one pull session. ``cancel()`` is
    idempotent and safe from any thread (HTTP handler, SSE generator
    finalizer, chaos harness); the pull checks at stage boundaries via
    :meth:`check`, which raises :class:`PullCancelled`."""

    def __init__(self):
        self._event = threading.Event()
        self.reason: str | None = None

    def cancel(self, reason: str = "cancelled") -> None:
        if not self._event.is_set():
            self.reason = reason
            self._event.set()

    @property
    def fired(self) -> bool:
        return self._event.is_set()

    def check(self) -> None:
        if self._event.is_set():
            raise PullCancelled(self.reason or "cancelled")


class ByteBudget:
    """Counting byte-semaphore bounding in-flight reassembly bytes.

    ``acquire(n)`` blocks while admitting ``n`` more bytes would push the
    in-flight total past the budget — except when nothing is in flight,
    where an oversized item (n > budget) is admitted alone rather than
    deadlocking (the classic bounded-buffer starvation case: a file
    larger than the whole budget must still be pullable, serially).
    ``peak_bytes`` records the high-watermark for the bench/tests to
    assert the bound held.

    Historically private to one pull's file pipeline
    (``transfer.pull._FilePipeline``); with tenancy on, ONE instance is
    shared by every admitted session — the "aggregate in-flight bytes"
    budget — which is why it lives here (pull re-exports it)."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = max(1, int(budget_bytes))
        self._cv = threading.Condition(threading.Lock())
        self._inflight = 0
        self.peak_bytes = 0

    def acquire(self, nbytes: int) -> None:
        nbytes = max(0, int(nbytes))
        with self._cv:
            while (self._inflight > 0
                   and self._inflight + nbytes > self.budget_bytes):
                self._cv.wait()
            self._inflight += nbytes
            self.peak_bytes = max(self.peak_bytes, self._inflight)

    def try_acquire(self, nbytes: int) -> bool:
        """Non-blocking :meth:`acquire` (same oversized-alone admission):
        the async materialization handoff runs in the landing's decode
        thread, where a blocked acquire would put file writes right back
        on the time-to-HBM critical path — a full budget means *decline*
        (the file falls to the post-commit cache lane), never wait."""
        nbytes = max(0, int(nbytes))
        with self._cv:
            if (self._inflight > 0
                    and self._inflight + nbytes > self.budget_bytes):
                return False
            self._inflight += nbytes
            self.peak_bytes = max(self.peak_bytes, self._inflight)
            return True

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._inflight -= max(0, int(nbytes))
            self._cv.notify_all()


class StackedBudget:
    """A session-local :class:`ByteBudget` stacked under the shared
    aggregate one: every acquire must clear BOTH bounds — the per-pull
    ``ZEST_PULL_INFLIGHT`` contract keeps holding (tests pin its peak),
    and the process-wide ``ZEST_TENANT_INFLIGHT`` cap holds across
    every admitted session. Acquire order is local-then-shared,
    release is shared-then-local, everywhere — a session blocked on
    the shared budget holds only its own local bytes, so progress
    needs nothing from it. Reported bounds/peaks are the LOCAL ones
    (the shared peak lives in the tenancy summary)."""

    def __init__(self, local: ByteBudget, shared: ByteBudget):
        self.local = local
        self.shared = shared

    @property
    def budget_bytes(self) -> int:
        return self.local.budget_bytes

    @property
    def peak_bytes(self) -> int:
        return self.local.peak_bytes

    def _shared_take(self, nbytes: int) -> int:
        """Bytes charged to the shared tier. A single item LARGER than
        the whole aggregate budget bypasses it (charged 0): the
        shared oversized-alone rule would need process-wide inflight
        to hit zero, which concurrent tenants' steady acquires never
        let happen — the pull would hold its admission slot forever
        without progressing. Such an item stays bounded by its own
        per-pull budget (whose oversized-alone rule is per-session,
        so it CAN drain) and by the admission slot count. The
        predicate is a pure function of nbytes, so acquire and
        release always agree."""
        return 0 if nbytes > self.shared.budget_bytes else nbytes

    def acquire(self, nbytes: int) -> None:
        self.local.acquire(nbytes)
        shared = self._shared_take(nbytes)
        if shared:
            self.shared.acquire(shared)

    def try_acquire(self, nbytes: int) -> bool:
        if not self.local.try_acquire(nbytes):
            return False
        shared = self._shared_take(nbytes)
        if shared and not self.shared.try_acquire(shared):
            self.local.release(nbytes)
            return False
        return True

    def release(self, nbytes: int) -> None:
        shared = self._shared_take(nbytes)
        if shared:
            self.shared.release(shared)
        self.local.release(nbytes)


# ── Singleflight fetch dedupe ──


class _Flight:
    """One in-flight fetch: a leader, subscribed waiters, and a
    terminal state. Lives in the table only while running; resolve /
    fail / dissolve remove it, so a later miss starts a fresh flight."""

    __slots__ = ("key", "state", "error", "waiters", "promotions")

    def __init__(self, key):
        self.key = key
        self.state = "running"   # running | done | failed | gone
        self.error: BaseException | None = None
        self.waiters = 0
        self.promotions = 0      # pending leadership offers


class Singleflight:
    """Process-wide in-flight fetch table. Protocol (see
    ``XetBridge._deduped`` for the one real caller):

    - ``join(key)`` → ``("lead", flight)`` for the first caller (fetch,
      then ``resolve``/``fail``/``abdicate``), or ``("wait", flight)``.
    - waiters call ``wait(flight, cancel)`` → ``"done"`` (read the
      winner's cache entry), ``"lead"`` (the leader abdicated — this
      waiter now owns the fetch), ``"failed"`` (raise ``flight.error``,
      the leader's typed error, struck exactly once), or
      ``"cancelled"`` (this waiter's own session aborted — it detaches
      without touching the flight)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._flights: dict = {}
        self.led = 0
        self.hits = 0
        # Outcome book (ISSUE 15 satellite): leader / waiter / handoff
        # counts, mirrored into zest_singleflight_total{outcome}.
        self.outcomes = {"leader": 0, "waiter": 0, "handoff": 0}

    def _outcome(self, outcome: str) -> None:
        # Callers hold self._cv.
        self.outcomes[outcome] += 1
        _M_SINGLEFLIGHT.inc(outcome=outcome)

    def join(self, key) -> tuple[str, _Flight]:
        with self._cv:
            flight = self._flights.get(key)
            if flight is None:
                flight = self._flights[key] = _Flight(key)
                self.led += 1
                _M_FLIGHTS.inc()
                self._outcome("leader")
                return "lead", flight
            return "wait", flight

    def wait(self, flight: _Flight, cancel: CancelToken | None = None,
             poll_s: float = 0.05) -> str:
        with self._cv:
            flight.waiters += 1
            try:
                while True:
                    if flight.promotions > 0:
                        flight.promotions -= 1
                        self.led += 1
                        _M_FLIGHTS.inc()
                        self._outcome("handoff")
                        return "lead"
                    if flight.state == "done":
                        self._outcome("waiter")
                        return "done"
                    if flight.state == "failed":
                        self._outcome("waiter")
                        return "failed"
                    if flight.state == "gone":
                        # Leader abdicated with no waiter counted yet
                        # (we raced the dissolve): fetch ourselves.
                        self._outcome("handoff")
                        return "lead"
                    if cancel is not None and cancel.fired:
                        return "cancelled"
                    # Timed wait: a lost wakeup (or a cancel fired with
                    # no notify) must never park a waiter forever.
                    self._cv.wait(poll_s)
            finally:
                flight.waiters -= 1

    def note_hit(self) -> None:
        with self._cv:
            self.hits += 1
        _M_DEDUPE_HITS.inc()

    def resolve(self, flight: _Flight) -> None:
        with self._cv:
            flight.state = "done"
            self._flights.pop(flight.key, None)
            self._cv.notify_all()

    def fail(self, flight: _Flight, error: BaseException) -> None:
        with self._cv:
            flight.state = "failed"
            flight.error = error
            self._flights.pop(flight.key, None)
            self._cv.notify_all()

    def abdicate(self, flight: _Flight) -> None:
        """The leader's session was cancelled mid-flight: hand
        leadership to a live waiter (one pending promotion) instead of
        failing the flight; with no waiters the flight dissolves and
        the next miss starts fresh."""
        with self._cv:
            if flight.waiters > flight.promotions:
                flight.promotions += 1
            else:
                flight.state = "gone"
                self._flights.pop(flight.key, None)
            self._cv.notify_all()

    def in_flight(self) -> int:
        with self._cv:
            return len(self._flights)

    def summary(self) -> dict:
        with self._cv:
            return {"in_flight": len(self._flights),
                    "led": self.led, "dedupe_hits": self.hits,
                    "outcomes": dict(self.outcomes)}


# ── Pinning + eviction ──


class PinBook:
    """Refcounted pins on xorb hashes. Owners are opaque strings — a
    pull session pins the hashes of every reconstruction it resolves
    (owner ``sess:<id>``, released when the pull ends), and a landed
    HBM tree pins its manifest's hashes (owner ``tree:<repo>``,
    replaced when a newer revision of the same repo lands) so the
    delta/hot-swap evidence a live mesh depends on stays readable."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owners: dict[str, set[str]] = {}
        self._counts: dict[str, int] = {}

    def pin(self, owner: str, hashes) -> None:
        with self._lock:
            held = self._owners.setdefault(owner, set())
            for h in hashes:
                if h not in held:
                    held.add(h)
                    self._counts[h] = self._counts.get(h, 0) + 1

    def replace(self, owner: str, hashes) -> None:
        """Atomically swap an owner's pin set (the tree-pin pattern)."""
        with self._lock:
            self._release_locked(owner)
            held = self._owners.setdefault(owner, set())
            for h in hashes:
                if h not in held:
                    held.add(h)
                    self._counts[h] = self._counts.get(h, 0) + 1

    def release(self, owner: str) -> bool:
        """Returns whether the owner actually held pins — the pool's
        release_tree reports a no-op release honestly."""
        with self._lock:
            held = owner in self._owners
            self._release_locked(owner)
            return held

    def _release_locked(self, owner: str) -> None:
        for h in self._owners.pop(owner, ()):
            n = self._counts.get(h, 0) - 1
            if n <= 0:
                self._counts.pop(h, None)
            else:
                self._counts[h] = n

    def pinned(self, hash_hex: str) -> bool:
        with self._lock:
            return hash_hex in self._counts

    def summary(self) -> dict:
        with self._lock:
            return {"owners": len(self._owners),
                    "pinned_hashes": len(self._counts)}


class CacheEvictor:
    """LRU eviction over the on-disk xorb cache, honoring pins.

    Usage is judged by summing entry sizes under the cache dir (not fs
    free space — deterministic for tests and benches). Above the high
    watermark, unpinned entries evict oldest-mtime-first down to the
    low watermark; pinned entries are NEVER evicted, even when that
    leaves usage above the mark (the flight recorder says so). A pull
    whose entry vanishes mid-read degrades to a refetch: every reader
    treats a missing entry as a cache miss."""

    def __init__(self, cache_dir, high_bytes: int, low_bytes: int,
                 pins: PinBook):
        self.cache_dir = cache_dir
        self.high_bytes = max(0, int(high_bytes))
        low = int(low_bytes) if low_bytes else int(self.high_bytes * 0.8)
        self.low_bytes = max(0, low)
        self.pins = pins
        self._lock = threading.Lock()
        self.evictions = 0
        self.evicted_bytes = 0
        self.pinned_survivals = 0
        # Watermark-pass throttle: usage is computed by walking every
        # cache entry (O(entries) stat calls) — at a 200 GiB cache
        # that's ~1e5 syscalls, far too much to pay on EVERY pull
        # admission. Unforced passes run at most once per interval;
        # ENOSPC and explicit (force=True) passes always run.
        self.check_interval_s = 2.0
        self._last_check = float("-inf")

    def _entries(self) -> list[tuple[float, int, object, str]]:
        """(mtime, size, path, hash_hex) per cache entry; partial
        entries (``hash.start``) pin/evict under their xorb's hash."""
        out = []
        root = self.cache_dir
        if not root.is_dir():
            return out
        for sub in root.iterdir():
            if not sub.is_dir():
                continue
            for f in sub.iterdir():
                name = f.name
                if name.startswith(".tmp-"):
                    continue
                hash_hex = name.split(".", 1)[0]
                if len(hash_hex) != 64:
                    continue
                try:
                    st = f.stat()
                except OSError:
                    continue
                out.append((st.st_mtime, st.st_size, f, hash_hex))
        return out

    def usage_bytes(self) -> int:
        return sum(size for _m, size, _p, _h in self._entries())

    def maybe_evict(self, force: bool = False) -> int:
        """Watermark trigger: evict down to the low mark when usage
        exceeds the high mark. No-op when unarmed (high == 0);
        unforced calls are rate-limited (``check_interval_s``) so the
        per-admission trigger doesn't pay the O(entries) usage walk on
        every pull."""
        if not self.high_bytes:
            return 0
        if not force:
            now = time.monotonic()
            with self._lock:
                if now - self._last_check < self.check_interval_s:
                    return 0
                self._last_check = now
        return self._evict(self.low_bytes, reason="watermark",
                           only_if_above=self.high_bytes)

    def on_enospc(self) -> bool:
        """ENOSPC trigger (the :func:`storage.set_disk_full_hook`
        callable): the filesystem itself said we are out of space, so
        the watermark arithmetic is moot — free down to HALF the
        current usage (or the low mark, whichever is lower): bounded,
        guaranteed progress even when usage sits below the armed
        watermarks (something else filled the disk). True when
        anything was freed."""
        return self._evict(None, reason="enospc") > 0

    def _evict(self, target_bytes: int | None, reason: str,
               only_if_above: int | None = None) -> int:
        with self._lock:
            entries = self._entries()
            usage = sum(size for _m, size, _p, _h in entries)
            if only_if_above is not None and usage <= only_if_above:
                return 0
            if target_bytes is None:  # the ENOSPC half-usage rule
                target_bytes = min(self.low_bytes or usage // 2,
                                   usage // 2)
            freed = 0
            pinned_skips = 0
            pinned_skip_bytes = 0
            for mtime, size, path, hash_hex in sorted(entries):
                if usage - freed <= target_bytes:
                    break
                if self.pins.pinned(hash_hex):
                    self.pinned_survivals += 1
                    pinned_skips += 1
                    pinned_skip_bytes += size
                    continue
                try:
                    os.unlink(path)
                except OSError:
                    continue
                freed += size
                self.evictions += 1
                self.evicted_bytes += size
                _M_EVICTIONS.inc(reason=reason)
                telemetry.record("cache_evict", xorb=hash_hex,
                                 bytes=size, reason=reason)
            if pinned_skips:
                # One event per PASS, not per entry: a pressured cache
                # full of pinned trees would otherwise flood the ring
                # with thousands of identical skip events.
                telemetry.record("cache_evict_pinned_skip",
                                 reason=reason, entries=pinned_skips,
                                 bytes=pinned_skip_bytes)
            if freed and usage - freed > target_bytes:
                telemetry.record("cache_evict_short", reason=reason,
                                 remaining=usage - freed,
                                 target=target_bytes)
            return freed

    def summary(self) -> dict:
        return {"evictions": self.evictions,
                "evicted_bytes": self.evicted_bytes,
                "pinned_survivals": self.pinned_survivals,
                "high_bytes": self.high_bytes,
                "low_bytes": self.low_bytes}


# ── Admission control ──


class _Waiter:
    __slots__ = ("tenant", "weight", "admitted", "session", "shed")

    def __init__(self, tenant: str, weight: float, session=None):
        self.tenant = tenant
        self.weight = weight
        self.admitted = False
        self.session = session
        # Set to a retry-after estimate when a load-shed evicts this
        # waiter from the queue: its acquire() raises AdmissionRejected
        # instead of parking on (ISSUE 17).
        self.shed: float | None = None


class AdmissionController:
    """Global concurrent-pull admission with per-tenant fairness.

    ``max_pulls`` sessions hold slots at once; excess sessions park in
    per-tenant FIFO queues drained by deficit round-robin (each visit
    tops the tenant's deficit by ``quantum``; a session admits when
    the deficit covers its weight — with unit weights this is strict
    tenant round-robin, and a tenant queueing 50 sessions still yields
    to every other tenant's next session). ``max_queue`` bounds TOTAL
    queued sessions: beyond it, :meth:`acquire` raises
    :class:`AdmissionRejected` immediately — typed backpressure, not
    unbounded parking."""

    def __init__(self, max_pulls: int, max_queue: int,
                 quantum: float = 1.0):
        self.max_pulls = max(1, int(max_pulls))
        self.max_queue = max(0, int(max_queue))
        self.quantum = quantum
        self._cv = threading.Condition()
        self._active = 0
        self._queues: dict[str, deque] = {}
        self._order: list[str] = []
        self._deficit: dict[str, float] = {}
        self._rr = 0
        self._queued = 0
        self.admitted_total = 0
        self.rejected_total = 0
        self.shed_total = 0
        # Load-shed mode (ISSUE 17): while True, sessions that would
        # queue are rejected with 429 + Retry-After instead of parked
        # — the remediation engine flips this on when the queue is
        # stuck AND the SLO burn rate projects a breach, and back off
        # when the burn recovers. Admitted sessions are never touched.
        self._shedding = False
        # Recent admission walls, for the 429 retry-after estimate.
        self._recent_walls: deque = deque(maxlen=16)

    # — internals (lock held) —

    def _dispatch_locked(self) -> None:
        while self._active < self.max_pulls and self._queued:
            admitted_one = False
            for _ in range(len(self._order)):
                tenant = self._order[self._rr % len(self._order)]
                self._rr += 1
                q = self._queues.get(tenant)
                if not q:
                    continue
                self._deficit[tenant] = (self._deficit.get(tenant, 0.0)
                                         + self.quantum)
                head = q[0]
                if self._deficit[tenant] + 1e-9 >= head.weight:
                    self._deficit[tenant] -= head.weight
                    q.popleft()
                    self._queued -= 1
                    if not q:
                        del self._queues[tenant]
                    head.admitted = True
                    self._active += 1
                    self.admitted_total += 1
                    admitted_one = True
                    break
            if not admitted_one:
                break
        # Tenants with no queue left fall out of the rotation (their
        # deficit resets — credit must not accumulate while idle).
        if len(self._order) != len(self._queues):
            self._order = [t for t in self._order if t in self._queues]
            self._deficit = {t: d for t, d in self._deficit.items()
                             if t in self._queues}
        _M_QUEUE_DEPTH.set(self._queued)
        _M_ADMITTED.set(self._active)
        self._cv.notify_all()

    def _remove_locked(self, waiter: _Waiter) -> None:
        q = self._queues.get(waiter.tenant)
        if q is not None:
            try:
                q.remove(waiter)
                self._queued -= 1
            except ValueError:
                pass
            if not q:
                del self._queues[waiter.tenant]
        _M_QUEUE_DEPTH.set(self._queued)

    # — load shedding (ISSUE 17) —

    def shed(self) -> dict:
        """Enter shed mode and evict the lowest-deficit tenant's queued
        waiters (the tenant with the LEAST accumulated fairness credit
        — it queued most recently / least underserved, so shedding it
        costs the least accrued fairness debt). Evicted waiters raise
        :class:`AdmissionRejected` (→ 429 + Retry-After); admitted
        sessions are never touched. While shedding, new sessions that
        would queue are rejected immediately. Reversible via
        :meth:`recover`."""
        with self._cv:
            self._shedding = True
            victim = None
            if self._queues:
                victim = min(self._queues,
                             key=lambda t: self._deficit.get(t, 0.0))
            n = 0
            retry = self._retry_after_locked()
            if victim is not None:
                q = self._queues.pop(victim, None) or ()
                for waiter in q:
                    waiter.shed = retry
                    n += 1
                self._queued -= n
                self.shed_total += n
                self.rejected_total += n
                for _ in range(n):
                    _M_REJECTS.inc()
                _M_QUEUE_DEPTH.set(self._queued)
            self._cv.notify_all()
            return {"tenant": victim, "shed": n,
                    "retry_after_s": retry,
                    "queued_left": self._queued}

    def recover(self) -> dict:
        """Leave shed mode: new sessions queue normally again."""
        with self._cv:
            was = self._shedding
            self._shedding = False
            return {"was_shedding": was, "queued": self._queued}

    @property
    def shedding(self) -> bool:
        with self._cv:
            return self._shedding

    def retry_after_s(self) -> float:
        """Advice for a rejected client: roughly one mean recent pull
        wall per queued-sessions-per-slot, clamped to [1, 60]."""
        with self._cv:
            return self._retry_after_locked()

    def _retry_after_locked(self) -> float:
        walls = list(self._recent_walls)
        backlog = self._queued + self._active
        mean = (sum(walls) / len(walls)) if walls else 5.0
        est = mean * max(1.0, backlog / self.max_pulls)
        return round(min(60.0, max(1.0, est)), 1)

    # — the public protocol —

    def acquire(self, tenant: str, cancel: CancelToken | None = None,
                session=None, weight: float = 1.0) -> None:
        """Block until admitted. Raises :class:`AdmissionRejected` when
        the queue is full, :class:`PullCancelled` when the session's
        token fires while queued (the waiter leaves the queue — its
        spot frees immediately)."""
        waiter = _Waiter(tenant, weight, session)
        t_enter = time.monotonic()
        with self._cv:
            if self._active < self.max_pulls and not self._queued:
                self._active += 1
                self.admitted_total += 1
                _M_ADMITTED.set(self._active)
                _M_ADMISSION_WAIT.observe(0.0)
                return
            if self._shedding:
                self.rejected_total += 1
                _M_REJECTS.inc()
                raise AdmissionRejected(
                    "load shedding active (SLO burn); retry later",
                    self._retry_after_locked())
            if self._queued >= self.max_queue:
                self.rejected_total += 1
                _M_REJECTS.inc()
                raise AdmissionRejected(
                    f"admission queue full ({self.max_queue} queued); "
                    "retry later", self._retry_after_locked())
            self._queues.setdefault(tenant, deque()).append(waiter)
            if tenant not in self._order:
                self._order.append(tenant)
            self._queued += 1
            _M_QUEUE_DEPTH.set(self._queued)
            if session is not None:
                session.set_phase("queued")
            self._dispatch_locked()
            try:
                while not waiter.admitted:
                    if waiter.shed is not None:
                        # A load-shed evicted us from the queue; the
                        # shed pass already did the removal/accounting.
                        raise AdmissionRejected(
                            "shed while queued (SLO burn); retry later",
                            waiter.shed)
                    if cancel is not None and cancel.fired:
                        self._remove_locked(waiter)
                        raise PullCancelled(
                            cancel.reason or "cancelled while queued")
                    self._cv.wait(0.05)
            except BaseException:
                if not waiter.admitted:
                    self._remove_locked(waiter)
                else:
                    # Admitted between the failure and this cleanup:
                    # give the slot back or it leaks forever.
                    self._active -= 1
                    self._dispatch_locked()
                raise
        _M_ADMISSION_WAIT.observe(time.monotonic() - t_enter)
        if session is not None:
            session.set_phase("starting")

    def probe_reject(self) -> tuple[bool, float]:
        """Would a new session be REJECTED right now? The HTTP layer's
        pre-SSE 429 check — it lives HERE so the predicate (and its
        reject accounting) can never drift from what :meth:`acquire`
        actually does. A full answer IS the request's rejection (the
        caller returns 429 on it), so it counts toward the totals.
        Returns (rejected, retry_after_s)."""
        with self._cv:
            would_queue = self._active >= self.max_pulls or self._queued > 0
            if would_queue and (self._shedding
                                or self._queued >= self.max_queue):
                self.rejected_total += 1
                _M_REJECTS.inc()
                return True, self._retry_after_locked()
        return False, 0.0

    def release(self, wall_s: float | None = None) -> None:
        with self._cv:
            self._active = max(0, self._active - 1)
            if wall_s is not None:
                self._recent_walls.append(wall_s)
            self._dispatch_locked()

    def summary(self) -> dict:
        with self._cv:
            return {
                "max_pulls": self.max_pulls,
                "active": self._active,
                "queued": self._queued,
                "queue_cap": self.max_queue,
                "admitted_total": self.admitted_total,
                "rejected_total": self.rejected_total,
                "shed_total": self.shed_total,
                "shedding": self._shedding,
            }


# ── Process-global state ──


class TenancyState:
    """Everything one process' sessions share: the admission
    controller, the singleflight table, the pin book, the evictor, and
    the aggregate in-flight byte budget."""

    def __init__(self, cfg, pins: PinBook | None = None):
        self.knobs = _knob_tuple(cfg)
        self.controller = AdmissionController(
            cfg.tenant_max_pulls, cfg.tenant_queue)
        self.flights = Singleflight()
        # Pins survive a knob rebuild (state() passes the old book):
        # tree:<repo> pins are documented to outlive sessions — a
        # rebuild dropping them would let the next eviction pass evict
        # a live HBM tree's delta/hot-swap manifest xorbs.
        self.pins = pins if pins is not None else PinBook()
        self.evictor = CacheEvictor(
            cfg.xorb_cache_dir(), cfg.tenant_disk_high,
            cfg.tenant_disk_low, self.pins)
        self.byte_budget = ByteBudget(cfg.tenant_inflight_bytes)
        storage.set_disk_full_hook(self.evictor.on_enospc)
        # Live structural gauges for the timeline sampler (ISSUE 15):
        # queue depth, admitted sessions, singleflight in-flight count
        # — the history the anomaly detector's queue-growth rule reads.
        # Replace semantics: a knob rebuild just re-registers the names
        # over the old state's probes.
        c = self.controller
        telemetry.timeline.register_probe(
            "tenancy.queue_depth", lambda: c.summary()["queued"])
        telemetry.timeline.register_probe(
            "tenancy.active_pulls", lambda: c.summary()["active"])
        telemetry.timeline.register_probe(
            "tenancy.admitted_total",
            lambda: c.summary()["admitted_total"])
        telemetry.timeline.register_probe(
            "tenancy.inflight_fetches", self.flights.in_flight)
        # Remediation action target (ISSUE 17): the policy engine sheds
        # the lowest-deficit tenant's queued sessions when queue_stuck
        # coincides with an SLO burn projecting a breach, and recovers
        # when the burn subsides. Replace semantics, like the probes.
        telemetry.remediate.register_target("shed", self._shed_cmd)

    def _shed_cmd(self, cmd: str) -> dict:
        if cmd == "recover":
            return self.controller.recover()
        return self.controller.shed()

    def summary(self) -> dict:
        doc = self.controller.summary()
        doc["inflight"] = {
            "budget_bytes": self.byte_budget.budget_bytes,
            "peak_bytes": self.byte_budget.peak_bytes,
        }
        doc["dedupe"] = self.flights.summary()
        doc["eviction"] = self.evictor.summary()
        doc["pins"] = self.pins.summary()
        return doc


_lock = threading.Lock()
_state: TenancyState | None = None


def _knob_tuple(cfg) -> tuple:
    return (cfg.tenant_max_pulls, cfg.tenant_queue,
            cfg.tenant_inflight_bytes, cfg.tenant_disk_high,
            cfg.tenant_disk_low, str(cfg.xorb_cache_dir()))


def enabled(cfg) -> bool:
    return bool(getattr(cfg, "tenancy_enabled", False))


def state(cfg) -> TenancyState:
    """The process singleton, built from the first caller's Config.
    A later caller with DIFFERENT knob values rebuilds it — but only
    while idle (no active or queued sessions): mid-flight, the first
    admitted configuration wins, because swapping budgets under live
    holders would strand their releases."""
    global _state
    with _lock:
        if _state is None:
            _state = TenancyState(cfg)
        elif _state.knobs != _knob_tuple(cfg):
            c = _state.controller
            with c._cv:
                idle = c._active == 0 and c._queued == 0
            if idle:
                _state = TenancyState(cfg, pins=_state.pins)
        return _state


def summary(cfg=None) -> dict | None:
    """The ``tenancy{}`` status block, or None when the layer is
    knob-off for this caller (or never configured and no cfg given).
    With a cfg, the process state is (re)configured from it first —
    the daemon's ``/v1/status`` must report the daemon's own knobs,
    not whichever embedded pull happened to configure the state last
    (``state()`` only rebuilds while idle, so live sessions are never
    re-budgeted)."""
    if cfg is not None:
        if not enabled(cfg):
            return None
        st = state(cfg)
    else:
        with _lock:
            st = _state
        if st is None:
            return None
    doc = st.summary()
    doc["enabled"] = True
    return doc


def can_enqueue(cfg) -> tuple[bool, float]:
    """Cheap pre-SSE backpressure probe for the HTTP layer: would a
    new session be REJECTED right now? (Advisory — admission itself
    re-checks; the race just turns a 429 into an SSE-stream typed
    error.) Predicate + accounting live on the controller
    (:meth:`AdmissionController.probe_reject`) so they can never
    drift from the real admission decision. Returns
    (ok, retry_after_s)."""
    if not enabled(cfg):
        return True, 0.0
    rejected, retry_after = state(cfg).controller.probe_reject()
    return (not rejected), retry_after


class admit:
    """Context manager one pull session holds for its whole run:
    admission (queued phase, fairness, backpressure) on entry plus a
    watermark eviction pass; slot release, byte-share release (the
    shared budget is released by the file pipeline itself), and pin
    release on exit — however the pull ends."""

    def __init__(self, cfg, tenant: str | None,
                 cancel: CancelToken | None = None, session=None):
        self.cfg = cfg
        self.tenant = tenant or "default"
        self.cancel = cancel
        self.session = session
        self._st: TenancyState | None = None
        self._owner: str | None = None
        self._t0: float | None = None

    @property
    def pin_owner(self) -> str | None:
        return self._owner

    def __enter__(self) -> "admit":
        if not enabled(self.cfg):
            return self
        self._st = state(self.cfg)
        # The queue wait gets its own span so the critical-path
        # analyzer blames parked time as a distinct "queued" stage
        # (ISSUE 15 satellite) instead of untraced idle.
        with telemetry.span("tenancy.queued", tenant=self.tenant):
            self._st.controller.acquire(self.tenant, cancel=self.cancel,
                                        session=self.session)
        self._t0 = time.monotonic()
        sid = getattr(self.session, "id", None) or f"{id(self):x}"
        self._owner = f"sess:{sid}"
        # Disk-pressure check at the one safe, amortized point: before
        # the session's plan pins anything (its own entries are then
        # still fair game if older pulls left the cache over the mark).
        try:
            self._st.evictor.maybe_evict()
        except Exception:  # noqa: BLE001 - eviction is advisory
            pass
        return self

    def pin(self, hashes) -> None:
        """Pin a resolved reconstruction's xorb hashes for the life of
        this admission (no-op when knob-off)."""
        if self._st is not None and self._owner is not None:
            self._st.pins.pin(self._owner, hashes)

    def pin_tree(self, repo: str, hashes) -> None:
        """Replace the live-HBM-tree pin for ``repo``: the manifest a
        delta/hot-swap will diff against stays unevictable after this
        session's own pins release."""
        if self._st is not None:
            self._st.pins.replace(f"tree:{repo}", hashes)

    def __exit__(self, *exc) -> None:
        if self._st is None:
            return
        if self._owner is not None:
            self._st.pins.release(self._owner)
        wall = (time.monotonic() - self._t0) if self._t0 else None
        self._st.controller.release(wall_s=wall)


def release_tree(cfg, repo: str) -> bool:
    """Drop the live-HBM-tree pin for ``repo`` (the inverse of
    ``admit.pin_tree``). The HBM pool calls this when a model's tree
    leaves the pool for good — its xorbs become ordinary eviction
    candidates again instead of staying pinned for a swap that will
    never come. No-op (False) when tenancy is off or nothing was
    pinned."""
    if not enabled(cfg):
        return False
    st = state(cfg)
    return bool(st.pins.release(f"tree:{repo}"))


def reset() -> None:
    """Tests: drop the process state (the next pull reconfigures)."""
    global _state
    with _lock:
        _state = None
    storage.set_disk_full_hook(None)
    for name in ("tenancy.queue_depth", "tenancy.active_pulls",
                 "tenancy.admitted_total", "tenancy.inflight_fetches"):
        telemetry.timeline.unregister_probe(name)
    telemetry.remediate.unregister_target("shed")
