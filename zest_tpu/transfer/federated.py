"""Federated multi-pod rounds: cross-pod bytes over the DCN chunk RPC.

zest_tpu.parallel.hierarchy covers multi-pod distribution when every host
joins ONE jax.distributed mesh — the cross-pod stage is then an XLA
all-gather that XLA routes over DCN. This module covers the other
deployment shape, the one the reference's WAN swarm actually serves
(SURVEY.md §2.4 "peer-to-peer transport" row): pods that are *separate
processes/jobs* with no shared mesh — separate trainers, a warm pod
seeding a cold one, staggered pod startup. Between such pods no
collective exists; bytes move over zest_tpu.transfer.dcn instead.

The round keeps the reference's waterfall contract per unit
(xet_bridge.zig:149-218), with the DCN pod tier slotted between the local
cache and the CDN:

    local cache  →  owner pod over DCN  →  (BT peers)  →  CDN

Ownership is the same HRW pod draw as the hierarchical plan
(hierarchy.owner_pod_host), so every pod independently computes the same
owner map with no coordination, CDN ingress stays balanced across pods
(each unit leaves the CDN once, through its owning pod), and DCN carries
each unit at most (n_pods - 1) times. A failed/missing owner degrades the
unit to CDN — the waterfall's safety net (SURVEY.md §5 failure
detection).

After the cross-pod stage, every unit is in the local cache and an
ordinary in-pod pod_round distributes it over ICI; the two stages
compose exactly like the hierarchical distributor's dcn/ici stages, but
across process boundaries.
"""

from __future__ import annotations

import time

from zest_tpu import telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas.reconstruction import FetchInfo, Reconstruction
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.parallel.hierarchy import owner_pod_host
from zest_tpu.parallel.plan import collect_units
from zest_tpu.transfer.dcn import DcnPool, DcnResponse


def pod_owned_units(
    recs: list[Reconstruction], pod_index: int, n_pods: int
) -> tuple[list[tuple[str, FetchInfo]], dict[int, list[tuple[str, FetchInfo]]]]:
    """Split the deduplicated fetch units into (mine, theirs-by-pod).

    Host-level fan-out inside the pod is the in-pod round's business;
    here only the pod draw matters, so hosts_per_pod is pinned to 1 in
    the HRW call (the pod draw is independent of it by construction).
    """
    mine: list[tuple[str, FetchInfo]] = []
    theirs: dict[int, list[tuple[str, FetchInfo]]] = {}
    for (hash_hex, start), fi in collect_units(recs):
        pod, _host = owner_pod_host(
            hashing.hex_to_hash(hash_hex), start, n_pods, 1
        )
        if pod == pod_index:
            mine.append((hash_hex, fi))
        else:
            theirs.setdefault(pod, []).append((hash_hex, fi))
    return mine, theirs


def _blob_covers(data: bytes, n_chunks: int) -> bool:
    """Structural gate before caching a DCN blob (same rule as the BT
    peer tier, bridge._blob_covers): parses and holds >= n_chunks frames.
    BLAKE3 content verification happens at extraction, as everywhere."""
    try:
        return len(XorbReader(data)) >= n_chunks
    except Exception:
        return False


def _already_cached(bridge, hash_hex: str, fi: FetchInfo) -> bool:
    """True when the local cache already serves [fi.range) — both to skip
    the fetch and, critically, to never *write*: a blob that round-tripped
    through a fetch_unit cache hit can be a narrower slice of the cached
    entry (e.g. a full xorb answering a [0,3) unit), and re-putting it
    would evict chunks already local."""
    def covers(res) -> bool:
        # Coverage inside the lookup: a non-covering full entry (the
        # resolve-order truncation race, ISSUE 13) falls through to the
        # exact partial instead of shadowing it into a refetch.
        return (res.chunk_offset <= fi.range.start
                and _blob_covers(res.data,
                                 fi.range.end - res.chunk_offset))

    return bridge.cache.get_with_range(hash_hex, fi.range.start,
                                       covers=covers) is not None


def _entries_by_hash(recs: list[Reconstruction]) -> dict[str, list[FetchInfo]]:
    out: dict[str, list[FetchInfo]] = {}
    for rec in recs:
        for hash_hex, entries in rec.fetch_info.items():
            out.setdefault(hash_hex, []).extend(entries)
    return out


def _cache_unit(bridge, entries_map, hash_hex: str, fi: FetchInfo,
                chunk_offset: int, data: bytes) -> None:
    """Cache a fetched unit under the same full-vs-partial rule as the
    bridge (_cache_fetched): full key only with whole-xorb evidence —
    including the bridge's evidence-integrity flag (a pull with
    unresolved aux references forces partial keys everywhere).
    ``provably_whole`` dedupes ranges, so the same whole-xorb reference
    appearing in several files' fetch_info still counts as whole.
    Routed through the bridge's guarded write (never-narrower under
    the hash-striped lock, ENOSPC absorbed)."""
    bridge.cache_blob(
        hash_hex, chunk_offset, data,
        whole=bridge.whole_xorb_provable(entries_map.get(hash_hex, []),
                                         chunk_offset))


def warm_units_parallel(
    bridge, recs: list[Reconstruction], max_concurrent: int | None = None,
    entries_map: dict[str, list[FetchInfo]] | None = None,
    units: list[tuple[str, FetchInfo]] | None = None,
    on_unit=None,
) -> dict:
    """Fetch every uncached unit of ``recs`` into the local cache with
    ``max_concurrent`` waterfall fetches in flight (the reference's
    16-way term concurrency, config.zig:13 / parallel_download.zig).

    This is the single-host stand-in for a distribution round: when no
    collective or owner pod exists (one chip, pod round skipped), the
    direct-to-HBM landing would otherwise pull terms SEQUENTIALLY
    through the waterfall. Idempotent; respects cached entries.

    ``entries_map`` (default: built from ``recs``) is the evidence the
    full-vs-partial cache-key decision is judged against. A caller
    warming ONE shard of a multi-shard checkpoint MUST pass a map built
    over the whole checkpoint (``_entries_by_hash``, prebuilt once — it
    is invariant across shards): a xorb deduped across shards can look
    whole from one shard's fetch_info (single entry at chunk 0) while
    another shard reads its later chunks — caching the truncated blob
    under the full key would shadow the other shard's partial entries
    and poison extraction.

    ``units`` restricts the warm to an explicit subset of ``recs``'s
    fetch units — the cooperative round's fetch phase (transfer.coop)
    warms exactly its ownership-plan share through this same resilient
    path (width heuristics, retry pass, streamed CDN tier) instead of
    reimplementing it. ``entries_map`` must still span ALL files, for
    the same evidence reason as above.

    ``on_unit(key)``, when given, is called with a unit's
    ``(hash_hex, range_start)`` key the moment that unit is RESOLVED:
    immediately for units already cached, at fetch completion for
    fetched ones (completion order, not submission order), and after
    the final retry attempt for units that failed it (the caller's
    per-term waterfall is the terminal fallback, so "resolved" never
    means "guaranteed cached"). The streaming landing's tensor gate
    rides this to start decoding a tensor while the rest of the shard
    is still on the wire.
    """
    with telemetry.span("warm.units", shards=len(recs)):
        return _warm_units_parallel(bridge, recs, max_concurrent,
                                    entries_map, units, on_unit)


def _warm_units_parallel(
    bridge, recs: list[Reconstruction], max_concurrent: int | None = None,
    entries_map: dict[str, list[FetchInfo]] | None = None,
    units: list[tuple[str, FetchInfo]] | None = None,
    on_unit=None,
) -> dict:
    import os
    from concurrent.futures import ThreadPoolExecutor, as_completed

    if entries_map is None:
        entries_map = _entries_by_hash(recs)
    if units is None:
        units = [(hash_hex, fi)
                 for (hash_hex, _s), fi in collect_units(recs)]
    wanted = [
        (hash_hex, fi)
        for hash_hex, fi in units
        if not _already_cached(bridge, hash_hex, fi)
    ]
    if on_unit is not None:
        wanted_keys = {(hh, fi.range.start) for hh, fi in wanted}
        for hh, fi in units:
            if (hh, fi.range.start) not in wanted_keys:
                on_unit((hh, fi.range.start))
    if max_concurrent is None:
        max_concurrent = bridge.cfg.max_concurrent_downloads
        urls = {bridge._absolute_url(fi.url) for _h, fi in wanted[:8]}
        if urls and all("127.0.0.1" in u or "localhost" in u
                        for u in urls):
            # Bytes verifiably flow from loopback (the units' OWN fetch
            # URLs, not the control-plane endpoint — a local hub can
            # hand out presigned remote-CDN URLs): bandwidth-bound on
            # the local CPU, where threads beyond ~4x the cores only
            # thrash the GIL (measured: 16-wide ~15% slower than 2-wide
            # on 1 core). A remote CDN is latency-bound and keeps the
            # configured width — more streams there hide RTT.
            max_concurrent = min(max_concurrent,
                                 max(2, 4 * (os.cpu_count() or 1)))
    stats = {"units": len(wanted), "bytes": 0, "failed": 0}
    if not wanted:
        return stats

    def fetch(unit):
        hash_hex, fi = unit
        if bridge.swarm is None and bridge.cas is not None:
            # No peer tier to try and the cache was checked when
            # building ``wanted``: stream the CDN body straight into
            # the cache file — one full memory pass fewer than
            # fetch-then-put, which is worth ~15% of the whole fetch
            # stage at GB scale on one core.
            full = bridge.whole_xorb_provable(entries_map.get(hash_hex, []),
                                              fi.range.start)
            return bridge.stream_unit_from_cdn(hash_hex, fi, full)
        data = bridge.fetch_unit(hash_hex, fi)
        if bridge.flights is None:
            # Deduped mode already cached the bytes INSIDE fetch_unit
            # (waiters probe the cache the moment the flight resolves)
            # — a second guarded write here would read the just-written
            # entry back only to skip.
            _cache_unit(bridge, entries_map, hash_hex, fi,
                        fi.range.start, data)
        return len(data)

    failed_units = []
    # Futures + as_completed rather than pool.map: submission order is
    # the caller's priority order (the layer-ordered streaming warm),
    # and completion events must reach ``on_unit`` the moment a unit
    # lands — map()'s in-order iteration would park a finished layer-0
    # unit behind a slow earlier one.
    with ThreadPoolExecutor(max_workers=max_concurrent) as pool:
        futures = {pool.submit(_safe, fetch, u): u for u in wanted}
        for fut in as_completed(futures):
            unit = futures[fut]
            result = fut.result()
            if result is None:
                failed_units.append(unit)
            else:
                stats["bytes"] += result
                if on_unit is not None:
                    on_unit((unit[0], unit[1].range.start))
    # One sequential retry pass: under load, concurrent fetches can fail
    # on timeouts the same transfer survives alone (observed: >half of
    # 16-wide ~32 MB unit fetches truncated on a contended host). A
    # unit that fails here too degrades to the landing waterfall — a
    # sequential per-TERM refetch inside the commit stage — which is
    # correct but far slower, so the retry is worth one more attempt.
    for unit in failed_units:
        n = _safe(fetch, unit)
        if n is None:
            stats["failed"] += 1
        else:
            stats["retried"] = stats.get("retried", 0) + 1
            stats["bytes"] += n
        if on_unit is not None:
            on_unit((unit[0], unit[1].range.start))
    return stats


def _safe(fn, arg):
    try:
        return fn(arg)
    except Exception:
        return None  # the landing's own waterfall retries per term


def federated_round(
    bridge,
    recs: list[Reconstruction],
    pod_index: int,
    n_pods: int,
    pod_addrs: dict[int, tuple[str, int]],
    dcn_pool: DcnPool | None = None,
    pipeline_depth: int = 16,
    log=None,
) -> dict:
    """One cross-pod stage: fetch owned units via the waterfall, pull
    foreign-owned units from their owner pods over DCN (pipelined,
    ``pipeline_depth`` in flight per channel — the reference's
    max_concurrent analog, config.zig:13), CDN-fallback anything the
    owner can't serve. Afterwards every unit is locally cached; run
    pod_round(mesh) to spread them in-pod over ICI.

    ``pod_addrs`` maps pod index → (host, dcn_port). Missing pods are
    treated as unreachable (their units degrade to CDN).
    """
    with telemetry.span("federated.round", pod=pod_index, pods=n_pods):
        return _federated_round(bridge, recs, pod_index, n_pods, pod_addrs,
                                dcn_pool, pipeline_depth, log)


def _federated_round(
    bridge,
    recs: list[Reconstruction],
    pod_index: int,
    n_pods: int,
    pod_addrs: dict[int, tuple[str, int]],
    dcn_pool: DcnPool | None = None,
    pipeline_depth: int = 16,
    log=None,
) -> dict:
    t0 = time.monotonic()
    pool = dcn_pool or DcnPool()
    own_pool = dcn_pool is None
    mine, theirs = pod_owned_units(recs, pod_index, n_pods)
    entries_map = _entries_by_hash(recs)

    stats = {
        "pod": pod_index,
        "pods": n_pods,
        "own_units": 0,
        "own_bytes": 0,
        "cached_units": 0,
        "dcn_units": 0,
        "dcn_bytes": 0,
        "fallback_units": 0,
        "fallback_bytes": 0,
        "failed_units": 0,
    }

    # Stage 1: own units through the regular waterfall (cache/peers/CDN),
    # persisted so this pod can serve them to the others.
    for hash_hex, fi in mine:
        if _already_cached(bridge, hash_hex, fi):
            stats["own_units"] += 1
            continue
        try:
            data = bridge.fetch_unit(hash_hex, fi)
        except Exception:
            stats["failed_units"] += 1
            continue
        _cache_unit(bridge, entries_map, hash_hex, fi, fi.range.start, data)
        stats["own_units"] += 1
        stats["own_bytes"] += len(data)

    # Stage 2: foreign units from their owner pod, pipelined per channel.
    def fallback(units, owner_pod=None):
        for hash_hex, fi in units:
            if _already_cached(bridge, hash_hex, fi):
                stats["fallback_units"] += 1
                continue
            try:
                data = bridge.fetch_unit(hash_hex, fi)
            except Exception:
                stats["failed_units"] += 1
                continue
            _cache_unit(bridge, entries_map, hash_hex, fi,
                        fi.range.start, data)
            stats["fallback_units"] += 1
            stats["fallback_bytes"] += len(data)
            telemetry.record("cdn_fallback", unit=hash_hex[:16],
                             owner=owner_pod, tier="federated",
                             bytes=len(data))

    for pod, all_units in sorted(theirs.items()):
        units = []
        for hash_hex, fi in all_units:
            if _already_cached(bridge, hash_hex, fi):
                stats["cached_units"] += 1
            else:
                units.append((hash_hex, fi))
        if not units:
            continue
        addr = pod_addrs.get(pod)
        if addr is None:
            fallback(units, owner_pod=pod)
            continue
        i = 0
        while i < len(units):
            window = units[i : i + pipeline_depth]
            missed = []
            try:
                # The pool transparently reconnects and retries a window
                # once when a pooled channel was idle-closed (a stale
                # channel after a long gap, a blip mid-transfer) — so a
                # failure surfacing here is a hard one, and the pod's
                # remaining units degrade to CDN.
                replies = pool.request_many(*addr, [
                    (hashing.hex_to_hash(hh), fi.range.start, fi.range.end)
                    for hh, fi in window
                ])
            except (ConnectionError, TimeoutError, OSError):
                fallback(units[i:], owner_pod=pod)
                break
            for (hash_hex, fi), reply in zip(window, replies):
                if (
                    isinstance(reply, DcnResponse)
                    and reply.chunk_offset <= fi.range.start
                    and _blob_covers(
                        reply.data,
                        fi.range.end - reply.chunk_offset,
                    )
                ):
                    _cache_unit(
                        bridge, entries_map, hash_hex, fi,
                        reply.chunk_offset, reply.data,
                    )
                    bridge.stats.record("peer", len(reply.data))
                    stats["dcn_units"] += 1
                    stats["dcn_bytes"] += len(reply.data)
                else:
                    missed.append((hash_hex, fi))
            fallback(missed, owner_pod=pod)
            i += pipeline_depth

    if own_pool:
        pool.close()
    stats["units"] = len(mine) + sum(len(u) for u in theirs.values())
    stats["elapsed_s"] = round(time.monotonic() - t0, 3)
    if log is not None:
        log(
            f"federated round pod {pod_index}/{n_pods}: "
            f"{stats['own_units']} own, {stats['dcn_units']} over DCN "
            f"({stats['dcn_bytes']} bytes), {stats['fallback_units']} "
            f"CDN-fallback, {stats['failed_units']} failed"
        )
    return stats
