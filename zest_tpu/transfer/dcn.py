"""DCN host-to-host chunk RPC — the cross-pod transport tier.

In-pod, bulk bytes ride ICI as XLA collectives (zest_tpu.transfer.pod);
off-pod BitTorrent peers ride the full BT interop stack (zest_tpu.p2p).
Between *our own* hosts across DCN neither fits: collectives need one
jax.distributed mesh spanning every host, and the BT stack pays for a
handshake dance + bencoded extension negotiation that exists only for
interop with foreign clients. This module is the third transport: a lean,
pipelined request/response protocol between zest hosts, bound to
``Config.dcn_port``, with exactly the reference's BEP XET semantics —
CHUNK_REQUEST / CHUNK_RESPONSE / CHUNK_NOT_FOUND / CHUNK_ERROR over one
long-lived TCP stream with request-ID matching (reference:
src/bep_xet.zig:66-124, pipelining: src/bt_peer.zig:188-248) — minus the
BT framing it doesn't need.

Wire format (version 1, all integers little-endian; both sides send an
8-byte hello on connect, then messages flow in either direction):

    hello:   "ZDCN" u8 version  u8 flags(0)  u16 reserved(0)
    message: u8 type  u8 flags(0)  u16 reserved(0)  u32 req_id  u32 len
             + len payload bytes
    REQUEST   (1): 32B xorb hash + u64 chunk_start + u64 chunk_end
    RESPONSE  (2): u64 chunk_offset + frame bytes
    NOT_FOUND (3): 32B xorb hash
    ERROR     (4): utf-8 message

Ranges are chunk-index ranges within a xorb and responses carry the
``chunk_offset`` their frames start at — identical coordinate frames to
BEP XET, so cache rebasing logic is shared. The 64 MiB+1KB payload cap
matches the BT wire cap (src/bt_wire.zig:22): a full xorb always fits.

Serving reads the same two cache tiers as the BT seeding server — the
lookup is factored into :func:`lookup_chunk_range` and shared by both —
so a host answers identically whether asked over DCN or BT wire.
"""

from __future__ import annotations

import socket
import struct
import threading
from dataclasses import dataclass

from zest_tpu import faults, telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas.xorb import XorbFormatError, XorbReader, encode_frame
from zest_tpu.config import Config
from zest_tpu.p2p.wire import MAX_MESSAGE_SIZE
from zest_tpu.storage import XorbCache, read_chunk

_M_CHUNKS_SERVED = telemetry.counter(
    "zest_dcn_chunks_served_total",
    "Chunks served to other pods over the DCN RPC")
_M_BYTES_SERVED = telemetry.counter(
    "zest_dcn_bytes_served_total",
    "Payload bytes served over the DCN RPC")

MAGIC = b"ZDCN"
VERSION = 1
_HELLO = MAGIC + bytes([VERSION, 0, 0, 0])
_HEADER = struct.Struct("<BBHII")

MSG_REQUEST = 1
MSG_RESPONSE = 2
MSG_NOT_FOUND = 3
MSG_ERROR = 4

# A silent peer (half-open connection, port scanner that said hello)
# releases its serving thread after this long; clients hold channels
# with in-flight traffic, and an expired channel just reconnects.
IDLE_TIMEOUT_S = 300.0

_REQ_BODY = struct.Struct("<32sQQ")


class DcnProtocolError(ConnectionError):
    pass


@dataclass(frozen=True)
class DcnRequest:
    request_id: int
    chunk_hash: bytes
    range_start: int
    range_end: int


@dataclass(frozen=True)
class DcnResponse:
    request_id: int
    chunk_offset: int
    data: bytes


@dataclass(frozen=True)
class DcnNotFound:
    request_id: int
    chunk_hash: bytes


@dataclass(frozen=True)
class DcnError:
    request_id: int
    message: str


DcnMessage = DcnRequest | DcnResponse | DcnNotFound | DcnError


# ── Codec (fixed-buffer roundtrip-testable, no sockets) ──


_OFFSET = struct.Struct("<Q")


def encode_response_prefix(
    request_id: int, chunk_offset: int, data_len: int
) -> bytes:
    """Header + chunk_offset prefix of a RESPONSE carrying ``data_len``
    payload bytes. The single source of truth for RESPONSE framing: both
    ``encode_message`` and the server's zero-copy scatter-gather send
    (which must not memcpy the blob into one bytestring) build from it.
    """
    body_len = _OFFSET.size + data_len
    if body_len > MAX_MESSAGE_SIZE:
        raise DcnProtocolError(f"payload of {body_len} bytes over cap")
    return (_HEADER.pack(MSG_RESPONSE, 0, 0, request_id, body_len)
            + _OFFSET.pack(chunk_offset))


def encode_message(msg: DcnMessage) -> bytes:
    if isinstance(msg, DcnRequest):
        body = _REQ_BODY.pack(msg.chunk_hash, msg.range_start, msg.range_end)
        mtype = MSG_REQUEST
    elif isinstance(msg, DcnResponse):
        return encode_response_prefix(
            msg.request_id, msg.chunk_offset, len(msg.data)
        ) + msg.data
    elif isinstance(msg, DcnNotFound):
        body = msg.chunk_hash
        mtype = MSG_NOT_FOUND
    elif isinstance(msg, DcnError):
        body = msg.message.encode()
        mtype = MSG_ERROR
    else:  # pragma: no cover - type system guards this
        raise DcnProtocolError(f"unencodable message {msg!r}")
    if len(body) > MAX_MESSAGE_SIZE:
        raise DcnProtocolError(f"payload of {len(body)} bytes over cap")
    return _HEADER.pack(mtype, 0, 0, msg.request_id, len(body)) + body


def decode_message(header: bytes, body: bytes) -> DcnMessage:
    mtype, _flags, _rsvd, req_id, length = _HEADER.unpack(header)
    if length != len(body):
        raise DcnProtocolError("body length disagrees with header")
    if mtype == MSG_REQUEST:
        if len(body) != _REQ_BODY.size:
            raise DcnProtocolError("bad REQUEST body")
        h, start, end = _REQ_BODY.unpack(body)
        return DcnRequest(req_id, h, start, end)
    if mtype == MSG_RESPONSE:
        if len(body) < 8:
            raise DcnProtocolError("bad RESPONSE body")
        (offset,) = struct.unpack_from("<Q", body)
        return DcnResponse(req_id, offset, body[8:])
    if mtype == MSG_NOT_FOUND:
        if len(body) != hashing.HASH_LEN:
            raise DcnProtocolError("bad NOT_FOUND body")
        return DcnNotFound(req_id, body)
    if mtype == MSG_ERROR:
        return DcnError(req_id, body.decode(errors="replace"))
    raise DcnProtocolError(f"unknown message type {mtype}")


def _sendmsg_all(sock: socket.socket, buffers: list[bytes]) -> None:
    """sendall semantics over scatter-gather buffers (no concat copy).

    sendmsg can send fewer bytes than given; resume from the split point
    with memoryviews rather than re-joining."""
    views = [memoryview(b) for b in buffers if len(b)]
    while views:
        sent = sock.sendmsg(views)
        while views and sent >= len(views[0]):
            sent -= len(views[0])
            views.pop(0)
        if views and sent:
            views[0] = views[0][sent:]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("DCN peer closed the stream")
        buf += part
    return bytes(buf)


def _recv_message(sock: socket.socket) -> DcnMessage:
    header = _recv_exact(sock, _HEADER.size)
    length = struct.unpack_from("<I", header, 8)[0]
    if length > MAX_MESSAGE_SIZE:
        raise DcnProtocolError(f"message of {length} bytes over cap")
    return decode_message(header, _recv_exact(sock, length))


def _exchange_hello(sock: socket.socket) -> None:
    sock.sendall(_HELLO)
    theirs = _recv_exact(sock, len(_HELLO))
    if theirs[:4] != MAGIC:
        raise DcnProtocolError("peer is not a zest DCN endpoint")
    if theirs[4] != VERSION:
        raise DcnProtocolError(f"unsupported DCN version {theirs[4]}")


# ── Shared cache lookup (BT server and DCN server answer identically) ──


def lookup_chunk_range(
    cfg: Config,
    cache: XorbCache,
    chunk_hash: bytes,
    range_start: int,
    range_end: int,
) -> tuple[int, bytes] | None:
    """Two-tier cache read for a chunk-range request: the chunk cache
    (single chunk, wrapped into one frame), then the xorb cache with
    range rebasing (reference: src/server.zig:187-215). Returns
    (chunk_offset, frame bytes) or None."""
    data = read_chunk(cfg, chunk_hash)
    if data is not None:
        frame, _h = encode_frame(data)
        return 0, frame

    hash_hex = hashing.hash_to_hex(chunk_hash)
    cached = cache.get_with_range(hash_hex, range_start)
    if cached is None:
        return None
    blob, offset = cached.data, cached.chunk_offset
    try:
        reader = XorbReader(blob)
        local_start = range_start - offset
        local_end = range_end - offset
        if 0 <= local_start < local_end <= len(reader):
            blob = reader.slice_range(local_start, local_end)
            offset = range_start
    except XorbFormatError:
        pass  # serve the whole entry; requester re-slices
    return offset, blob


# ── Server ──


class ConnTracker:
    """Live-connection registry shared by the socket servers (BtServer,
    DcnServer). Serving threads register/discard their connection; at
    shutdown ``wake_all`` sends SHUT_RDWR to a snapshot so threads
    blocked in recv exit now instead of at their idle timeout. Invariant:
    only the owning thread ever close()s (a second close here could race
    a recycled fd); threads registered after the snapshot must re-check
    the server's shutdown flag themselves."""

    def __init__(self) -> None:
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()

    def add(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.add(conn)

    def discard(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.discard(conn)

    def wake_all(self) -> None:
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


@dataclass
class DcnServerStats:
    connections: int = 0
    chunks_served: int = 0
    bytes_served: int = 0
    not_found: int = 0


class DcnServer:
    """Chunk-RPC listener bound to ``cfg.dcn_port`` (0 = ephemeral).

    One thread per connection, sequential request service per stream —
    responses go back in request order, and clients pipeline by tagging
    request IDs (the reference's model: one serve loop per peer,
    src/server.zig:158-172).
    """

    def __init__(self, cfg: Config, cache: XorbCache | None = None):
        self.cfg = cfg
        self.cache = cache or XorbCache(cfg)
        self.port: int | None = None
        self.stats = DcnServerStats()
        self._stats_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._sock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns = ConnTracker()

    def start(self) -> int:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind(("0.0.0.0", self.cfg.dcn_port))
            sock.listen(64)
            # Periodic timeout so shutdown() is observed promptly — a
            # close() does not wake a thread blocked in accept(), and the
            # kernel keeps the port bound while the syscall holds the fd
            # (same discipline as BtServer.start).
            sock.settimeout(0.25)
        except OSError:
            sock.close()
            raise
        self._sock = sock
        self.port = sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="dcn-accept", daemon=True
        )
        self._accept_thread.start()
        return self.port

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # The accept loop polls the flag every 0.25s; join it so no
        # further connection can be handed out after this point, then
        # wake live serving threads (ConnTracker invariants).
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        self._conns.wake_all()

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue  # poll the shutdown flag
            except OSError:
                return  # listener closed
            with self._stats_lock:
                self.stats.connections += 1
            # Daemon threads, deliberately not tracked: each exits when
            # its peer disconnects, idles past IDLE_TIMEOUT_S, or the
            # listener shuts down — holding references would only grow a
            # list for the daemon's lifetime.
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        self._conns.add(conn)
        try:
            with conn:
                # A connection accepted in the same beat as shutdown()
                # may miss its SHUT_RDWR (registered after the snapshot);
                # re-checking here closes that window.
                if self._shutdown.is_set():
                    return
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(IDLE_TIMEOUT_S)
                _exchange_hello(conn)
                while not self._shutdown.is_set():
                    msg = _recv_message(conn)
                    if not isinstance(msg, DcnRequest):
                        conn.sendall(encode_message(DcnError(
                            msg.request_id, "server accepts only REQUEST"
                        )))
                        continue
                    self._serve_request(conn, msg)
        except (ConnectionError, DcnProtocolError, OSError):
            return  # peer went away / spoke garbage: drop the connection
        finally:
            self._conns.discard(conn)

    def _serve_request(self, conn: socket.socket, req: DcnRequest) -> None:
        if not req.range_start < req.range_end:
            conn.sendall(encode_message(DcnError(
                req.request_id,
                f"invalid range [{req.range_start},{req.range_end})",
            )))
            return
        found = lookup_chunk_range(
            self.cfg, self.cache, req.chunk_hash,
            req.range_start, req.range_end,
        )
        if found is None:
            with self._stats_lock:
                self.stats.not_found += 1
            conn.sendall(encode_message(
                DcnNotFound(req.request_id, req.chunk_hash)
            ))
            return
        offset, blob = found
        if _OFFSET.size + len(blob) > MAX_MESSAGE_SIZE:
            # An over-cap cached entry (e.g. served whole after a footer
            # parse failure) must fail as a clean ERROR, not stream an
            # over-cap message the client will kill the channel over.
            conn.sendall(encode_message(DcnError(
                req.request_id, f"entry of {len(blob)} bytes over cap"
            )))
            return
        # Count before sending: a client that got the last response must
        # observe the stats it implies (the send is the visibility edge).
        with self._stats_lock:
            self.stats.chunks_served += 1
            self.stats.bytes_served += len(blob)
        _M_CHUNKS_SERVED.inc()
        _M_BYTES_SERVED.inc(len(blob))
        # Scatter-gather send: the blob can be a whole 64 MiB xorb, and
        # encode_message would memcpy it twice building one bytestring.
        _sendmsg_all(conn, [
            encode_response_prefix(req.request_id, offset, len(blob)), blob,
        ])


# ── Client ──


class DcnChannel:
    """One pipelined stream to a remote host's DcnServer.

    Thread-safe: senders tag monotonically increasing request IDs; a
    single reader thread matches responses back to waiting callers, so
    any number of threads can have requests in flight on one TCP
    connection (queue-depth management per SURVEY.md §2.4 row "request
    pipelining")."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.address = (host, port)
        self.timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _exchange_hello(self._sock)
        except Exception:
            self._sock.close()  # not a zest endpoint / hello timeout
            raise
        # The connect/hello timeout must not linger: the reader thread
        # blocks between requests indefinitely (idle ≠ dead); per-request
        # deadlines live in _Waiter.wait, not on the socket.
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._next_id = 0
        self._pending: dict[int, _Waiter] = {}
        self._pending_lock = threading.Lock()
        self._closed = False
        self.dead = False  # reader saw EOF/error; pool must reconnect
        self._reader = threading.Thread(
            target=self._read_loop, name="dcn-reader", daemon=True
        )
        self._reader.start()

    def close(self) -> None:
        self._closed = True
        self.dead = True
        try:
            self._sock.close()
        except OSError:
            pass
        self._fail_all(ConnectionError("channel closed"))

    def _fail_all(self, exc: Exception) -> None:
        with self._pending_lock:
            waiters = list(self._pending.values())
            self._pending.clear()
        for w in waiters:
            w.error = exc
            w.event.set()

    def _read_loop(self) -> None:
        try:
            while True:
                msg = _recv_message(self._sock)
                with self._pending_lock:
                    waiter = self._pending.pop(msg.request_id, None)
                if waiter is not None:
                    waiter.result = msg
                    waiter.event.set()
        except (ConnectionError, DcnProtocolError, OSError) as exc:
            self.dead = True
            if not self._closed:
                self._fail_all(exc)

    def send_request(
        self, chunk_hash: bytes, range_start: int, range_end: int
    ) -> "_Waiter":
        """Fire one request; returns a waiter to collect later — callers
        batch N sends then collect N waits to pipeline."""
        if faults.fire("dcn_reset",
                       key=f"{self.address[0]}:{self.address[1]}"):
            self.dead = True
            raise ConnectionError("injected dcn_reset")
        if self.dead:
            raise ConnectionError("DCN channel is dead")
        with self._send_lock:
            req_id = self._next_id
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            waiter = _Waiter(req_id)
            with self._pending_lock:
                self._pending[req_id] = waiter
            try:
                self._sock.sendall(encode_message(
                    DcnRequest(req_id, chunk_hash, range_start, range_end)
                ))
            except OSError as exc:
                with self._pending_lock:
                    self._pending.pop(req_id, None)
                raise ConnectionError(f"DCN send failed: {exc}") from exc
        return waiter

    def request(
        self, chunk_hash: bytes, range_start: int, range_end: int
    ) -> DcnMessage:
        return self.send_request(
            chunk_hash, range_start, range_end
        ).wait(self.timeout)

    def request_many(
        self, wants: list[tuple[bytes, int, int]],
        timeout: float | None = None,
    ) -> list[DcnMessage]:
        """Pipelined batch: all requests go out before any response is
        awaited; results come back in ``wants`` order. ``timeout``
        overrides the channel default per call — the cooperative
        exchange bounds each window by its round deadline's remainder
        instead of letting one silent owner hold a 30 s default."""
        waiters = [self.send_request(*w) for w in wants]
        t = self.timeout if timeout is None else timeout
        return [w.wait(t) for w in waiters]


class _Waiter:
    __slots__ = ("request_id", "event", "result", "error")

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.event = threading.Event()
        self.result: DcnMessage | None = None
        self.error: Exception | None = None

    def wait(self, timeout: float) -> DcnMessage:
        if not self.event.wait(timeout):
            raise TimeoutError(
                f"DCN request {self.request_id} timed out after {timeout}s"
            )
        if self.error is not None:
            raise self.error
        assert self.result is not None
        return self.result


class DcnPool:
    """Long-lived channels keyed by (host, port). Pod topology is static,
    so channels persist for the process lifetime — the reference's
    LRU-evicting PeerPool degenerates to a plain dict here (SURVEY.md
    §2.1 row 8: "mostly subsumed by persistent pod topology")."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout
        self._channels: dict[tuple[str, int], DcnChannel] = {}
        self._lock = threading.Lock()

    def channel(self, host: str, port: int) -> DcnChannel:
        return self._lease(host, port)[0]

    def _lease(self, host: str, port: int) -> tuple[DcnChannel, bool]:
        """``(channel, reused)``: whether the channel predates this call.
        A reused channel can be silently stale — the server idle-closes
        after IDLE_TIMEOUT_S and the reader may not have observed the
        FIN yet — which is why :meth:`request_many` treats a reused
        channel's failure as retryable and a fresh one's as real."""
        key = (host, port)
        with self._lock:
            ch = self._channels.get(key)
            if ch is not None and ch.dead:
                # Server-side idle close (IDLE_TIMEOUT_S) or a dropped
                # link killed the reader; an expired channel reconnects
                # instead of poisoning every later round.
                del self._channels[key]
                ch.close()
                ch = None
        if ch is not None:
            return ch, True
        ch = DcnChannel(host, port, timeout=self.timeout)
        with self._lock:
            # connect raced: keep the first live one, close ours
            existing = self._channels.get(key)
            if existing is not None and not existing.dead:
                ch.close()
                return existing, True
            self._channels[key] = ch
            return ch, False

    def request_many(
        self, host: str, port: int, wants: list[tuple[bytes, int, int]],
        timeout: float | None = None,
    ) -> list[DcnMessage]:
        """Pipelined batch through a pooled channel, transparently
        reconnecting and retrying ONCE when a previously pooled channel
        turns out to be dead (the server's IDLE_TIMEOUT_S drop lands
        exactly here: the pool believed the channel was live, the first
        send/response proves otherwise). A *fresh* connection's failure
        propagates — that's a real peer problem, not staleness.
        ``timeout`` caps each response wait for this call only."""
        # Forwarded only when set: injected channel doubles (tests,
        # wrappers) predate the parameter.
        kw = {} if timeout is None else {"timeout": timeout}
        with telemetry.span("dcn.request_many", peer=f"{host}:{port}",
                            requests=len(wants)):
            ch, reused = self._lease(host, port)
            try:
                return ch.request_many(wants, **kw)
            except (ConnectionError, TimeoutError, OSError):
                self.drop(host, port)
                if not reused:
                    raise
                ch, _ = self._lease(host, port)
                try:
                    return ch.request_many(wants, **kw)
                except (ConnectionError, TimeoutError, OSError):
                    self.drop(host, port)
                    raise

    def drop(self, host: str, port: int) -> None:
        with self._lock:
            ch = self._channels.pop((host, port), None)
        if ch is not None:
            ch.close()

    def close(self) -> None:
        with self._lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            ch.close()
