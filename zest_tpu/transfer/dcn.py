"""DCN host-to-host chunk RPC — the cross-pod transport tier.

In-pod, bulk bytes ride ICI as XLA collectives (zest_tpu.transfer.pod);
off-pod BitTorrent peers ride the full BT interop stack (zest_tpu.p2p).
Between *our own* hosts across DCN neither fits: collectives need one
jax.distributed mesh spanning every host, and the BT stack pays for a
handshake dance + bencoded extension negotiation that exists only for
interop with foreign clients. This module is the third transport: a lean,
pipelined request/response protocol between zest hosts, bound to
``Config.dcn_port``, with exactly the reference's BEP XET semantics —
CHUNK_REQUEST / CHUNK_RESPONSE / CHUNK_NOT_FOUND / CHUNK_ERROR over one
long-lived TCP stream with request-ID matching (reference:
src/bep_xet.zig:66-124, pipelining: src/bt_peer.zig:188-248) — minus the
BT framing it doesn't need.

Wire format (all integers little-endian; both sides send an 8-byte
hello on connect, then messages flow in either direction):

    hello:   "ZDCN" u8 version(1)  u8 flags(0)  u16 hello_sub
    trace:   16B trace_id  u16 host_index  u16 flags  f64 epoch_s  4B rsvd
             (32 bytes; exchanged only when BOTH hellos advertised
             hello_sub >= 2, immediately after the hellos)
    message: u8 type  u8 flags(0)  u16 tag  u32 req_id  u32 len
             + len payload bytes
    REQUEST   (1): 32B xorb hash + u64 chunk_start + u64 chunk_end
    RESPONSE  (2): u64 chunk_offset + frame bytes
    NOT_FOUND (3): 32B xorb hash
    ERROR     (4): utf-8 message

Hello versioning (ISSUE 7): v1 peers validate the magic and the
version byte ONLY and hard-reject any other version byte — so the
negotiable hello version rides the u16 the v1 hello reserved (and
never read), with 0 meaning "v1 legacy". Old peers therefore
interoperate in both directions with zero extra round trips: a v1
peer ignores our sub-version advert and sends rsvd=0, and each side
sends the 32-byte trace-context block only after reading a >=2 advert
from the other (send-hello, read-hello, then block exchange — never a
deadlock, never unexpected bytes at a v1 peer). The block carries the
fleet ``trace_id`` + the sender's coop host index (server-side serve
spans stamp both, which is what flow-links them to the client's
``dcn.request_many`` spans in the merged trace) and the sender's wall
clock, from which the reader estimates the peer clock offset within
±rtt/2 (telemetry.fleet uses it to normalize merged-trace timelines).
Similarly, v2 REQUESTs carry a ``tag`` in the per-message u16 that v1
reserved: the requester's window id, echoed into the serve span.

Ranges are chunk-index ranges within a xorb and responses carry the
``chunk_offset`` their frames start at — identical coordinate frames to
BEP XET, so cache rebasing logic is shared. The 64 MiB+1KB payload cap
matches the BT wire cap (src/bt_wire.zig:22): a full xorb always fits.

Serving reads the same two cache tiers as the BT seeding server — the
lookup is factored into :func:`lookup_chunk_range` and shared by both —
so a host answers identically whether asked over DCN or BT wire.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass

from zest_tpu import faults, telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas.xorb import XorbFormatError, XorbReader, encode_frame
from zest_tpu.config import Config
from zest_tpu.p2p.wire import MAX_MESSAGE_SIZE
from zest_tpu.storage import XorbCache, read_chunk

_M_CHUNKS_SERVED = telemetry.counter(
    "zest_dcn_chunks_served_total",
    "Chunks served to other pods over the DCN RPC")
_M_BYTES_SERVED = telemetry.counter(
    "zest_dcn_bytes_served_total",
    "Payload bytes served over the DCN RPC")

MAGIC = b"ZDCN"
VERSION = 1
# Negotiable hello sub-version, carried in the u16 the v1 hello
# reserved (v1 validates only magic + version byte, so old peers read
# our advert as padding and send 0 back — that IS the negotiation).
HELLO_SUBVERSION = 2
_HELLO_STRUCT = struct.Struct("<4sBBH")
_HELLO = _HELLO_STRUCT.pack(MAGIC, VERSION, 0, HELLO_SUBVERSION)
# v2 trace-context block: trace_id, coop host index (0xFFFF = none),
# flags, sender wall clock, reserved.
_TRACE_BLOCK = struct.Struct("<16sHHd4x")
_NO_HOST = 0xFFFF
_HEADER = struct.Struct("<BBHII")

MSG_REQUEST = 1
MSG_RESPONSE = 2
MSG_NOT_FOUND = 3
MSG_ERROR = 4
# Gossip piggyback (ISSUE 16): one anti-entropy push-pull round rides
# the existing chunk-RPC channel as a JSON request/reply pair — no new
# listener, no new port. A pre-gossip server answers GOSSIP with the
# generic "server accepts only REQUEST" ERROR, which clients treat as
# "gossip unavailable there", never a connection fault.
MSG_GOSSIP = 5
MSG_GOSSIP_REPLY = 6

# Per-message flag bits (ISSUE 20), carried in the u8 the v1 header
# reserved and always sent as 0 — a v1 peer never reads it, so setting
# a bit is wire-compatible in both directions. On a REQUEST,
# FLAG_LOSSY_OK means "I accept lossy payloads" (the server may
# forward a staged quantized container it received itself) and
# FLAG_QUANT_OK additionally invites the server to quantize fresh
# byte-exact cache data for this bandwidth-starved link. On a
# RESPONSE, FLAG_LOSSY marks a quantized "ZQLS" container (see
# transfer.lossy). Defaults of 0 keep every wire byte identical to
# the pre-lossy protocol.
FLAG_LOSSY_OK = 0x01
FLAG_QUANT_OK = 0x02
FLAG_LOSSY = 0x01

# A silent peer (half-open connection, port scanner that said hello)
# releases its serving thread after this long; clients hold channels
# with in-flight traffic, and an expired channel just reconnects.
IDLE_TIMEOUT_S = 300.0

_REQ_BODY = struct.Struct("<32sQQ")


class DcnProtocolError(ConnectionError):
    pass


class GossipUnavailable(ConnectionError):
    """The peer's server answered GOSSIP with an ERROR — a pre-gossip
    build (or one with no node attached). Callers skip the peer for
    this round; chunk RPCs to it still work."""


@dataclass(frozen=True)
class DcnRequest:
    request_id: int
    chunk_hash: bytes
    range_start: int
    range_end: int
    # v2 window tag (the per-message u16 v1 reserved): identifies the
    # requester's ``dcn.request_many`` window so the server's serve
    # span flow-links to it in the merged trace. 0 = untagged.
    tag: int = 0
    # Per-message flag bits (FLAG_LOSSY_OK). 0 = byte-exact only.
    flags: int = 0


@dataclass(frozen=True)
class DcnResponse:
    request_id: int
    chunk_offset: int
    data: bytes
    # FLAG_LOSSY set ⇒ ``data`` is a quantized "ZQLS" container, not
    # frame bytes — admissible to HBM staging only, never the cache.
    flags: int = 0


@dataclass(frozen=True)
class DcnNotFound:
    request_id: int
    chunk_hash: bytes


@dataclass(frozen=True)
class DcnError:
    request_id: int
    message: str


@dataclass(frozen=True)
class DcnGossip:
    """One gossip push-pull payload (request or reply — symmetric):
    ``payload`` is the transfer.gossip vv+delta dict, JSON on the wire
    (gossip deltas are small bounded metadata, not chunk payloads)."""

    request_id: int
    payload: dict
    reply: bool = False


DcnMessage = DcnRequest | DcnResponse | DcnNotFound | DcnError | DcnGossip


# ── Codec (fixed-buffer roundtrip-testable, no sockets) ──


_OFFSET = struct.Struct("<Q")


def encode_response_prefix(
    request_id: int, chunk_offset: int, data_len: int, flags: int = 0
) -> bytes:
    """Header + chunk_offset prefix of a RESPONSE carrying ``data_len``
    payload bytes. The single source of truth for RESPONSE framing: both
    ``encode_message`` and the server's zero-copy scatter-gather send
    (which must not memcpy the blob into one bytestring) build from it.
    """
    body_len = _OFFSET.size + data_len
    if body_len > MAX_MESSAGE_SIZE:
        raise DcnProtocolError(f"payload of {body_len} bytes over cap")
    return (_HEADER.pack(MSG_RESPONSE, flags & 0xFF, 0, request_id,
                         body_len)
            + _OFFSET.pack(chunk_offset))


def encode_message(msg: DcnMessage) -> bytes:
    if isinstance(msg, DcnRequest):
        body = _REQ_BODY.pack(msg.chunk_hash, msg.range_start, msg.range_end)
        if len(body) > MAX_MESSAGE_SIZE:
            raise DcnProtocolError(f"payload of {len(body)} bytes over cap")
        return _HEADER.pack(MSG_REQUEST, msg.flags & 0xFF,
                            msg.tag & 0xFFFF,
                            msg.request_id, len(body)) + body
    elif isinstance(msg, DcnResponse):
        return encode_response_prefix(
            msg.request_id, msg.chunk_offset, len(msg.data), msg.flags
        ) + msg.data
    elif isinstance(msg, DcnNotFound):
        body = msg.chunk_hash
        mtype = MSG_NOT_FOUND
    elif isinstance(msg, DcnError):
        body = msg.message.encode()
        mtype = MSG_ERROR
    elif isinstance(msg, DcnGossip):
        import json as _json

        body = _json.dumps(msg.payload,
                           separators=(",", ":")).encode()
        mtype = MSG_GOSSIP_REPLY if msg.reply else MSG_GOSSIP
    else:  # pragma: no cover - type system guards this
        raise DcnProtocolError(f"unencodable message {msg!r}")
    if len(body) > MAX_MESSAGE_SIZE:
        raise DcnProtocolError(f"payload of {len(body)} bytes over cap")
    return _HEADER.pack(mtype, 0, 0, msg.request_id, len(body)) + body


def decode_message(header: bytes, body: bytes) -> DcnMessage:
    mtype, _flags, tag, req_id, length = _HEADER.unpack(header)
    if length != len(body):
        raise DcnProtocolError("body length disagrees with header")
    if mtype == MSG_REQUEST:
        if len(body) != _REQ_BODY.size:
            raise DcnProtocolError("bad REQUEST body")
        h, start, end = _REQ_BODY.unpack(body)
        return DcnRequest(req_id, h, start, end, tag, _flags)
    if mtype == MSG_RESPONSE:
        if len(body) < 8:
            raise DcnProtocolError("bad RESPONSE body")
        (offset,) = struct.unpack_from("<Q", body)
        return DcnResponse(req_id, offset, body[8:], _flags)
    if mtype == MSG_NOT_FOUND:
        if len(body) != hashing.HASH_LEN:
            raise DcnProtocolError("bad NOT_FOUND body")
        return DcnNotFound(req_id, body)
    if mtype == MSG_ERROR:
        return DcnError(req_id, body.decode(errors="replace"))
    if mtype in (MSG_GOSSIP, MSG_GOSSIP_REPLY):
        import json as _json

        try:
            payload = _json.loads(body.decode())
        except (UnicodeDecodeError, ValueError) as exc:
            raise DcnProtocolError(f"bad GOSSIP body: {exc}") from exc
        if not isinstance(payload, dict):
            raise DcnProtocolError("GOSSIP body is not an object")
        return DcnGossip(req_id, payload,
                         reply=mtype == MSG_GOSSIP_REPLY)
    raise DcnProtocolError(f"unknown message type {mtype}")


def _sendmsg_all(sock: socket.socket, buffers: list[bytes]) -> None:
    """sendall semantics over scatter-gather buffers (no concat copy).

    sendmsg can send fewer bytes than given; resume from the split point
    with memoryviews rather than re-joining."""
    views = [memoryview(b) for b in buffers if len(b)]
    while views:
        sent = sock.sendmsg(views)
        while views and sent >= len(views[0]):
            sent -= len(views[0])
            views.pop(0)
        if views and sent:
            views[0] = views[0][sent:]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("DCN peer closed the stream")
        buf += part
    return bytes(buf)


def _recv_message(sock: socket.socket) -> DcnMessage:
    header = _recv_exact(sock, _HEADER.size)
    length = struct.unpack_from("<I", header, 8)[0]
    if length > MAX_MESSAGE_SIZE:
        raise DcnProtocolError(f"message of {length} bytes over cap")
    return decode_message(header, _recv_exact(sock, length))


@dataclass
class HelloInfo:
    """Negotiated per-connection state from the hello exchange."""

    subversion: int = 1
    peer_trace_id: str | None = None    # hex, from the peer's block
    peer_host: int | None = None        # peer's coop host index
    peer_epoch_s: float | None = None
    rtt_s: float | None = None
    # Estimated (peer wall clock − our wall clock); error ≤ ±rtt/2
    # (single-exchange NTP bound). telemetry.fleet normalizes merged
    # trace timelines with it.
    clock_offset_s: float | None = None


def _our_trace_block() -> bytes:
    """This side's 32-byte trace-context block, from the process/thread
    trace context (set by the cooperative round). All-zero trace_id and
    host 0xFFFF when none — the block is transport framing, sent
    whenever v2 negotiated, so the wire shape does not depend on the
    telemetry knob."""
    ctx = telemetry.trace.current_context()
    tid = ctx.get("trace_id")
    host = ctx.get("host")
    try:
        tid_bytes = bytes.fromhex(tid) if tid else b"\0" * 16
    except ValueError:
        tid_bytes = b"\0" * 16
    if len(tid_bytes) != 16:
        tid_bytes = (tid_bytes + b"\0" * 16)[:16]
    host_u16 = host if isinstance(host, int) and 0 <= host < _NO_HOST \
        else _NO_HOST
    return _TRACE_BLOCK.pack(tid_bytes, host_u16, 0, time.time())


def _exchange_hello(sock: socket.socket) -> HelloInfo:
    """Send-then-read hello (both sides, symmetric — no deadlock), then
    exchange trace-context blocks when both advertised sub-version ≥2.
    Returns the negotiated :class:`HelloInfo`; raises on a non-zest or
    wrong-version peer exactly as v1 did."""
    t0 = time.monotonic()
    sock.sendall(_HELLO)
    theirs = _recv_exact(sock, _HELLO_STRUCT.size)
    magic, version, _flags, their_sub = _HELLO_STRUCT.unpack(theirs)
    if magic != MAGIC:
        raise DcnProtocolError("peer is not a zest DCN endpoint")
    if version != VERSION:
        raise DcnProtocolError(f"unsupported DCN version {version}")
    info = HelloInfo(subversion=min(HELLO_SUBVERSION, their_sub or 1))
    if info.subversion < 2:
        return info
    sock.sendall(_our_trace_block())
    block = _recv_exact(sock, _TRACE_BLOCK.size)
    t1 = time.monotonic()
    tid_bytes, host_u16, _bflags, peer_epoch = _TRACE_BLOCK.unpack(block)
    if tid_bytes != b"\0" * 16:
        info.peer_trace_id = tid_bytes.hex()
    if host_u16 != _NO_HOST:
        info.peer_host = host_u16
    info.peer_epoch_s = peer_epoch
    rtt = max(0.0, t1 - t0)
    info.rtt_s = rtt
    # peer_epoch was stamped ~rtt/2 before our read of it (symmetric
    # path assumption — the NTP single-exchange estimator).
    info.clock_offset_s = peer_epoch - (time.time() - rtt / 2.0)
    return info


# ── Shared cache lookup (BT server and DCN server answer identically) ──


def lookup_chunk_range(
    cfg: Config,
    cache: XorbCache,
    chunk_hash: bytes,
    range_start: int,
    range_end: int,
) -> tuple[int, bytes] | None:
    """Two-tier cache read for a chunk-range request: the chunk cache
    (single chunk, wrapped into one frame), then the xorb cache with
    range rebasing (reference: src/server.zig:187-215). Returns
    (chunk_offset, frame bytes) or None."""
    data = read_chunk(cfg, chunk_hash)
    if data is not None:
        frame, _h = encode_frame(data)
        return 0, frame

    hash_hex = hashing.hash_to_hex(chunk_hash)
    cached = cache.get_with_range(hash_hex, range_start)
    if cached is None:
        return None
    blob, offset = cached.data, cached.chunk_offset
    try:
        reader = XorbReader(blob)
        local_start = range_start - offset
        local_end = range_end - offset
        if 0 <= local_start < local_end <= len(reader):
            blob = reader.slice_range(local_start, local_end)
            offset = range_start
    except XorbFormatError:
        pass  # serve the whole entry; requester re-slices
    return offset, blob


def serve_chunk_range(
    cfg: Config,
    cache: XorbCache,
    chunk_hash: bytes,
    range_start: int,
    range_end: int,
    flags: int = 0,
) -> tuple[int, bytes, int] | None:
    """:func:`lookup_chunk_range` plus the lossy-tier serving decision,
    shared by the socket server and the in-process loopback transport
    so every backend answers identically. Returns ``(chunk_offset,
    blob, response_flags)`` or None.

    Byte-exact cache data always wins. With FLAG_QUANT_OK the exact
    blob may be replaced by a quantized container when that shrinks the
    wire bytes; with FLAG_LOSSY_OK a cache miss falls through to the
    host's lossy staging (a container this host itself received over a
    lossy link earlier in the round — forwarded VERBATIM, so the
    quantization error never compounds across store-and-forward hops).
    Either way the response is flagged FLAG_LOSSY, and a requester that
    set neither bit can never receive lossy bytes."""
    found = lookup_chunk_range(cfg, cache, chunk_hash,
                               range_start, range_end)
    if found is not None:
        offset, blob = found
        if flags & FLAG_QUANT_OK:
            from zest_tpu.transfer import lossy as _lossy

            packed = _lossy.quantize_blob(blob)
            if packed is not None and len(packed) < len(blob):
                return offset, packed, FLAG_LOSSY
        return offset, blob, 0
    if flags & FLAG_LOSSY_OK:
        from zest_tpu.transfer import lossy as _lossy

        staged = _lossy.staging_for(cfg.cache_dir).get_with_range(
            hashing.hash_to_hex(chunk_hash), range_start)
        if staged is not None:
            blob, offset = staged
            return offset, blob, FLAG_LOSSY
    return None


# ── Server ──


class ConnTracker:
    """Live-connection registry shared by the socket servers (BtServer,
    DcnServer). Serving threads register/discard their connection; at
    shutdown ``wake_all`` sends SHUT_RDWR to a snapshot so threads
    blocked in recv exit now instead of at their idle timeout. Invariant:
    only the owning thread ever close()s (a second close here could race
    a recycled fd); threads registered after the snapshot must re-check
    the server's shutdown flag themselves."""

    def __init__(self) -> None:
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()

    def add(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.add(conn)

    def discard(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.discard(conn)

    def wake_all(self) -> None:
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


@dataclass
class DcnServerStats:
    connections: int = 0
    chunks_served: int = 0
    bytes_served: int = 0
    not_found: int = 0


class DcnServer:
    """Chunk-RPC listener bound to ``cfg.dcn_port`` (0 = ephemeral).

    One thread per connection, sequential request service per stream —
    responses go back in request order, and clients pipeline by tagging
    request IDs (the reference's model: one serve loop per peer,
    src/server.zig:158-172).
    """

    def __init__(self, cfg: Config, cache: XorbCache | None = None,
                 span_attrs: dict | None = None, rate_bps: int = 0,
                 window_rtt_s: float = 0.0,
                 shape_slices: tuple[int, ...] | None = None,
                 shape_host: int | None = None,
                 shape_pods: tuple[int, ...] | None = None,
                 wan_rtt_s: float = 0.0, wan_bps: int = 0):
        self.cfg = cfg
        self.cache = cache or XorbCache(cfg)
        # Extra attrs stamped on every serve span (the in-process
        # multi-host simulations pass {"host": i}; production servers
        # inherit the process trace context instead).
        self.span_attrs = dict(span_attrs or {})
        # Link shaping for the multihost simulations (the token-bucket
        # hub the coop bench rides): ``rate_bps`` bounds served payload
        # bytes through one shared shaping.TokenBucket, and
        # ``window_rtt_s`` charges one WAN round trip per request
        # WINDOW — the v2 wire tag marks window boundaries, so a
        # pipelined request_many window pays the RTT once while
        # untagged per-unit traffic pays it per request (exactly the
        # asymmetry the collective-vs-point-to-point rows measure).
        # With ``shape_slices`` (a ZEST_COOP_TOPOLOGY tuple) and
        # ``shape_host`` (this server's coop host index), shaping
        # applies ONLY to cross-slice connections — the physical
        # asymmetry where intra-slice traffic rides ICI at full speed
        # and only the DCN plane is scarce; the client's slice comes
        # from the hello's peer host index (an anonymous client is
        # conservatively treated as cross-slice). Both default off:
        # production serving is unshaped here (the seeding tier has
        # its own upload policy).
        # ``shape_pods`` (a ZEST_COOP_PODS tuple) adds a third link
        # class: cross-pod connections are WAN and pay ``wan_rtt_s``
        # per window through their own ``wan_bps`` bucket (scarcer
        # than the DCN plane), which is what the fleet bench's
        # 3-level ICI < DCN < WAN asymmetry rides on.
        self._bucket = None
        self._wan_bucket = None
        if rate_bps or wan_bps:
            from zest_tpu.shaping import TokenBucket

            if rate_bps:
                self._bucket = TokenBucket(rate_bps)
            if wan_bps:
                self._wan_bucket = TokenBucket(wan_bps)
        self.window_rtt_s = float(window_rtt_s)
        self.wan_rtt_s = float(wan_rtt_s)
        self.shape_slices = shape_slices
        self.shape_host = shape_host
        self.shape_pods = shape_pods
        # Gossip responder (attach_gossip): anti-entropy exchanges
        # piggyback on the same listener/connection the chunk RPCs
        # use, so fleet metadata spread costs zero extra sockets.
        self.gossip = None
        self.port: int | None = None
        self.stats = DcnServerStats()
        self._stats_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._sock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns = ConnTracker()

    def attach_gossip(self, node) -> None:
        """Answer MSG_GOSSIP on this listener with ``node``'s
        anti-entropy responder. Without an attached node the server
        keeps its pre-gossip behavior (ERROR: "server accepts only
        REQUEST"), which clients read as gossip-unavailable."""
        self.gossip = node

    def start(self) -> int:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind(("0.0.0.0", self.cfg.dcn_port))
            sock.listen(64)
            # Periodic timeout so shutdown() is observed promptly — a
            # close() does not wake a thread blocked in accept(), and the
            # kernel keeps the port bound while the syscall holds the fd
            # (same discipline as BtServer.start).
            sock.settimeout(0.25)
        except OSError:
            sock.close()
            raise
        self._sock = sock
        self.port = sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="dcn-accept", daemon=True
        )
        self._accept_thread.start()
        return self.port

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # The accept loop polls the flag every 0.25s; join it so no
        # further connection can be handed out after this point, then
        # wake live serving threads (ConnTracker invariants).
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        self._conns.wake_all()

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue  # poll the shutdown flag
            except OSError:
                return  # listener closed
            with self._stats_lock:
                self.stats.connections += 1
            # Daemon threads, deliberately not tracked: each exits when
            # its peer disconnects, idles past IDLE_TIMEOUT_S, or the
            # listener shuts down — holding references would only grow a
            # list for the daemon's lifetime.
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        self._conns.add(conn)
        try:
            with conn:
                # A connection accepted in the same beat as shutdown()
                # may miss its SHUT_RDWR (registered after the snapshot);
                # re-checking here closes that window.
                if self._shutdown.is_set():
                    return
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(IDLE_TIMEOUT_S)
                hello = _exchange_hello(conn)
                link = self._conn_link(hello)
                rtt, bucket = self._link_shaping(link)
                # Per-connection window tracking for the RTT shaper:
                # a tag change (or an untagged request) starts a new
                # window.
                last_tag: list[int | None] = [None]
                while not self._shutdown.is_set():
                    msg = _recv_message(conn)
                    if isinstance(msg, DcnGossip) and not msg.reply:
                        node = self.gossip
                        if node is None:
                            conn.sendall(encode_message(DcnError(
                                msg.request_id,
                                "server accepts only REQUEST",
                            )))
                            continue
                        reply = node.handle_exchange(msg.payload)
                        if rtt > 0:
                            time.sleep(rtt)
                            last_tag[0] = None
                        conn.sendall(encode_message(
                            DcnGossip(msg.request_id, reply, reply=True)
                        ))
                        continue
                    if not isinstance(msg, DcnRequest):
                        conn.sendall(encode_message(DcnError(
                            msg.request_id, "server accepts only REQUEST"
                        )))
                        continue
                    if rtt > 0:
                        if msg.tag == 0 or msg.tag != last_tag[0]:
                            time.sleep(rtt)
                        last_tag[0] = msg.tag or None
                    self._serve_request(conn, msg, hello,
                                        bucket=bucket)
        except (ConnectionError, DcnProtocolError, OSError):
            return  # peer went away / spoke garbage: drop the connection
        finally:
            self._conns.discard(conn)

    def _conn_link(self, hello: HelloInfo | None) -> str:
        """Classify this connection's link: ``"ici"`` (same slice,
        unshaped), ``"dcn"`` (cross-slice), or ``"wan"`` (cross-pod,
        when a pod map is configured). Without a slice map every
        connection is the most expensive configured class; an
        anonymous client is conservatively the farthest one."""
        if self._bucket is None and self._wan_bucket is None \
                and self.window_rtt_s <= 0 and self.wan_rtt_s <= 0:
            return "ici"  # shaping entirely off
        worst = "wan" if self.shape_pods is not None else "dcn"
        if self.shape_slices is None or self.shape_host is None:
            return worst
        peer = getattr(hello, "peer_host", None)
        if peer is None or not 0 <= peer < len(self.shape_slices) \
                or not 0 <= self.shape_host < len(self.shape_slices):
            return worst  # anonymous client: conservatively far
        pods = self.shape_pods
        if pods is not None and peer < len(pods) \
                and self.shape_host < len(pods) \
                and pods[peer] != pods[self.shape_host]:
            return "wan"
        if self.shape_slices[peer] != self.shape_slices[self.shape_host]:
            return "dcn"
        return "ici"

    def _link_shaping(self, link: str):
        """``(window_rtt, bucket)`` for a link class. WAN falls back
        to the DCN knobs when no WAN-specific ones were given, so a
        pods map alone still shapes cross-pod links at least as hard
        as cross-slice ones."""
        if link == "wan":
            return (self.wan_rtt_s or self.window_rtt_s,
                    self._wan_bucket or self._bucket)
        if link == "dcn":
            return self.window_rtt_s, self._bucket
        return 0.0, None

    def _serve_request(self, conn: socket.socket, req: DcnRequest,
                       hello: HelloInfo | None = None,
                       bucket=None) -> None:
        # Server-side request span (ISSUE 7): stamped with the v2 tag
        # and the requester's host/trace identity from the hello block,
        # which is what the merged trace flow-links to the client-side
        # ``dcn.request_many`` window span. NULL_SPAN when no tracer.
        attrs = dict(self.span_attrs)
        attrs["tag"] = req.tag
        if hello is not None and hello.peer_host is not None:
            attrs["client_host"] = hello.peer_host
        if hello is not None and hello.peer_trace_id is not None:
            attrs.setdefault("trace_id", hello.peer_trace_id)
        with telemetry.span("dcn.serve", **attrs) as sp:
            self._serve_request_inner(conn, req, sp, bucket=bucket)

    def _serve_request_inner(self, conn: socket.socket, req: DcnRequest,
                             sp, bucket=None) -> None:
        if not req.range_start < req.range_end:
            conn.sendall(encode_message(DcnError(
                req.request_id,
                f"invalid range [{req.range_start},{req.range_end})",
            )))
            return
        found = serve_chunk_range(
            self.cfg, self.cache, req.chunk_hash,
            req.range_start, req.range_end, req.flags,
        )
        if found is None:
            with self._stats_lock:
                self.stats.not_found += 1
            sp.set("outcome", "not_found")
            conn.sendall(encode_message(
                DcnNotFound(req.request_id, req.chunk_hash)
            ))
            return
        offset, blob, resp_flags = found
        if _OFFSET.size + len(blob) > MAX_MESSAGE_SIZE:
            # An over-cap cached entry (e.g. served whole after a footer
            # parse failure) must fail as a clean ERROR, not stream an
            # over-cap message the client will kill the channel over.
            conn.sendall(encode_message(DcnError(
                req.request_id, f"entry of {len(blob)} bytes over cap"
            )))
            return
        # Count before sending: a client that got the last response must
        # observe the stats it implies (the send is the visibility edge).
        with self._stats_lock:
            self.stats.chunks_served += 1
            self.stats.bytes_served += len(blob)
        if bucket is not None:
            bucket.acquire(len(blob))
        _M_CHUNKS_SERVED.inc()
        _M_BYTES_SERVED.inc(len(blob))
        sp.add_bytes(len(blob))
        # Scatter-gather send: the blob can be a whole 64 MiB xorb, and
        # encode_message would memcpy it twice building one bytestring.
        _sendmsg_all(conn, [
            encode_response_prefix(req.request_id, offset, len(blob),
                                   resp_flags), blob,
        ])


# ── Client ──


class DcnChannel:
    """One pipelined stream to a remote host's DcnServer.

    Thread-safe: senders tag monotonically increasing request IDs; a
    single reader thread matches responses back to waiting callers, so
    any number of threads can have requests in flight on one TCP
    connection (queue-depth management per SURVEY.md §2.4 row "request
    pipelining")."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.address = (host, port)
        self.timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Negotiated hello: sub-version, the peer's trace identity,
            # and the clock-offset estimate (hello.clock_offset_s) the
            # merged-trace normalization reads via DcnPool.clock_offsets.
            self.hello = _exchange_hello(self._sock)
        except Exception:
            self._sock.close()  # not a zest endpoint / hello timeout
            raise
        # The connect/hello timeout must not linger: the reader thread
        # blocks between requests indefinitely (idle ≠ dead); per-request
        # deadlines live in _Waiter.wait, not on the socket.
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._next_id = 0
        self._pending: dict[int, _Waiter] = {}
        self._pending_lock = threading.Lock()
        self._closed = False
        self.dead = False  # reader saw EOF/error; pool must reconnect
        self._reader = threading.Thread(
            target=self._read_loop, name="dcn-reader", daemon=True
        )
        self._reader.start()

    def close(self) -> None:
        self._closed = True
        self.dead = True
        try:
            self._sock.close()
        except OSError:
            pass
        self._fail_all(ConnectionError("channel closed"))

    def _fail_all(self, exc: Exception) -> None:
        with self._pending_lock:
            waiters = list(self._pending.values())
            self._pending.clear()
        for w in waiters:
            w.error = exc
            w.event.set()

    def _read_loop(self) -> None:
        try:
            while True:
                msg = _recv_message(self._sock)
                with self._pending_lock:
                    waiter = self._pending.pop(msg.request_id, None)
                if waiter is not None:
                    waiter.result = msg
                    waiter.event.set()
        except (ConnectionError, DcnProtocolError, OSError) as exc:
            self.dead = True
            if not self._closed:
                self._fail_all(exc)

    def send_request(
        self, chunk_hash: bytes, range_start: int, range_end: int,
        tag: int = 0, flags: int = 0,
    ) -> "_Waiter":
        """Fire one request; returns a waiter to collect later — callers
        batch N sends then collect N waits to pipeline. ``tag`` is the
        v2 window tag (0 = untagged; a v1 server reads it as the
        reserved bytes it always ignored); ``flags`` rides the reserved
        flag byte (FLAG_LOSSY_OK — a v1 server ignores it too)."""
        if faults.fire("dcn_reset",
                       key=f"{self.address[0]}:{self.address[1]}"):
            self.dead = True
            raise ConnectionError("injected dcn_reset")
        if self.dead:
            raise ConnectionError("DCN channel is dead")
        with self._send_lock:
            req_id = self._next_id
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            waiter = _Waiter(req_id)
            with self._pending_lock:
                self._pending[req_id] = waiter
            try:
                self._sock.sendall(encode_message(
                    DcnRequest(req_id, chunk_hash, range_start, range_end,
                               tag, flags)
                ))
            except OSError as exc:
                with self._pending_lock:
                    self._pending.pop(req_id, None)
                raise ConnectionError(f"DCN send failed: {exc}") from exc
        return waiter

    def request(
        self, chunk_hash: bytes, range_start: int, range_end: int
    ) -> DcnMessage:
        return self.send_request(
            chunk_hash, range_start, range_end
        ).wait(self.timeout)

    def gossip_exchange(self, payload: dict,
                        timeout: float | None = None) -> dict:
        """One anti-entropy round trip on this channel: send our
        digest delta, return the peer's reply payload. A pre-gossip
        server answers with ERROR ("server accepts only REQUEST"),
        surfaced as :class:`DcnError` via ``GossipUnavailable``."""
        if self.dead:
            raise ConnectionError("DCN channel is dead")
        with self._send_lock:
            req_id = self._next_id
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            waiter = _Waiter(req_id)
            with self._pending_lock:
                self._pending[req_id] = waiter
            try:
                self._sock.sendall(encode_message(
                    DcnGossip(req_id, payload)
                ))
            except OSError as exc:
                with self._pending_lock:
                    self._pending.pop(req_id, None)
                raise ConnectionError(f"DCN send failed: {exc}") from exc
        msg = waiter.wait(self.timeout if timeout is None else timeout)
        if isinstance(msg, DcnGossip):
            return msg.payload
        if isinstance(msg, DcnError):
            raise GossipUnavailable(msg.message)
        raise DcnProtocolError(
            f"unexpected reply to GOSSIP: {type(msg).__name__}"
        )

    def request_many(
        self, wants: list[tuple[bytes, int, int]],
        timeout: float | None = None,
        tag: int = 0,
        flags: int = 0,
    ) -> list[DcnMessage]:
        """Pipelined batch: all requests go out before any response is
        awaited; results come back in ``wants`` order. ``timeout``
        overrides the channel default per call — the cooperative
        exchange bounds each window by its round deadline's remainder
        instead of letting one silent owner hold a 30 s default."""
        waiters = [self.send_request(*w, tag=tag, flags=flags)
                   for w in wants]
        t = self.timeout if timeout is None else timeout
        return [w.wait(t) for w in waiters]


class _Waiter:
    __slots__ = ("request_id", "event", "result", "error")

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.event = threading.Event()
        self.result: DcnMessage | None = None
        self.error: Exception | None = None

    def wait(self, timeout: float) -> DcnMessage:
        if not self.event.wait(timeout):
            raise TimeoutError(
                f"DCN request {self.request_id} timed out after {timeout}s"
            )
        if self.error is not None:
            raise self.error
        assert self.result is not None
        return self.result


class DcnPool:
    """Long-lived channels keyed by (host, port). Pod topology is static,
    so channels persist for the process lifetime — the reference's
    LRU-evicting PeerPool degenerates to a plain dict here (SURVEY.md
    §2.1 row 8: "mostly subsumed by persistent pod topology")."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout
        self._channels: dict[tuple[str, int], DcnChannel] = {}
        self._lock = threading.Lock()
        self._next_tag = 0
        # Wire-tag accounting (ISSUE 14): how many request windows went
        # out, how many individual REQUESTs they carried, and how many
        # windows were UNTAGGED (no window tag on the wire — the
        # per-unit round-trip shape the collective exchange must never
        # produce; the coop smoke asserts untagged_windows == 0 on its
        # collective leg).
        self.counters = {"windows": 0, "requests": 0,
                         "tagged_windows": 0, "untagged_windows": 0}

    def _alloc_tag(self) -> int:
        """Next nonzero u16 window tag (wraps; 0 stays 'untagged')."""
        with self._lock:
            self._next_tag = (self._next_tag % 0xFFFF) + 1
            return self._next_tag

    def window_tag(self) -> int:
        """Public window-tag allocator for callers that batch their own
        windows (the collective exchange tags every phase sub-window so
        the serve side can see window boundaries — shaping charges RTT
        per window — and the wire-tag counters can prove no per-unit
        round trips happened)."""
        return self._alloc_tag()

    def clock_offsets(self) -> dict:
        """Per-peer hello measurements: ``{(host, port): {"offset_s",
        "rtt_s", "host"}}`` for every live v2 channel — the cooperative
        round copies them into the trace metadata for the merge's
        clock normalization."""
        with self._lock:
            channels = dict(self._channels)
        out = {}
        for addr, ch in channels.items():
            hello = getattr(ch, "hello", None)
            if hello is None or hello.clock_offset_s is None:
                continue
            row = {"offset_s": round(hello.clock_offset_s, 6),
                   "rtt_s": round(hello.rtt_s or 0.0, 6)}
            if hello.peer_host is not None:
                row["host"] = hello.peer_host
            out[addr] = row
        return out

    def channel(self, host: str, port: int) -> DcnChannel:
        return self._lease(host, port)[0]

    def _lease(self, host: str, port: int) -> tuple[DcnChannel, bool]:
        """``(channel, reused)``: whether the channel predates this call.
        A reused channel can be silently stale — the server idle-closes
        after IDLE_TIMEOUT_S and the reader may not have observed the
        FIN yet — which is why :meth:`request_many` treats a reused
        channel's failure as retryable and a fresh one's as real."""
        key = (host, port)
        with self._lock:
            ch = self._channels.get(key)
            if ch is not None and ch.dead:
                # Server-side idle close (IDLE_TIMEOUT_S) or a dropped
                # link killed the reader; an expired channel reconnects
                # instead of poisoning every later round.
                del self._channels[key]
                ch.close()
                ch = None
        if ch is not None:
            return ch, True
        ch = DcnChannel(host, port, timeout=self.timeout)
        with self._lock:
            # connect raced: keep the first live one, close ours
            existing = self._channels.get(key)
            if existing is not None and not existing.dead:
                ch.close()
                return existing, True
            self._channels[key] = ch
            return ch, False

    def request_many(
        self, host: str, port: int, wants: list[tuple[bytes, int, int]],
        timeout: float | None = None,
        tag: int | None = None,
        flags: int = 0,
    ) -> list[DcnMessage]:
        """Pipelined batch through a pooled channel, transparently
        reconnecting and retrying ONCE when a previously pooled channel
        turns out to be dead (the server's IDLE_TIMEOUT_S drop lands
        exactly here: the pool believed the channel was live, the first
        send/response proves otherwise). A *fresh* connection's failure
        propagates — that's a real peer problem, not staleness.
        ``timeout`` caps each response wait for this call only.
        ``tag`` stamps an explicit window tag on every REQUEST of this
        batch (callers allocate via :meth:`window_tag`); ``flags``
        stamps the per-message flag byte (FLAG_LOSSY_OK)."""
        # Forwarded only when set: injected channel doubles (tests,
        # wrappers) predate the parameters. Without an explicit ``tag``
        # the window tag is allocated only while a trace is actually
        # recording — it exists to flow-link this window span to the
        # server's serve spans, and skipping it otherwise keeps the
        # wire bytes (and the doubles' call shape) identical to the
        # untraced path.
        kw = {} if timeout is None else {"timeout": timeout}
        if flags:
            kw["flags"] = flags
        if tag is None and telemetry.enabled() \
                and telemetry.trace.active() is not None:
            tag = self._alloc_tag()
        if tag:
            kw["tag"] = tag
        else:
            tag = 0
        with self._lock:
            self.counters["windows"] += 1
            self.counters["requests"] += len(wants)
            self.counters["tagged_windows" if tag
                          else "untagged_windows"] += 1
        attrs = {"peer": f"{host}:{port}", "requests": len(wants)}
        if tag:
            attrs["flow_tag"] = tag
        with telemetry.span("dcn.request_many", **attrs):
            ch, reused = self._lease(host, port)
            try:
                return ch.request_many(wants, **kw)
            except (ConnectionError, TimeoutError, OSError):
                self.drop(host, port)
                if not reused:
                    raise
                ch, _ = self._lease(host, port)
                try:
                    return ch.request_many(wants, **kw)
                except (ConnectionError, TimeoutError, OSError):
                    self.drop(host, port)
                    raise

    def gossip_exchange(self, host: str, port: int, payload: dict,
                        timeout: float | None = None) -> dict:
        """One anti-entropy round trip through a pooled channel, with
        the same stale-channel reconnect-retry-once discipline as
        :meth:`request_many`. ``GossipUnavailable`` propagates without
        a retry — the peer is alive, it just doesn't speak gossip."""
        ch, reused = self._lease(host, port)
        try:
            return ch.gossip_exchange(payload, timeout=timeout)
        except GossipUnavailable:
            raise
        except (ConnectionError, TimeoutError, OSError):
            self.drop(host, port)
            if not reused:
                raise
            ch, _ = self._lease(host, port)
            try:
                return ch.gossip_exchange(payload, timeout=timeout)
            except (ConnectionError, TimeoutError, OSError):
                self.drop(host, port)
                raise

    def drop(self, host: str, port: int) -> None:
        with self._lock:
            ch = self._channels.pop((host, port), None)
        if ch is not None:
            ch.close()

    def close(self) -> None:
        with self._lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            ch.close()
