"""Delta pulls: chunk-level revision diffs over the content-addressed cache.

Fine-tune/RL loops re-pull checkpoints that are ~99% identical to what is
already cached (and often already resident in HBM), yet a plain pull of
revision B over a cached revision A re-plans every byte as if the host
were cold. The CAS layer's CDC chunking makes revision-to-revision deltas
structurally cheap — B's reconstruction references mostly the same xorb
chunk ranges A's did — so the delta machinery here is *planning and
evidence*, never a new byte-moving tier:

- **Manifests** — every pull persists a tiny JSON manifest (file → term
  list) under ``cache_dir/manifests/``. That is the "revision A
  evidence" a later pull of B diffs against; without it the delta plan
  degrades to a full pull (recorded as a ``delta_degraded`` flight
  event, never an error).
- **:class:`DeltaPlan`** — partitions revision B's fetch units into
  *changed* (chunk ranges B references that A never did — a pure
  function of the two revisions' content-addressed metadata, so every
  host of a cooperative pull computes the same set regardless of how
  warm its cache is) and *reused* (already referenced by A; normally a
  local cache hit, counted *stale* when evicted). Only changed + stale
  bytes flow through the waterfall/coop tiers; ``delta_bytes_ratio``
  is the headline.
- **Per-tensor fingerprints** — a tensor's bytes are identified by the
  canonical (xorb hash, chunk range, intra-segment offsets) cover of
  its file span: equal covers ⇒ byte-identical tensors, by content
  addressing. The landing uses the comparison to *short-circuit*
  decode + verify + ``device_put`` for tensors an already-resident
  revision-A tree holds unchanged (the in-place hot-swap,
  models.loader).

Everything here is conservative by construction: any metadata mismatch
(re-sharded files, shifted headers, missing manifests) classifies as
*changed*, which costs work, never correctness — the landing decodes
from the verified cache either way, and ``params_digest`` pins the
swapped tree byte-identical to a cold pull of B.
"""

from __future__ import annotations

import bisect
import json
import os
import time
from dataclasses import dataclass, field
from pathlib import Path

from zest_tpu import telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas import reconstruction as recon

_M_DELTA_BYTES = telemetry.counter(
    "zest_delta_bytes_total",
    "Checkpoint bytes classified by the delta plan: reused = served "
    "from the local cache with zero network, fetched = changed (or "
    "evicted) bytes that crossed the waterfall/coop tiers",
    ("kind",))

MANIFEST_FORMAT = 1


# ── Revision manifests: the persisted rev-A evidence ──


def manifest_dir(cfg) -> Path:
    return cfg.cache_dir / "manifests"


def manifest_path(cfg, repo_id: str, commit_sha: str) -> Path:
    """``manifests/models--{org}--{name}@{sha}.json`` — same repo-dir
    naming the HF cache uses, so the manifest set is greppable next to
    the snapshots it describes."""
    safe = "models--" + repo_id.replace("/", "--")
    return manifest_dir(cfg) / f"{safe}@{commit_sha}.json"


def terms_of(rec: recon.Reconstruction) -> list[list]:
    """A reconstruction's terms in the manifest wire shape:
    ``[hash_hex, chunk_start, chunk_end, unpacked_length]``."""
    return [[t.hash_hex, t.range.start, t.range.end, t.unpacked_length]
            for t in rec.terms]


def save_manifest(cfg, repo_id: str, commit_sha: str, entries,
                  rec_of, parent: str | None = None) -> bool:
    """Persist this revision's file → term-list map (atomic write).

    ``rec_of(entry)`` returns the entry's resolved Reconstruction or
    None. A manifest is only written when EVERY xet file's terms are
    known — a partial manifest would make a future delta plan classify
    the missing files' unchanged chunks as changed (costing re-fetch)
    or, worse, be mistaken for complete evidence. Returns whether a
    manifest was written.

    ``parent`` (ISSUE 19) records lineage: the sha this revision was
    derived from — what ``zest push`` built its dedup index against, or
    what a delta pull actually diffed with. Additive field (format
    unchanged); :func:`find_base_manifest` uses the chain to prefer the
    closest ancestor and to never hand back a descendant as base."""
    files: dict[str, dict] = {}
    for entry in entries:
        if not entry.is_xet:
            continue
        rec = rec_of(entry)
        if rec is None:
            return False
        files[entry.path] = {
            "size": int(entry.size),
            "xet_hash": entry.xet_hash,
            "terms": terms_of(rec),
        }
    doc = {
        "format": MANIFEST_FORMAT,
        "repo": repo_id,
        "revision": commit_sha,
        "saved_at": round(time.time(), 3),
        "files": files,
    }
    if parent and parent != commit_sha:
        doc["parent"] = parent
    from zest_tpu import storage

    storage.atomic_write(manifest_path(cfg, repo_id, commit_sha),
                         json.dumps(doc).encode())
    return True


def load_manifest(cfg, repo_id: str, commit_sha: str) -> dict | None:
    try:
        doc = json.loads(
            manifest_path(cfg, repo_id, commit_sha).read_text())
    except (OSError, ValueError):
        return None
    if (not isinstance(doc, dict)
            or doc.get("format") != MANIFEST_FORMAT
            or not isinstance(doc.get("files"), dict)):
        return None
    return doc


def find_base_manifest(cfg, repo_id: str, commit_sha: str,
                       base_revision: str | None = None) -> dict | None:
    """The revision-A evidence for a pull of ``commit_sha``.

    With an explicit ``base_revision`` (ref name or sha) only that
    revision's manifest qualifies — refs resolve through the HF refs
    file the previous pull wrote (``storage.read_ref``), which still
    points at A because this pull updates it only at exit.

    Without one, selection is ancestry-aware (ISSUE 19 — ``zest push``
    exercises this on every publish, when several revisions' manifests
    coexist): the CLOSEST ANCESTOR of ``commit_sha`` along the recorded
    ``parent`` chain wins outright, and a manifest whose own parent
    chain passes through ``commit_sha`` (a DESCENDANT — i.e. a newer
    revision derived from the one being pulled) is never selected — a
    descendant base would make the plan "reuse" chunks the target
    revision predates. Among the remaining candidates the newest
    manifest wins (the fine-tune-loop common case: the previous
    iteration); manifests without lineage keep the historical
    newest-mtime behaviour bit-for-bit."""
    from zest_tpu import storage

    if base_revision:
        sha = base_revision
        if not manifest_path(cfg, repo_id, sha).exists():
            # Not a sha with a manifest: treat it as a ref name the
            # previous pull recorded (refs/main still points at A —
            # this pull rewrites it only at exit).
            sha = storage.read_ref(cfg, repo_id, base_revision) \
                or base_revision
        if sha == commit_sha:
            return None
        return load_manifest(cfg, repo_id, sha)
    prefix = "models--" + repo_id.replace("/", "--") + "@"
    root = manifest_dir(cfg)
    shas: dict[str, float] = {}
    try:
        candidates = list(root.iterdir())
    except OSError:
        return None
    for p in candidates:
        if not p.name.startswith(prefix) or not p.name.endswith(".json"):
            continue
        sha = p.name[len(prefix):-len(".json")]
        if sha == commit_sha:
            continue
        try:
            shas[sha] = p.stat().st_mtime
        except OSError:
            continue
    if not shas:
        return None

    docs: dict[str, dict | None] = {}

    def _parent(sha: str) -> str | None:
        if sha not in docs:
            docs[sha] = load_manifest(cfg, repo_id, sha)
        doc = docs[sha]
        par = doc.get("parent") if doc else None
        return par if isinstance(par, str) and par else None

    # Closest ancestor wins: walk commit_sha's own recorded lineage
    # (its manifest exists on the publishing node) and return the first
    # hop that has evidence. Visited set + candidate bound guard
    # against a corrupt/cyclic chain.
    hops = 0
    seen = {commit_sha}
    cur = _parent(commit_sha)
    while cur and cur not in seen and hops <= len(shas) + 1:
        if cur in shas:
            return load_manifest(cfg, repo_id, cur)
        seen.add(cur)
        cur = _parent(cur)
        hops += 1

    def _descends_from_target(sha: str) -> bool:
        walked = {sha}
        cur = _parent(sha)
        while cur and cur not in walked and len(walked) <= len(shas) + 1:
            if cur == commit_sha:
                return True
            walked.add(cur)
            cur = _parent(cur)
        return False

    eligible = [s for s in shas if not _descends_from_target(s)]
    if not eligible:
        return None
    best = max(eligible, key=lambda s: shas[s])
    return load_manifest(cfg, repo_id, best)


# ── Canonical segments + per-tensor fingerprints ──


def _canonical_segments(terms) -> list[tuple[int, int, str, int, int]]:
    """Merge a term list into canonical ``(file_lo, file_hi, xorb_hex,
    chunk_start, chunk_end)`` segments: adjacent terms referencing
    contiguous chunk ranges of the same xorb collapse into one. Two
    revisions that cut the same underlying chunk runs into differently
    sized terms (A: one whole-xorb term; B: the same chunks split
    around an interleaved reused run) then compare equal where their
    bytes are equal — the property the fingerprint needs. ``terms`` is
    the manifest wire shape (``terms_of``)."""
    segs: list[tuple[int, int, str, int, int]] = []
    off = 0
    for hh, s, e, n in terms:
        hi = off + int(n)
        if segs:
            p_lo, p_hi, p_hex, p_cs, p_ce = segs[-1]
            if p_hex == hh and p_ce == s and p_hi == off:
                segs[-1] = (p_lo, hi, hh, p_cs, e)
                off = hi
                continue
        segs.append((off, hi, hh, int(s), int(e)))
        off = hi
    return segs


def tensor_fingerprints(terms, header) -> dict[str, str]:
    """name → content fingerprint of the tensor's backing chunk cover.

    The fingerprint hashes the tensor's dtype, shape, and the canonical
    segment windows covering its file span: (xorb hash, chunk range,
    byte window within the segment). Chunk content is content-addressed,
    so equal fingerprints between two revisions mean byte-identical
    tensor data — the per-tensor merkle comparison the hot-swap
    short-circuits on. Computed for revision A from its *manifest*
    terms against revision B's header spans (same-shape revisions share
    header layout byte-for-byte; a revision that moved tensor offsets
    compares unequal everywhere, which is the conservative answer)."""
    segs = _canonical_segments(terms)
    starts = [s[0] for s in segs]
    out: dict[str, str] = {}
    for name, info in header.tensors.items():
        lo, hi = info.file_range(header.data_start)
        parts = [name, info.dtype, repr(tuple(info.shape))]
        j = max(0, bisect.bisect_right(starts, lo) - 1)
        covered = lo
        while j < len(segs) and segs[j][0] < hi:
            s_lo, s_hi, hh, cs, ce = segs[j]
            if s_hi > lo:
                if max(lo, s_lo) != covered:
                    break  # gap: cover incomplete
                parts.append(
                    f"{hh}:{cs}:{ce}:{max(lo, s_lo) - s_lo}"
                    f":{min(hi, s_hi) - s_lo}")
                covered = min(hi, s_hi)
            j += 1
        if covered < hi:
            # Span not fully covered by the terms (foreign/partial
            # manifest): a unique token keeps it from matching anything.
            parts.append(f"uncovered:{covered}:{hi}")
        out[name] = hashing.blake3_hash(
            "|".join(parts).encode()).hex()
    return out


def unchanged_tensor_names(base_terms, rec: recon.Reconstruction,
                           header) -> set[str]:
    """Tensors of ``header`` whose bytes are provably identical between
    the base revision (``base_terms``, manifest shape) and ``rec`` —
    the short-circuit set: skip decode + verify + device_put and reuse
    the resident array."""
    fa = tensor_fingerprints(base_terms, header)
    fb = tensor_fingerprints(terms_of(rec), header)
    return {n for n, fp in fb.items() if fa.get(n) == fp}


# ── The delta plan ──


def _coverage_map(manifest: dict) -> dict[str, list[tuple[int, int]]]:
    """xorb hex → merged, sorted chunk-range intervals the base
    revision referenced anywhere (cross-file: a chunk range reused from
    ANY base file is local)."""
    raw: dict[str, list[tuple[int, int]]] = {}
    for f in manifest.get("files", {}).values():
        for hh, s, e, _n in f.get("terms", []):
            raw.setdefault(hh, []).append((int(s), int(e)))
    out: dict[str, list[tuple[int, int]]] = {}
    for hh, ivs in raw.items():
        ivs.sort()
        merged: list[tuple[int, int]] = []
        for s, e in ivs:
            if merged and s <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], e))
            else:
                merged.append((s, e))
        out[hh] = merged
    return out


def _covered(cov: dict[str, list[tuple[int, int]]], hh: str,
             s: int, e: int) -> bool:
    ivs = cov.get(hh)
    if not ivs:
        return False
    i = bisect.bisect_right(ivs, (s, float("inf"))) - 1
    return i >= 0 and ivs[i][0] <= s and e <= ivs[i][1]


@dataclass
class DeltaPlan:
    """Chunk-level revision diff of a pull against its base manifest.

    ``changed_*`` is a pure function of the two revisions'
    content-addressed metadata — identical on every host regardless of
    cache warmth, which is what lets the cooperative ownership plan
    hash only the changed unit set and still fingerprint-agree across
    differently-warm hosts. ``stale_*`` is the per-host correction:
    content-unchanged units this host's cache no longer holds (evicted
    since the base pull) — they re-fetch through the ordinary
    waterfall, never through the coop plan."""

    base_revision: str
    total_bytes: int = 0          # unpacked checkpoint bytes (B)
    changed_bytes: int = 0        # content-changed unpacked bytes
    total_chunks: int = 0
    changed_chunks: int = 0
    stale_units: int = 0
    stale_bytes: int = 0          # wire-size estimate of evicted units
    per_file: dict[str, dict] = field(default_factory=dict)
    changed_keys: frozenset = frozenset()
    changed_units: list = field(default_factory=list)
    # Unit keys both content-unchanged AND locally present (stat-level
    # locate at plan time): the warm fetch can skip even hit-TESTING
    # them — `_already_cached` reads and frame-parses the whole ~32 MB
    # entry per unit, which on a 2 GB delta pull re-reads the entire
    # cache just to learn what the plan already knew. A key that lies
    # (entry evicted/corrupt after the stat) costs nothing but a
    # per-term waterfall fetch at decode time — the landing's existing
    # terminal fallback ("resolved never means guaranteed cached").
    reused_local_keys: frozenset = frozenset()

    @property
    def reused_bytes(self) -> int:
        return self.total_bytes - self.changed_bytes

    @property
    def delta_bytes_ratio(self) -> float:
        """Content-changed fraction of the checkpoint — the headline:
        what fraction of bytes a warm delta pull must move at all."""
        return (self.changed_bytes / self.total_bytes
                if self.total_bytes else 0.0)

    def summary(self) -> dict:
        out = {
            "base_revision": self.base_revision,
            "total_bytes": self.total_bytes,
            "changed_bytes": self.changed_bytes,
            "reused_bytes": self.reused_bytes,
            "delta_bytes_ratio": round(self.delta_bytes_ratio, 4),
            "chunks": {"total": self.total_chunks,
                       "changed": self.changed_chunks},
            "changed_units": len(self.changed_units),
            "files": self.per_file,
        }
        if self.stale_units:
            out["stale_units"] = self.stale_units
            out["stale_bytes"] = self.stale_bytes
        return out


def build_plan(base_manifest: dict, files_terms, units=None,
               cache=None) -> DeltaPlan:
    """Diff revision B against the base manifest.

    ``files_terms`` is ``[(path, terms)]`` in the manifest wire shape
    (``terms_of``); ``units`` the deduped ``[(hash_hex, FetchInfo)]``
    fetch units of B (``parallel.plan.collect_units``) when the caller
    has real reconstructions — they feed ``changed_units`` (the set the
    cooperative plan shards) and, with ``cache``, the stale-unit
    accounting. Emits the ``zest_delta_bytes_total`` counters."""
    with telemetry.span("delta.plan",
                        base=base_manifest.get("revision", "")):
        plan = _build_plan(base_manifest, files_terms, units, cache)
    _M_DELTA_BYTES.inc(plan.reused_bytes, kind="reused")
    _M_DELTA_BYTES.inc(plan.changed_bytes + plan.stale_bytes,
                       kind="fetched")
    return plan


def _build_plan(base_manifest, files_terms, units, cache) -> DeltaPlan:
    cov = _coverage_map(base_manifest)
    plan = DeltaPlan(base_revision=base_manifest.get("revision", ""))
    for path, terms in files_terms:
        f_bytes = f_changed = f_chunks = f_chunks_changed = 0
        for hh, s, e, n in terms:
            n, nchunks = int(n), int(e) - int(s)
            f_bytes += n
            f_chunks += nchunks
            if not _covered(cov, hh, int(s), int(e)):
                f_changed += n
                f_chunks_changed += nchunks
        plan.total_bytes += f_bytes
        plan.changed_bytes += f_changed
        plan.total_chunks += f_chunks
        plan.changed_chunks += f_chunks_changed
        plan.per_file[path] = {
            "bytes": f_bytes,
            "bytes_changed": f_changed,
            "chunks": f_chunks,
            "chunks_changed": f_chunks_changed,
            "ratio": round(f_changed / f_bytes, 4) if f_bytes else 0.0,
        }
    if units is not None:
        changed = [(hh, fi) for hh, fi in units
                   if not _covered(cov, hh, fi.range.start, fi.range.end)]
        # Deterministic order (the coop plan sorts again internally;
        # this is the waterfall/diff display order).
        changed.sort(key=lambda u: (u[0], u[1].range.start))
        plan.changed_units = changed
        plan.changed_keys = frozenset(
            (hh, fi.range.start) for hh, fi in changed)
        if cache is not None:
            present = set()
            for hh, fi in units:
                key = (hh, fi.range.start)
                if key in plan.changed_keys:
                    continue
                if cache.locate_with_range(hh, fi.range.start) is None:
                    plan.stale_units += 1
                    plan.stale_bytes += (fi.url_range_end
                                         - fi.url_range_start)
                else:
                    present.add(key)
            plan.reused_local_keys = frozenset(present)
    return plan


# Delta landing order note: there is deliberately NO delta-specific
# ordering helper. The changed-unit subset inherits the one shared
# ``models.direct.unit_priority_sort_key`` everywhere units are
# iterated — the solo warm sorts its (skip-filtered) shard units with
# it, and coop_round's ``_layer_order`` sorts both phases with it —
# so a delta that touches layer 0 still lands it first and
# ``time_to_first_layer_s`` stays meaningful, with one definition of
# the order instead of two.


# ── `zest diff`: the dry-run CLI surface ──


def _resolve_spec_sha(cfg, hub, repo_id: str, rev: str) -> str:
    """Revision spec → commit sha, offline-first: a local manifest or
    refs entry answers without the hub."""
    from zest_tpu import storage

    if load_manifest(cfg, repo_id, rev) is not None:
        return rev
    ref = storage.read_ref(cfg, repo_id, rev)
    if ref:
        return ref
    return hub.resolve_revision(repo_id, rev)


def _revision_terms(cfg, hub, repo_id: str, sha: str):
    """``(files_terms, units)`` for one revision: the local manifest
    when present (zero network), else KB-scale metadata fetches
    (reconstructions only — never payloads)."""
    man = load_manifest(cfg, repo_id, sha)
    if man is not None:
        return ([(p, f["terms"]) for p, f in sorted(man["files"].items())],
                None, man)
    from zest_tpu.parallel.plan import collect_units
    from zest_tpu.transfer.bridge import XetBridge

    bridge = XetBridge(cfg, swarm=None)
    try:
        bridge.authenticate(repo_id, sha, hub=hub)
        files_terms, recs = [], []
        for entry in hub.list_files(repo_id, sha):
            if not entry.is_xet:
                continue
            rec = bridge.get_reconstruction(entry.xet_hash)
            files_terms.append((entry.path, terms_of(rec)))
            recs.append(rec)
    finally:
        bridge.close()
    units = [(hh, fi) for (hh, _s), fi in collect_units(recs)]
    man = {"format": MANIFEST_FORMAT, "repo": repo_id, "revision": sha,
           "files": {p: {"terms": t} for p, t in files_terms}}
    return files_terms, units, man


def diff_revisions(cfg, repo_a: str, rev_a: str, repo_b: str,
                   rev_b: str) -> dict:
    """Dry-run the DeltaPlan for ``repo_b@rev_b`` over ``repo_a@rev_a``
    against the local cache: changed/unchanged chunk counts, byte
    totals, per-file ratios — metadata only, no payload fetch."""
    from zest_tpu.cas.hub import HubClient

    hub = HubClient(cfg)
    sha_a = _resolve_spec_sha(cfg, hub, repo_a, rev_a)
    sha_b = _resolve_spec_sha(cfg, hub, repo_b, rev_b)
    _ft_a, _units_a, man_a = _revision_terms(cfg, hub, repo_a, sha_a)
    ft_b, units_b, _man_b = _revision_terms(cfg, hub, repo_b, sha_b)
    from zest_tpu.storage import XorbCache

    plan = build_plan(man_a, ft_b, units=units_b, cache=XorbCache(cfg))
    out = plan.summary()
    out.update({"base": f"{repo_a}@{sha_a}", "target": f"{repo_b}@{sha_b}"})
    return out


def format_diff(out: dict) -> str:
    """Human table for ``zest diff`` (kept pure for tests)."""
    lines = [f"delta {out['base']} -> {out['target']}"]
    width = max([len(p) for p in out["files"]] + [4])
    for path, f in sorted(out["files"].items()):
        lines.append(
            f"  {path:<{width}}  chunks {f['chunks_changed']:>6}/"
            f"{f['chunks']:<6}  bytes {f['bytes_changed']:>12}/"
            f"{f['bytes']:<12}  {f['ratio']:>7.2%}")
    chunks = out["chunks"]
    total_line = (
        f"total: {out['changed_bytes']} of {out['total_bytes']} bytes "
        f"changed ({out['delta_bytes_ratio']:.2%}); "
        f"{chunks['changed']}/{chunks['total']} chunks")
    if out["changed_units"] or not out["changed_bytes"]:
        # Unit counts exist only when real fetch_info was resolved
        # (manifest-only diffs classify terms, not units).
        total_line += (f"; {out['changed_units']} fetch unit(s) "
                       "would hit the network")
    lines.append(total_line)
    if out.get("stale_units"):
        lines.append(
            f"stale: {out['stale_units']} unchanged unit(s) "
            f"(~{out['stale_bytes']} wire bytes) evicted locally — "
            "a delta pull would re-fetch them too")
    return "\n".join(lines)
