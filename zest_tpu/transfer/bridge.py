"""XetBridge: the cache → P2P → CDN waterfall — the heart of the pipeline.

Faithful to the reference's contract (src/xet_bridge.zig:149-218), which is
the stable seam everything else builds on: per-term fetch consults the local
xorb cache (range-aware), then the swarm, then CDN byte-range — and every
CDN fetch is cached (full or partial) so this host can seed it and receivers
never need CDN themselves.

Coordinate frames (the reference's trickiest invariant,
xet_bridge.zig:162-214): the returned blob's chunk 0 is absolute chunk
``chunk_offset``; callers extract ``[term.start - chunk_offset,
term.end - chunk_offset)``. All three waterfall tiers produce the same
frame-stream blob shape, so extraction code is tier-agnostic.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass, field

from zest_tpu import telemetry
from zest_tpu.cas import reconstruction as recon
from zest_tpu.cas.client import CasClient
from zest_tpu.cas.hub import HubClient
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.config import Config
from zest_tpu.p2p.health import PROVENANCE
from zest_tpu.storage import CacheFullError, XorbCache
from zest_tpu.transfer.tenancy import PullCancelled

# Process-wide mirrors of the per-session FetchStats: the session object
# stays the per-pull report; these outlive it so the daemon's
# /v1/metrics aggregates across every pull this process served.
_M_XORBS = telemetry.counter(
    "zest_fetch_xorbs_total", "Xorb fetches by source tier", ("source",))
_M_BYTES = telemetry.counter(
    "zest_fetch_bytes_total", "Fetched payload bytes by source tier",
    ("source",))
_M_EVENTS = telemetry.counter(
    "zest_fetch_events_total",
    "Resilience events on the fetch path (retries, hedges, heals)",
    ("event",))

# Hedging: with a pull deadline armed, the peer tier gets at most this
# fraction of the remaining budget (capped) as a head start before a
# CDN fetch races it — the bound that turns "one slow peer stalls a
# term for 60 s" into "one slow peer costs a bounded head start".
_HEDGE_PEER_FRACTION = 0.3
_HEDGE_PEER_WAIT_CAP_S = 10.0
_HEDGE_PEER_WAIT_FLOOR_S = 0.05
# Head start for EVIDENCE-armed hedges (ISSUE 17): no deadline to take
# a fraction of, so the peer tier gets a fixed window before the CDN
# racer starts. Generous next to the deadline path's floor — the
# anomaly evidence says the peer is slow, not that a budget is burning.
_HEDGE_EVIDENCE_WAIT_S = 1.0

# Serializes partial cache writes PER XORB (64-way striped by hash):
# entries keyed ``{hash}.{start}`` can collide across different-width
# units, and the never-narrower check in ``_cache_fetched`` must be
# atomic with its write across bridges (one bridge per pull session) —
# but only for the SAME xorb; one global lock would serialize every
# concurrent session's partial cache writes behind each other's disk
# I/O.
_PARTIAL_WRITE_LOCKS = [threading.Lock() for _ in range(64)]


def _partial_write_lock(hash_hex: str) -> threading.Lock:
    try:
        idx = int(hash_hex[:2], 16) % len(_PARTIAL_WRITE_LOCKS)
    except ValueError:
        idx = 0
    return _PARTIAL_WRITE_LOCKS[idx]


class BridgeError(RuntimeError):
    pass


class NotAuthenticated(BridgeError):
    pass


class NoMatchingFetchInfo(BridgeError):
    pass


@dataclass
class FetchStats:
    """Per-session source accounting (reference: xet_bridge.zig:35-42).

    The P2P byte ratio derived from these is the headline BASELINE metric.
    """

    xorbs_from_cache: int = 0
    xorbs_from_peer: int = 0
    xorbs_from_cdn: int = 0
    bytes_from_cache: int = 0
    bytes_from_peer: int = 0
    bytes_from_cdn: int = 0
    # Resilience counters: CDN retry/backoff rounds, xet-token
    # refreshes, deadline hedges (won = the CDN racer delivered, lost =
    # it failed and the peer tier finished after all), and corruption
    # attributions (a peer-served blob failed structural or BLAKE3
    # verification and was refetched).
    cdn_retries: int = 0
    token_refreshes: int = 0
    hedges: int = 0
    hedges_won: int = 0
    hedges_lost: int = 0
    corrupt_from_peer: int = 0
    corrupt_healed: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record(self, source: str, nbytes: int) -> None:
        with self._lock:
            setattr(self, f"xorbs_from_{source}",
                    getattr(self, f"xorbs_from_{source}") + 1)
            setattr(self, f"bytes_from_{source}",
                    getattr(self, f"bytes_from_{source}") + nbytes)
        _M_XORBS.inc(source=source)
        _M_BYTES.inc(nbytes, source=source)

    def bump(self, name: str, amount: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + amount)
        _M_EVENTS.inc(amount, event=name)

    @property
    def p2p_ratio(self) -> float:
        total = self.bytes_from_peer + self.bytes_from_cdn
        return self.bytes_from_peer / total if total else 0.0

    def summary(self) -> dict:
        return {
            "xorbs": {
                "cache": self.xorbs_from_cache,
                "peer": self.xorbs_from_peer,
                "cdn": self.xorbs_from_cdn,
            },
            "bytes": {
                "cache": self.bytes_from_cache,
                "peer": self.bytes_from_peer,
                "cdn": self.bytes_from_cdn,
            },
            "p2p_ratio": round(self.p2p_ratio, 4),
            "resilience": {
                "cdn_retries": self.cdn_retries,
                "token_refreshes": self.token_refreshes,
                "hedges": self.hedges,
                "hedges_won": self.hedges_won,
                "hedges_lost": self.hedges_lost,
                "corrupt_from_peer": self.corrupt_from_peer,
                "corrupt_healed": self.corrupt_healed,
            },
        }


@dataclass(frozen=True)
class XorbFetchResult:
    """Blob + the term's chunk range rebased into it.

    ``source``/``peer_addr`` let extraction-time verification failures
    route back to their origin: a corrupt blob from a peer strikes that
    peer's health, and anything not already CDN-sourced self-heals with
    a forced CDN refetch (overwriting the poisoned cache key)."""

    data: bytes
    local_start: int
    local_end: int
    source: str = "cache"                      # cache | peer | cdn
    peer_addr: tuple[str, int] | None = None


def _blob_covers(data: bytes, local_start: int, local_end: int) -> bool:
    """Cheap structural check: the blob parses as a frame stream and holds
    chunks [local_start, local_end). Content verification (BLAKE3) happens
    at extraction; this gate keeps short/garbage blobs from being returned
    or cached, where they would defeat the waterfall's fallback."""
    if local_start < 0 or local_end <= local_start:
        return False
    try:
        return len(XorbReader(data)) >= local_end
    except Exception:
        return False


def provably_whole(entries, chunk_offset: int) -> bool:
    """Whole-xorb evidence for the full-vs-partial cache-key decision.

    A blob fetched at ``chunk_offset`` is provably the whole xorb only
    when every known reference to the hash (``entries``, ideally drawn
    from ALL files' reconstructions) is the same single range starting
    at chunk 0 — then the range demonstrably covers everything any
    consumer reads. Any second distinct range means some reader sees
    chunks this blob may not carry."""
    ranges = {(e.range.start, e.range.end) for e in entries}
    return (chunk_offset == 0 and len(ranges) == 1
            and next(iter(ranges))[0] == 0)


class XetBridge:
    def __init__(
        self,
        cfg: Config,
        swarm=None,  # zest_tpu.transfer.swarm.SwarmDownloader | None
        cache: XorbCache | None = None,
    ):
        self.cfg = cfg
        self.cache = cache or XorbCache(cfg)
        self.swarm = swarm
        self.cas: CasClient | None = None
        self.stats = FetchStats()
        # Per-pull wall-clock budget (resilience.Deadline | None), set by
        # transfer.pull before any fetch; flows into the CAS client at
        # authenticate() and into the swarm per call.
        self.deadline = None
        # Whole-xorb evidence integrity (ADVICE r5): ``provably_whole``
        # judges "is this blob the complete xorb?" against every KNOWN
        # reference — which is only sound while every reference is
        # actually known. When a file's reconstruction fails to resolve
        # (pull.py's best-effort aux-evidence loop), the pull marks the
        # bridge and every cache write downgrades to a partial key: an
        # evidence gap can then never cache a truncated blob under the
        # full key that seeding advertises as the whole xorb.
        self.evidence_incomplete = False
        self._recons: dict[str, recon.Reconstruction] = {}
        # Guards the reconstruction memo: the pipelined pull resolves
        # and fetches from several file workers at once, and an unlocked
        # dict would let _known_entries iterate mid-insert.
        self._recons_lock = threading.Lock()
        # Lazy: only a hedging pull (deadline- or evidence-armed) ever
        # builds the pool.
        self._hedge_pool: ThreadPoolExecutor | None = None
        self._hedge_lock = threading.Lock()
        # Evidence-armed hedging (ISSUE 17): the remediation engine
        # arms this mid-flight on stall/collapse anomalies — the same
        # hedge race the deadline path runs, without requiring
        # ZEST_PULL_DEADLINE_S. Reads are racy-by-design (a fetch
        # already past the check hedges on its next term).
        self._hedge_armed = False
        self._hedge_reason: str | None = None
        # A DCN listener the cooperative round started for this pull
        # (transfer.coop): it must outlive the round — peer hosts still
        # mid-exchange read from it — so it lives until close().
        self._coop_server = None
        # Multi-tenant shared pools (ISSUE 13), wired by pull_model when
        # tenancy is on; all None ⇒ the pre-tenancy bridge bit-for-bit.
        # ``flights``: the process-wide Singleflight table deduping
        # in-flight network fetches across sessions. ``cancel``: this
        # pull's CancelToken (waiters detach, a cancelled leader hands
        # off). ``on_reconstruction``: called once per freshly-resolved
        # reconstruction (the session pins its xorb hashes against
        # eviction).
        self.flights = None
        self.cancel = None
        self.on_reconstruction = None

    def arm_hedge(self, reason: str = "policy") -> dict:
        """Arm mid-flight hedging on evidence instead of a deadline
        (ISSUE 17): every subsequent peer-tier fetch gives the peer a
        fixed ``_HEDGE_EVIDENCE_WAIT_S`` head start, then races the
        CDN — through the SAME ``FetchStats`` hedge counters as the
        deadline path (the satellite accounting fix). Idempotent and
        reversible by construction: the primary fetch is never
        cancelled, only raced."""
        already = self._hedge_armed
        self._hedge_armed = True
        self._hedge_reason = reason
        return {"armed": True, "already": already, "reason": reason}

    def adopt_coop_server(self, server) -> None:
        """Own a coop-round DCN listener until :meth:`close` (see
        transfer.coop.coop_round: the server serves peer hosts that are
        still exchanging after this host's round returned)."""
        self._coop_server = server

    def close(self) -> None:
        """Release the hedge pool's threads (per-pull bridges in a
        long-lived daemon must not accumulate idle workers) and any
        coop-round DCN listener."""
        with self._hedge_lock:
            pool, self._hedge_pool = self._hedge_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        server, self._coop_server = self._coop_server, None
        if server is not None:
            try:
                server.shutdown()
            except Exception:  # noqa: BLE001 - closing is best-effort
                pass

    # ── Auth (reference: xet_bridge.zig:76-130) ──

    def authenticate(self, repo_id: str, revision: str = "main",
                     hub: HubClient | None = None) -> None:
        hub = hub or HubClient(self.cfg)
        cas_url, access_token = hub.xet_read_token(repo_id, revision)
        self.cas = CasClient(
            cas_url, access_token,
            # Tokens expire during long pulls: a 401/403 mid-pull re-runs
            # the exchange once and retries instead of failing the file.
            token_refresher=lambda: hub.xet_read_token(repo_id, revision),
            deadline=self.deadline,
            on_event=self.stats.bump,
        )

    def get_reconstruction(self, file_hash_hex: str) -> recon.Reconstruction:
        """Memoized per bridge: the pod pre-pass plans from the same
        reconstructions the per-file loop consumes moments later, and a
        pull session's reconstructions are immutable (content-addressed),
        so each file costs one CAS round-trip total."""
        if self.cas is None:
            raise NotAuthenticated("call authenticate() first")
        with self._recons_lock:
            cached = self._recons.get(file_hash_hex)
        if cached is None:
            # CAS round-trip outside the lock (slow I/O must not
            # serialize the memo); a racing double-fetch is benign —
            # reconstructions are content-addressed, last write wins.
            cached = self.cas.get_reconstruction(file_hash_hex)
            with self._recons_lock:
                cached = self._recons.setdefault(file_hash_hex, cached)
            hook = self.on_reconstruction
            if hook is not None:
                try:
                    hook(cached)  # tenancy: pin this plan's xorbs
                except Exception:  # noqa: BLE001 - pinning is advisory
                    pass
        return cached

    def resolved_xorb_hashes(self) -> set[str]:
        """Every xorb hash referenced by a reconstruction this bridge
        resolved — the pin set for a landed HBM tree (ISSUE 13)."""
        with self._recons_lock:
            return {h for rec in self._recons.values()
                    for h in rec.fetch_info}

    def known_reconstruction(
        self, file_hash_hex: str
    ) -> recon.Reconstruction | None:
        """The memoized reconstruction, or None — never a CAS round
        trip. The delta manifest writer runs at pull exit, where every
        file the pull touched is already memoized; a file that is NOT
        (fully-skipped resume pull) means the manifest would be
        incomplete and the writer declines instead of fetching."""
        with self._recons_lock:
            return self._recons.get(file_hash_hex)

    # ── The waterfall (reference: xet_bridge.zig:149-218) ──

    def fetch_xorb_for_term(
        self, term: recon.Term, rec: recon.Reconstruction
    ) -> XorbFetchResult:
        if self.cancel is not None:
            # Per-term cancellation point (ISSUE 13): a cancelled
            # session stops fetching at the next term instead of
            # finishing whole files it no longer wants.
            self.cancel.check()
        with telemetry.span("fetch.term", xorb=term.hash_hex) as sp:
            result = self._fetch_xorb_for_term(term, rec)
            sp.set("source", result.source)
            sp.add_bytes(len(result.data))
            return result

    def _fetch_xorb_for_term(
        self, term: recon.Term, rec: recon.Reconstruction
    ) -> XorbFetchResult:
        hash_hex = term.hash_hex
        fi = rec.find_fetch_info(term)
        if fi is None:
            raise NoMatchingFetchInfo(
                f"no fetch info covers chunks [{term.range.start},"
                f"{term.range.end}) of {hash_hex}"
            )

        # 1. Local cache — full xorb or the partial entry for fi's range.
        hit = self._cached_term_result(term, fi, hash_hex)
        if hit is not None:
            self.stats.record("cache", len(hit.data))
            return hit
        # Corrupt/short entry fell through above — a CDN refetch
        # overwrites the bad cache key, so the tier self-heals.

        # Network tiers, deduped across sessions (ISSUE 13): one flight
        # per (xorb, range) process-wide — the loser reads the winner's
        # cache entry instead of refetching.
        return self._deduped(
            (hash_hex, fi.range.start, fi.range.end),
            lambda: self._network_fetch_for_term(term, rec, fi, hash_hex),
            lambda: self._probe_term(term, fi, hash_hex),
        )

    def _cached_term_result(self, term: recon.Term, fi: recon.FetchInfo,
                            hash_hex: str) -> XorbFetchResult | None:
        """Tier 1 without stats: the covering cache entry as a term
        result, or None (miss OR structurally-corrupt entry — the
        caller's network path self-heals the latter). The coverage
        predicate runs INSIDE the lookup so a non-covering full entry
        falls through to the exact partial instead of shadowing it."""
        def covers(res) -> bool:
            return _blob_covers(res.data,
                                term.range.start - res.chunk_offset,
                                term.range.end - res.chunk_offset)

        cached = self.cache.get_with_range(hash_hex, fi.range.start,
                                           covers=covers)
        if cached is None:
            return None
        return XorbFetchResult(cached.data,
                               term.range.start - cached.chunk_offset,
                               term.range.end - cached.chunk_offset,
                               source="cache")

    def _probe_term(self, term: recon.Term, fi: recon.FetchInfo,
                    hash_hex: str) -> XorbFetchResult | None:
        hit = self._cached_term_result(term, fi, hash_hex)
        if hit is not None:
            self.stats.record("cache", len(hit.data))
        return hit

    def _deduped(self, key, fetch_fn, probe_fn):
        """Run ``fetch_fn`` under the process singleflight table (when
        wired): the first session to want ``key`` leads; every
        concurrent session waits, then serves itself from the winner's
        cache entry via ``probe_fn`` (a probe miss — the entry was
        evicted in the gap — degrades to a solo refetch, never an
        error). A failed flight re-raises the leader's typed error in
        every waiter; a cancelled leader abdicates so a live waiter
        takes over the fetch instead of the flight failing."""
        flights = self.flights
        if flights is None:
            return fetch_fn()
        role, flight = flights.join(key)
        first_lead = role == "lead"
        while True:
            if role == "lead":
                if first_lead:
                    # Close the miss-then-join race: this session's
                    # cache check may predate another flight's winner
                    # writing the entry AND resolving (both strictly
                    # before table removal) — re-probing here turns
                    # that window into a hit instead of a duplicate
                    # fetch. (A promoted waiter skips it: its probe
                    # semantics are the abdication handoff's.)
                    hit = probe_fn()
                    if hit is not None:
                        flights.resolve(flight)
                        flights.note_hit()
                        return hit
                try:
                    if self.cancel is not None:
                        self.cancel.check()
                    result = fetch_fn()
                except BaseException as exc:
                    if isinstance(exc, PullCancelled):
                        flights.abdicate(flight)
                    else:
                        flights.fail(flight, exc)
                    raise
                flights.resolve(flight)
                return result
            outcome = flights.wait(flight, cancel=self.cancel)
            if outcome == "lead":
                role = "lead"
                continue
            if outcome == "cancelled":
                raise PullCancelled(
                    "cancelled while waiting on a shared fetch")
            if outcome == "failed":
                raise flight.error
            hit = probe_fn()  # "done": the winner's bytes are cached
            if hit is not None:
                flights.note_hit()
                return hit
            return fetch_fn()  # evicted before we read: refetch solo

    def _network_fetch_for_term(
        self, term: recon.Term, rec: recon.Reconstruction,
        fi: recon.FetchInfo, hash_hex: str
    ) -> XorbFetchResult:
        # 2. Swarm (peers) — request fi's full chunk range so the cached
        #    result can serve future terms that share this fetch_info.
        #    With a deadline armed this tier is hedged: the peer fetch
        #    gets a bounded head start, then races a CDN fetch.
        if self.swarm is not None:
            peer_result = self._peer_tier(term, rec, fi, hash_hex)
            if isinstance(peer_result, XorbFetchResult):
                return peer_result  # the CDN hedge won; already cached
            if peer_result is not None:
                local_start = term.range.start - peer_result.chunk_offset
                local_end = term.range.end - peer_result.chunk_offset
                if _blob_covers(peer_result.data, local_start, local_end) \
                        and self._peer_blob_verifies(term, rec, hash_hex,
                                                     peer_result):
                    self.stats.record("peer", len(peer_result.data))
                    # Cache for seeding (reference: swarm.zig:414-420).
                    # Unlike the reference, "full" requires fetch-info
                    # evidence that the blob really is the whole xorb, not
                    # just offset 0 — a sliced prefix cached as full would
                    # poison later reads.
                    self._cache_fetched(
                        rec, hash_hex, peer_result.chunk_offset,
                        peer_result.data,
                    )
                    # Provenance for the seeding tier (ISSUE 12): a blob
                    # admitted WITHOUT a whole-xorb merkle proof keeps
                    # its source on record, so the server can refuse to
                    # re-serve it if that peer is later quarantined.
                    # Clearing uses the EVIDENCE-GATED predicate (same
                    # as the cache write above): under
                    # evidence_incomplete even a root-verified blob is
                    # cached under a partial key and does NOT displace
                    # other peers' unproven ranges — their suspicion
                    # must survive.
                    if self.whole_xorb_provable(
                            self._known_entries(rec, hash_hex),
                            peer_result.chunk_offset):
                        PROVENANCE.clear(hash_hex)
                    else:
                        PROVENANCE.record(hash_hex, peer_result.addr)
                    return XorbFetchResult(
                        peer_result.data, local_start, local_end,
                        source="peer", peer_addr=peer_result.addr,
                    )
                # Malformed/short/hash-mismatched peer blob: never cache
                # it; attribute the strike and fall to CDN.
                if peer_result.addr is not None:
                    self.stats.bump("corrupt_from_peer")
                    self.swarm.report_corrupt(peer_result.addr)

        # 3. CDN byte-range; cache everything for seeding.
        return self._cdn_fetch_for_term(term, rec, fi, hash_hex)

    def _unit_blob_verifies(self, xorb_hash: bytes, hash_hex: str,
                            peer_result) -> bool:
        """The unit-fetch twin of :meth:`_peer_blob_verifies`: the warm
        fetch and pod rounds pull whole units through
        :meth:`fetch_unit`, whose peer tier used to check only blob
        *structure* (`_blob_covers`) — a flipped byte inside a
        stored-scheme chunk parses fine, and the `--device=tpu` landing
        would cache and commit it silently (the hole the ISSUE-5
        copy-lane chaos test caught: the file lane, the decode lane,
        and HBM all inherit whatever this tier admits). Same trust
        rule as the term path: a blob that is — by the evidence across
        every resolved reconstruction — the whole xorb must hash back
        to the merkle root before it is accepted; partial blobs stay
        under the documented extraction-time model."""
        entries: list[recon.FetchInfo] = []
        with self._recons_lock:
            recons = list(self._recons.values())
        for rec in recons:
            entries.extend(rec.fetch_info.get(hash_hex, []))
        if not self.whole_xorb_provable(entries,
                                        peer_result.chunk_offset):
            return True
        try:
            return XorbReader(peer_result.data).xorb_hash() == xorb_hash
        except Exception:
            return False

    def _peer_blob_verifies(self, term: recon.Term,
                            rec: recon.Reconstruction, hash_hex: str,
                            peer_result) -> bool:
        """Content-verify a peer-served blob at the P2P trust boundary,
        when provable: a blob that is (by fetch-info evidence) the whole
        xorb must hash back to the xorb's merkle root. This catches
        corrupt bytes BEFORE they are cached or extracted — crucial for
        wire blobs, which are footerless frame streams carrying no
        per-chunk hashes for extraction to check. (A blob that keeps a
        forged footer consistent with the root is still caught at
        extraction, where payloads verify against the footer hashes.)
        Partial blobs can't be proven against the root here and stay
        under the extraction-time checks."""
        if not provably_whole(self._known_entries(rec, hash_hex),
                              peer_result.chunk_offset):
            return True
        try:
            return XorbReader(peer_result.data).xorb_hash() == term.xorb_hash
        except Exception:
            return False

    def _cdn_fetch_for_term(self, term: recon.Term, rec: recon.Reconstruction,
                            fi: recon.FetchInfo,
                            hash_hex: str) -> XorbFetchResult:
        """Tier 3, callable directly: the hedge racer and the corruption
        self-heal both force it regardless of cache/peer state (the
        cache write overwrites any poisoned key)."""
        if self.cas is None:
            raise NotAuthenticated("no CAS client and no peers had the xorb")
        with telemetry.span("cdn.fetch", xorb=hash_hex) as sp:
            data = self.cas.fetch_xorb_from_url(
                self._absolute_url(fi.url),
                (fi.url_range_start, fi.url_range_end)
            )
            sp.add_bytes(len(data))
        self.stats.record("cdn", len(data))
        self._cache_fetched(rec, hash_hex, fi.range.start, data)
        # Clear suspicion only when this CDN write provably replaced the
        # WHOLE xorb (the full cache key): a partial-range refetch
        # leaves other peer-sourced ranges of the same xorb in cache,
        # and wiping the book would let the server re-serve them after
        # their source is quarantined.
        if self.whole_xorb_provable(self._known_entries(rec, hash_hex),
                                    fi.range.start):
            PROVENANCE.clear(hash_hex)
        if self.swarm is not None:
            self.swarm.announce_available(term.xorb_hash, hash_hex)
        return XorbFetchResult(
            data,
            term.range.start - fi.range.start,
            term.range.end - fi.range.start,
            source="cdn",
        )

    def _peer_tier(self, term: recon.Term, rec: recon.Reconstruction,
                   fi: recon.FetchInfo, hash_hex: str):
        """The swarm attempt, hedged when armed — by a deadline OR by
        anomaly evidence (:meth:`arm_hedge`).

        Returns the swarm's result (or None) in the common case. When
        hedging, the peer fetch runs in a side thread with a head start
        — ``_HEDGE_PEER_FRACTION`` of the remaining budget (capped) on
        the deadline path, a fixed ``_HEDGE_EVIDENCE_WAIT_S`` on the
        evidence path — then a CDN fetch races it from this thread and
        the winner's :class:`XorbFetchResult` is returned. Both arming
        modes share ONE code path past the head-start choice, so the
        ``hedges``/``hedges_won``/``hedges_lost`` counters stay
        mutually consistent however the hedge was armed (the satellite
        accounting fix: the old shape bumped them deadline-only)."""
        deadline = self.deadline
        if (deadline is None and not self._hedge_armed) \
                or self.cas is None:
            return self.swarm.try_peer_download(
                term.xorb_hash, hash_hex, fi.range.start, fi.range.end,
                deadline=deadline,
            )
        if deadline is not None:
            remaining = deadline.remaining()
            if remaining <= 0:
                return None  # budget gone: tier 3 fails fast with its own
                #              check
            wait_s = min(max(remaining * _HEDGE_PEER_FRACTION,
                             _HEDGE_PEER_WAIT_FLOOR_S),
                         _HEDGE_PEER_WAIT_CAP_S)
        else:
            wait_s = _HEDGE_EVIDENCE_WAIT_S
        fut = self._ensure_hedge_pool().submit(
            self.swarm.try_peer_download,
            term.xorb_hash, hash_hex, fi.range.start, fi.range.end, deadline,
        )
        try:
            # Swarm-internal failures (peer errors, timeouts) are already
            # absorbed inside try_peer_download; an exception surfacing
            # here is a real bug and must propagate exactly as it would
            # on the unhedged path.
            return fut.result(timeout=wait_s)
        except FutureTimeoutError:
            pass
        # Peer still in flight with the head start spent: hedge to CDN.
        self.stats.bump("hedges")
        try:
            result = self._cdn_fetch_for_term(term, rec, fi, hash_hex)
        except Exception:
            # The CDN racer failed; the in-flight peer fetch is the last
            # hope — wait it out, bounded by the deadline when one is
            # armed (the evidence path has no budget to cap by: wait
            # the adaptive peer timeouts out, like the unhedged path
            # would have).
            self.stats.bump("hedges_lost")
            try:
                timeout = (max(deadline.remaining(), 0.001)
                           if deadline is not None else None)
                return fut.result(timeout=timeout)
            except FutureTimeoutError:
                return None
        self.stats.bump("hedges_won")
        # A STARTED straggler runs to completion (its result is dropped,
        # its connection returns to the pool); a still-QUEUED one — the
        # saturated-pool case — is cancelled so it never burns peer
        # bandwidth on bytes the CDN already delivered.
        fut.cancel()
        return result

    def _ensure_hedge_pool(self) -> ThreadPoolExecutor:
        with self._hedge_lock:
            if self._hedge_pool is None:
                # Sized to the term-fetch concurrency: a smaller pool
                # would queue hedged peer fetches behind each other, and
                # a queued fetch that times out its head start counts as
                # a hedge without the peer ever being tried.
                width = max(4, getattr(self.cfg, "max_concurrent_downloads",
                                       4))
                self._hedge_pool = ThreadPoolExecutor(
                    width, thread_name_prefix="zest-hedge")
            return self._hedge_pool

    def fetch_unit(self, hash_hex: str, fi: recon.FetchInfo) -> bytes:
        """Raw blob for one fetch unit (a fetch_info chunk range) through
        the same waterfall tiers, without term rebasing — the fetch_fn the
        pod distribution round hands to PodDistributor (owners source
        their assigned units here, then the ICI all-gather carries them
        to everyone)."""
        return self.fetch_unit_tiered(hash_hex, fi)[0]

    def fetch_unit_tiered(
        self, hash_hex: str, fi: recon.FetchInfo
    ) -> tuple[bytes, str]:
        """:meth:`fetch_unit` plus the serving tier (``cache`` | ``peer``
        | ``cdn``) — the cooperative round attributes its fallback bytes
        per tier (peer_served_ratio must not count a peer-served
        fallback as CDN spend)."""
        with telemetry.span("fetch.unit", xorb=hash_hex) as sp:
            data, source = self._fetch_unit(hash_hex, fi)
            sp.set("source", source)
            sp.add_bytes(len(data))
            return data, source

    def _fetch_unit(self, hash_hex: str,
                    fi: recon.FetchInfo) -> tuple[bytes, str]:
        if self.cancel is not None:
            self.cancel.check()  # per-unit cancellation point
        data = self._cached_unit(hash_hex, fi)
        if data is not None:
            self.stats.record("cache", len(data))
            return data, "cache"
        return self._deduped(
            (hash_hex, fi.range.start, fi.range.end),
            lambda: self._network_fetch_unit(hash_hex, fi),
            lambda: self._probe_unit(hash_hex, fi),
        )

    def _cached_unit(self, hash_hex: str,
                     fi: recon.FetchInfo) -> bytes | None:
        """The unit path's tier 1, without stats: the unit's bytes from
        a covering cache entry, or None (miss or corrupt entry). The
        coverage predicate runs inside the lookup (fall-through rule —
        see storage.get_with_range)."""
        sliced: list[bytes] = []

        def covers(res) -> bool:
            if res.chunk_offset > fi.range.start:
                return False
            lo = fi.range.start - res.chunk_offset
            hi = fi.range.end - res.chunk_offset
            try:
                reader = XorbReader(res.data)  # one parse per hit
            except Exception:
                return False  # corrupt entry: fall through, CDN self-heals
            if not (lo >= 0 and lo < hi <= len(reader)):
                return False
            # A covering entry wider than the unit (offset below
            # fi.range.start, or more chunks than fi.range.end — e.g.
            # a full xorb cached by an earlier pull while this plan's
            # unit covers a prefix) is re-framed to exactly the unit's
            # range: a wider blob would overflow its pool row capacity
            # and be zero-rowed, refetching from CDN despite the local
            # hit. Stats count the bytes actually served.
            sliced.append(res.data if lo == 0 and len(reader) == hi
                          else reader.slice_range(lo, hi))
            return True

        if self.cache.get_with_range(hash_hex, fi.range.start,
                                     covers=covers) is None:
            return None
        return sliced[0]

    def _probe_unit(self, hash_hex: str,
                    fi: recon.FetchInfo) -> tuple[bytes, str] | None:
        data = self._cached_unit(hash_hex, fi)
        if data is None:
            return None
        self.stats.record("cache", len(data))
        return data, "cache"

    def _network_fetch_unit(self, hash_hex: str,
                            fi: recon.FetchInfo) -> tuple[bytes, str]:
        data, source = self._network_fetch_unit_raw(hash_hex, fi)
        if self.flights is not None and source != "cache":
            # Deduped mode: the flight's waiters serve themselves from
            # the cache the moment we resolve, so the bytes must be
            # cached HERE (the callers' own cache-write pass runs after
            # return — too late for a subscribed waiter). Same evidence
            # rule as every other write site; _cache_fetched absorbs
            # ENOSPC (the waiters then degrade to their own fetches).
            self._cache_fetched(None, hash_hex, fi.range.start, data)
        return data, source

    def _network_fetch_unit_raw(self, hash_hex: str,
                                fi: recon.FetchInfo) -> tuple[bytes, str]:
        if self.swarm is not None:
            xorb_hash = None
            try:
                from zest_tpu.cas import hashing
                xorb_hash = hashing.hex_to_hash(hash_hex)
            except ValueError:
                pass
            if xorb_hash is not None:
                peer_result = self.swarm.try_peer_download(
                    xorb_hash, hash_hex, fi.range.start, fi.range.end,
                    deadline=self.deadline,
                )
                if peer_result is not None:
                    if (peer_result.chunk_offset == fi.range.start
                            and _blob_covers(peer_result.data, 0,
                                             fi.range.end - fi.range.start)
                            and self._unit_blob_verifies(
                                xorb_hash, hash_hex, peer_result)):
                        self.stats.record("peer", len(peer_result.data))
                        return peer_result.data, "peer"
                    if peer_result.chunk_offset == fi.range.start \
                            and peer_result.addr is not None:
                        # Right frame, bad bytes: structural failure is
                        # attributable (an off-offset blob may just be a
                        # differently-framed tier answer, not corruption).
                        self.stats.bump("corrupt_from_peer")
                        self.swarm.report_corrupt(peer_result.addr)

        if self.cas is None:
            raise NotAuthenticated("no CAS client and no peers had the xorb")
        data = self.cas.fetch_xorb_from_url(
            self._absolute_url(fi.url), (fi.url_range_start, fi.url_range_end)
        )
        self.stats.record("cdn", len(data))
        return data, "cdn"

    def stream_unit_from_cdn(self, hash_hex: str, fi: recon.FetchInfo,
                             full_key: bool) -> int:
        """CDN tier streamed straight into the cache file — no
        whole-unit buffer (storage.atomic_write_stream). The GB-scale
        warm path's fast lane: callers have already checked the cache
        and peer tiers. ``full_key`` follows the same whole-xorb
        evidence rule as ``_cache_fetched``. Trust model unchanged:
        cached bytes are BLAKE3-verified at extraction.

        Deduped like the other network tiers (ISSUE 13): the same key
        space as the term/unit paths, so a warm fetch in one session
        and a term fetch in another collapse to ONE wire transfer; the
        waiter's "result" is the size of the entry the winner wrote."""
        return self._deduped(
            (hash_hex, fi.range.start, fi.range.end),
            lambda: self._stream_unit_from_cdn(hash_hex, fi, full_key),
            lambda: self._probe_stream(hash_hex, fi),
        )

    def _probe_stream(self, hash_hex: str,
                      fi: recon.FetchInfo) -> int | None:
        located = self.cache.locate_with_range(hash_hex, fi.range.start)
        if located is None:
            return None
        try:
            n = os.stat(located[0]).st_size
        except OSError:
            return None  # evicted between locate and stat: refetch
        self.stats.record("cache", n)
        return n

    def _stream_unit_from_cdn(self, hash_hex: str, fi: recon.FetchInfo,
                              full_key: bool) -> int:
        if self.cas is None:
            raise NotAuthenticated("no CAS client")
        with telemetry.span("cdn.stream", xorb=hash_hex) as sp:
            it = self.cas.fetch_xorb_iter(
                self._absolute_url(fi.url),
                (fi.url_range_start, fi.url_range_end)
            )
            if full_key and not self.evidence_incomplete:
                n = self.cache.put_stream(hash_hex, it)
            else:
                n = self.cache.put_partial_stream(hash_hex, fi.range.start,
                                                  it)
            sp.add_bytes(n)
        self.stats.record("cdn", n)
        return n

    def mark_evidence_incomplete(self) -> None:
        """Record that some file's references could not be resolved:
        from here on every cache write uses a partial key (see
        ``evidence_incomplete`` in ``__init__``)."""
        self.evidence_incomplete = True

    def whole_xorb_provable(self, entries, chunk_offset: int) -> bool:
        """``provably_whole`` gated on this bridge's evidence integrity
        — the one predicate every cache-write site (here, federated's
        ``_cache_unit``, pod's expert path) should consult."""
        return (not self.evidence_incomplete
                and provably_whole(entries, chunk_offset))

    def _cache_fetched(self, rec: recon.Reconstruction, hash_hex: str,
                       chunk_offset: int, data: bytes) -> None:
        """Persist a fetched blob so this host can seed it ("the package IS
        the seeder"). Full entry only with whole-xorb evidence; otherwise
        a partial entry keyed by its chunk offset.

        Evidence is judged across EVERY reconstruction this bridge has
        resolved (the memo), not just ``rec``: a xorb deduped across
        files can look whole from one file's fetch_info (single entry at
        chunk 0) while another file reads its later chunks — caching the
        truncated blob under the full key would shadow those partial
        entries and advertise an incomplete xorb as seedable.

        A cache write hitting ENOSPC (typed CacheFullError — the
        eviction pass already ran via the storage hook) is ABSORBED:
        the fetched bytes are in hand and the pull keeps serving, it
        just doesn't cache this blob (graceful degradation, never a
        raw mid-pull OSError over half-written temps).

        **Never-narrower rule** (BOTH key kinds): partial entries are
        keyed by chunk offset only (``{hash}.{start}``), so two fetch
        units sharing a start but not an end — e.g. revision B
        referencing chunks [0,1) of a xorb revision A reads as [0,16)
        — land on the SAME key; and two bridges with different
        resolve-order evidence can BOTH judge their (different-width)
        blobs "provably whole" and race the FULL key. Either way a
        blindly-written narrower blob clobbers the wider one, turning
        later reads of the wide range into cache misses + duplicate
        network fetches (exactly the dups the tenancy bench's
        duplicate-fetch gate caught). The write is skipped when an
        existing entry at the target offset already covers at least
        this blob's chunks; the check+write runs under a
        hash-striped lock because the clobber race is cross-bridge."""
        self.cache_blob(
            hash_hex, chunk_offset, data,
            whole=self.whole_xorb_provable(
                self._known_entries(rec, hash_hex), chunk_offset))

    def cache_blob(self, hash_hex: str, chunk_offset: int, data: bytes,
                   whole: bool) -> None:
        """The ONE guarded cache-write every blob-caching site uses
        (the term/unit paths here, federated's warm ``_cache_unit``,
        the pod round): never-narrower check + write under the
        hash-striped lock, ENOSPC absorbed. ``whole`` is the caller's
        whole-xorb evidence verdict (full vs partial key)."""
        try:
            with _partial_write_lock(hash_hex):
                existing = self.cache.get_with_range(hash_hex,
                                                     chunk_offset)
                if existing is not None \
                        and existing.chunk_offset <= chunk_offset:
                    try:
                        have_end = (existing.chunk_offset
                                    + len(XorbReader(existing.data)))
                        new_end = chunk_offset + len(XorbReader(data))
                        if have_end >= new_end:
                            return  # existing covers everything we have
                    except Exception:  # noqa: BLE001 - corrupt: overwrite
                        pass
                if whole:
                    self.cache.put(hash_hex, data)
                else:
                    self.cache.put_partial(hash_hex, chunk_offset, data)
        except CacheFullError:
            telemetry.record("cache_write_skipped", xorb=hash_hex,
                             reason="disk_full")

    def _known_entries(self, rec: recon.Reconstruction | None,
                       hash_hex: str) -> list[recon.FetchInfo]:
        """Every resolved reference to ``hash_hex`` — ``rec``'s (when
        given) plus the whole memo (``rec=None``: the unit path, which
        has no single owning reconstruction)."""
        entries = (list(rec.fetch_info.get(hash_hex, []))
                   if rec is not None else [])
        with self._recons_lock:
            others = list(self._recons.values())
        for other in others:
            if other is not rec:
                entries.extend(other.fetch_info.get(hash_hex, []))
        return entries

    def _absolute_url(self, url: str) -> str:
        if url.startswith(("http://", "https://")):
            return url
        if self.cas is None:
            raise NotAuthenticated("relative fetch url without CAS client")
        return self.cas.cas_url + url

    # ── Term extraction + sequential reconstruction ──

    def extract_term(self, term: recon.Term, result: XorbFetchResult) -> bytes:
        """Decode + BLAKE3-verify the term's bytes out of a fetched blob."""
        reader = XorbReader(result.data)
        data = reader.extract_chunk_range(result.local_start, result.local_end)
        if len(data) != term.unpacked_length:
            raise BridgeError(
                f"term decoded to {len(data)} bytes, expected "
                f"{term.unpacked_length}"
            )
        return data

    def fetch_term(self, term: recon.Term, rec: recon.Reconstruction) -> bytes:
        result = self.fetch_xorb_for_term(term, rec)
        try:
            return self.extract_term(term, result)
        except Exception:
            # Content-level corruption: the blob parsed structurally but
            # BLAKE3/length verification failed at extraction. The old
            # behavior let the bad blob sit in the cache (peer blobs are
            # cached before extraction) and every retry refail. Now:
            # attribute peer-served corruption to the serving peer (a
            # strike toward quarantine), then force a CDN refetch that
            # overwrites the poisoned cache key, and verify again.
            if result.source == "cdn":
                raise  # CDN bytes failing verification is not healable here
            if result.peer_addr is not None and self.swarm is not None:
                self.stats.bump("corrupt_from_peer")
                self.swarm.report_corrupt(result.peer_addr)
            fi = rec.find_fetch_info(term)
            if fi is None or self.cas is None:
                raise
            healed = self._cdn_fetch_for_term(term, rec, fi, term.hash_hex)
            data = self.extract_term(term, healed)
            self.stats.bump("corrupt_healed")
            return data

    def reconstruct_to_file(self, file_hash_hex: str, out_path) -> int:
        """Sequential fallback path (reference: xet_bridge.zig:231-264).

        The parallel downloader (transfer.parallel) is the primary path;
        this one trades speed for simplicity and is the second rung of the
        per-file fallback chain (main.zig:232-256).
        """
        rec = self.get_reconstruction(file_hash_hex)
        from zest_tpu.storage import atomic_write

        out = bytearray()
        for term in rec.terms:
            out += self.fetch_term(term, rec)
        atomic_write(out_path, bytes(out))
        return len(out)
