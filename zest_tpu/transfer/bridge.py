"""XetBridge: the cache → P2P → CDN waterfall — the heart of the pipeline.

Faithful to the reference's contract (src/xet_bridge.zig:149-218), which is
the stable seam everything else builds on: per-term fetch consults the local
xorb cache (range-aware), then the swarm, then CDN byte-range — and every
CDN fetch is cached (full or partial) so this host can seed it and receivers
never need CDN themselves.

Coordinate frames (the reference's trickiest invariant,
xet_bridge.zig:162-214): the returned blob's chunk 0 is absolute chunk
``chunk_offset``; callers extract ``[term.start - chunk_offset,
term.end - chunk_offset)``. All three waterfall tiers produce the same
frame-stream blob shape, so extraction code is tier-agnostic.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from zest_tpu.cas import reconstruction as recon
from zest_tpu.cas.client import CasClient
from zest_tpu.cas.hub import HubClient
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.config import Config
from zest_tpu.storage import XorbCache


class BridgeError(RuntimeError):
    pass


class NotAuthenticated(BridgeError):
    pass


class NoMatchingFetchInfo(BridgeError):
    pass


@dataclass
class FetchStats:
    """Per-session source accounting (reference: xet_bridge.zig:35-42).

    The P2P byte ratio derived from these is the headline BASELINE metric.
    """

    xorbs_from_cache: int = 0
    xorbs_from_peer: int = 0
    xorbs_from_cdn: int = 0
    bytes_from_cache: int = 0
    bytes_from_peer: int = 0
    bytes_from_cdn: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record(self, source: str, nbytes: int) -> None:
        with self._lock:
            setattr(self, f"xorbs_from_{source}",
                    getattr(self, f"xorbs_from_{source}") + 1)
            setattr(self, f"bytes_from_{source}",
                    getattr(self, f"bytes_from_{source}") + nbytes)

    @property
    def p2p_ratio(self) -> float:
        total = self.bytes_from_peer + self.bytes_from_cdn
        return self.bytes_from_peer / total if total else 0.0

    def summary(self) -> dict:
        return {
            "xorbs": {
                "cache": self.xorbs_from_cache,
                "peer": self.xorbs_from_peer,
                "cdn": self.xorbs_from_cdn,
            },
            "bytes": {
                "cache": self.bytes_from_cache,
                "peer": self.bytes_from_peer,
                "cdn": self.bytes_from_cdn,
            },
            "p2p_ratio": round(self.p2p_ratio, 4),
        }


@dataclass(frozen=True)
class XorbFetchResult:
    """Blob + the term's chunk range rebased into it."""

    data: bytes
    local_start: int
    local_end: int


def _blob_covers(data: bytes, local_start: int, local_end: int) -> bool:
    """Cheap structural check: the blob parses as a frame stream and holds
    chunks [local_start, local_end). Content verification (BLAKE3) happens
    at extraction; this gate keeps short/garbage blobs from being returned
    or cached, where they would defeat the waterfall's fallback."""
    if local_start < 0 or local_end <= local_start:
        return False
    try:
        return len(XorbReader(data)) >= local_end
    except Exception:
        return False


def provably_whole(entries, chunk_offset: int) -> bool:
    """Whole-xorb evidence for the full-vs-partial cache-key decision.

    A blob fetched at ``chunk_offset`` is provably the whole xorb only
    when every known reference to the hash (``entries``, ideally drawn
    from ALL files' reconstructions) is the same single range starting
    at chunk 0 — then the range demonstrably covers everything any
    consumer reads. Any second distinct range means some reader sees
    chunks this blob may not carry."""
    ranges = {(e.range.start, e.range.end) for e in entries}
    return (chunk_offset == 0 and len(ranges) == 1
            and next(iter(ranges))[0] == 0)


class XetBridge:
    def __init__(
        self,
        cfg: Config,
        swarm=None,  # zest_tpu.transfer.swarm.SwarmDownloader | None
        cache: XorbCache | None = None,
    ):
        self.cfg = cfg
        self.cache = cache or XorbCache(cfg)
        self.swarm = swarm
        self.cas: CasClient | None = None
        self.stats = FetchStats()
        self._recons: dict[str, recon.Reconstruction] = {}
        # Guards the reconstruction memo: the pipelined pull resolves
        # and fetches from several file workers at once, and an unlocked
        # dict would let _known_entries iterate mid-insert.
        self._recons_lock = threading.Lock()

    # ── Auth (reference: xet_bridge.zig:76-130) ──

    def authenticate(self, repo_id: str, revision: str = "main",
                     hub: HubClient | None = None) -> None:
        hub = hub or HubClient(self.cfg)
        cas_url, access_token = hub.xet_read_token(repo_id, revision)
        self.cas = CasClient(cas_url, access_token)

    def get_reconstruction(self, file_hash_hex: str) -> recon.Reconstruction:
        """Memoized per bridge: the pod pre-pass plans from the same
        reconstructions the per-file loop consumes moments later, and a
        pull session's reconstructions are immutable (content-addressed),
        so each file costs one CAS round-trip total."""
        if self.cas is None:
            raise NotAuthenticated("call authenticate() first")
        with self._recons_lock:
            cached = self._recons.get(file_hash_hex)
        if cached is None:
            # CAS round-trip outside the lock (slow I/O must not
            # serialize the memo); a racing double-fetch is benign —
            # reconstructions are content-addressed, last write wins.
            cached = self.cas.get_reconstruction(file_hash_hex)
            with self._recons_lock:
                cached = self._recons.setdefault(file_hash_hex, cached)
        return cached

    # ── The waterfall (reference: xet_bridge.zig:149-218) ──

    def fetch_xorb_for_term(
        self, term: recon.Term, rec: recon.Reconstruction
    ) -> XorbFetchResult:
        hash_hex = term.hash_hex
        fi = rec.find_fetch_info(term)
        if fi is None:
            raise NoMatchingFetchInfo(
                f"no fetch info covers chunks [{term.range.start},"
                f"{term.range.end}) of {hash_hex}"
            )

        # 1. Local cache — full xorb or the partial entry for fi's range.
        cached = self.cache.get_with_range(hash_hex, fi.range.start)
        if cached is not None:
            local_start = term.range.start - cached.chunk_offset
            local_end = term.range.end - cached.chunk_offset
            if _blob_covers(cached.data, local_start, local_end):
                self.stats.record("cache", len(cached.data))
                return XorbFetchResult(cached.data, local_start, local_end)
            # Corrupt/short entry: fall through — a CDN refetch overwrites
            # the bad cache key, so the tier self-heals.

        # 2. Swarm (peers) — request fi's full chunk range so the cached
        #    result can serve future terms that share this fetch_info.
        if self.swarm is not None:
            peer_result = self.swarm.try_peer_download(
                term.xorb_hash, hash_hex, fi.range.start, fi.range.end
            )
            if peer_result is not None:
                local_start = term.range.start - peer_result.chunk_offset
                local_end = term.range.end - peer_result.chunk_offset
                if _blob_covers(peer_result.data, local_start, local_end):
                    self.stats.record("peer", len(peer_result.data))
                    # Cache for seeding (reference: swarm.zig:414-420).
                    # Unlike the reference, "full" requires fetch-info
                    # evidence that the blob really is the whole xorb, not
                    # just offset 0 — a sliced prefix cached as full would
                    # poison later reads.
                    self._cache_fetched(
                        rec, hash_hex, peer_result.chunk_offset,
                        peer_result.data,
                    )
                    return XorbFetchResult(
                        peer_result.data, local_start, local_end
                    )
                # Malformed/short peer blob: never cache it; fall to CDN.

        # 3. CDN byte-range; cache everything for seeding.
        if self.cas is None:
            raise NotAuthenticated("no CAS client and no peers had the xorb")
        data = self.cas.fetch_xorb_from_url(
            self._absolute_url(fi.url), (fi.url_range_start, fi.url_range_end)
        )
        self.stats.record("cdn", len(data))
        self._cache_fetched(rec, hash_hex, fi.range.start, data)
        if self.swarm is not None:
            self.swarm.announce_available(term.xorb_hash, hash_hex)
        return XorbFetchResult(
            data,
            term.range.start - fi.range.start,
            term.range.end - fi.range.start,
        )

    def fetch_unit(self, hash_hex: str, fi: recon.FetchInfo) -> bytes:
        """Raw blob for one fetch unit (a fetch_info chunk range) through
        the same waterfall tiers, without term rebasing — the fetch_fn the
        pod distribution round hands to PodDistributor (owners source
        their assigned units here, then the ICI all-gather carries them
        to everyone)."""
        cached = self.cache.get_with_range(hash_hex, fi.range.start)
        if cached is not None and cached.chunk_offset <= fi.range.start:
            lo = fi.range.start - cached.chunk_offset
            hi = fi.range.end - cached.chunk_offset
            try:
                reader = XorbReader(cached.data)  # one parse per hit
            except Exception:
                reader = None  # corrupt entry: fall through, CDN self-heals
            if reader is not None and lo >= 0 and lo < hi <= len(reader):
                # A covering entry wider than the unit (offset below
                # fi.range.start, or more chunks than fi.range.end — e.g.
                # a full xorb cached by an earlier pull while this plan's
                # unit covers a prefix) is re-framed to exactly the unit's
                # range: a wider blob would overflow its pool row capacity
                # and be zero-rowed, refetching from CDN despite the local
                # hit. Stats count the bytes actually served.
                if lo == 0 and len(reader) == hi:
                    data = cached.data
                else:
                    data = reader.slice_range(lo, hi)
                self.stats.record("cache", len(data))
                return data

        if self.swarm is not None:
            xorb_hash = None
            try:
                from zest_tpu.cas import hashing
                xorb_hash = hashing.hex_to_hash(hash_hex)
            except ValueError:
                pass
            if xorb_hash is not None:
                peer_result = self.swarm.try_peer_download(
                    xorb_hash, hash_hex, fi.range.start, fi.range.end
                )
                if peer_result is not None \
                        and peer_result.chunk_offset == fi.range.start \
                        and _blob_covers(peer_result.data, 0,
                                         fi.range.end - fi.range.start):
                    self.stats.record("peer", len(peer_result.data))
                    return peer_result.data

        if self.cas is None:
            raise NotAuthenticated("no CAS client and no peers had the xorb")
        data = self.cas.fetch_xorb_from_url(
            self._absolute_url(fi.url), (fi.url_range_start, fi.url_range_end)
        )
        self.stats.record("cdn", len(data))
        return data

    def stream_unit_from_cdn(self, hash_hex: str, fi: recon.FetchInfo,
                             full_key: bool) -> int:
        """CDN tier streamed straight into the cache file — no
        whole-unit buffer (storage.atomic_write_stream). The GB-scale
        warm path's fast lane: callers have already checked the cache
        and peer tiers. ``full_key`` follows the same whole-xorb
        evidence rule as ``_cache_fetched``. Trust model unchanged:
        cached bytes are BLAKE3-verified at extraction."""
        if self.cas is None:
            raise NotAuthenticated("no CAS client")
        it = self.cas.fetch_xorb_iter(
            self._absolute_url(fi.url), (fi.url_range_start, fi.url_range_end)
        )
        if full_key:
            n = self.cache.put_stream(hash_hex, it)
        else:
            n = self.cache.put_partial_stream(hash_hex, fi.range.start, it)
        self.stats.record("cdn", n)
        return n

    def _cache_fetched(self, rec: recon.Reconstruction, hash_hex: str,
                       chunk_offset: int, data: bytes) -> None:
        """Persist a fetched blob so this host can seed it ("the package IS
        the seeder"). Full entry only with whole-xorb evidence; otherwise
        a partial entry keyed by its chunk offset.

        Evidence is judged across EVERY reconstruction this bridge has
        resolved (the memo), not just ``rec``: a xorb deduped across
        files can look whole from one file's fetch_info (single entry at
        chunk 0) while another file reads its later chunks — caching the
        truncated blob under the full key would shadow those partial
        entries and advertise an incomplete xorb as seedable."""
        if provably_whole(self._known_entries(rec, hash_hex), chunk_offset):
            self.cache.put(hash_hex, data)
        else:
            self.cache.put_partial(hash_hex, chunk_offset, data)

    def _known_entries(self, rec: recon.Reconstruction,
                       hash_hex: str) -> list[recon.FetchInfo]:
        entries = list(rec.fetch_info.get(hash_hex, []))
        with self._recons_lock:
            others = list(self._recons.values())
        for other in others:
            if other is not rec:
                entries.extend(other.fetch_info.get(hash_hex, []))
        return entries

    def _absolute_url(self, url: str) -> str:
        if url.startswith(("http://", "https://")):
            return url
        if self.cas is None:
            raise NotAuthenticated("relative fetch url without CAS client")
        return self.cas.cas_url + url

    # ── Term extraction + sequential reconstruction ──

    def extract_term(self, term: recon.Term, result: XorbFetchResult) -> bytes:
        """Decode + BLAKE3-verify the term's bytes out of a fetched blob."""
        reader = XorbReader(result.data)
        data = reader.extract_chunk_range(result.local_start, result.local_end)
        if len(data) != term.unpacked_length:
            raise BridgeError(
                f"term decoded to {len(data)} bytes, expected "
                f"{term.unpacked_length}"
            )
        return data

    def fetch_term(self, term: recon.Term, rec: recon.Reconstruction) -> bytes:
        return self.extract_term(term, self.fetch_xorb_for_term(term, rec))

    def reconstruct_to_file(self, file_hash_hex: str, out_path) -> int:
        """Sequential fallback path (reference: xet_bridge.zig:231-264).

        The parallel downloader (transfer.parallel) is the primary path;
        this one trades speed for simplicity and is the second rung of the
        per-file fallback chain (main.zig:232-256).
        """
        rec = self.get_reconstruction(file_hash_hex)
        from zest_tpu.storage import atomic_write

        out = bytearray()
        for term in rec.terms:
            out += self.fetch_term(term, rec)
        atomic_write(out_path, bytes(out))
        return len(out)
