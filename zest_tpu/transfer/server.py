"""Seeding server: answers CHUNK_REQUESTs from the local caches.

The src/server.zig equivalent: TCP listener, per-connection thread,
responder-side handshake (echo the requester's info_hash — the responder
serves all swarms, server.zig:122-139), BEP 10 negotiation, then a serve
loop answering each CHUNK_REQUEST from the chunk cache (plain-hex keys)
first, then the range-aware xorb cache (LE-u64-hex keys), else
CHUNK_NOT_FOUND.

Improvements over the reference:
- responds with the *negotiated* ext id, not a hardcoded 1
  (quirk at server.zig:194-213);
- when a full xorb is cached but only a range was requested, slices the
  frame stream and sends just that range (the reference ships the whole
  cached entry).
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass

from zest_tpu.config import Config
from zest_tpu.p2p import bep_xet, peer_id as peer_id_mod, wire
from zest_tpu.p2p.peer import LOCAL_UT_XET_ID
from zest_tpu.storage import XorbCache
from zest_tpu.transfer.dcn import ConnTracker, lookup_chunk_range


@dataclass
class ServerStats:
    active_peers: int
    chunks_served: int


class BtServer:
    def __init__(self, cfg: Config, cache: XorbCache | None = None):
        self.cfg = cfg
        self.cache = cache or XorbCache(cfg)
        self.peer_id = peer_id_mod.generate()
        self._listener: socket.socket | None = None
        self._shutdown = threading.Event()
        self._active_peers = 0
        self._chunks_served = 0
        self._stats_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.port: int | None = None
        self._conns = ConnTracker()

    # ── Lifecycle ──

    def start(self) -> int:
        """Bind + spawn the accept loop; returns the bound port."""
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", self.cfg.listen_port))
        listener.listen(64)
        # Periodic timeout so shutdown() is observed promptly — closing a
        # socket does not reliably interrupt a blocked accept().
        listener.settimeout(0.25)
        self._listener = listener
        self.port = listener.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Wake serving threads blocked in recv so peers' connections die
        # now, not at their 120s timeout (ConnTracker invariants).
        self._conns.wake_all()

    def get_stats(self) -> ServerStats:
        with self._stats_lock:
            return ServerStats(self._active_peers, self._chunks_served)

    # ── Accept + serve (reference: server.zig:45-172) ──

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            conn.settimeout(None)
            threading.Thread(
                target=self._handle_peer, args=(conn,), daemon=True
            ).start()

    def _handle_peer(self, conn: socket.socket) -> None:
        conn.settimeout(120)
        self._conns.add(conn)
        stream = wire.SocketStream(conn)
        with self._stats_lock:
            self._active_peers += 1
        try:
            if self._shutdown.is_set():
                return  # accepted in the same beat as shutdown()
            self._handle_peer_inner(stream)
        except (wire.WireError, OSError, bep_xet.XetMessageError):
            pass  # peer went away or spoke garbage; drop quietly
        finally:
            with self._stats_lock:
                self._active_peers -= 1
            stream.close()
            self._conns.discard(conn)

    def _handle_peer_inner(self, stream: wire.SocketStream) -> None:
        their_hs = stream.recv_handshake()
        # Responder echoes the requester's info_hash: one server seeds
        # every xorb swarm it has data for (server.zig:122-139).
        stream.send_handshake(their_hs.info_hash, self.peer_id)
        stream.send_raw(wire.encode_extended(
            0, bep_xet.make_ext_handshake(LOCAL_UT_XET_ID, self.port)
        ))
        stream.send_message(wire.MessageId.UNCHOKE)

        requester_ext_id = LOCAL_UT_XET_ID  # until their handshake arrives
        while not self._shutdown.is_set():
            msg = stream.recv_message()
            if msg.msg_id is None:
                continue
            if msg.msg_id != wire.MessageId.EXTENDED:
                continue  # interested/keepalive chatter
            ext_id, payload = wire.parse_extended(msg.payload)
            if ext_id == 0:
                caps = bep_xet.parse_ext_handshake(payload)
                if caps.ut_xet_id is not None:
                    requester_ext_id = caps.ut_xet_id
                continue
            xet = bep_xet.decode(payload)
            if isinstance(xet, bep_xet.ChunkRequest):
                self._handle_chunk_request(stream, requester_ext_id, xet)

    # ── Request service (reference: server.zig:187-215) ──

    def _handle_chunk_request(
        self,
        stream: wire.SocketStream,
        ext_id: int,
        req: bep_xet.ChunkRequest,
    ) -> None:
        # Shared two-tier lookup (chunk cache, then range-aware xorb
        # cache) — identical answers over BT wire and DCN RPC.
        found = lookup_chunk_range(
            self.cfg, self.cache, req.chunk_hash,
            req.range_start, req.range_end,
        )
        if found is not None:
            offset, blob = found
            self._respond(stream, ext_id, req.request_id, offset, blob)
            return

        stream.send_raw(bep_xet.encode_framed(
            ext_id,
            bep_xet.ChunkNotFound(req.request_id, req.chunk_hash),
        ))

    def _respond(self, stream, ext_id: int, request_id: int,
                 chunk_offset: int, data: bytes) -> None:
        # encode_framed copies the chunk data once (native framer) instead
        # of three times through the pure concat chain — the serving hot
        # loop's analog of the reference's bt_wire fast path.
        stream.send_raw(bep_xet.encode_framed(
            ext_id,
            bep_xet.ChunkResponse(request_id, chunk_offset, data),
        ))
        with self._stats_lock:
            self._chunks_served += 1
