"""Seeding server: answers CHUNK_REQUESTs from the local caches.

The src/server.zig equivalent: TCP listener, per-connection thread,
responder-side handshake (echo the requester's info_hash — the responder
serves all swarms, server.zig:122-139), BEP 10 negotiation, then a serve
loop answering each CHUNK_REQUEST from the chunk cache (plain-hex keys)
first, then the range-aware xorb cache (LE-u64-hex keys), else
CHUNK_NOT_FOUND.

Production upload policy (ISSUE 12 — "the package IS the seeder" is only
true if serving survives real swarms):

- **Rate shaping**: one global :class:`zest_tpu.shaping.TokenBucket`
  (``ZEST_SEED_RATE_BPS``) bounds the host's total upload rate, and a
  per-peer bucket (``ZEST_SEED_PEER_BPS``) keeps one aggressive leecher
  from starving the rest. Responses stream in shaped chunks, so the
  bound holds within a frame, not just between frames.
- **Choke/unchoke reciprocity** (BEP-XET heritage): the K
  (``ZEST_SEED_SLOTS``) peers that served *us* the most bytes recently
  — the health registry's decayed reciprocity book — hold unchoke
  slots, plus ONE optimistic-unchoke slot rotating through the rest so
  strangers can bootstrap. Choked peers get ``CHUNK_ERROR(CHOKED)``
  (the requester's swarm moves on without a health strike). The same
  K+1 bounds concurrent in-flight uploads.
- **Per-request deadlines**: a chunk response must complete within
  ``ZEST_SEED_DEADLINE_S`` end-to-end. A reader that stops draining its
  socket is disconnected and struck in the health registry with the
  distinct ``stalled_reader`` kind instead of pinning an upload slot; a
  deadline consumed by the server's OWN shaping budget or queueing
  expires the upload without blaming anyone. (The ``seed_stall`` kind
  is the mirror image, recorded by the PULL side for a peer that times
  out while serving us — see transfer.swarm.)
- **Quarantine-aware refusal**: content whose bytes came (unproven)
  from a peer this host has since quarantined is refused with a loud
  ``CHUNK_ERROR(NOT_AVAILABLE)`` — suspect bytes are never laundered
  back into the swarm (:class:`zest_tpu.p2p.health.ContentProvenance`).
- **Graceful drain**: shutdown stops accepting first, then gives
  in-flight responses ``ZEST_SEED_DRAIN_S`` to complete before waking
  blocked readers — a shutdown mid-upload never hands a puller a
  truncated-but-accepted blob.

Improvements over the reference kept from the seed build:
- responds with the *negotiated* ext id, not a hardcoded 1
  (quirk at server.zig:194-213);
- when a full xorb is cached but only a range was requested, slices the
  frame stream and sends just that range (the reference ships the whole
  cached entry).
"""

from __future__ import annotations

import socket
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

from zest_tpu import faults, telemetry
from zest_tpu.config import Config
from zest_tpu.p2p import bep_xet, peer_id as peer_id_mod, wire
from zest_tpu.p2p.health import PROVENANCE, HealthRegistry
from zest_tpu.p2p.peer import LOCAL_UT_XET_ID
from zest_tpu.shaping import TokenBucket
from zest_tpu.storage import XorbCache
from zest_tpu.transfer.dcn import ConnTracker, lookup_chunk_range

# Process-registry mirrors: serving economics must be visible on
# /v1/metrics across sessions ("which peers do we feed, whom do we
# choke, what did we refuse").
_M_SEED_BYTES = telemetry.counter(
    "zest_seed_bytes_total",
    "Payload bytes served by the seeding tier, by unchoke slot kind",
    ("peer_state",))
_M_CHOKE_EVENTS = telemetry.counter(
    "zest_seed_choke_events_total",
    "Choke/unchoke state transitions sent to leechers")
_M_REFUSALS = telemetry.counter(
    "zest_seed_refusals_total",
    "Chunk requests refused for quarantined-source content")
_M_EXPIRED = telemetry.counter(
    "zest_seed_uploads_expired_total",
    "Uploads aborted at the per-request deadline (stalled readers)")

# How often the choke book re-ranks (and the optimistic slot rotates).
RECHOKE_INTERVAL_S = 10.0
# Shaped-send granularity: small enough that the token buckets bound
# rate within a frame, large enough that syscall overhead is noise.
_SEND_CHUNK = 256 * 1024
# Per-peer bucket book bound: honest clients key by their stable
# (host, listen_port) serving identity and reuse one bucket across
# reconnects; clients that never advertise a port key by ephemeral
# source address and would otherwise grow the book one bucket per
# connection forever. LRU-evicting past this cap bounds memory; the
# GLOBAL bucket still caps aggregate rate either way.
_PEER_BUCKET_CAP = 256


class UploadExpired(RuntimeError):
    """A response overran its per-request deadline — the connection is
    dropped. Only a genuine send timeout (the reader stopped draining)
    also strikes the peer, with kind ``stalled_reader``; a starved
    shaping budget or queue delay is the server's own doing and blames
    nobody."""


@dataclass
class ServerStats:
    active_peers: int
    chunks_served: int
    bytes_served: int = 0
    choke_events: int = 0
    refused_quarantined: int = 0
    uploads_expired: int = 0
    unchoked_peers: int = 0
    choked_peers: int = 0


class _ChokeBook:
    """Reciprocity-ranked unchoke slots over the registered leechers.

    ``slots`` reciprocal winners (most decayed bytes served to US, from
    the health registry) stay unchoked; one optimistic slot rotates
    through the rest each rechoke interval so a new peer with nothing
    to its name can still bootstrap — the standard BitTorrent answer to
    both free-riders and cold-start. With ≤ slots+1 leechers everyone
    is unchoked (the policy only bites under contention), which also
    keeps the single-leecher loopback behavior identical to the
    pre-policy server."""

    def __init__(self, slots: int, health: HealthRegistry | None,
                 rechoke_s: float = RECHOKE_INTERVAL_S,
                 time_fn=time.monotonic):
        self.slots = max(1, slots)
        self.health = health
        self.rechoke_s = rechoke_s
        self._time = time_fn
        self._lock = threading.Lock()
        self._peers: dict[int, tuple[str, int]] = {}  # conn key -> addr
        self._order: list[int] = []                   # registration order
        self._unchoked: dict[int, str] = {}           # key -> slot kind
        self._next_rechoke = 0.0
        self._rotation = 0
        self.transitions = 0

    def register(self, key: int, addr: tuple[str, int]) -> None:
        with self._lock:
            if key not in self._peers:
                self._order.append(key)
            self._peers[key] = addr
            self._next_rechoke = 0.0  # membership change: re-rank now

    def unregister(self, key: int) -> None:
        with self._lock:
            self._peers.pop(key, None)
            self._unchoked.pop(key, None)
            if key in self._order:
                self._order.remove(key)
            self._next_rechoke = 0.0

    def _recompute_locked(self, now: float) -> None:
        keys = list(self._order)
        if len(keys) <= self.slots + 1:
            self._unchoked = {k: "reciprocal" for k in keys}
        else:
            def served(k: int) -> float:
                if self.health is None:
                    return 0.0
                return self.health.served_bytes(self._peers[k])
            ranked = sorted(keys, key=served, reverse=True)  # stable:
            # ties keep registration order (sorted() stability over the
            # registration-ordered input).
            winners = ranked[: self.slots]
            rest = [k for k in keys if k not in winners]
            self._unchoked = {k: "reciprocal" for k in winners}
            self._unchoked[rest[self._rotation % len(rest)]] = "optimistic"
            self._rotation += 1
        self._next_rechoke = now + self.rechoke_s

    def slot(self, key: int) -> str | None:
        """The unchoke slot kind for this leecher (``"reciprocal"`` |
        ``"optimistic"``), or None = choked. Re-ranks lazily on the
        rechoke interval; the ``seeder_choke_flap`` fault injects a
        spurious one-query choke here (the chaos matrix's probe that a
        flapping policy can't corrupt or stall a pull)."""
        now = self._time()
        with self._lock:
            if now >= self._next_rechoke:
                self._recompute_locked(now)
            kind = self._unchoked.get(key)
            addr = self._peers.get(key)
        if kind is not None and addr is not None \
                and faults.fire("seeder_choke_flap",
                                key=f"{addr[0]}:{addr[1]}"):
            return None
        return kind

    def kind(self, key: int) -> str | None:
        """Current slot kind WITHOUT re-ranking or fault rolls — for
        labeling work already authorized by :meth:`slot`."""
        with self._lock:
            return self._unchoked.get(key)

    def count_transition(self) -> None:
        """Choke-state flip sent on some wire; serve threads race, so
        the counter lives under the book's lock."""
        with self._lock:
            self.transitions += 1

    def counts(self) -> tuple[int, int]:
        with self._lock:
            unchoked = len(self._unchoked)
            return unchoked, max(0, len(self._peers) - unchoked)


class BtServer:
    def __init__(self, cfg: Config, cache: XorbCache | None = None,
                 health: HealthRegistry | None = None,
                 rechoke_s: float = RECHOKE_INTERVAL_S):
        self.cfg = cfg
        self.cache = cache or XorbCache(cfg)
        # The health registry doubles as (a) the reciprocity book behind
        # unchoke ranking, (b) the strike target for stalled readers,
        # and (c) the quarantine oracle for source-refusal. Share the
        # swarm's registry when this process also pulls (cmd_serve
        # does); a private one still enforces slots/shaping/deadlines.
        self.health = health or HealthRegistry()
        self.peer_id = peer_id_mod.generate()
        self._listener: socket.socket | None = None
        self._shutdown = threading.Event()
        self._draining = threading.Event()
        self._active_peers = 0
        self._chunks_served = 0
        self._bytes_served = 0
        self._refused_quarantined = 0
        self._uploads_expired = 0
        self._uploads_inflight = 0
        self._stats_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.port: int | None = None
        self._conns = ConnTracker()
        self._choke = _ChokeBook(cfg.seed_slots, self.health,
                                 rechoke_s=rechoke_s)
        # Upload transfer slots: the same K+1 bound as the unchoke set —
        # an unchoked peer pipelining requests cannot multiply past it.
        self._slots = threading.BoundedSemaphore(cfg.seed_slots + 1)
        self._rate = (TokenBucket(cfg.seed_rate_bps)
                      if cfg.seed_rate_bps else None)
        self._peer_rate_lock = threading.Lock()
        self._peer_rates: OrderedDict[tuple[str, int], TokenBucket] = \
            OrderedDict()

    # ── Lifecycle ──

    def start(self) -> int:
        """Bind + spawn the accept loop; returns the bound port."""
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", self.cfg.listen_port))
        listener.listen(64)
        # Periodic timeout so shutdown() is observed promptly — closing a
        # socket does not reliably interrupt a blocked accept().
        listener.settimeout(0.25)
        self._listener = listener
        self.port = listener.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self, drain_s: float | None = None) -> None:
        """Graceful drain: stop accepting, let in-flight responses
        finish within ``drain_s`` (default ``cfg.seed_drain_s``), then
        wake everything. A response that completes inside the drain
        window reaches its puller whole — no truncated-but-accepted
        blobs; one that cannot is cut at the wire-frame level, which
        the puller's framing rejects loudly."""
        if drain_s is None:
            drain_s = self.cfg.seed_drain_s
        self._draining.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        deadline = time.monotonic() + max(0.0, drain_s)
        while time.monotonic() < deadline:
            with self._stats_lock:
                if self._uploads_inflight == 0:
                    break
            time.sleep(0.02)
        self._shutdown.set()
        # Wake serving threads blocked in recv so peers' connections die
        # now, not at their 120s timeout (ConnTracker invariants).
        self._conns.wake_all()

    def get_stats(self) -> ServerStats:
        unchoked, choked = self._choke.counts()
        with self._stats_lock:
            return ServerStats(
                active_peers=self._active_peers,
                chunks_served=self._chunks_served,
                bytes_served=self._bytes_served,
                choke_events=self._choke.transitions,
                refused_quarantined=self._refused_quarantined,
                uploads_expired=self._uploads_expired,
                unchoked_peers=unchoked,
                choked_peers=choked,
            )

    # ── Accept + serve (reference: server.zig:45-172) ──

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not (self._shutdown.is_set() or self._draining.is_set()):
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            conn.settimeout(None)
            threading.Thread(
                target=self._handle_peer, args=(conn,), daemon=True
            ).start()

    def _peer_bucket(self, addr: tuple[str, int]) -> TokenBucket | None:
        if not self.cfg.seed_peer_bps:
            return None
        with self._peer_rate_lock:
            bucket = self._peer_rates.get(addr)
            if bucket is None:
                bucket = self._peer_rates[addr] = TokenBucket(
                    self.cfg.seed_peer_bps)
            self._peer_rates.move_to_end(addr)
            while len(self._peer_rates) > _PEER_BUCKET_CAP:
                self._peer_rates.popitem(last=False)
            return bucket

    def _handle_peer(self, conn: socket.socket) -> None:
        conn.settimeout(120)
        self._conns.add(conn)
        stream = wire.SocketStream(conn)
        with self._stats_lock:
            self._active_peers += 1
        key = id(stream)
        try:
            host = conn.getpeername()[0]
        except OSError:
            host = "?"
        try:
            if self._shutdown.is_set():
                return  # accepted in the same beat as shutdown()
            self._handle_peer_inner(stream, key, host)
        except UploadExpired:
            # The reader stalled (or starved the shaped budget) past the
            # request deadline while holding an upload slot: drop the
            # connection and strike the peer with the SERVING-side kind,
            # so health.detail() attributes "bad leecher" distinctly
            # from "bad seeder".
            with self._stats_lock:
                self._uploads_expired += 1
            _M_EXPIRED.inc()
        except (wire.WireError, OSError, bep_xet.XetMessageError):
            pass  # peer went away or spoke garbage; drop quietly
        finally:
            self._choke.unregister(key)
            with self._stats_lock:
                self._active_peers -= 1
            stream.close()
            self._conns.discard(conn)

    def _handle_peer_inner(self, stream: wire.SocketStream, key: int,
                           host: str) -> None:
        their_hs = stream.recv_handshake()
        # Responder echoes the requester's info_hash: one server seeds
        # every xorb swarm it has data for (server.zig:122-139).
        stream.send_handshake(their_hs.info_hash, self.peer_id)
        stream.send_raw(wire.encode_extended(
            0, bep_xet.make_ext_handshake(LOCAL_UT_XET_ID, self.port)
        ))
        # Until the ext handshake names the peer's listen port, the
        # reciprocity book keys on the connection's source address (a
        # stranger entry: no history, neutral rank).
        try:
            peer_addr = (host, stream.sock.getpeername()[1])
        except OSError:
            peer_addr = (host, 0)
        self._choke.register(key, peer_addr)
        # Health strikes only target ADVERTISED identities: keying them
        # by ephemeral source port would grow the registry one entry
        # per reconnect of any client that never sends a listen_port.
        advertised = False
        sent_unchoked = self._sync_choke_state(stream, key, None)

        requester_ext_id = LOCAL_UT_XET_ID  # until their handshake arrives
        while not self._shutdown.is_set():
            msg = stream.recv_message()
            if msg.msg_id is None:
                continue
            if msg.msg_id != wire.MessageId.EXTENDED:
                continue  # interested/keepalive chatter
            ext_id, payload = wire.parse_extended(msg.payload)
            if ext_id == 0:
                caps = bep_xet.parse_ext_handshake(payload)
                if caps.ut_xet_id is not None:
                    requester_ext_id = caps.ut_xet_id
                if caps.listen_port:
                    # The peer's SERVING identity: reciprocity and
                    # strikes key on (host, listen_port) — the address
                    # our own swarm fetches from.
                    peer_addr = (host, caps.listen_port)
                    advertised = True
                    self._choke.register(key, peer_addr)
                continue
            xet = bep_xet.decode(payload)
            if isinstance(xet, bep_xet.ChunkRequest):
                sent_unchoked = self._sync_choke_state(
                    stream, key, sent_unchoked)
                self._handle_chunk_request(
                    stream, requester_ext_id, xet, key, peer_addr,
                    unchoked=bool(sent_unchoked), advertised=advertised)

    def _sync_choke_state(self, stream: wire.SocketStream, key: int,
                          last_sent: bool | None) -> bool:
        """Send CHOKE/UNCHOKE on state transitions, from the connection's
        own serve thread (all writes stay serialized). Returns the state
        just ensured on the wire."""
        unchoked = self._choke.slot(key) is not None
        if unchoked != last_sent:
            stream.send_message(wire.MessageId.UNCHOKE if unchoked
                                else wire.MessageId.CHOKE)
            if last_sent is not None:
                self._choke.count_transition()
                _M_CHOKE_EVENTS.inc()
                telemetry.record("seed_choke",
                                 state="unchoke" if unchoked else "choke")
        return unchoked

    # ── Request service (reference: server.zig:187-215) ──

    def _handle_chunk_request(
        self,
        stream: wire.SocketStream,
        ext_id: int,
        req: bep_xet.ChunkRequest,
        key: int,
        peer_addr: tuple[str, int],
        unchoked: bool = True,
        advertised: bool = True,
    ) -> None:
        from zest_tpu.cas import hashing

        hash_hex = hashing.hash_to_hex(req.chunk_hash)
        if not unchoked:
            # Choked peers get a prompt, honest denial — the requester's
            # swarm moves to another candidate without a strike.
            stream.send_raw(bep_xet.encode_framed(
                ext_id,
                bep_xet.ChunkError(req.request_id, bep_xet.ERR_CHOKED,
                                   b"choked: upload policy"),
            ))
            return

        # Quarantine-aware refusal: bytes cached UNPROVEN from a peer
        # this host has since quarantined are never re-served — and the
        # key may carry several contributors' ranges, so ANY quarantined
        # source refuses. Loud — a typed wire error plus a
        # flight-recorder event — instead of silently seeding suspect
        # data onward.
        src = next((s for s in PROVENANCE.sources(hash_hex)
                    if self.health.is_quarantined(s)), None)
        if src is not None:
            with self._stats_lock:
                self._refused_quarantined += 1
            _M_REFUSALS.inc()
            telemetry.record("seed_refused", xorb=hash_hex,
                             source=f"{src[0]}:{src[1]}")
            stream.send_raw(bep_xet.encode_framed(
                ext_id,
                bep_xet.ChunkError(
                    req.request_id, bep_xet.ERR_NOT_AVAILABLE,
                    b"not available: quarantined source"),
            ))
            return

        # Shared two-tier lookup (chunk cache, then range-aware xorb
        # cache) — identical answers over BT wire and DCN RPC.
        found = lookup_chunk_range(
            self.cfg, self.cache, req.chunk_hash,
            req.range_start, req.range_end,
        )
        if found is not None:
            offset, blob = found
            self._respond(stream, ext_id, req.request_id, offset, blob,
                          key, peer_addr, advertised)
            return

        stream.send_raw(bep_xet.encode_framed(
            ext_id,
            bep_xet.ChunkNotFound(req.request_id, req.chunk_hash),
        ))

    def _respond(self, stream, ext_id: int, request_id: int,
                 chunk_offset: int, data: bytes, key: int,
                 peer_addr: tuple[str, int],
                 advertised: bool = True) -> None:
        """One upload: slot-bounded, rate-shaped, deadline-bounded.

        encode_framed copies the chunk data once (native framer) instead
        of three times through the pure concat chain — the serving hot
        loop's analog of the reference's bt_wire fast path. The frame
        then streams out in shaped pieces so the token buckets bound
        the rate *within* the transfer, and every piece re-checks the
        per-request deadline: a reader that stops draining its socket
        (or an injected ``seeder_stall``) frees the slot at the
        deadline instead of pinning it."""
        peer_key = f"{peer_addr[0]}:{peer_addr[1]}"
        give_up_at = time.monotonic() + self.cfg.seed_request_deadline_s
        if not self._slots.acquire(
                timeout=max(0.0, give_up_at - time.monotonic())):
            # All transfer slots busy for a full deadline: deny like a
            # choke (healthy server, try elsewhere), don't stall.
            stream.send_raw(bep_xet.encode_framed(
                ext_id,
                bep_xet.ChunkError(request_id, bep_xet.ERR_CHOKED,
                                   b"busy: no upload slot"),
            ))
            return
        with self._stats_lock:
            self._uploads_inflight += 1
        try:
            # Chaos sites (ISSUE 12): a seeder that stalls mid-upload,
            # and one that serves corrupt bytes (the puller's verify
            # tiers must catch it — corrupt-bytes-admitted stays 0).
            faults.sleep_if("seeder_stall", key=peer_key, default_s=2.0)
            if time.monotonic() > give_up_at:
                # The response can no longer complete inside its budget
                # (WE stalled, or it queued too long behind the slots):
                # abort BEFORE the frame starts — a partial frame would
                # desync the stream either way. No strike: this is the
                # server's own congestion, not the reader's fault.
                raise UploadExpired("request deadline exceeded pre-send")
            if faults.fire("upload_corrupt", key=peer_key):
                data = faults.corrupt(data)
            frame = bep_xet.encode_framed(
                ext_id,
                bep_xet.ChunkResponse(request_id, chunk_offset, data),
            )
            self._send_shaped(stream, frame, peer_addr, give_up_at)
        except (socket.timeout, TimeoutError):
            if advertised:
                self._strike_stalled(peer_addr)
            raise UploadExpired(f"upload to {peer_key} timed out")
        finally:
            with self._stats_lock:
                self._uploads_inflight -= 1
            self._slots.release()
        slot_kind = self._choke.kind(key) or "reciprocal"
        with self._stats_lock:
            self._chunks_served += 1
            self._bytes_served += len(data)
        _M_SEED_BYTES.inc(len(data), peer_state=slot_kind)

    def _send_shaped(self, stream, frame: bytes,
                     peer_addr: tuple[str, int], give_up_at: float) -> None:
        rate = self._rate
        peer_rate = self._peer_bucket(peer_addr)
        if rate is None and peer_rate is None:
            # Unshaped fast path: one send, deadline via socket timeout.
            stream.sock.settimeout(
                max(0.1, give_up_at - time.monotonic()))
            try:
                stream.send_raw(frame)
            finally:
                stream.sock.settimeout(120)
            return
        view = memoryview(frame)
        try:
            for off in range(0, len(view), _SEND_CHUNK):
                piece = view[off:off + _SEND_CHUNK]
                # Per-peer fairness first, then the global allocation —
                # a peer-starved wait must not hold global tokens.
                # A bucket give-up or a deadline consumed by shaping
                # waits is the SERVER's own budget running out — expire
                # the upload but never strike the reader for it. A
                # give-up refunds the buckets already debited for this
                # piece: the bytes were never sent, and the peer bucket
                # persists across reconnects — phantom debt would shape
                # the peer below its knob on every retry.
                granted: list[TokenBucket] = []
                for bucket in (peer_rate, rate):
                    if bucket is None:
                        continue
                    if not bucket.acquire(len(piece),
                                          give_up_at=give_up_at):
                        for prior in granted:
                            prior.refund(len(piece))
                        raise UploadExpired(
                            "shaping budget overran request deadline")
                    granted.append(bucket)
                if time.monotonic() > give_up_at:
                    raise UploadExpired("request deadline exceeded")
                stream.sock.settimeout(
                    max(0.1, give_up_at - time.monotonic()))
                try:
                    stream.send_raw(piece)
                finally:
                    stream.sock.settimeout(120)
        finally:
            view.release()

    def _strike_stalled(self, peer_addr: tuple[str, int]) -> None:
        """Serving-side strike attribution: a reader that stops
        draining its socket (the send itself timed out — NOT a shaping
        give-up or queue delay, which are the server's own doing) gets
        the distinct ``stalled_reader`` kind — visible in
        ``health.detail()`` next to (not conflated with) its fetch-side
        record."""
        if peer_addr[1]:
            self.health.record_failure(peer_addr, kind="stalled_reader")
