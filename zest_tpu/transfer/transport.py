"""Pluggable exchange transports for the collective phase planner.

The PR-13 collective (transfer.collective) derives a deterministic
phase schedule from the shared plan; until ISSUE 20 the only way to
*execute* a phase was the hardwired ``DcnPool.request_many`` path.
This module splits transport from schedule: the planner speaks the
:class:`ExchangeTransport` protocol — open a tagged window, send/recv
the phase payloads, expose clock offsets and the wire-tag counters,
abort by raising the connection-error family — and three backends
implement it:

- :class:`DcnWireTransport` — the existing pooled ``DcnChannel``
  path, now one implementation instead of the default. With the
  default knobs its calls into the pool are ARGUMENT-IDENTICAL to the
  pre-split code (same positional shape, same tag allocator, no flag
  byte), which is what lets ``ZEST_COLLECTIVE_BACKEND=dcn`` pin the
  old exchange bit-for-bit.
- :class:`JaxIciTransport` — intra-slice (ICI-class) phases move
  their payloads as device-to-device ``jax.Array`` permutes: the
  ragged frame blobs of a window pack into a fixed uint8 lane whose
  width derives from the SHARED plan (so every host compiles the
  identical program; a blob that outgrows the lane — a whole-entry
  serve after a footer-parse failure — passes through host-side and
  is counted in ``lane_overflows``). Cross-slice DCN/WAN phases keep
  the wire transport untouched.
- :class:`LoopbackTransport` — in-process serving against a
  registered fabric of ``(cfg, cache)`` per address: the 256–1024-host
  simulations exchange through direct :func:`~zest_tpu.transfer.dcn.
  serve_chunk_range` calls with zero sockets and zero serialization,
  while still honoring the ``dcn_reset`` fault hook and the tagged
  window discipline so the conformance suite can drive all three
  backends through one set of assertions.

Backend selection: ``ZEST_COLLECTIVE_BACKEND`` → ``Config.
collective_backend`` → :func:`make_transport`. An unbuildable backend
raises :class:`TransportUnavailable` before any wire traffic; the
collective turns that into ``CollectiveUnavailable`` and the round
degrades down the PR-6 point-to-point ladder exactly as before.
"""

from __future__ import annotations

import threading

from zest_tpu import faults, telemetry
from zest_tpu.transfer.dcn import (
    DcnNotFound,
    DcnResponse,
    FLAG_LOSSY_OK,
    FLAG_QUANT_OK,
    serve_chunk_range,
)

LINK_ICI = "ici"


class TransportUnavailable(RuntimeError):
    """The configured backend cannot run here (missing runtime, no
    fabric entry): raised BEFORE any wire traffic so the caller can
    degrade to the point-to-point exchange."""


def _request_flags(lossy_ok: bool, quant_ok: bool) -> int:
    return ((FLAG_LOSSY_OK if lossy_ok else 0)
            | (FLAG_QUANT_OK if quant_ok else 0))


class ExchangeTransport:
    """Protocol the phase planner executes against.

    ``request_window`` issues one tagged phase sub-window to a partner
    and returns per-want replies (``DcnResponse`` / ``DcnNotFound``) in
    request order; a dead partner is signalled by raising
    ``ConnectionError`` / ``TimeoutError`` / ``OSError``, which is the
    planner's abort hook. ``counters`` exposes the wire-tag accounting
    the no-per-unit-round-trips gate reads; ``clock_offsets`` feeds
    the merged-trace clock normalization."""

    name = "?"

    def window_tag(self) -> int:
        raise NotImplementedError

    def request_window(self, partner: int, addr: tuple[str, int],
                       wants: list[tuple[bytes, int, int]], *,
                       timeout: float, tag: int,
                       link: str = "dcn",
                       lossy_ok: bool = False,
                       quant_ok: bool = False) -> list:
        raise NotImplementedError

    @property
    def counters(self) -> dict:
        raise NotImplementedError

    def clock_offsets(self) -> dict:
        return {}

    def close(self) -> None:
        pass


class DcnWireTransport(ExchangeTransport):
    """The pooled ``DcnChannel`` path — PR-13's hardwired transport as
    one implementation. Every request with default (byte-exact) knobs
    reaches ``pool.request_many`` with the exact argument shape the
    pre-split collective used."""

    name = "dcn"

    def __init__(self, pool) -> None:
        self.pool = pool

    def window_tag(self) -> int:
        return self.pool.window_tag()

    @property
    def counters(self) -> dict:
        return self.pool.counters

    def clock_offsets(self) -> dict:
        return self.pool.clock_offsets()

    def request_window(self, partner, addr, wants, *, timeout, tag,
                       link="dcn", lossy_ok=False, quant_ok=False):
        host, port = addr
        flags = _request_flags(lossy_ok, quant_ok)
        if flags:
            return self.pool.request_many(host, port, wants,
                                          timeout=timeout, tag=tag,
                                          flags=flags)
        # No kwargs beyond the pre-split ones: the dcn-backend
        # bit-for-bit pin intercepts this call shape.
        return self.pool.request_many(host, port, wants,
                                      timeout=timeout, tag=tag)


# ── In-process loopback fabric ──
#
# The simulations register each simulated host's (cfg, cache) under
# its advertised address; loopback/jax transports serve against the
# registry directly instead of dialing sockets.

_FABRIC: dict[tuple[str, int], tuple] = {}
_FABRIC_LOCK = threading.Lock()


def register_loopback(addr: tuple[str, int], cfg, cache) -> None:
    with _FABRIC_LOCK:
        _FABRIC[(str(addr[0]), int(addr[1]))] = (cfg, cache)


def fabric_entry(addr: tuple[str, int]):
    with _FABRIC_LOCK:
        return _FABRIC.get((str(addr[0]), int(addr[1])))


def reset_loopback() -> None:
    """Drop every fabric registration (tests/bench isolation)."""
    with _FABRIC_LOCK:
        _FABRIC.clear()


class _TagAlloc:
    """Nonzero u16 window-tag allocator (mirrors DcnPool's)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0

    def alloc(self) -> int:
        with self._lock:
            self._next = (self._next % 0xFFFF) + 1
            return self._next


def _serve_window(entry, addr, wants, flags: int) -> list:
    """Answer one window against a fabric entry — the same
    ``serve_chunk_range`` decision tree the socket server runs, so
    every backend serves identically (including the lossy tier)."""
    cfg, cache = entry
    out = []
    for i, (chunk_hash, start, end) in enumerate(wants):
        found = serve_chunk_range(cfg, cache, chunk_hash, start, end,
                                  flags)
        if found is None:
            out.append(DcnNotFound(i, chunk_hash))
        else:
            offset, blob, resp_flags = found
            out.append(DcnResponse(i, offset, blob, resp_flags))
    return out


class LoopbackTransport(ExchangeTransport):
    """Zero-socket exchange against the registered fabric — the
    256–1024-host simulation backend. Keeps the tagged-window
    counters and the ``dcn_reset`` fault hook so the conformance
    suite and the fault ladder behave exactly as over the wire."""

    name = "loopback"

    def __init__(self) -> None:
        self._counters = {"windows": 0, "requests": 0,
                          "tagged_windows": 0, "untagged_windows": 0}
        self._lock = threading.Lock()
        self._tags = _TagAlloc()

    def window_tag(self) -> int:
        return self._tags.alloc()

    @property
    def counters(self) -> dict:
        return self._counters

    def request_window(self, partner, addr, wants, *, timeout, tag,
                       link="dcn", lossy_ok=False, quant_ok=False):
        host, port = addr
        if faults.fire("dcn_reset", key=f"{host}:{port}"):
            raise ConnectionError("injected dcn_reset")
        entry = fabric_entry(addr)
        if entry is None:
            raise ConnectionError(
                f"no loopback fabric entry for {host}:{port}")
        with self._lock:
            self._counters["windows"] += 1
            self._counters["requests"] += len(wants)
            self._counters["tagged_windows" if tag
                           else "untagged_windows"] += 1
        # Bare ``collective.*`` span name: critpath blames transport
        # work as "exchange" (the stage-prefix table learned these in
        # the same PR that split the transports out).
        with telemetry.span("collective.loopback", requests=len(wants),
                            link=link) as _sp:
            replies = _serve_window(entry, addr, wants,
                                    _request_flags(lossy_ok, quant_ok))
            _sp.add_bytes(sum(len(r.data) for r in replies
                              if isinstance(r, DcnResponse)))
        return replies


# ICI lane sizing: lanes quantize to 64 KiB so minor per-unit size
# variation never changes the compiled program shape, plus slack for
# frame headers / whole-entry serves slightly over the plan estimate.
_LANE_QUANTUM = 64 * 1024
_LANE_SLACK = 4096


class JaxIciTransport(ExchangeTransport):
    """Intra-slice phases as device-to-device uint8 lane permutes.

    The lane width derives from the shared plan's largest unit wire
    estimate — a pure function of the fingerprint-identical plan, so
    every host compiles the identical lane program without any
    negotiation. Payload bytes for an ICI phase come from the loopback
    fabric when the partner is registered (the in-process sims) or
    from the wire transport otherwise, then round-trip through the
    device as a ``jax.Array`` — the host-level stand-in for the real
    multi-host ICI permute, exercising the exact pack/unpack and
    shape-agreement machinery. DCN/WAN phases delegate to the wire
    transport untouched."""

    name = "jax"

    def __init__(self, pool, plan=None) -> None:
        try:
            import jax
        except Exception as exc:  # noqa: BLE001 - gated dependency
            raise TransportUnavailable(f"jax unavailable: {exc}")
        self._jax = jax
        self._wire = DcnWireTransport(pool)
        lane = _LANE_QUANTUM
        if plan is not None:
            biggest = max(
                (fi.url_range_end - fi.url_range_start
                 for _key, fi in plan.units), default=0)
            lane = -(-(biggest + _LANE_SLACK) // _LANE_QUANTUM) \
                * _LANE_QUANTUM
        self.lane_bytes = lane
        self._counters = {"windows": 0, "requests": 0,
                          "tagged_windows": 0, "untagged_windows": 0,
                          "ici_windows": 0, "ici_lane_bytes": 0,
                          "lane_overflows": 0}
        self._lock = threading.Lock()

    def window_tag(self) -> int:
        return self._wire.window_tag()

    @property
    def counters(self) -> dict:
        return self._counters

    def clock_offsets(self) -> dict:
        return self._wire.clock_offsets()

    def request_window(self, partner, addr, wants, *, timeout, tag,
                       link="dcn", lossy_ok=False, quant_ok=False):
        with self._lock:
            self._counters["windows"] += 1
            self._counters["requests"] += len(wants)
            self._counters["tagged_windows" if tag
                           else "untagged_windows"] += 1
        if link != LINK_ICI:
            return self._wire.request_window(
                partner, addr, wants, timeout=timeout, tag=tag,
                link=link, lossy_ok=lossy_ok, quant_ok=quant_ok)
        host, port = addr
        if faults.fire("dcn_reset", key=f"{host}:{port}"):
            raise ConnectionError("injected dcn_reset")
        entry = fabric_entry(addr)
        if entry is not None:
            replies = _serve_window(entry, addr, wants,
                                    _request_flags(lossy_ok, quant_ok))
        else:
            replies = self._wire.request_window(
                partner, addr, wants, timeout=timeout, tag=tag,
                link=link, lossy_ok=lossy_ok, quant_ok=quant_ok)
        with self._lock:
            self._counters["ici_windows"] += 1
        return self._lane_permute(replies)

    def _lane_permute(self, replies: list) -> list:
        import numpy as np

        rows = [i for i, r in enumerate(replies)
                if isinstance(r, DcnResponse)
                and 0 < len(r.data) <= self.lane_bytes]
        overflow = sum(1 for r in replies
                       if isinstance(r, DcnResponse)
                       and len(r.data) > self.lane_bytes)
        if overflow:
            with self._lock:
                self._counters["lane_overflows"] += overflow
        if not rows:
            return replies
        with telemetry.span("collective.lane", rows=len(rows),
                            lane_bytes=self.lane_bytes) as _sp:
            lanes = np.zeros((len(rows), self.lane_bytes),
                             dtype=np.uint8)
            for j, i in enumerate(rows):
                data = replies[i].data
                lanes[j, :len(data)] = np.frombuffer(data,
                                                     dtype=np.uint8)
            moved = np.asarray(self._jax.device_put(lanes))
            _sp.add_bytes(int(lanes.nbytes))
        with self._lock:
            self._counters["ici_lane_bytes"] += int(lanes.nbytes)
        out = list(replies)
        for j, i in enumerate(rows):
            r = replies[i]
            out[i] = DcnResponse(r.request_id, r.chunk_offset,
                                 moved[j, :len(r.data)].tobytes(),
                                 r.flags)
        return out


def make_transport(backend: str | None, pool,
                   plan=None) -> ExchangeTransport:
    """Build the configured backend. ``pool`` is the round's DcnPool
    (wire/jax backends share it — channels, tag allocator, counters);
    ``plan`` sizes the jax backend's uint8 lanes."""
    if backend in (None, "", "dcn"):
        return DcnWireTransport(pool)
    if backend == "loopback":
        return LoopbackTransport()
    if backend == "jax":
        return JaxIciTransport(pool, plan=plan)
    raise TransportUnavailable(
        f"unknown collective backend {backend!r}")
