"""Deadline budgets and retry backoff shared across the fetch path.

A pull's failure handling is budgeted, not unbounded: ``Deadline`` is
the per-pull wall-clock budget (``ZEST_PULL_DEADLINE_S``) that flows
from ``transfer.pull`` through the bridge into the swarm and CDN tiers
— every blocking timeout is capped by what's left of it, so one dead
peer can never spend more of the budget than its share. ``Backoff`` is
the capped exponential retry pacing with deterministic jitter used by
the CDN client.
"""

from __future__ import annotations

import random
import time


class DeadlineExceeded(TimeoutError):
    """The pull's wall-clock budget ran out mid-operation."""


class Deadline:
    """Monotonic wall-clock budget, immutable and thread-safe by
    construction (two floats set once)."""

    __slots__ = ("total_s", "t_end")

    # Timeouts capped by an expired deadline degrade to this floor so
    # socket/HTTP calls error out promptly instead of raising ValueError
    # on a non-positive timeout.
    MIN_TIMEOUT_S = 0.001

    def __init__(self, total_s: float):
        self.total_s = float(total_s)
        self.t_end = time.monotonic() + self.total_s

    @classmethod
    def after(cls, total_s: float | None) -> "Deadline | None":
        """None for a falsy/non-positive budget — deadline off."""
        if not total_s or total_s <= 0:
            return None
        return cls(total_s)

    def remaining(self) -> float:
        return self.t_end - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "operation") -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"{what} exceeded the {self.total_s:.1f}s pull deadline"
            )

    def cap(self, timeout_s: float) -> float:
        """``timeout_s`` bounded by the remaining budget (floored so the
        caller's blocking call still errors fast rather than misusing a
        non-positive timeout)."""
        return max(min(timeout_s, self.remaining()), self.MIN_TIMEOUT_S)

    def fraction_left(self) -> float:
        if self.total_s <= 0:
            return 0.0
        return max(0.0, min(1.0, self.remaining() / self.total_s))


class Backoff:
    """Capped exponential backoff with equal jitter.

    Delay ``n`` is ``min(cap, base * 2**n)`` scaled into
    ``[0.5, 1.0]``× by the jitter RNG — entropy-seeded by default so a
    fleet of hosts retrying the same CDN origin de-synchronizes instead
    of stampeding in lockstep. Pass ``seed`` for reproducible delays in
    tests (chaos determinism lives in the fault *firing* sequence, not
    in sleep lengths, so production keeps real entropy)."""

    def __init__(self, base_s: float = 0.2, cap_s: float = 5.0,
                 seed: int | None = None):
        self.base_s = max(0.0, base_s)
        self.cap_s = cap_s
        self._rng = random.Random(seed)  # None -> system entropy
        self._attempt = 0

    def next_delay(self) -> float:
        delay = min(self.cap_s, self.base_s * (2.0 ** self._attempt))
        self._attempt += 1
        return delay * (0.5 + 0.5 * self._rng.random())

    def sleep(self, deadline: Deadline | None = None) -> bool:
        """Sleep the next delay, truncated to the deadline's remainder.
        False when the deadline has no room left (caller should abort
        the retry loop instead of burning the tail of the budget)."""
        delay = self.next_delay()
        if deadline is not None:
            room = deadline.remaining()
            if room <= 0.0:
                return False
            delay = min(delay, room)
        if delay > 0.0:
            time.sleep(delay)
        return True
