"""Client side of the public API: pull + daemon status.

The reference shells out to a bundled native binary for ``pull``
(python/zest/client.py:32-36); here the transfer pipeline is in-process
Python/JAX, so ``pull`` calls it directly and ``status`` talks to the local
daemon's REST API (python/zest/client.py:48-54).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import requests

from zest_tpu.config import Config

if TYPE_CHECKING:
    from zest_tpu.transfer.pull import PullResult


class ZestClient:
    def __init__(self, config: Config | None = None):
        self.config = config or Config.load()

    def pull(
        self,
        repo_id: str,
        revision: str = "main",
        device: str | None = None,
    ) -> "PullResult":
        """Download ``repo_id`` through the swarm. The result is
        os.PathLike for the snapshot dir (the reference contract,
        python/zest/client.py:22-46) and carries ``.stats`` and — for
        ``device='tpu'`` — the staged ``.params``."""
        from zest_tpu.transfer.pull import pull_model

        return pull_model(
            self.config, repo_id, revision=revision, device=device
        )

    def status(self) -> dict:
        """Daemon status via ``GET /v1/status`` on the loopback REST API."""
        resp = requests.get(
            f"http://127.0.0.1:{self.config.effective_http_port()}"
            "/v1/status", timeout=5
        )
        resp.raise_for_status()
        return resp.json()
