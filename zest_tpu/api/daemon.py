"""Local seeding-daemon lifecycle management.

Mirrors the reference's ZestServer (python/zest/server.py:27-95): health-check
the loopback REST API, spawn a detached ``serve`` process when absent, poll
``/v1/health`` until ready, stop via ``POST /v1/stop``. The spawned process is
``python -m zest_tpu serve`` instead of a bundled binary.
"""

from __future__ import annotations

import subprocess
import sys
import time

import requests

from zest_tpu.config import Config

_HEALTH_TIMEOUT_S = 5.0
_POLL_INTERVAL_S = 0.1


class ZestServer:
    def __init__(self, config: Config | None = None):
        self.config = config or Config.load()
        self._proc: subprocess.Popen | None = None

    @property
    def _base(self) -> str:
        # effective_http_port: a daemon started with http_port=0 binds an
        # ephemeral port and records it next to its pid file.
        return f"http://127.0.0.1:{self.config.effective_http_port()}"

    def is_running(self) -> bool:
        try:
            return (
                requests.get(f"{self._base}/v1/health", timeout=1).status_code
                == 200
            )
        except requests.RequestException:
            return False

    def ensure_running(self) -> None:
        """Spawn the daemon if the health check fails (server.py:27-41)."""
        if self.is_running():
            return
        self._proc = subprocess.Popen(
            [
                sys.executable, "-m", "zest_tpu", "serve",
                "--http-port", str(self.config.http_port),
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        deadline = time.monotonic() + _HEALTH_TIMEOUT_S
        while time.monotonic() < deadline:
            if self.is_running():
                return
            time.sleep(_POLL_INTERVAL_S)
        raise RuntimeError(
            f"zest daemon failed to become healthy within {_HEALTH_TIMEOUT_S}s"
        )

    def stop(self) -> None:
        """Stop via the REST API; tolerate an already-stopped daemon."""
        try:
            requests.post(f"{self._base}/v1/stop", timeout=5)
        except requests.RequestException:
            pass
