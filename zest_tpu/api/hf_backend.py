"""huggingface_hub integration: transparent snapshot_download interception.

Monkey-patches ``huggingface_hub.snapshot_download`` so existing
``from_pretrained()`` code paths pull through the swarm with zero workflow
change, falling back to the original implementation on ANY exception —
zest must never make a download fail that would otherwise succeed
(reference: python/zest/hf_backend.py:9-50).
"""

from __future__ import annotations

_original_snapshot_download = None


def patch_hf_hub(client) -> None:
    global _original_snapshot_download
    import huggingface_hub

    if _original_snapshot_download is not None:
        return  # already patched

    original = huggingface_hub.snapshot_download

    def zest_snapshot_download(repo_id: str, *args, **kwargs):
        revision = kwargs.get("revision") or "main"
        try:
            return str(client.pull(repo_id, revision=revision))
        except Exception:
            return original(repo_id, *args, **kwargs)

    _original_snapshot_download = original
    huggingface_hub.snapshot_download = zest_snapshot_download


def unpatch_hf_hub() -> None:
    global _original_snapshot_download
    if _original_snapshot_download is None:
        return
    import huggingface_hub

    huggingface_hub.snapshot_download = _original_snapshot_download
    _original_snapshot_download = None
