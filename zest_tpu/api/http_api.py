"""Localhost REST control plane + dashboard.

The reference's http_api.zig: loopback-bound HTTP server routing
``GET /v1/health``, ``GET /v1/status``, ``POST /v1/pull``, ``POST /v1/stop``,
``GET /v1/models`` and an embedded single-page dashboard polling status every
2 s (src/http_api.zig:96-114, 235-351). Differences by design:

- ``POST /v1/pull`` is implemented for real (the reference shipped a stub,
  src/http_api.zig:138-142): it streams SSE progress events while the pull
  runs, per DESIGN.md's intended contract.
- ``POST /v1/generate`` (no reference counterpart — the serving surface):
  pull + family-model decode, streamed as SSE ``start``/``pulled``/``done``
  events with output token ids (and text when a tokenizer is present).
- ``/v1/status`` additionally reports pod-level fields (HBM staging
  occupancy, mesh axes) — the TPU build's control plane surfaces the
  device tier too (SURVEY.md §2.1 row 16).
- Fleet observability surfaces (ISSUE 7): ``GET /v1/trace`` (live span
  snapshot as Chrome trace JSON — what ``zest trace --coop`` gathers
  from every host), ``GET /v1/debug`` (flight-recorder tail + the coop
  block the dashboard's panel polls), and ``GET /v1/metrics?scope=pod``
  (the coordinator scrapes each pod peer's ``/v1/metrics`` and serves
  one aggregated exposition: counters summed, gauges host-labeled,
  derived ``zest_coop_straggler_seconds`` & co — telemetry.fleet).
- Pull-session surfaces (ISSUE 11): ``GET /v1/pulls`` (active pulls +
  the recent ring from the process session table), ``GET
  /v1/pulls/<id>`` detail, and the SSE progress stream ``GET
  /v1/pulls/<id>/events`` mirroring ``POST /v1/pull``'s event schema —
  what ``zest ps --watch`` and the dashboard's active-pulls panel
  render. ``POST /v1/pull`` accepts a ``tenant`` field that labels the
  session.
- Multi-tenant service surfaces (ISSUE 13): ``DELETE /v1/pulls/<id>``
  cancels a running session (202; the pull stops at its next stage
  boundary and finishes ``cancelled``), ``POST /v1/pull`` answers a
  typed ``429`` + ``Retry-After`` when the admission queue is full, a
  disconnected ``POST /v1/pull`` SSE client cancels its pull, and
  ``/v1/status`` gains a ``tenancy{}`` block (admission, dedupe,
  eviction, pins) when ``ZEST_TENANCY`` is on.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from zest_tpu import faults, storage, telemetry
from zest_tpu.config import Config
from zest_tpu.telemetry import fleet
from zest_tpu.transfer import tenancy
from zest_tpu.version import __version__


_WARMED = threading.Event()  # process-global serve warm-up latch


class WatchHub:
    """Fan-out of push notifications to ``POST /v1/watch`` subscribers
    (ISSUE 19).

    One condition + per-subscriber event queues: ``notify()`` (called
    from a ``/v1/push`` handler thread) appends to every matching
    subscriber's queue and wakes them; each subscriber's ``subscribe()``
    generator drains its own queue into the SSE stream, emitting a
    ``ping`` keepalive when ``ping_s`` passes quietly (so dead clients
    surface as BrokenPipe instead of idling forever). A disconnect
    (GeneratorExit from ``_stream_sse``) unregisters the subscriber.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._subs: list[dict] = []

    def watchers(self) -> int:
        with self._cond:
            return len(self._subs)

    def notify(self, event: dict) -> int:
        """Deliver ``event`` to matching subscribers; returns count."""
        delivered = 0
        with self._cond:
            for sub in self._subs:
                repos = sub["repos"]
                if repos and event.get("repo") not in repos:
                    continue
                sub["queue"].append(dict(event))
                delivered += 1
            self._cond.notify_all()
        return delivered

    def subscribe(self, repos=None, ping_s: float = 15.0):
        """SSE event generator for one subscriber. ``repos`` filters
        (empty/None = all repos)."""
        sub = {"queue": [], "repos": set(repos) if repos else None}
        with self._cond:
            self._subs.append(sub)
        try:
            yield {"event": "hello",
                   "watching": sorted(sub["repos"] or [])}
            while True:
                with self._cond:
                    if not sub["queue"]:
                        self._cond.wait(timeout=ping_s)
                    batch, sub["queue"] = sub["queue"], []
                if not batch:
                    yield {"event": "ping"}
                for ev in batch:
                    yield ev
        finally:
            with self._cond:
                if sub in self._subs:
                    self._subs.remove(sub)


class HttpApi:
    """Control-plane server. ``run()`` blocks until ``/v1/stop``."""

    def __init__(
        self,
        cfg: Config,
        bt_server=None,
        registry=None,
        hbm_cache=None,
        swarm=None,
        dcn_server=None,
        pod_peers: dict | None = None,
        gossip_node=None,
    ):
        self.cfg = cfg
        self.bt_server = bt_server
        self.registry = registry
        self.hbm_cache = hbm_cache
        self.swarm = swarm
        self.dcn_server = dcn_server
        self.gossip_node = gossip_node
        # Push fan-out (ISSUE 19): /v1/watch subscribers + the hub-
        # shaped serving index a second node's `zest pull` reads.
        self.watch_hub = WatchHub()
        self._pub_index = None
        # host index → (host, http_port) of the OTHER pod daemons, for
        # the ?scope=pod aggregation (ZEST_POD_PEERS / --pod-peer).
        self.pod_peers = dict(pod_peers if pod_peers is not None
                              else getattr(cfg, "pod_peers", {}) or {})
        self.http_requests = 0
        # Live-state metrics: event counters mirror at bump time, but
        # occupancy/quarantine are *states*, so they register a
        # scrape-time collector closed over the live objects. Removed in
        # close() — tests build many HttpApi instances per process and a
        # leaked collector would pin each one (and double-report gauges).
        self._collector = self._collect_gauges
        telemetry.REGISTRY.add_collector(self._collector)
        self.shutdown_event = threading.Event()
        self._httpd: ThreadingHTTPServer | None = None
        # snapshot_dir → (model_type, generate); see _generator_for.
        self._generators: dict = {}
        self._gen_lock = threading.Lock()
        self._gen_loading: dict = {}
        # (repo_id, revision) → (snapshot_dir, expiry); see _pull_memo.
        self._pulled: dict = {}
        # Snapshot pinning + per-key pull singleflight (ISSUE 13
        # satellite): a generator streaming from a snapshot pins its
        # memo key — a pinned entry never TTL-expires, so a concurrent
        # request can't kick off a re-pull that rewrites the snapshot
        # under the reader — and concurrent misses for the same
        # repo@rev share ONE pull_model call instead of racing.
        self._snapshot_pins: dict = {}
        self._pull_inflight: dict = {}

    # ── Lifecycle ──

    def start(self) -> int:
        """Bind loopback (reference binds 127.0.0.1 only, http_api.zig:49)
        and serve in a background thread; returns the bound port."""
        api = self

        class Handler(_Handler):
            pass

        Handler.api = api
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", self.cfg.http_port), Handler
        )
        self._httpd.daemon_threads = True
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        # Serve cold-start (VERDICT r5 item 6): the daemon is healthy
        # the instant the socket binds — jax import, backend init, and
        # the XLA warm-up compile run in a background thread, NOT on
        # the health-check or first-request path. By the time a real
        # generate request finishes its pull, the runtime is warm and
        # (with the persistent compile cache) the decode executable is
        # often already on disk.
        if not _WARMED.is_set():
            threading.Thread(target=self._warmup, daemon=True,
                             name="zest-serve-warmup").start()
        # Live timelines (ISSUE 15): a serving daemon samples for its
        # whole life, so `/v1/timeline` / `zest top` have history the
        # moment the first pull starts (no-op when ZEST_TIMELINE=0).
        telemetry.timeline.ensure_started()
        return self._httpd.server_address[1]

    @staticmethod
    def _warmup() -> None:
        """Pay the jax/backend/first-compile fixed costs off-path, once
        per process (tests construct many HttpApi instances; the warm
        state is process-global). Best-effort: a machine without a
        working backend still serves status/pull — only generate needs
        jax, and it degrades to paying these costs inline as before."""
        if _WARMED.is_set():
            return
        _WARMED.set()
        try:
            from zest_tpu.models.generate import enable_compile_cache

            enable_compile_cache()
            import jax
            import jax.numpy as jnp

            jax.devices()  # backend init (the multi-second term on TPU)
            jax.jit(lambda x: x * 2)(jnp.ones((8,))).block_until_ready()
        except Exception:  # noqa: BLE001 - warmup must never kill serve
            pass

    def run(self) -> None:
        """Blocking serve-until-stopped (reference main.zig:458-467)."""
        self.start()
        self.shutdown_event.wait()
        self.close()

    def trigger_shutdown(self) -> None:
        self.shutdown_event.set()
        if self.bt_server is not None:
            self.bt_server.shutdown()

    def close(self) -> None:
        telemetry.REGISTRY.remove_collector(self._collector)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def _collect_gauges(self, registry) -> None:
        """Scrape-time gauges from the live objects this daemon owns."""
        if self.hbm_cache is not None:
            h = self.hbm_cache.summary()
            registry.gauge(
                "zest_hbm_cache_used_bytes",
                "HBM staging-cache occupancy").set(h["used_bytes"])
            registry.gauge(
                "zest_hbm_cache_entries",
                "HBM staging-cache entry count").set(h["entries"])
        if self.swarm is not None:
            health = getattr(self.swarm, "health", None)
            if health is not None:
                s = health.summary()
                registry.gauge(
                    "zest_peers_tracked",
                    "Peers with recorded health").set(s["tracked"])
                registry.gauge(
                    "zest_peers_quarantined",
                    "Peers currently quarantined").set(s["quarantined_now"])
        if self.bt_server is not None:
            bt = self.bt_server.get_stats()
            registry.gauge(
                "zest_bt_active_peers",
                "Active inbound BT-wire connections").set(bt.active_peers)
            # Seeding-tier live state (ISSUE 12): who we feed and whom
            # the reciprocity policy is currently holding back.
            registry.gauge(
                "zest_seed_active_leechers",
                "Leechers connected to the seeding server"
            ).set(bt.active_peers)
            registry.gauge(
                "zest_seed_choked_peers",
                "Leechers currently choked by the upload policy"
            ).set(bt.choked_peers)
            registry.gauge(
                "zest_seed_unchoked_peers",
                "Leechers currently holding unchoke slots"
            ).set(bt.unchoked_peers)

    @property
    def port(self) -> int:
        return (
            self._httpd.server_address[1]
            if self._httpd
            else self.cfg.http_port
        )

    # ── Push fan-out (ISSUE 19) ──

    def publisher_index(self):
        """The hub-shaped serving index (lazy: most daemons never get
        asked to act as an endpoint)."""
        if self._pub_index is None:
            from zest_tpu.transfer.push import PublisherIndex

            self._pub_index = PublisherIndex(self.cfg)
        return self._pub_index

    def push_notify(self, req: dict) -> dict:
        """Handle a ``POST /v1/push`` from a local ``zest push``: make
        the new xorbs seedable *now* (registry + swarm announce), bump
        the revision on the gossip plane, chart the push, and wake
        every ``/v1/watch`` subscriber. Raises ValueError on a
        malformed notification (answered as 400)."""
        repo, revision = req.get("repo"), req.get("revision")
        if not repo or not revision:
            raise ValueError("push notify needs repo and revision")
        try:
            xorbs = [(str(h), int(n)) for h, n in (req.get("xorbs") or [])]
        except (TypeError, ValueError) as exc:
            raise ValueError("xorbs must be [[hex, size], ...]") from exc
        if self.registry is not None:
            for h, n in xorbs:
                self.registry.add(h, n)
        if self.swarm is not None and xorbs:
            try:
                self.swarm.announce_xorbs([h for h, _ in xorbs])
            except Exception:  # noqa: BLE001 - announce is best-effort
                pass
        if self.gossip_node is not None:
            try:
                self.gossip_node.announce_manifest(
                    f"{repo}@{revision}",
                    {"repo": repo, "revision": revision,
                     "parent": req.get("parent"),
                     "pushed_at": req.get("pushed_at")})
            except Exception:  # noqa: BLE001 - gossip is best-effort
                pass
        telemetry.timeline.post("push.new_xorb_bytes",
                                float(req.get("new_xorb_bytes") or 0))
        if req.get("dedup_ratio") is not None:
            telemetry.timeline.post("push.dedup_ratio",
                                    float(req["dedup_ratio"]))
        event = {"event": "revision", "repo": repo, "revision": revision,
                 "parent": req.get("parent"),
                 "pushed_at": req.get("pushed_at"),
                 "dedup_ratio": req.get("dedup_ratio"),
                 "new_xorb_bytes": req.get("new_xorb_bytes")}
        delivered = self.watch_hub.notify(event)
        telemetry.record("push_notify", repo=repo, revision=revision,
                         xorbs=len(xorbs), delivered=delivered)
        return {"ok": True, "watchers": self.watch_hub.watchers(),
                "delivered": delivered}

    # ── Payloads ──

    def status_payload(self) -> dict:
        bt = self.bt_server.get_stats() if self.bt_server else None
        payload = {
            "version": __version__,
            "bt_peers": bt.active_peers if bt else 0,
            "chunks_served": bt.chunks_served if bt else 0,
            "xorbs_cached": len(self.registry) if self.registry is not None
            else len(storage.list_cached_xorbs(self.cfg)),
            "http_requests": self.http_requests,
            "listen_port": self.cfg.listen_port,
            "http_port": self.port,
        }
        if bt is not None:
            # Seeding economics (ISSUE 12): the upload policy's live
            # view — slots, choke churn, refusals, shaped-rate knobs.
            payload["seeding"] = {
                "active_leechers": bt.active_peers,
                "unchoked": bt.unchoked_peers,
                "choked": bt.choked_peers,
                "chunks_served": bt.chunks_served,
                "bytes_served": bt.bytes_served,
                "choke_events": bt.choke_events,
                "refused_quarantined": bt.refused_quarantined,
                "uploads_expired": bt.uploads_expired,
                "rate_bps": self.cfg.seed_rate_bps or None,
                "peer_bps": self.cfg.seed_peer_bps or None,
                "slots": self.cfg.seed_slots,
            }
        if self.dcn_server is not None and self.dcn_server.port is not None:
            d = self.dcn_server.stats
            payload["dcn"] = {
                "port": self.dcn_server.port,
                "connections": d.connections,
                "chunks_served": d.chunks_served,
                "bytes_served": d.bytes_served,
                "not_found": d.not_found,
            }
        if self.hbm_cache is not None:
            payload["hbm"] = self.hbm_cache.summary()
        if self.cfg.mesh.mesh_axes:
            payload["mesh_axes"] = self.cfg.mesh.mesh_axes
        if self.swarm is not None:
            # summary() folds in the health registry's aggregate view;
            # injected doubles may only carry bare stats.
            payload["swarm"] = (
                self.swarm.summary() if hasattr(self.swarm, "summary")
                else self.swarm.stats.summary())
            health = getattr(self.swarm, "health", None)
            if health is not None and hasattr(health, "detail"):
                # Per-peer EWMA latency / strikes / quarantine windows:
                # the circuit-breaker decisions used to be invisible
                # outside the process (ISSUE 4 satellite).
                payload["peers"] = health.detail()
        payload["telemetry"] = telemetry.status_snapshot()
        sessions = telemetry.session.SESSIONS
        payload["pulls"] = {"active": len(sessions.active_ids()),
                            "recent": len(sessions.recent())}
        burn = sessions.slo_burn()
        if burn:
            payload["slo"] = burn
        fired = faults.counters()
        if fired:
            payload["faults"] = dict(sorted(fired.items()))
        # Multi-tenant pool state (ISSUE 13): admission (active/queued/
        # rejects), singleflight dedupe, eviction, pins. Absent with
        # ZEST_TENANCY=0 (the knob-off schema identity).
        tn = tenancy.summary(self.cfg)
        if tn is not None:
            payload["tenancy"] = tn
        # Timeline store state (ISSUE 15): series/cursor/anomaly counts.
        # Absent when knob-off, same schema rule as tenancy.
        tl = telemetry.timeline.status_block()
        if tl.get("enabled"):
            payload["timeline"] = tl
        # HBM serving pool (ISSUE 18): occupancy vs watermark, hit/miss,
        # evictions, per-model rows. Absent with ZEST_HBM_POOL=0 —
        # same knob-off schema rule as tenancy/timeline.
        from zest_tpu.models import hbm_pool

        hp = hbm_pool.pool(self.cfg)
        if hp is not None:
            payload["hbm_pool"] = hp.summary()
        return payload

    # ── Live timelines (ISSUE 15) ──

    def timeline_payload(self, since: int = 0,
                         prefix: str | None = None) -> dict:
        """``GET /v1/timeline?since=<cursor>``: every series' samples
        past the cursor plus the recent anomaly ring (see
        telemetry.timeline.payload)."""
        return telemetry.timeline.payload(since=since, prefix=prefix)

    def pod_timeline_payload(self) -> dict:
        """``GET /v1/timeline?scope=pod``: this host's timeline plus a
        concurrent scrape of every configured pod peer's, merged onto
        this host's clock via the hello offsets the last coop round
        recorded (PR 7). A peer that fails the scrape is reported under
        ``scrape_errors`` instead of failing the surface — same rule as
        ``?scope=pod`` metrics."""
        local_label = str(
            self.cfg.coop_index if self.cfg.coop_index is not None
            else self.cfg.mesh.process_id)
        docs = {local_label: telemetry.timeline.payload()}
        errors: dict = {}
        peers = {str(k): v for k, v in self.pod_peers.items()
                 if str(k) != local_label}
        if peers:
            def scrape(item):
                label, (host, port) = item
                url = f"http://{host}:{port}/v1/timeline"
                try:
                    with urllib.request.urlopen(url, timeout=2.0) as r:
                        return label, json.loads(r.read().decode()), None
                except Exception as exc:  # noqa: BLE001 - per-host report
                    return label, None, str(exc)

            # Shared bounded pool (telemetry.fleet.scrape_pool): the
            # fan-out is capped process-wide, not per request — at
            # hundreds of peers concurrent pod-scope requests queue
            # instead of bursting a thread per peer each.
            pool = fleet.scrape_pool(self.cfg.pod_scrape_workers)
            for label, doc, err in pool.map(scrape, peers.items()):
                if doc is not None:
                    docs[label] = doc
                else:
                    errors[label] = err
        merged = telemetry.timeline.merge_timelines(
            docs, reference=local_label)
        if errors:
            merged["scrape_errors"] = errors
        return merged

    # ── Pull sessions (ISSUE 11) ──

    def pulls_payload(self) -> dict:
        """``GET /v1/pulls``: active + recent sessions, newest first,
        plus the admission queue state (ISSUE 13) so ``zest ps`` and
        the dashboard can show queued vs active without a second
        round trip."""
        doc = telemetry.session.payload()
        tn = tenancy.summary(self.cfg)
        if tn is not None:
            doc["tenancy"] = {"active": tn["active"],
                              "queued": tn["queued"],
                              "max_pulls": tn["max_pulls"],
                              "queue_cap": tn["queue_cap"],
                              "rejected_total": tn["rejected_total"]}
        return doc

    def cancel_pull(self, sid: str) -> tuple[dict, int]:
        """``DELETE /v1/pulls/<id>`` (ISSUE 13 satellite): fire the
        session's cancel token. The pull stops at its next stage
        boundary, releases its budget shares and pins, detaches from
        shared flights, and finishes with the ``cancelled`` terminal
        status. 404 for unknown ids; 409 when the session is already
        terminal or carries no token (registered outside pull_model)."""
        sess = telemetry.session.get(sid)
        if sess is None:
            return {"error": "unknown session"}, 404
        if sess.cancel(reason=f"DELETE /v1/pulls/{sid}"):
            return {"id": sid, "status": "cancelling"}, 202
        snap = sess.snapshot()
        if snap["status"] != "running":
            return {"id": sid, "error": "already terminal",
                    "status": snap["status"]}, 409
        return {"id": sid, "error": "not cancellable"}, 409

    def pull_detail(self, sid: str) -> dict | None:
        sess = telemetry.session.get(sid)
        return sess.snapshot(detail=True) if sess is not None else None

    def session_events(self, sid: str):
        """Generator of SSE progress events for one session (``GET
        /v1/pulls/<id>/events``), mirroring ``POST /v1/pull``'s schema:
        ``start`` → [``progress``…] → ``done``/``error``. Progress
        events fire on phase/version change (the session's condition)
        with a 1 s heartbeat; the stream ends the moment the session
        goes terminal — tailing a finished session yields ``start``
        then the terminal event immediately."""
        sess = telemetry.session.get(sid)
        if sess is None:
            yield {"event": "error", "message": "unknown session"}
            return
        yield {"event": "start", **sess.snapshot(detail=True)}
        while True:
            snap = sess.snapshot()
            if snap["status"] != "running":
                break
            yield {"event": "progress", **snap}
            sess.wait(snap["version"], timeout=1.0)
        final = sess.snapshot(detail=True)
        yield {"event": "done" if final["status"] == "ok" else "error",
               **final}

    def models_payload(self) -> dict:
        """Pulled models in the HF hub cache (http_api.zig:152-210),
        plus — with the pool on (ISSUE 18) — which of them are resident
        or landing in HBM right now (``resident`` rows; each disk row
        whose repo matches also gains a ``pool_state``). Knob-off keeps
        the original single-key schema."""
        from zest_tpu.models import hbm_pool
        from zest_tpu.storage import list_models

        doc: dict = {"models": list_models(self.cfg)}
        hp = hbm_pool.pool(self.cfg)
        if hp is not None:
            rows = hp.resident()
            doc["resident"] = rows
            states = {r["repo"]: r["state"] for r in rows}
            for m in doc["models"]:
                state = states.get(m.get("repo_id"))
                if state is not None:
                    m["pool_state"] = state
        return doc

    def trace_payload(self) -> dict:
        """Live tracer snapshot as Chrome trace JSON (``GET /v1/trace``)
        — the per-host piece ``zest trace --coop`` merges. Empty (with
        a note) when no tracer is armed; gathering tools treat that as
        a per-host error, not a gather failure."""
        tracer = telemetry.trace.active()
        if tracer is None:
            return {"traceEvents": [],
                    "otherData": {"tool": "zest-tpu",
                                  "note": "no tracer armed "
                                          "(set ZEST_TRACE)"}}
        return tracer.to_chrome()

    def debug_payload(self, tail: int = 100) -> dict:
        """``GET /v1/debug``: the flight-recorder tail plus the live
        coop summary the dashboard's panel renders — one JSON artifact
        replacing the old ssh-and-grep triage loop."""
        rec = telemetry.recorder.RECORDER
        payload: dict = {
            "recorder": {
                "capacity": rec.capacity,
                "recorded_total": rec.recorded,
                "events": rec.tail(tail),
            },
            "telemetry": telemetry.status_snapshot(),
        }
        ctx = telemetry.trace.current_context()
        if ctx:
            payload["trace_context"] = ctx
        fired = faults.counters()
        if fired:
            payload["faults"] = dict(sorted(fired.items()))

        tiers = {}
        for labels, value in self._metric_samples("zest_coop_bytes_total"):
            tiers[labels.get("tier", "")] = int(value)
        coop: dict = {}
        if tiers:
            peer = tiers.get("peer", 0) + tiers.get("dcn", 0)
            total = peer + tiers.get("cdn", 0) + tiers.get("fallback", 0)
            coop["tier_bytes"] = tiers
            coop["peer_served_ratio"] = (
                round(peer / total, 4) if total else None)
        wall = self._metric_samples("zest_coop_exchange_wall_seconds")
        if wall:
            coop["exchange_wall_s"] = round(wall[0][1], 3)
        for labels, value in self._metric_samples(
                "zest_coop_fallbacks_total"):
            coop["fallbacks"] = int(value)
        # Collective-exchange line (ISSUE 14): last round's phase
        # count/wall and cumulative wire bytes per link class — what
        # the dashboard coop panel and `zest stats --watch` render as
        # the "bytes moved as collectives over ICI/DCN" evidence.
        collective: dict = {}
        phases = self._metric_samples("zest_coop_collective_phases")
        if phases and phases[0][1] > 0:
            collective["phases"] = int(phases[0][1])
        cwall = self._metric_samples(
            "zest_coop_collective_wall_seconds")
        if cwall and cwall[0][1] > 0:
            collective["wall_s"] = round(cwall[0][1], 3)
        link_bytes = {}
        for labels, value in self._metric_samples(
                "zest_coop_collective_bytes_total"):
            link_bytes[labels.get("link", "")] = int(value)
        if link_bytes:
            collective["link_bytes"] = link_bytes
        for _labels, value in self._metric_samples(
                "zest_coop_collective_aborts_total"):
            collective["aborts"] = int(value)
        if collective:
            coop["collective"] = collective
        if coop:
            payload["coop"] = coop

        # Streaming-landing block (ISSUE 8): the last pull's first-layer
        # vs HBM walls — what the dashboard/`zest stats --watch` render
        # as "how soon was this model USABLE". Routed through the
        # SESSION table (ISSUE 11): the `zest_last_pull_*` process
        # gauges clobber each other under concurrent pulls, so the
        # block is read from the most recent terminal session — one
        # pull's values, internally consistent — with the gauges kept
        # only as a fallback for processes whose session table is
        # empty (e.g. metrics restored from an older daemon).
        landing = telemetry.session.last_landing() or {}
        if not landing:
            last_fl = self._metric_samples(
                "zest_last_pull_first_layer_seconds")
            if last_fl and last_fl[0][1] > 0:
                landing["first_layer_s"] = round(last_fl[0][1], 3)
            last_hbm = self._metric_samples("zest_last_pull_hbm_seconds")
            if last_hbm and last_hbm[0][1] > 0:
                landing["time_to_hbm_s"] = round(last_hbm[0][1], 3)
            if "first_layer_s" in landing and "time_to_hbm_s" in landing:
                landing["first_layer_ratio"] = round(
                    landing["first_layer_s"] / landing["time_to_hbm_s"],
                    4)
            # Per-pull gauge, not zest_land_ring_stalls_total: the
            # cumulative counter would attribute earlier pulls' stalls
            # to the last pull's first_layer/hbm walls shown beside it.
            for _labels, value in self._metric_samples(
                    "zest_last_pull_ring_stalls"):
                if value:
                    landing["ring_stalls"] = int(value)
            # Delta-pull line (ISSUE 10): the last pull's network-
            # fetched fraction (0.0 is meaningful — fully reused — so
            # the sentinel for "not a delta" is -1, not 0) and the
            # hot-swap wall.
            last_delta = self._metric_samples(
                "zest_last_pull_delta_ratio")
            if last_delta and last_delta[0][1] >= 0:
                landing["delta_ratio"] = round(last_delta[0][1], 4)
            last_swap = self._metric_samples(
                "zest_last_pull_swap_seconds")
            if last_swap and last_swap[0][1] > 0:
                landing["swap_s"] = round(last_swap[0][1], 3)
        if landing:
            payload["landing"] = landing

        health = getattr(self.swarm, "health", None) \
            if self.swarm is not None else None
        if health is not None and hasattr(health, "detail"):
            payload["quarantined_peers"] = [
                r for r in health.detail() if r["quarantined_for_s"] > 0]
        return payload

    @staticmethod
    def _metric_samples(name: str) -> list:
        for m in telemetry.REGISTRY.metrics():
            if m.name == name:
                return m.samples()
        return []

    def pod_metrics_text(self) -> str:
        """``GET /v1/metrics?scope=pod``: this host's exposition plus a
        concurrent scrape of every configured pod peer, aggregated by
        telemetry.fleet (counters summed, gauges per-host labeled,
        derived pod gauges). A peer that fails the scrape is reported
        as ``zest_pod_scrape_errors{host=...}`` instead of failing the
        whole surface — a flapping host is exactly when the operator
        needs this endpoint."""
        local_label = str(
            self.cfg.coop_index if self.cfg.coop_index is not None
            else self.cfg.mesh.process_id)
        texts = {local_label: telemetry.render_prometheus()}
        errors: dict = {}
        peers = {str(k): v for k, v in self.pod_peers.items()
                 if str(k) != local_label}
        if peers:
            def scrape(item):
                label, (host, port) = item
                url = f"http://{host}:{port}/v1/metrics"
                try:
                    with urllib.request.urlopen(url, timeout=2.0) as r:
                        return label, r.read().decode(), None
                except Exception as exc:  # noqa: BLE001 - per-host report
                    return label, None, str(exc)

            pool = fleet.scrape_pool(self.cfg.pod_scrape_workers)
            for label, text, err in pool.map(scrape, peers.items()):
                if text is not None:
                    texts[label] = text
                else:
                    errors[label] = err
        return fleet.aggregate_prometheus(texts, errors)

    def pull_events(self, repo_id: str, revision: str, device: str | None,
                    tenant: str | None = None):
        """Generator of SSE progress events for one pull.

        **Disconnect = cancel** (ISSUE 13 satellite): the generator
        owns the pull's CancelToken; when the client goes away
        mid-stream (GeneratorExit from the SSE writer) the token fires
        and the pull stops at its next stage boundary instead of
        running to completion unattended. Admission backpressure
        surfaces typed: a queue-full rejection is an ``error`` event
        carrying ``code: 429`` + ``retry_after_s``."""
        from zest_tpu.transfer.pull import pull_model

        done = threading.Event()
        events: list[dict] = []
        cond = threading.Condition()
        token = tenancy.CancelToken()

        def log(*args, **_kw):
            with cond:
                events.append({"event": "log",
                               "message": " ".join(str(a) for a in args)})
                cond.notify()

        result: dict = {}

        def work():
            try:
                res = pull_model(self.cfg, repo_id, revision=revision,
                                 device=device, swarm=self.swarm,
                                 tenant=tenant, cancel=token, log=log)
                result["ok"] = {"snapshot_dir": str(res.snapshot_dir),
                                "stats": res.stats}
            except tenancy.PullCancelled as exc:
                result["cancelled"] = str(exc)
            except tenancy.AdmissionRejected as exc:
                result["rejected"] = {"message": str(exc),
                                      "retry_after_s": exc.retry_after_s}
            except Exception as exc:  # noqa: BLE001 - reported to client
                result["error"] = str(exc)
            finally:
                done.set()
                with cond:
                    cond.notify()

        threading.Thread(target=work, daemon=True).start()
        try:
            yield {"event": "start", "repo_id": repo_id,
                   "revision": revision}
            sent = 0
            while True:
                with cond:
                    cond.wait(timeout=1.0)
                    new = events[sent:]
                    sent = len(events)
                yield from new
                if done.is_set():
                    with cond:
                        yield from events[sent:]
                    break
            if "ok" in result:
                yield {"event": "done", **result["ok"]}
            elif "cancelled" in result:
                yield {"event": "cancelled",
                       "message": result["cancelled"]}
            elif "rejected" in result:
                yield {"event": "error", "code": 429,
                       **result["rejected"]}
            else:
                yield {"event": "error",
                       "message": result.get("error", "?")}
        finally:
            # Reached on normal completion AND on GeneratorExit (the
            # SSE writer saw the client disconnect). Firing the token
            # after the pull finished is a no-op.
            if not done.is_set():
                token.cancel("client disconnected from /v1/pull stream")

    def _generator_for(self, snapshot_dir):
        """Memoized ``(model_type, generate)`` per snapshot.

        load_generator reads every tensor and compiles the decode scan —
        seconds-to-minutes a real model must not pay again per request.
        Concurrency-safe: one loader per key (latecomers wait on its
        event instead of duplicating the load, which would hold two full
        param trees at once); LRU-bounded so hot models stay resident.
        """
        from zest_tpu.models.generate import load_generator

        key = str(snapshot_dir)
        while True:
            with self._gen_lock:
                cached = self._generators.get(key)
                if cached is not None:
                    self._generators.pop(key)          # LRU: move to end
                    self._generators[key] = cached
                    return cached
                pending = self._gen_loading.get(key)
                if pending is None:
                    pending = self._gen_loading[key] = threading.Event()
                    loading = True
                else:
                    loading = False
            if not loading:
                pending.wait()
                continue  # loader finished (or failed) — re-check cache
            try:
                cached = load_generator(snapshot_dir)
                with self._gen_lock:
                    self._generators[key] = cached
                    while len(self._generators) > 4:
                        self._generators.pop(next(iter(self._generators)))
                return cached
            finally:
                with self._gen_lock:
                    self._gen_loading.pop(key, None)
                pending.set()

    def generate_events(self, repo_id: str, req: dict):
        """Generator of SSE events for one pull+decode (serving path):
        ``start`` → ``pulled`` → [``token``…] → ``done`` with output ids
        (and text when the snapshot carries a tokenizer). Decodes with
        the family's best path via models.generate.load_generator.

        With ``"stream": true`` each generated token is its own SSE
        event the moment the scan produces it — an ordered io_callback
        inside the compiled decode posts to a queue this generator
        drains (one host round-trip per token: serving UX; the
        non-streamed path stays single-dispatch)."""
        from zest_tpu.models.generate import try_tokenizer

        yield {"event": "start", "repo_id": repo_id}
        memo_key = (repo_id, req.get("revision", "main"))
        self._pin_snapshot(memo_key)
        try:
            snapshot_dir = self._pull_memo(
                repo_id, req.get("revision", "main")
            )
            yield {"event": "pulled", "snapshot_dir": str(snapshot_dir)}
            tok = try_tokenizer(snapshot_dir)
            if "ids" in req:
                prompt = [int(t) for t in req["ids"]]
            elif "prompt" in req and tok is not None:
                prompt = tok.encode(req["prompt"])
            else:
                yield {"event": "error",
                       "message": "need ids, or prompt + a tokenizer "
                                  "in the snapshot"}
                return
            model_type, generate, pool_info = self._decode_path(
                snapshot_dir, repo_id)
            top_k = req.get("top_k")
            top_p = req.get("top_p")
            kwargs = dict(
                temperature=float(req.get("temperature", 0.0)),
                top_k=None if top_k is None else int(top_k),
                top_p=None if top_p is None else float(top_p),
                seed=int(req.get("seed", 0)),
                stop_at_eos=bool(req.get("stop_at_eos", True)),
            )
            steps = int(req.get("steps", 20))
            if req.get("stream"):
                yield from self._streamed_decode(
                    generate, model_type, prompt, steps, tok, kwargs,
                    pool_info=pool_info,
                )
                return
            out = generate(prompt, steps, **kwargs)
            ev = self._done_event(model_type, out, tok)
            if pool_info:
                ev["pool"] = dict(pool_info)
            yield ev
        except Exception as exc:  # noqa: BLE001 - reported to client
            yield {"event": "error", "message": str(exc)}
        finally:
            self._unpin_snapshot(memo_key)

    def _decode_path(self, snapshot_dir, repo_id: str):
        """Route one generate to the HBM pool or the classic path.

        Returns ``(model_type, generate, pool_info)``. With the pool on
        (ISSUE 18) and a pool-served family, ``generate`` is a thin
        wrapper over ``HbmPool.generate_for`` — the pool pins the tree,
        re-lands it from the local snapshot if it was evicted
        (scale-to-zero), and starts decoding at first-layer commit; the
        TTFT/temperature facts it returns accumulate into ``pool_info``
        (a dict the caller folds into the ``done`` event as ``pool``).
        gpt2/unknown families — and ``ZEST_HBM_POOL=0`` entirely — take
        the pre-pool single-model path, ``pool_info=None``, and the
        event schema is byte-identical to before the pool existed."""
        from zest_tpu.models import hbm_pool

        pool = hbm_pool.pool(self.cfg)
        if pool is not None:
            model_type, eos_ids = hbm_pool.snapshot_meta(snapshot_dir)
            if pool.supports(model_type):
                pool_info: dict = {}

                def generate(prompt, steps, on_token=None, **kw):
                    out, info = pool.generate_for(
                        snapshot_dir, repo_id, prompt, steps,
                        on_token=on_token, **kw)
                    pool_info.update(info)
                    return out

                # _streamed_decode reads eos_ids off the callable to
                # stop token events at the first generated EOS — same
                # contract the family generate functions carry.
                generate.eos_ids = eos_ids
                return model_type, generate, pool_info
        model_type, generate = self._generator_for(snapshot_dir)
        return model_type, generate, None

    _PULL_TTL_S = 30.0

    def _pin_snapshot(self, key) -> None:
        with self._gen_lock:
            self._snapshot_pins[key] = self._snapshot_pins.get(key, 0) + 1

    def _unpin_snapshot(self, key) -> None:
        with self._gen_lock:
            n = self._snapshot_pins.get(key, 0) - 1
            if n <= 0:
                self._snapshot_pins.pop(key, None)
            else:
                self._snapshot_pins[key] = n

    def _pull_memo(self, repo_id: str, revision: str):
        """Snapshot dir for (repo, revision), memoized for a short TTL.

        pull_model is idempotent but not free: even a fully-cached pull
        re-checks revision + file listing against the hub (several HTTP
        round trips — the bulk of a warm /v1/generate request's
        latency). Serving memoizes the resolved snapshot briefly; the
        TTL bounds staleness for moving revisions (same 30 s figure as
        swarm peer discovery, reference swarm.zig:252), and a snapshot
        dir that vanished (cache eviction) is a miss regardless.

        Two safety rules (ISSUE 13 satellite — the TTL evict+insert
        race): a key PINNED by a live ``_generate_events`` never
        expires (the generator would otherwise be handed a
        ``snapshot_dir`` a concurrent re-pull of the same repo@rev is
        rewriting), and concurrent misses for one key share a single
        ``pull_model`` call (per-key singleflight) instead of racing
        two pulls over the same snapshot."""
        import time

        from zest_tpu.transfer.pull import pull_model

        key = (repo_id, revision)
        # The memo dict is shared across request-handler threads; its
        # read and its evict+insert hold the same lock the generator
        # cache uses. The pull itself runs unlocked — a slow cold pull
        # must not serialize every other request.
        while True:
            with self._gen_lock:
                hit = self._pulled.get(key)
                now = time.monotonic()
                if hit is not None and hit[0].is_dir() and (
                        hit[1] > now or self._snapshot_pins.get(key)):
                    return hit[0]
                pending = self._pull_inflight.get(key)
                if pending is None:
                    pending = self._pull_inflight[key] = threading.Event()
                    leading = True
                else:
                    leading = False
            if not leading:
                # Another request is mid-pull for this exact key: wait
                # it out, then re-read the memo it will have inserted
                # (or lead the retry if it failed).
                pending.wait()
                continue
            try:
                res = pull_model(self.cfg, repo_id, revision=revision,
                                 swarm=self.swarm,
                                 log=lambda *a, **k: None)
                # Evict expired entries on insert — except pinned keys
                # (live generators) — so a long-lived daemon serving
                # many repos doesn't grow this dict forever (the
                # generator cache above is LRU-capped for the same
                # reason).
                with self._gen_lock:
                    now = time.monotonic()
                    self._pulled = {
                        k: v for k, v in self._pulled.items()
                        if v[1] > now or self._snapshot_pins.get(k)}
                    self._pulled[key] = (res.snapshot_dir,
                                         now + self._PULL_TTL_S)
                return res.snapshot_dir
            finally:
                with self._gen_lock:
                    self._pull_inflight.pop(key, None)
                pending.set()

    @staticmethod
    def _done_event(model_type: str, out, tok) -> dict:
        payload = {"event": "done", "model_type": model_type,
                   "ids": [int(t) for t in out]}
        if tok is not None:
            payload["text"] = tok.decode(list(out))
        return payload

    def _streamed_decode(self, generate, model_type: str, prompt, steps,
                         tok, kwargs: dict, pool_info: dict | None = None):
        """Run the decode in a worker; relay its io_callback token queue
        as SSE events. Prompt prefill positions are filtered here (the
        callback reports every written position), and token events stop
        at the first generated EOS (the frozen tail repeats EOS).

        A disconnected client (GeneratorExit at a yield) sets the
        cancel flag; cancellation is cooperative — later io_callbacks
        just drop their tokens and the bounded scan runs out. (Raising
        from inside a host callback is NOT a safe abort: JAX doesn't
        define exception propagation out of callbacks on all backends —
        on TPU it can surface at an undefined point or take down the
        runtime, wedging the daemon over one impatient client.)"""
        import queue

        import numpy as np

        q: queue.Queue = queue.Queue()
        n0 = len(prompt)
        cancelled = threading.Event()

        def on_token(pos, toks):
            if cancelled.is_set():
                return  # client gone: drop; the bounded scan drains
            q.put(("tok", int(pos), int(np.asarray(toks).ravel()[0])))

        def worker():
            try:
                # generate() drains this request's token callbacks
                # before returning (per-request sentinel in
                # sampling.cached_decode_loop), so nothing can land
                # after the 'done' sentinel below.
                out = generate(prompt, steps, on_token=on_token, **kwargs)
                q.put(("done", out))
            except Exception as exc:  # noqa: BLE001 - relayed as SSE
                q.put(("error", exc))

        threading.Thread(target=worker, daemon=True,
                         name="zest-generate-stream").start()
        eos_ids = getattr(generate, "eos_ids", None)
        if not kwargs.get("stop_at_eos", True):
            eos_ids = None
        ended = False
        gen_ids: list[int] = []
        sent_text = ""
        try:
            while True:
                item = q.get()
                if item[0] == "done":
                    out = item[1]
                    break
                if item[0] == "error":
                    yield {"event": "error", "message": str(item[1])}
                    return
                _, pos, tid = item
                if pos >= n0 and not ended:
                    ev = {"event": "token", "pos": pos, "id": tid}
                    if tok is not None:
                        # Diff of full decodes, not per-token decode:
                        # BPE/sentencepiece merges and multi-byte UTF-8
                        # only render correctly in context (a lone
                        # trailing replacement char means a split byte
                        # sequence — hold it back until it completes).
                        gen_ids.append(tid)
                        full = tok.decode(gen_ids)
                        if not full.endswith("�"):
                            ev["text"] = full[len(sent_text):]
                            sent_text = full
                    yield ev
                    ended = bool(eos_ids) and tid in eos_ids
        finally:
            cancelled.set()
        ev = self._done_event(model_type, out, tok)
        if pool_info:
            # Filled in by the pool wrapper during generate(); the
            # worker finished before 'done' was queued, so it's final.
            ev["pool"] = dict(pool_info)
        yield ev


class _Handler(BaseHTTPRequestHandler):
    api: HttpApi
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # quiet; reference logs nothing per-request
        pass

    def _json(self, payload: dict, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, body: bytes, content_type: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        self.api.http_requests += 1
        url = urlparse(self.path)
        query = parse_qs(url.query)
        path = url.path
        if path == "/v1/health":
            self._json({"status": "ok"})
        elif path == "/v1/status":
            self._json(self.api.status_payload())
        elif path == "/v1/metrics":
            # Prometheus text exposition format (0.0.4) — the scrape
            # surface fleet collection points at. ``?scope=pod`` on the
            # coordinator aggregates every configured pod peer.
            if query.get("scope", [""])[0] == "pod":
                text = self.api.pod_metrics_text()
            else:
                text = telemetry.render_prometheus()
            self._text(text.encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/v1/timeline":
            try:
                since = int(query.get("since", ["0"])[0])
            except ValueError:
                since = 0
            if query.get("scope", [""])[0] == "pod":
                self._json(self.api.pod_timeline_payload())
            else:
                prefix = query.get("series", [None])[0]
                self._json(self.api.timeline_payload(since=since,
                                                     prefix=prefix))
        elif path == "/v1/trace":
            self._json(self.api.trace_payload())
        elif path == "/v1/debug":
            try:
                tail = int(query.get("tail", ["100"])[0])
            except ValueError:
                tail = 100
            self._json(self.api.debug_payload(tail=tail))
        elif path == "/v1/pulls":
            self._json(self.api.pulls_payload())
        elif path.startswith("/v1/pulls/"):
            rest = path[len("/v1/pulls/"):].strip("/")
            if rest.endswith("/events"):
                sid = rest[:-len("/events")].strip("/")
                if telemetry.session.get(sid) is None:
                    self._json({"error": "unknown session"}, 404)
                else:
                    self._begin_sse()
                    self._stream_sse(self.api.session_events(sid))
            else:
                detail = self.api.pull_detail(rest)
                if detail is None:
                    self._json({"error": "unknown session"}, 404)
                else:
                    self._json(detail)
        elif path == "/v1/remediations":
            # Self-healing control plane (ISSUE 17): the engine's
            # enable/mask/rate state, live knob overrides, per-action
            # outcome counts, and the recent decision log — what
            # ``zest heal`` renders and what the MTTR bench asserts.
            try:
                limit = int(query.get("limit", ["50"])[0])
            except ValueError:
                limit = 50
            self._json(telemetry.remediate.payload(limit=limit))
        elif path == "/v1/models":
            self._json(self.api.models_payload())
        elif path == "/":
            self._text(DASHBOARD_HTML.encode(),
                       "text/html; charset=utf-8")
        # ── Publisher endpoint surface (ISSUE 19): hub + CAS shapes
        # answered from local manifests/snapshots/xorb cache, so a
        # second node's unmodified `zest pull` can use THIS daemon as
        # its endpoint and reassemble pushed revisions. ──
        elif path.startswith("/api/models/"):
            self._hub_get(path)
        elif path.startswith("/v1/reconstructions/"):
            file_hex = path[len("/v1/reconstructions/"):].strip("/")
            doc = self.api.publisher_index().reconstruction_doc(
                file_hex, self.headers.get("Range"), self._base_url())
            if doc is None:
                self._json({"error": "unknown file"}, 404)
            elif doc == "range":
                self._json({"error": "range past EOF"}, 416)
            else:
                self._json(doc)
        elif path.startswith("/xorbs/"):
            blob = self.api.publisher_index().xorb_blob(
                path[len("/xorbs/"):].strip("/"))
            if blob is None:
                self._json({"error": "unknown xorb"}, 404)
            else:
                self._bytes_ranged(blob, self.headers.get("Range"))
        else:
            parts = path.strip("/").split("/")
            if len(parts) >= 5 and parts[2] == "resolve":
                data = self.api.publisher_index().resolve_file(
                    f"{parts[0]}/{parts[1]}", parts[3],
                    "/".join(parts[4:]))
                if data is None:
                    self._json({"error": "not found"}, 404)
                else:
                    self._bytes_ranged(data, self.headers.get("Range"))
            else:
                self._json({"error": "not found"}, 404)

    def do_POST(self) -> None:  # noqa: N802
        self.api.http_requests += 1
        if self.path == "/v1/stop":
            self._json({"status": "stopping"})
            self.api.trigger_shutdown()
        elif self.path == "/v1/pull":
            req = self._read_json_body()
            if req is None:
                return
            # Typed backpressure BEFORE the SSE stream opens (ISSUE
            # 13): a full admission queue answers a real HTTP 429 with
            # Retry-After instead of a 200 stream that errors. The
            # probe is advisory (admission re-checks atomically); the
            # race just turns a 429 into a typed in-stream error.
            ok, retry_after = tenancy.can_enqueue(self.api.cfg)
            if not ok:
                body = json.dumps({
                    "error": "admission queue full",
                    "retry_after_s": retry_after}).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After",
                                 str(int(retry_after) or 1))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self._begin_sse()
            self._stream_sse(self.api.pull_events(
                req["repo_id"], req.get("revision", "main"),
                req.get("device"), tenant=req.get("tenant"),
            ))
        elif self.path == "/v1/generate":
            req = self._read_json_body()
            if req is None:
                return
            self._begin_sse()
            self._stream_sse(self.api.generate_events(req["repo_id"], req))
        elif self.path == "/v1/watch":
            # Continuous fan-out, subscriber side (ISSUE 19). 404 when
            # ZEST_WATCH=0 — the rollback knob: pushes still land
            # locally, nobody is notified.
            if not getattr(self.api.cfg, "watch_enabled", True):
                self._json({"error": "watch disabled"}, 404)
                return
            n = int(self.headers.get("Content-Length") or 0)
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
                repos = [str(r) for r in (req.get("repos") or [])]
            except (json.JSONDecodeError, AttributeError, TypeError):
                self._json({"error": "body must be a JSON object"}, 400)
                return
            self._begin_sse()
            self._stream_sse(self.api.watch_hub.subscribe(repos=repos))
        elif self.path == "/v1/push":
            # Push notification from a local `zest push` (ISSUE 19).
            n = int(self.headers.get("Content-Length") or 0)
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(req, dict):
                    raise TypeError
            except (json.JSONDecodeError, TypeError):
                self._json({"error": "body must be a JSON object"}, 400)
                return
            try:
                self._json(self.api.push_notify(req))
            except ValueError as exc:
                self._json({"error": str(exc)}, 400)
        elif "/paths-info/" in self.path and \
                self.path.startswith("/api/models/"):
            parts = self.path[len("/api/models/"):].strip("/").split("/")
            n = int(self.headers.get("Content-Length") or 0)
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
                paths = [str(p) for p in (req.get("paths") or [])]
            except (json.JSONDecodeError, AttributeError, TypeError):
                self._json({"error": "body must be a JSON object"}, 400)
                return
            info = None
            if len(parts) >= 4 and parts[2] == "paths-info":
                info = self.api.publisher_index().paths_info(
                    f"{parts[0]}/{parts[1]}", "/".join(parts[3:]), paths)
            if info is None:
                self._json({"error": "unknown revision"}, 404)
            else:
                self._json(info)
        elif self.path == "/v1/remediations":
            # ``zest heal --dry-run on|off``: flip decision-only mode on
            # the live engine (decisions are logged and counted, no
            # action executes). Body: {"dry_run": true|false}.
            n = int(self.headers.get("Content-Length") or 0)
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
                dry = bool(req["dry_run"])
            except (json.JSONDecodeError, KeyError, TypeError,
                    ValueError):
                self._json({"error": "body must be JSON with dry_run"},
                           400)
                return
            self._json({"dry_run": telemetry.remediate.set_dry_run(dry)})
        else:
            self._json({"error": "not found"}, 404)

    def do_DELETE(self) -> None:  # noqa: N802
        self.api.http_requests += 1
        if self.path.startswith("/v1/pulls/"):
            sid = self.path[len("/v1/pulls/"):].strip("/")
            payload, code = self.api.cancel_pull(sid)
            self._json(payload, code)
        else:
            self._json({"error": "not found"}, 404)

    def _base_url(self) -> str:
        """This daemon's own URL — what fetch_info/casUrl absolutize to.
        Prefer the Host header (what the client actually dialed; a
        second node reaches us via a routable address, not loopback)."""
        host = self.headers.get("Host")
        return f"http://{host}" if host \
            else f"http://127.0.0.1:{self.api.port}"

    def _hub_get(self, path: str) -> None:
        """Hub metadata GETs: ``/api/models/{org}/{name}/revision/{rev}``
        and ``.../xet-read-token/{rev}`` (ISSUE 19 publisher surface)."""
        parts = path[len("/api/models/"):].strip("/").split("/")
        if len(parts) >= 4 and parts[2] == "revision":
            doc = self.api.publisher_index().revision_doc(
                f"{parts[0]}/{parts[1]}", "/".join(parts[3:]))
            if doc is None:
                self._json({"error": "unknown revision"}, 404)
            else:
                self._json(doc)
        elif len(parts) >= 4 and parts[2] == "xet-read-token":
            from zest_tpu.transfer.push import PUBLISHER_TOKEN

            self._json({"casUrl": self._base_url(),
                        "accessToken": PUBLISHER_TOKEN,
                        "exp": int(time.time()) + 3600})
        else:
            self._json({"error": "not found"}, 404)

    def _bytes_ranged(self, blob, range_header: str | None) -> None:
        """Serve bytes honoring an (inclusive, RFC 7233) Range header —
        206 partial, 416 past-EOF — the CAS data-plane contract the
        pull client and FixtureHub already speak."""
        total = len(blob)
        if range_header:
            try:
                spec = range_header.split("=", 1)[1]
                a_s, _, b_s = spec.partition("-")
                a = int(a_s or 0)
                b = min(int(b_s), total - 1) if b_s else total - 1
            except (IndexError, ValueError):
                a, b = 0, total - 1
            if a >= total or a > b:
                self.send_response(416)
                self.send_header("Content-Range", f"bytes */{total}")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            body = memoryview(blob)[a:b + 1]
            self.send_response(206)
            self.send_header("Content-Range", f"bytes {a}-{b}/{total}")
        else:
            body = blob
            self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json_body(self) -> dict | None:
        """JSON-object body with ``repo_id``, or None after a 400 (covers
        malformed JSON AND valid-but-non-object bodies like ``[1,2]``)."""
        n = int(self.headers.get("Content-Length") or 0)
        try:
            req = json.loads(self.rfile.read(n) or b"{}")
            req["repo_id"]
        except (json.JSONDecodeError, KeyError, TypeError):
            self._json({"error": "body must be JSON with repo_id"}, 400)
            return None
        return req

    def _begin_sse(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

    def _stream_sse(self, events) -> None:
        """Write an event generator as chunked SSE (headers sent via
        ``_begin_sse``)."""
        try:
            for ev in events:
                data = f"data: {json.dumps(ev)}\n\n".encode()
                self.wfile.write(f"{len(data):x}\r\n".encode()
                                 + data + b"\r\n")
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-work
        finally:
            # Deterministic generator finalization: a disconnected
            # client's pull generator must run its cleanup (fire the
            # cancel token) NOW, not whenever GC gets to it.
            close = getattr(events, "close", None)
            if close is not None:
                close()


DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>zest-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#101418;color:#e6e6e6}
 h1{font-size:1.3rem} .k{color:#8ab4f8} table{border-collapse:collapse}
 td,th{padding:.3rem .8rem;border-bottom:1px solid #333;text-align:left}
 .card{background:#1a2027;border-radius:8px;padding:1rem 1.4rem;margin:1rem 0;
       max-width:42rem}
 code{color:#7ee787}
</style></head><body>
<h1>zest-tpu <span id="ver" class="k"></span></h1>
<div class="card"><table id="status"></table></div>
<div class="card"><h2 style="font-size:1.05rem">Pulls</h2>
<table id="pulls"><thead><tr><th>id</th><th>repo</th><th>tenant</th>
<th>phase</th><th>progress</th><th>elapsed</th></tr></thead>
<tbody></tbody></table></div>
<div class="card"><h2 style="font-size:1.05rem">Timelines</h2>
<table id="spark"><tbody></tbody></table>
<div id="anomalies" style="font-size:.85rem;color:#f28b82"></div></div>
<div class="card"><h2 style="font-size:1.05rem">Cooperative pull</h2>
<table id="coop"></table>
<h3 style="font-size:.95rem;margin-bottom:.2rem">Flight recorder</h3>
<table id="recorder"><tbody></tbody></table></div>
<div class="card"><h2 style="font-size:1.05rem">Cached models</h2>
<table id="models"><thead><tr><th>repo</th><th>revision</th><th>files</th>
</tr></thead><tbody></tbody></table></div>
<div class="card" id="poolcard" style="display:none">
<h2 style="font-size:1.05rem">HBM pool</h2>
<div id="poolsum" style="font-size:.85rem;margin-bottom:.4rem"></div>
<table id="pool"><thead><tr><th>repo</th><th>state</th><th>bytes</th>
<th>pins</th><th>lands</th><th>gate stall</th><th>experts</th></tr></thead>
<tbody></tbody></table></div>
<script>
let tlCursor=0,tlSeries={};
async function tick(){
 try{
  const s=await (await fetch('/v1/status')).json();
  document.getElementById('ver').textContent='v'+s.version;
  const rows=Object.entries(s).filter(([k])=>k!=='version')
   .map(([k,v])=>`<tr><td class="k">${k}</td><td><code>${
     typeof v==='object'?JSON.stringify(v):v}</code></td></tr>`).join('');
  document.getElementById('status').innerHTML=rows;
  // Active-pulls panel (ISSUE 11): the live session table — running
  // pulls with phase/progress/ETA, then the most recent finished ones.
  // esc(): tenant (and repo) are free-form client-supplied strings
  // rendered via innerHTML — unescaped they'd be a stored-XSS vector
  // against the operator's dashboard session.
  const esc=v=>String(v??'').replace(/[&<>"']/g,c=>'&#'+c.charCodeAt(0)+';');
  const P=await (await fetch('/v1/pulls')).json();
  const prow=s=>{
   const pct=s.progress!=null?(s.progress*100).toFixed(0)+'%':'';
   const eta=s.eta_s!=null?' (eta '+Number(s.eta_s)+'s)':'';
   const st=s.status==='running'?s.phase:s.status;
   return `<tr><td><code>${esc(s.id)}</code></td><td>${esc(s.repo)}</td>
    <td>${esc(s.tenant||'')}</td><td class="k">${esc(st)}</td>
    <td>${pct}${eta}</td><td>${Number(s.elapsed_s)}s</td></tr>`;
  };
  document.querySelector('#pulls tbody').innerHTML=
   [...(P.active||[]),...(P.recent||[]).slice(0,4)].map(prow).join('')
   ||'<tr><td colspan="6">no pulls yet</td></tr>';
  const m=await (await fetch('/v1/models')).json();
  document.querySelector('#models tbody').innerHTML=m.models.map(x=>
   `<tr><td>${x.repo_id}${x.pool_state?' <span class="k">['
    +esc(x.pool_state)+']</span>':''}</td>
    <td><code>${(x.revision||'').slice(0,12)}</code>
    </td><td>${x.files}</td></tr>`).join('');
  // HBM pool panel (ISSUE 18): occupancy vs watermark, hit/miss/
  // eviction counters, and per-model rows (state, bytes, pins, land
  // count, gate-stall seconds, MoE expert residency).
  const HP=s.hbm_pool;
  document.getElementById('poolcard').style.display=HP?'':'none';
  if(HP){
   const MB=v=>(v/1048576).toFixed(1)+' MiB';
   document.getElementById('poolsum').textContent=
    'used '+MB(HP.used_bytes)+' ('+MB(HP.pinned_bytes)+' pinned) / '
    +(HP.budget_bytes?MB(HP.budget_bytes):'unbounded')
    +' · hits '+HP.hits+' · misses '+HP.misses
    +' · evictions '+HP.evictions+(HP.rush?' · RUSH':'');
   document.querySelector('#pool tbody').innerHTML=
    (HP.models||[]).map(r=>
     `<tr><td>${esc(r.repo)}</td><td class="k">${esc(r.state)}</td>
      <td>${MB(r.bytes)}</td><td>${r.pins}</td><td>${r.lands}</td>
      <td>${r.gate_stall_s}s</td><td>${r.experts?
       (r.experts.residency*100).toFixed(0)+'% resident':''}</td></tr>`
    ).join('')||'<tr><td colspan="7">empty</td></tr>';
  }
  // Coop panel (ISSUE 7): live peer-served ratio, per-tier bytes,
  // quarantined peers, and the flight-recorder tail from /v1/debug.
  const d=await (await fetch('/v1/debug?tail=8')).json();
  const c=d.coop||{}, crows=[];
  // Streaming-landing line (ISSUE 8): last pull's first-layer vs HBM.
  const L=d.landing||{};
  if(L.first_layer_s!=null)
   crows.push(['first_layer_s',L.first_layer_s+(L.first_layer_ratio!=null?
    ' ('+(L.first_layer_ratio*100).toFixed(0)+'% of hbm)':'')]);
  if(L.time_to_hbm_s!=null) crows.push(['time_to_hbm_s',L.time_to_hbm_s]);
  if(L.ring_stalls!=null) crows.push(['ring_stalls',L.ring_stalls]);
  // Delta line (ISSUE 10): last pull's fetched fraction + hot-swap wall.
  if(L.delta_ratio!=null)
   crows.push(['delta_fetched',(L.delta_ratio*100).toFixed(1)+'% of bytes']);
  if(L.swap_s!=null) crows.push(['time_to_swap_s',L.swap_s]);
  if(c.peer_served_ratio!=null)
   crows.push(['peer_served_ratio',(c.peer_served_ratio*100).toFixed(1)+'%']);
  for(const [t,b] of Object.entries(c.tier_bytes||{}))
   crows.push(['bytes['+t+']',b.toLocaleString()]);
  if(c.exchange_wall_s!=null)
   crows.push(['exchange_wall_s',c.exchange_wall_s]);
  // Collective-exchange line (ISSUE 14): phase count/wall + per-link
  // (ici vs dcn) wire bytes of the plan-derived all-to-all.
  const CX=c.collective||{};
  if(CX.phases!=null)
   crows.push(['collective',CX.phases+' phase(s)'
    +(CX.wall_s!=null?' in '+CX.wall_s+'s':'')
    +(CX.aborts?'; '+CX.aborts+' abort(s)':'')]);
  for(const [lk,b] of Object.entries(CX.link_bytes||{}))
   crows.push(['collective_bytes['+lk+']',b.toLocaleString()]);
  if(c.fallbacks!=null) crows.push(['fallbacks',c.fallbacks]);
  // Seeding line (ISSUE 12): upload policy at a glance — served bytes,
  // unchoked/choked split, refusals of quarantined-source content.
  const SD=s.seeding||{};
  if(SD.chunks_served!=null)
   crows.push(['seeding',SD.bytes_served.toLocaleString()+' B in '
    +SD.chunks_served+' chunks; unchoked '+SD.unchoked+'/'
    +(SD.unchoked+SD.choked)+(SD.refused_quarantined?
    '; refused '+SD.refused_quarantined:'')+(SD.rate_bps?
    '; shaped '+SD.rate_bps+' B/s':'')]);
  const q=(d.quarantined_peers||[]).map(p=>p.peer).join(', ');
  if(crows.length||q) crows.push(['quarantined',q||'none']);
  document.getElementById('coop').innerHTML=crows.map(([k,v])=>
   `<tr><td class="k">${k}</td><td><code>${v}</code></td></tr>`).join('')
   ||'<tr><td>no cooperative round yet</td></tr>';
  // Timeline sparklines (ISSUE 15): one inline-SVG polyline per live
  // series — rates (B/s) and structural gauges evolving over the ring
  // window — plus the recent anomaly list. Polled INCREMENTALLY: the
  // cursor from the last poll pages only new samples (a busy store is
  // 256 series x 512 samples — re-serializing all of it every 2 s per
  // open tab is exactly what ?since= exists to avoid); samples
  // accumulate into a client-side ring capped at 150 per series.
  // Series names come from the store (no free-form client input), but
  // esc() anyway.
  const page=await (await fetch('/v1/timeline?since='+tlCursor)).json();
  if(page.enabled!==false){
   if(page.cursor<tlCursor) tlSeries={};  // daemon/store restarted
   tlCursor=page.cursor||0;
   for(const [n,s] of Object.entries(page.series||{})){
    const row=tlSeries[n]||(tlSeries[n]={kind:s.kind,samples:[]});
    row.samples.push(...s.samples);
    if(row.samples.length>150) row.samples.splice(0,row.samples.length-150);
   }
   // Prune series that stopped producing (finished sessions' byte
   // series & co) so a long-lived tab stays bounded too.
   const names=Object.keys(tlSeries);
   if(names.length>30){
    names.sort((a,b)=>(tlSeries[a].samples.at(-1)?.[0]||0)
                     -(tlSeries[b].samples.at(-1)?.[0]||0));
    for(const n of names.slice(0,names.length-30)) delete tlSeries[n];
   }
  }
  const T={enabled:page.enabled,series:tlSeries,
           anomalies:page.anomalies||[]};
  const spark=(pts)=>{
   if(pts.length<2) return '<code>·</code>';
   const vs=pts.map(p=>p[1]),ts=pts.map(p=>p[0]);
   const [v0,v1]=[Math.min(...vs),Math.max(...vs)];
   const [t0,t1]=[Math.min(...ts),Math.max(...ts)];
   const W=140,H=22,sx=t1>t0?W/(t1-t0):0,sy=v1>v0?(H-2)/(v1-v0):0;
   const pl=pts.map(p=>((p[0]-t0)*sx).toFixed(1)+','
     +(H-1-(p[1]-v0)*sy).toFixed(1)).join(' ');
   return `<svg width="${W}" height="${H}"><polyline points="${pl}"
     fill="none" stroke="#8ab4f8" stroke-width="1.2"/></svg>`;
  };
  const fmt=v=>v>=1e9?(v/1e9).toFixed(2)+'G':v>=1e6?(v/1e6).toFixed(1)+'M'
    :v>=1e3?(v/1e3).toFixed(1)+'k':String(Math.round(v*100)/100);
  const srows=Object.entries(T.series||{}).slice(0,14).map(([n,s])=>{
   const pts=s.samples||[],last=pts.length?pts[pts.length-1][1]:0;
   return `<tr><td class="k">${esc(n)}</td><td>${spark(pts)}</td>
     <td><code>${fmt(last)}${s.kind==='rate'?'/s':''}</code></td></tr>`;
  }).join('');
  document.querySelector('#spark tbody').innerHTML=srows
   ||`<tr><td>${T.enabled===false?'timelines off (ZEST_TIMELINE=0)'
       :'no samples yet'}</td></tr>`;
  document.getElementById('anomalies').textContent=
   (T.anomalies||[]).slice(-4).map(a=>a.kind
     +(a.session?' ['+a.session+']':'')).join('  ');
  const evs=(d.recorder||{}).events||[];
  document.querySelector('#recorder tbody').innerHTML=evs.map(e=>{
   const t=new Date(e.t*1000).toISOString().slice(11,23);
   const extra=Object.entries(e).filter(([k])=>!['t','kind'].includes(k))
    .map(([k,v])=>`${k}=${v}`).join(' ');
   return `<tr><td><code>${t}</code></td><td class="k">${e.kind}</td>
    <td><code>${extra}</code></td></tr>`;
  }).join('')||'<tr><td>no events</td></tr>';
 }catch(e){}
}
tick();setInterval(tick,2000);
</script></body></html>
"""
