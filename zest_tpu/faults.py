"""Deterministic fault injection for the fetch path (the chaos harness).

The resilience layer (adaptive timeouts, retries, hedging, peer health
scoring) is only trustworthy if its failure handling is *provoked* on
demand, reproducibly. This registry injects failures at fixed points in
the waterfall — peer connect/IO, swarm chunk data, CDN GETs, DCN
channels — from one env-configurable spec:

    ZEST_FAULTS="peer_timeout:0.1,chunk_corrupt:0.05,cdn_503:0.2"
    ZEST_FAULTS_SEED=1337

Spec grammar: comma-separated ``name:prob[@arg[@arg...]]``. ``prob`` is
the firing probability in [0, 1]. Args are fault-specific and
position-free: an arg that parses as a float is the fault's numeric
parameter (e.g. ``peer_slow:1.0@2.5`` sleeps 2.5 s), any other arg is a
*scope filter* — the fault only fires at sites whose key (``host:port``
for peer-scoped faults) contains it (``chunk_corrupt:1.0@127.0.0.1:7001``
corrupts only that peer's chunks).

Registered fault names (injection sites):

==================  =====================================================
``peer_timeout``    ``BtPeer.connect`` raises ``TimeoutError`` pre-dial;
                    also fired per exchange window in the cooperative
                    round (transfer.coop — a silent owner host)
``peer_slow``       ``BtPeer.request_chunk`` sleeps *arg* seconds (1.0)
``chunk_corrupt``   swarm flips a byte in a successful peer response
``cdn_503``         ``CasClient`` GET observes an injected 503
``cdn_reset``       ``CasClient`` GET raises a connection reset
``dcn_reset``       ``DcnChannel.send_request`` dies mid-channel
``seeder_stall``    ``BtServer._respond`` sleeps *arg* seconds (2.0)
                    mid-upload — the per-request deadline must free the
                    slot WITHOUT blaming the reader (the server
                    stalled); pullers that time out on a leased peer
                    strike it as ``seed_stall``
``seeder_choke_flap``  ``_ChokeBook.slot`` reports a spurious one-query
                    choke — the requester's swarm must move on without
                    a strike and the pull must still complete
``upload_corrupt``  ``BtServer._respond`` flips a byte in the served
                    payload — the puller's verify tiers must reject it
                    (corrupt-bytes-admitted stays 0) and heal via CDN
==================  =====================================================

Determinism: each fault keeps a monotonically increasing trial counter;
trial ``n`` fires iff ``blake2b(seed:name:n)`` maps below ``prob``. The
firing *sequence* for a fault is therefore a pure function of
``(seed, name)`` — independent of wall clock, of other faults' traffic,
and of thread interleaving *across* faults (threads racing the same
fault draw disjoint trials from the same fixed sequence). Chaos tests
pin the seed, so a failure replays exactly.

Zero-cost when disabled: ``fire()`` is one global load and a ``None``
check — no parsing, no hashing, no locks on the hot path.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

from zest_tpu import telemetry

ENV_SPEC = "ZEST_FAULTS"
ENV_SEED = "ZEST_FAULTS_SEED"

# Fired-fault counts also land in the process metrics registry, so a
# chaos run can assert "the fault actually fired" from /v1/metrics (or
# stats["faults"]) instead of inferring it from downstream effects.
_M_FIRED = telemetry.counter(
    "zest_faults_fired_total", "Injected faults fired, by fault name",
    ("fault",))


class FaultSpecError(ValueError):
    """Malformed ZEST_FAULTS spec (fail loud: a typo silently disabling
    the chaos matrix would pass every test for the wrong reason)."""


class FaultSpec:
    """One parsed ``name:prob[@arg...]`` clause."""

    __slots__ = ("name", "prob", "args")

    def __init__(self, name: str, prob: float, args: tuple[str, ...] = ()):
        if not name:
            raise FaultSpecError("empty fault name")
        if not 0.0 <= prob <= 1.0:
            raise FaultSpecError(f"{name}: probability {prob} not in [0,1]")
        self.name = name
        self.prob = prob
        self.args = args

    def float_arg(self, default: float) -> float:
        """First numeric arg, or ``default``."""
        for a in self.args:
            try:
                return float(a)
            except ValueError:
                continue
        return default

    def scope(self) -> str | None:
        """First non-numeric arg: the site-key filter, if any."""
        for a in self.args:
            try:
                float(a)
            except ValueError:
                return a
        return None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        extra = "".join(f"@{a}" for a in self.args)
        return f"FaultSpec({self.name}:{self.prob}{extra})"


def parse_spec(spec: str) -> dict[str, FaultSpec]:
    out: dict[str, FaultSpec] = {}
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        name, sep, rest = clause.partition(":")
        if not sep:
            raise FaultSpecError(f"clause {clause!r} missing ':prob'")
        parts = rest.split("@")
        try:
            prob = float(parts[0])
        except ValueError as exc:
            raise FaultSpecError(
                f"clause {clause!r}: bad probability {parts[0]!r}"
            ) from exc
        out[name.strip()] = FaultSpec(
            name.strip(), prob, tuple(p for p in parts[1:] if p)
        )
    return out


class FaultInjector:
    """Seeded registry; ``roll`` is the one decision point."""

    def __init__(self, specs: dict[str, FaultSpec], seed: int = 0):
        self.specs = specs
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._trials: dict[str, int] = {}
        self.fired: dict[str, int] = {}

    def _fires(self, name: str, trial: int, prob: float) -> bool:
        digest = hashlib.blake2b(
            f"{self.seed}:{name}:{trial}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(digest, "big") / 2.0**64 < prob

    def roll(self, name: str, key: str | None = None) -> FaultSpec | None:
        """One trial of fault ``name`` at site ``key``; the spec when it
        fires, else None. Scoped faults never fire (and never consume a
        trial) at sites that don't match their filter."""
        spec = self.specs.get(name)
        if spec is None or spec.prob <= 0.0:
            return None
        scope = spec.scope()
        if scope is not None and (key is None or scope not in key):
            return None
        with self._lock:
            trial = self._trials.get(name, 0)
            self._trials[name] = trial + 1
        if not self._fires(name, trial, spec.prob):
            return None
        with self._lock:
            self.fired[name] = self.fired.get(name, 0) + 1
        _M_FIRED.inc(fault=name)
        # Flight-recorder breadcrumb (ISSUE 7): a chaos run's triage
        # needs the fault's position in the event ORDER, not just its
        # count — "dcn_reset fired, then the fallback, then the strike"
        # is the story the counters can't tell.
        telemetry.record("fault_fired", fault=name, key=key)
        return spec

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self.fired)


# ── Module-level switchboard (lazy env parse, test override) ──

_lock = threading.Lock()
_injector: FaultInjector | None = None
_resolved = False


def install(spec: str | None, seed: int | None = None) -> FaultInjector | None:
    """Install an injector directly (tests); ``spec=None`` disables."""
    global _injector, _resolved
    with _lock:
        _resolved = True
        if spec is None:
            _injector = None
        else:
            _injector = FaultInjector(
                parse_spec(spec), seed if seed is not None else 0
            )
        return _injector


def reset() -> None:
    """Back to the unresolved state: the next ``fire`` re-reads the env."""
    global _injector, _resolved
    with _lock:
        _injector = None
        _resolved = False


def active() -> FaultInjector | None:
    global _injector, _resolved
    if _resolved:
        return _injector
    with _lock:
        if not _resolved:
            spec = os.environ.get(ENV_SPEC)
            if spec:
                _injector = FaultInjector(
                    parse_spec(spec), int(os.environ.get(ENV_SEED, "0"))
                )
            _resolved = True
    return _injector


def fire(name: str, key: str | None = None) -> FaultSpec | None:
    """The hot-path hook: None when injection is disabled (the common
    case — one global read), else one deterministic trial."""
    inj = _injector
    if inj is None:
        if _resolved:
            return None
        inj = active()
        if inj is None:
            return None
    return inj.roll(name, key)


def sleep_if(name: str, key: str | None = None,
             default_s: float = 1.0) -> float:
    """Fire ``name``; on hit, sleep its numeric arg (or ``default_s``).
    Returns the seconds slept (0.0 = no fire)."""
    spec = fire(name, key)
    if spec is None:
        return 0.0
    delay = max(0.0, spec.float_arg(default_s))
    if delay:
        time.sleep(delay)
    return delay


def corrupt(data: bytes) -> bytes:
    """Deterministically corrupt a payload: XOR one mid-blob byte.

    The flip position is a pure function of the blob length, so a given
    fetch corrupts identically across runs. Empty blobs pass through."""
    if not data:
        return data
    pos = len(data) // 2
    out = bytearray(data)
    out[pos] ^= 0xFF
    return bytes(out)


def counters() -> dict[str, int]:
    inj = _injector
    return inj.counters() if inj is not None else {}
