"""zest-tpu: TPU-native P2P acceleration for ML model distribution.

A brand-new framework with the capabilities of the reference (praveer13/zest):
pull HuggingFace models by resolving files to content-addressed xorb chunks
via the Xet/CAS protocol, fetch chunks peer-first with CDN fallback, verify
everything with BLAKE3 — except the "swarm" here is a TPU pod. Pod hosts are
discovered via the JAX coordinator, bulk bytes move over ICI as collectives
and over DCN as chunk RPC, the staging cache is a sharded ``jax.Array`` in
HBM, and BLAKE3 verification runs as a Pallas kernel on-device, so
``zest pull --device=tpu`` lands checkpoints directly into a pjit mesh.

Public API (mirrors reference python/zest/__init__.py:33-66):

    import zest_tpu as zest
    zest.enable()                 # monkey-patch huggingface_hub
    path = zest.pull("openai-community/gpt2")
    zest.status(); zest.stop(); zest.disable()

Auto-enable with ``ZEST=1`` in the environment (reference __init__.py:68-73).
"""

from __future__ import annotations

import os as _os

from zest_tpu.version import __version__  # noqa: F401

_client = None
_server = None


def _get_server():
    global _server
    if _server is None:
        from zest_tpu.api.daemon import ZestServer

        _server = ZestServer()
    return _server


def _get_client():
    global _client
    if _client is None:
        from zest_tpu.api.client import ZestClient

        _client = ZestClient()
    return _client


def enable() -> None:
    """Start the local seeding daemon and patch huggingface_hub so
    ``snapshot_download`` goes through the swarm (reference __init__.py:33-43)."""
    _get_server().ensure_running()
    from zest_tpu.api import hf_backend

    hf_backend.patch_hf_hub(_get_client())


def disable() -> None:
    """Undo :func:`enable`'s monkey-patch."""
    from zest_tpu.api import hf_backend

    hf_backend.unpatch_hf_hub()


def pull(repo_id: str, revision: str = "main", device: str | None = None):
    """Download a model through the swarm; returns the snapshot directory.

    With ``device="tpu"`` the checkpoint additionally lands in a sharded HBM
    staging buffer ready for :mod:`zest_tpu.models` loading (the north-star
    path; no reference counterpart).
    """
    return _get_client().pull(repo_id, revision=revision, device=device)


def status() -> dict:
    """Daemon status via the localhost REST API (reference client.py:48-54)."""
    return _get_client().status()


def stop() -> None:
    """Stop the local daemon (reference __init__.py:59-62)."""
    _get_server().stop()


if _os.environ.get("ZEST") == "1":  # pragma: no cover - import side effect
    try:
        enable()
    except Exception:
        pass
