"""Driver benchmark: prints ONE JSON line carrying the full metric set.

Primary metric (the ``metric``/``value``/``vs_baseline`` triple) mirrors
the reference's published blake3_64kb synthetic bench (3,517 MB/s,
README.md:309-319 / DESIGN.md:645-657): BLAKE3 hashing throughput over
64 KiB chunks, run *on device* (the Pallas kernel on TPU) because that's
where the gathered pool's integrity gate runs.

``extra`` carries the BASELINE.md north-star metrics ("Targets for the
TPU-native build"):

- ``pull_gb``       — END-TO-END at GB scale: a Llama-8B-geometry bf16
  checkpoint (default 2 GB; ``ZEST_BENCH_GB`` overrides) pulled from a
  loopback hub straight into device HBM, 3 cold runs, per-stage medians
  (resolve / cas_metadata / fetch / hbm_commit / files) and a loud
  ``stable`` flag when the spread exceeds ±20% (zest_tpu.bench_scale).
- ``host_to_hbm``   — raw ``jax.device_put`` staging bandwidth swept to
  its asymptote (the upper bound for the commit stage).
- ``decode``        — KV-cached decode tok/s, whole-scan dispatch.
- ``http_warm``     — warm-request latency through the real
  ``POST /v1/generate`` HTTP path (CPU subprocess; serving overhead).
- ``ici_all_gather``— pod-axis all-gather GB/s (only with >1 device;
  the driver's chip is single-device, the virtual-mesh CI job covers it).

Every number here follows the round-3 methodology rule: either it is
measured by chained-dispatch differencing (blake3), swept to an
asymptote (host_to_hbm), medianed over repeat runs with the spread
reported and gated (pull_gb, decode, http_warm) — or it is not printed.
``ZEST_BENCH_SKIP=pull_gb,...`` skips named extras when a short run is
needed.

Methodology note: the chip sits behind a tunnel, so naive host-side
timing measures the ~67 ms round-trip, not the device. The blake3 bench
chains iterations inside one dispatch and differences N-vs-1 wall-clocks
(details in bench_blake3_device's docstring).
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

if os.environ.get("JAX_PLATFORMS"):
    # Belt-and-braces: sitecustomize imports jax (and registers the
    # axon TPU plugin) before this file runs, so the env var alone can
    # lose to the plugin at backend selection — and with the chip
    # tunnel down, axon init hangs indefinitely. Pinning the config
    # here makes `JAX_PLATFORMS=cpu python bench.py` reliably CPU.
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

BASELINE_MBPS = 3517.0  # reference blake3_64kb, ReleaseFast x86_64
CHUNK = 64 * 1024
BATCH = 512
# Chained iterations inside one dispatch. Must be deep enough that the
# summed device time (~0.45 ms/iter) dwarfs the tunnel round-trip's
# +-tens-of-ms jitter, or the N-vs-1 differencing can even go negative.
ITERS = 513


def bench_blake3_device() -> dict:
    """Device-time measurement of the Pallas BLAKE3 kernel.

    Methodology (and why rounds 1-2 under-measured by ~8x): the chip is
    reached through a relay, so ANY host-side timing of individual
    dispatches measures the ~67 ms tunnel round-trip, not the kernel —
    and repeating an identical call can be served without re-execution,
    which over-measures instead. Neither artifact can touch this method:
    N hash iterations are CHAINED inside one jitted computation (each
    iteration's input is xor-perturbed by the previous digest, a real
    data dependency, so nothing can be elided), the wall-clock of N and
    of 1 iterations are differenced to cancel the single round-trip, and
    the digest is materialized on the host to force completion.

    Roofline: per 64-byte block, 7 rounds x 8 G x 22 u32 ops (6 add,
    4 xor, 4 rotates at shift+shift+or) on 4-lane state columns
    ~= 77 u32 ops/byte. A v5e VPU (8 sublanes x 128 lanes x 4 ALUs at
    ~0.94 GHz ~= 3.9 T u32 op/s) rooflines at ~50 GB/s for that count;
    the measured 60-68 GB/s implies the compiler folds part of the
    rotate/select traffic, i.e. the kernel saturates the VPU. HBM
    traffic (~1.05 B moved per B hashed) is two orders below the HBM
    roofline — compute-bound, as a hash should be.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from zest_tpu.cas import hashing
    from zest_tpu.ops import best_hasher

    rng = np.random.default_rng(0)
    host = rng.integers(0, 256, size=(BATCH, CHUNK), dtype=np.uint8)
    words = jnp.asarray(host.view("<u4"))
    lengths = jnp.full((BATCH,), CHUNK, jnp.int32)
    hasher = best_hasher()

    # Correctness gate before timing: device digests must match the host
    # reference implementation bit-for-bit.
    got = np.asarray(hasher.hash_device(words, lengths))
    want = hashing.blake3_hash(host[0].tobytes())
    assert got[0].astype("<u4").tobytes() == want, "device BLAKE3 mismatch"

    if jax.default_backend() != "tpu":
        # No tunnel to cancel off-TPU, and the chained loop would grind
        # through interpret-mode Pallas — plain windowed timing of the
        # production hasher (the XLA lowering) is the right measure here.
        windows = []
        for _ in range(5):
            t0 = time.perf_counter()
            outs = [hasher.hash_device(words, lengths) for _ in range(8)]
            jax.block_until_ready(outs)
            windows.append((time.perf_counter() - t0) / 8)
        dt = sorted(windows)[len(windows) // 2]
        return {"mbps": round(BATCH * CHUNK / dt / 1e6, 1), "batch": BATCH,
                "method": "windowed-host-time"}

    @functools.partial(jax.jit, static_argnames=("n",))
    def chained(words, lengths, salt, n):
        def body(_i, acc):
            return hasher.hash_device(words ^ acc[0, 0] ^ salt, lengths)
        return jax.lax.fori_loop(
            0, n, body, jnp.zeros((words.shape[0], 8), jnp.uint32)
        )

    salt0 = jnp.uint32(0)
    np.asarray(chained(words, lengths, salt0, ITERS))  # compile + warm
    np.asarray(chained(words, lengths, salt0, 1))

    run = 0

    def wall(n: int) -> float:
        # Every timed dispatch gets a distinct salt: the chaining blocks
        # replay WITHIN a dispatch, the salt blocks it ACROSS repeats
        # (an identical repeated call can be served without re-executing).
        nonlocal run
        times = []
        for _ in range(5):
            run += 1
            t0 = time.perf_counter()
            np.asarray(chained(words, lengths, jnp.uint32(run), n))
            times.append(time.perf_counter() - t0)
        return min(times)

    t_n, t_1 = wall(ITERS), wall(1)
    dt = (t_n - t_1) / (ITERS - 1)
    assert dt > 0, (
        f"round-trip jitter swamped the measurement (t_{ITERS}={t_n:.3f}s "
        f"<= t_1={t_1:.3f}s); raise ITERS"
    )
    return {
        "mbps": round(BATCH * CHUNK / dt / 1e6, 1),
        "batch": BATCH,
        "chained_iters": ITERS,
        "roundtrip_ms": round(t_1 * 1e3, 1),
        "method": "chained-device-time",
    }


def bench_pull_gb() -> dict:
    """End-to-end GB-scale pull: loopback hub → CAS client → verified
    cache → HBM, at real Llama-8B tensor geometry, three cold runs with
    per-stage medians and a loud ``stable`` flag when the spread exceeds
    ±20% (zest_tpu.bench_scale). This is THE BASELINE "time-to-HBM"
    measurement; round 3's 50 MB single-shot version was noise by its
    own admission and is retired."""
    import os

    from zest_tpu.bench_scale import bench_gb_pull

    gb = float(os.environ.get("ZEST_BENCH_GB", "2.0"))
    runs = int(os.environ.get("ZEST_BENCH_GB_RUNS", "3"))
    # ZEST_BENCH_SCALE divides the geometry (smoke runs; 1 = real 8B
    # shapes — one layer is ~436 MB, so scale=1 floors near 1 GB).
    scale = int(os.environ.get("ZEST_BENCH_SCALE", "1"))
    # Wall-clock guard: on a slow chip tunnel the repeat runs are
    # dropped (never the checkpoint size) once the budget is spent —
    # one recorded GB-scale run beats a driver-window timeout with
    # none. <= 0 disables the budget (the conventional env-var "off").
    budget = float(os.environ.get("ZEST_BENCH_BUDGET_S", "1200"))
    return bench_gb_pull(gb=gb, runs=runs, scale=scale,
                         budget_s=budget if budget > 0 else None)


def bench_decode(steps: int = 64) -> dict:
    """KV-cached decode throughput (serving path): a tiny random-init
    Llama decodes ``steps`` tokens inside one jitted scan; tok/s from the
    min warm wall-clock (whole-scan dispatch, so the relay round-trip is
    amortized across all steps)."""
    import jax
    import jax.numpy as jnp

    from zest_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(n_ctx=steps + 8, n_embd=256, n_layer=4,
                                 n_head=8, n_kv_head=4, d_ff=512)
    params = llama.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    base = jnp.asarray(list(range(1, 9)), jnp.int32)

    # Salt every timed repeat via the first prompt token — an identical
    # repeated dispatch can be served without re-execution on the relay
    # (same countermeasure as the primary blake3 bench's salt).
    @jax.jit
    def fn(p, first):
        prompt = base.at[0].set(first)
        return llama.generate_cached(p, cfg, prompt, steps)

    t0 = time.perf_counter()
    np.asarray(fn(params, jnp.int32(0)))  # compile + warm
    compile_s = time.perf_counter() - t0
    times = []
    for i in range(1, 4):
        t0 = time.perf_counter()
        np.asarray(fn(params, jnp.int32(i)))
        times.append(time.perf_counter() - t0)
    dt = min(times)
    return {"tok_s": round((steps + base.shape[0]) / dt, 1),
            "steps": steps, "wall_s": round(dt, 3),
            "compile_s": round(compile_s, 1),
            "model": "llama-tiny-4L-256d-bf16"}


def bench_http_warm() -> dict:
    """Warm-request latency through the REAL ``POST /v1/generate`` HTTP
    path (serving-layer overhead: routing, pull idempotence check,
    generator cache hit, cached-jit decode dispatch, SSE framing).

    Runs in a ``JAX_PLATFORMS=cpu`` subprocess: the serving daemon's
    decode would otherwise compile through the chip relay for a model
    this small, and the number this probe defends is the serving-stack
    overhead on warm requests — the chip-side decode rate is
    ``decode.tok_s``. The first request (pull + load + compile) is
    reported separately as ``first_s``."""
    import os
    import subprocess
    import sys as _sys

    script = r"""
import json, pathlib, sys, tempfile, time
sys.path.insert(0, ".")
sys.path.insert(0, "tests")
# sitecustomize already imported jax and registered the axon plugin;
# the env var alone loses to it at backend init (which can then hang on
# a dead chip tunnel) — pin the config before anything touches devices.
import jax
jax.config.update("jax_platforms", "cpu")
import requests
from fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files
from zest_tpu.api.http_api import HttpApi
from zest_tpu.config import Config

files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
repo = FixtureRepo("bench/http-warm", files, chunks_per_xorb=4)
with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
    rootp = pathlib.Path(root)
    cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                 hf_token="hf_test", endpoint=hub.url, http_port=0)
    api = HttpApi(cfg)
    port = api.start()
    body = {"repo_id": "bench/http-warm", "ids": [1, 2, 3], "steps": 8}
    url = f"http://127.0.0.1:{port}/v1/generate"

    def request():
        t0 = time.perf_counter()
        r = requests.post(url, json=body, timeout=600, stream=True)
        events = [json.loads(l[6:]) for l in
                  r.iter_lines(decode_unicode=True) if l.startswith("data: ")]
        assert events[-1]["event"] == "done", events[-1]
        return time.perf_counter() - t0

    first = request()
    warms = [request() for _ in range(5)]
    api.close()
    print(json.dumps({"first_s": round(first, 3),
                      "warm_s": round(sorted(warms)[2], 4),
                      "warm_min_s": round(min(warms), 4)}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_TRACEBACK_FILTERING="off")
    out = subprocess.run(
        [_sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=600, cwd=str(pathlib.Path(__file__).parent),
    )
    if out.returncode != 0:
        raise RuntimeError(f"http probe failed: {out.stderr[-400:]}")
    result = json.loads(out.stdout.strip().splitlines()[-1])
    result["backend"] = "cpu-subprocess"
    return result


def bench_host_to_hbm(budget_s: float = 90.0) -> dict:
    """Raw ``jax.device_put`` staging bandwidth, swept to its asymptote.

    A single mid-size transfer is dominated by the per-dispatch relay
    round-trip (~67 ms) — exactly the mistake the blake3 methodology
    note warns about. The sweep doubles the transfer until the measured
    rate stops improving (<10% gain doubling the size twice in a row)
    or the budget runs out; the asymptotic rate is the defensible
    number, and the whole curve is reported so a reader can see where
    latency stopped mattering. Fails loudly (``"stable": false``) if
    the sweep never flattened within budget."""
    import jax

    sweep = []
    t_start = time.perf_counter()
    mbytes = 64
    prev_rate = 0.0
    flat_count = 0
    while True:
        x = np.empty(mbytes * 1024 * 1024, dtype=np.uint8)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.device_put(x).block_until_ready()
            times.append(time.perf_counter() - t0)
        dt = sorted(times)[len(times) // 2]
        rate = len(x) / dt / 1e9
        sweep.append({"mbytes": mbytes, "gbps": round(rate, 3)})
        # Plateau = the rate stopped CHANGING (|delta| < 10%), twice in
        # a row. A drop is not a plateau: two consecutive degradations
        # (e.g. the host starting to thrash) must not set stable=true
        # and crown the pre-thrash spike an asymptote.
        if prev_rate > 0 and abs(rate - prev_rate) / prev_rate < 0.10:
            flat_count += 1
            if flat_count >= 2:
                break
        else:
            flat_count = 0
        prev_rate = rate
        mbytes *= 2
        if mbytes > 4096 or time.perf_counter() - t_start > budget_s:
            break
    best = max(s["gbps"] for s in sweep)
    return {"gbps": best, "sweep": sweep, "stable": flat_count >= 2}


def bench_ici_all_gather() -> dict | None:
    import jax

    if len(jax.devices()) < 2:
        return None  # single-chip driver; the virtual-mesh CI job covers it
    from zest_tpu.bench_suite import bench_ici_all_gather as suite_bench

    r = suite_bench()
    return {"gbps": round(r.mb_per_s / 1e3, 3)}  # mb_per_s is a property


def main() -> None:
    import jax

    blake3 = bench_blake3_device()
    # The extras are far more moving parts (loopback hub, CAS client,
    # loader); a failure there must not cost the primary metric or the
    # one-JSON-line contract.
    extra = {}
    import os

    extras = [
        ("pull_gb", bench_pull_gb),
        ("host_to_hbm", bench_host_to_hbm),
        ("decode", bench_decode),
        ("http_warm", bench_http_warm),
        ("ici_all_gather", bench_ici_all_gather),
    ]
    skip = {s for s in os.environ.get("ZEST_BENCH_SKIP", "").split(",") if s}
    for name, fn in extras:
        if name in skip:
            continue
        try:
            result = fn()
        except Exception as exc:
            result = {"error": f"{type(exc).__name__}: {exc}"}
        if result is not None:
            extra[name] = result

    print(json.dumps({
        "metric": "blake3_64kb_device",
        "value": blake3["mbps"],
        "unit": "MB/s",
        "vs_baseline": round(blake3["mbps"] / BASELINE_MBPS, 3),
        "device": jax.devices()[0].platform,
        "batch": blake3["batch"],
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
