"""Driver benchmark: prints ONE JSON line.

Headline metric mirrors the reference's published blake3_64kb synthetic
bench (3,517 MB/s, README.md:309-319 / DESIGN.md:645-657): BLAKE3 hashing
throughput over 64 KiB chunks. Ours runs *on device* (the Pallas kernel
in zest_tpu.ops.blake3_pallas on TPU, the XLA lowering elsewhere) — the
integrity gate of the gathered pool — so the comparison is hash
throughput where the bytes live, not on a host core. ``vs_baseline`` is
the ratio to the reference's 3,517 MB/s.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_MBPS = 3517.0  # reference blake3_64kb, ReleaseFast x86_64
CHUNK = 64 * 1024
BATCH = 512
ITERS = 20


def main() -> None:
    import jax
    import jax.numpy as jnp

    from zest_tpu.ops import best_hasher
    from zest_tpu.cas import hashing

    rng = np.random.default_rng(0)
    host = rng.integers(0, 256, size=(BATCH, CHUNK), dtype=np.uint8)
    words = jnp.asarray(host.view("<u4"))
    lengths = jnp.full((BATCH,), CHUNK, jnp.int32)
    hasher = best_hasher()

    # Correctness gate before timing: device digests must match the host
    # reference implementation bit-for-bit.
    got = np.asarray(hasher.hash_device(words, lengths))
    want = hashing.blake3_hash(host[0].tobytes())
    assert got[0].astype("<u4").tobytes() == want, "device BLAKE3 mismatch"

    hasher.hash_device(words, lengths).block_until_ready()  # warm/compile
    # Pipelined timing: enqueue a window of iterations, block once —
    # measures device throughput rather than per-call host→device
    # round-trip latency (which dominates when the chip is reached through
    # a tunnel). Median over windows suppresses tunnel jitter.
    windows = []
    for _ in range(5):
        t0 = time.perf_counter()
        outs = [hasher.hash_device(words, lengths) for _ in range(ITERS)]
        jax.block_until_ready(outs)
        windows.append((time.perf_counter() - t0) / ITERS)
    dt = sorted(windows)[len(windows) // 2]

    mbps = BATCH * CHUNK / dt / 1e6
    print(json.dumps({
        "metric": "blake3_64kb_device",
        "value": round(mbps, 1),
        "unit": "MB/s",
        "vs_baseline": round(mbps / BASELINE_MBPS, 3),
        "device": jax.devices()[0].platform,
        "batch": BATCH,
    }))


if __name__ == "__main__":
    main()
