"""Driver benchmark: prints ONE JSON line carrying the full metric set.

Structure (new in round 5): a two-process design so the JSON line
survives ANY backend state. Round 4's lesson: the axon TPU plugin can
fail (or hang) at first device touch, and an in-process backend cannot
be un-frozen — so the measurement must live in a *child* process.

- **Supervisor** (this file run normally; never imports jax): probes
  backend init in a short-timeout subprocess, then runs the real bench
  as a child with ``ZEST_BENCH_CHILD=1``. If the TPU child fails or
  hangs, it reruns the child with ``JAX_PLATFORMS=cpu`` and records the
  TPU failure in ``tpu_error``. If even the CPU child dies, it emits a
  host-native BLAKE3 number (ctypes, no jax at all). One JSON line is
  printed in every one of those worlds — matching the reference bench's
  always-emits-JSON contract (src/bench.zig:273-287).
- **Child**: the actual measurements (below).

Primary metric (the ``metric``/``value``/``vs_baseline`` triple) mirrors
the reference's published blake3_64kb synthetic bench (3,517 MB/s,
README.md:309-319 / DESIGN.md:645-657): BLAKE3 hashing throughput over
64 KiB chunks, run *on device* (the Pallas kernel on TPU) because that's
where the gathered pool's integrity gate runs.

``extra`` carries the BASELINE.md north-star metrics ("Targets for the
TPU-native build"):

- ``pull_gb``       — END-TO-END at GB scale: a Llama-8B-geometry bf16
  checkpoint (default 2 GB; ``ZEST_BENCH_GB`` overrides) pulled from a
  loopback hub straight into device HBM, 3 cold runs (plus one untimed
  warmup), per-stage medians (resolve / cas_metadata / fetch /
  hbm_commit / files, each with wall AND busy thread-seconds — the pull
  pipelines `files` under `hbm_commit`, so walls no longer sum to the
  total), an ``overlap`` block attributing the pipelining win, and a
  loud ``stable`` flag when the spread exceeds ±20%
  (zest_tpu.bench_scale).
- ``mfu``           — model-compute efficiency: analytic flops for one
  jitted train step at real-ish geometry vs chained-dispatch device
  time; achieved TFLOP/s and fraction of chip peak.
- ``host_synthetics``— the host-side table directly comparable to the
  reference's published synthetic suite (blake3, LZ4, CDC, framing).
- ``decode_batch``  — the ISSUE-3 batch decode engine: a realistic
  frame stream through ``extract_range_into`` (native descriptor
  batches), 1-core vs N-core GB/s, ``vs_ref`` against the r05
  landing-decode 0.67 GB/s.
- ``host_to_hbm``   — raw ``jax.device_put`` staging bandwidth swept to
  its asymptote (the upper bound for the commit stage).
- ``decode``        — KV-cached decode tok/s, whole-scan dispatch.
- ``http_warm``     — warm-request latency through the real
  ``POST /v1/generate`` HTTP path (CPU subprocess; serving overhead).
- ``http_warm_device`` — the same probe with the decode on the real
  chip (TPU only): the end-to-end serving latency through the relay.
- ``ici_all_gather``— pod-axis all-gather GB/s (only with >1 device;
  the driver's chip is single-device, the virtual-mesh CI job covers it).

Every number here follows the round-3 methodology rule: either it is
measured by chained-dispatch differencing (blake3, mfu), swept to an
asymptote (host_to_hbm), medianed over repeat runs with the spread
reported and gated (pull_gb, decode, http_warm) — or it is not printed.
``ZEST_BENCH_SKIP=pull_gb,...`` skips named extras when a short run is
needed.

Methodology note: the chip sits behind a tunnel, so naive host-side
timing measures the ~67 ms round-trip, not the device. The blake3 bench
chains iterations inside one dispatch and differences N-vs-1 wall-clocks
(details in bench_blake3_device's docstring).
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

_IS_CHILD = bool(os.environ.get("ZEST_BENCH_CHILD"))

if _IS_CHILD and os.environ.get("JAX_PLATFORMS"):
    # Belt-and-braces: sitecustomize imports jax (and registers the
    # axon TPU plugin) before this file runs, so the env var alone can
    # lose to the plugin at backend selection — and with the chip
    # tunnel down, axon init hangs indefinitely. Pinning the config
    # here makes `JAX_PLATFORMS=cpu` children reliably CPU.
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

BASELINE_MBPS = 3517.0  # reference blake3_64kb, ReleaseFast x86_64
CHUNK = 64 * 1024
_SMOKE = bool(os.environ.get("ZEST_BENCH_SMOKE"))
BATCH = 8 if _SMOKE else 512
# Chained iterations inside one dispatch. Must be deep enough that the
# summed device time (~0.45 ms/iter) dwarfs the tunnel round-trip's
# +-tens-of-ms jitter, or the N-vs-1 differencing can even go negative.
ITERS = 9 if _SMOKE else 513


# --------------------------------------------------------------------
# Child-side measurements
# --------------------------------------------------------------------


def bench_blake3_device() -> dict:
    """Device-time measurement of the Pallas BLAKE3 kernel.

    Methodology (and why rounds 1-2 under-measured by ~8x): the chip is
    reached through a relay, so ANY host-side timing of individual
    dispatches measures the ~67 ms tunnel round-trip, not the kernel —
    and repeating an identical call can be served without re-execution,
    which over-measures instead. Neither artifact can touch this method:
    N hash iterations are CHAINED inside one jitted computation (each
    iteration's input is xor-perturbed by the previous digest, a real
    data dependency, so nothing can be elided), the wall-clock of N and
    of 1 iterations are differenced to cancel the single round-trip, and
    the digest is materialized on the host to force completion.

    Roofline: per 64-byte block, 7 rounds x 8 G x 22 u32 ops (6 add,
    4 xor, 4 rotates at shift+shift+or) on 4-lane state columns
    ~= 77 u32 ops/byte. A v5e VPU (8 sublanes x 128 lanes x 4 ALUs at
    ~0.94 GHz ~= 3.9 T u32 op/s) rooflines at ~50 GB/s for that count;
    the measured 60-68 GB/s implies the compiler folds part of the
    rotate/select traffic, i.e. the kernel saturates the VPU. HBM
    traffic (~1.05 B moved per B hashed) is two orders below the HBM
    roofline — compute-bound, as a hash should be.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from zest_tpu.cas import hashing
    from zest_tpu.ops import best_hasher

    rng = np.random.default_rng(0)
    host = rng.integers(0, 256, size=(BATCH, CHUNK), dtype=np.uint8)
    words = jnp.asarray(host.view("<u4"))
    lengths = jnp.full((BATCH,), CHUNK, jnp.int32)
    hasher = best_hasher()

    # Correctness gate before timing: device digests must match the host
    # reference implementation bit-for-bit.
    got = np.asarray(hasher.hash_device(words, lengths))
    want = hashing.blake3_hash(host[0].tobytes())
    assert got[0].astype("<u4").tobytes() == want, "device BLAKE3 mismatch"

    if jax.default_backend() != "tpu":
        # No tunnel to cancel off-TPU, and the chained loop would grind
        # through interpret-mode Pallas — plain windowed timing of the
        # production hasher (the XLA lowering) is the right measure here.
        reps = 2 if _SMOKE else 8
        windows = []
        for _ in range(2 if _SMOKE else 5):
            t0 = time.perf_counter()
            outs = [hasher.hash_device(words, lengths) for _ in range(reps)]
            jax.block_until_ready(outs)
            windows.append((time.perf_counter() - t0) / reps)
        dt = sorted(windows)[len(windows) // 2]
        return {"mbps": round(BATCH * CHUNK / dt / 1e6, 1), "batch": BATCH,
                "method": "windowed-host-time"}

    @functools.partial(jax.jit, static_argnames=("n",))
    def chained(words, lengths, salt, n):
        def body(_i, acc):
            return hasher.hash_device(words ^ acc[0, 0] ^ salt, lengths)
        return jax.lax.fori_loop(
            0, n, body, jnp.zeros((words.shape[0], 8), jnp.uint32)
        )

    salt0 = jnp.uint32(0)
    np.asarray(chained(words, lengths, salt0, ITERS))  # compile + warm
    np.asarray(chained(words, lengths, salt0, 1))

    run = 0

    def wall(n: int) -> float:
        # Every timed dispatch gets a distinct salt: the chaining blocks
        # replay WITHIN a dispatch, the salt blocks it ACROSS repeats
        # (an identical repeated call can be served without re-executing).
        nonlocal run
        times = []
        for _ in range(5):
            run += 1
            t0 = time.perf_counter()
            np.asarray(chained(words, lengths, jnp.uint32(run), n))
            times.append(time.perf_counter() - t0)
        return min(times)

    t_n, t_1 = wall(ITERS), wall(1)
    dt = (t_n - t_1) / (ITERS - 1)
    assert dt > 0, (
        f"round-trip jitter swamped the measurement (t_{ITERS}={t_n:.3f}s "
        f"<= t_1={t_1:.3f}s); raise ITERS"
    )
    return {
        "mbps": round(BATCH * CHUNK / dt / 1e6, 1),
        "batch": BATCH,
        "chained_iters": ITERS,
        "roundtrip_ms": round(t_1 * 1e3, 1),
        "method": "chained-device-time",
    }


def host_blake3_fallback() -> dict:
    """Last-ditch primary metric: host BLAKE3 throughput via the native
    C++ library (ctypes — no jax anywhere on this path). Used only when
    the device measurement is impossible; the ``method`` field makes the
    substitution impossible to miss."""
    from zest_tpu.cas import hashing
    from zest_tpu.native import lib as native

    batch = BATCH if native.available() else 2
    data = np.random.default_rng(0).integers(
        0, 256, size=batch * CHUNK, dtype=np.uint8).tobytes()
    if native.available():
        def fn():
            return native.blake3_batch(data, batch, CHUNK)
    else:  # pure-Python fallback: measure far less data
        def fn():
            return [hashing.blake3_hash(data[i * CHUNK:(i + 1) * CHUNK])
                    for i in range(batch)]
    fn()  # warm (lib load)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    dt = min(times)
    return {"mbps": round(len(data) / dt / 1e6, 1), "batch": batch,
            "method": "host-native" if native.available() else "host-python"}


# TPU bf16 peak TFLOP/s per chip, by device_kind substring (ordered:
# first match wins; v5e reports itself as "TPU v5 lite" on some stacks).
_TPU_PEAKS = [
    ("v6", 918e12), ("v5p", 459e12), ("v5e", 197e12), ("v5 lit", 197e12),
    ("v5", 459e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def bench_mfu() -> dict:
    """Model FLOP utilization of one jitted Llama train step.

    The single-chip compute-efficiency number the model planes are
    judged on: analytic matmul flops for one fwd+bwd+SGD step divided by
    chained-dispatch device time, as a fraction of the chip's bf16 peak.

    Geometry: a ~0.7B-param Llama (2048 hidden / 7168 FFN / 12 layers,
    GQA 16:8, vocab cut to 8192 so init and the embedding don't dominate
    a 12-layer model) at batch 4 x 1024 tokens — large enough that every
    matmul tiles the MXU ((1024x4)x2048x7168 GEMMs), small enough to
    init over the relay in seconds. bf16 params, f32 softmax/CE (the
    production layout, models/llama.py).

    Flop accounting (per token, per layer, causal factor 0.5 on
    attention, x3 for fwd+bwd): qkvo 4h(h+kv) + mlp 6*h*ffn + attn
    2*T*h_attn; plus the lm_head 2*h*V. No remat (flops counted once).

    Timing is the blake3 methodology: N steps chained in a fori_loop
    with the params carried (a real dependency — step i+1 consumes step
    i's updated params, nothing can be elided), N-vs-1 differenced to
    cancel the relay round-trip, batch salted per dispatch to block
    replay serving."""
    import functools

    import jax
    import jax.numpy as jnp

    from zest_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = llama.LlamaConfig(
            vocab_size=8192, n_ctx=1024, n_embd=2048, n_layer=12,
            n_head=16, n_kv_head=8, d_ff=7168, rope_scaling_factor=None)
        B, T, n_steps = 4, 1024, 8
    else:  # keep the machinery testable where there is no MXU to fill
        cfg = llama.LlamaConfig.tiny(vocab_size=512, n_ctx=128, n_embd=128,
                                     n_layer=2, n_head=4, n_kv_head=2,
                                     d_ff=256)
        B, T, n_steps = 2, 128, 2

    h, ffn, L, V = cfg.n_embd, cfg.d_ff, cfg.n_layer, cfg.vocab_size
    head_dim = cfg.head_dim_override or h // cfg.n_head
    h_attn = cfg.n_head * head_dim
    kv_dim = cfg.n_kv_head * head_dim
    per_token = L * (4 * h * (h + kv_dim) + 6 * h * ffn
                     + 2 * T * h_attn) + 2 * h * V
    step_flops = 3 * B * T * per_token  # fwd + bwd(2x)
    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree.leaves(
                       jax.eval_shape(lambda: llama.init_params(
                           jax.random.key(0), cfg, dtype=jnp.bfloat16))))

    params = llama.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    tokens = jax.random.randint(jax.random.key(1), (B, T + 1), 0, V,
                                dtype=jnp.int32)

    @functools.partial(jax.jit, static_argnames=("n",))
    def chained(params, tokens, salt, n):
        def body(i, p):
            batch = (tokens + salt + i) % V
            p2, _ = llama.train_step(p, batch, cfg)
            return p2
        return jax.lax.fori_loop(0, n, body, params)

    t0 = time.perf_counter()
    jax.block_until_ready(chained(params, tokens, jnp.int32(0), n_steps))
    compile_s = time.perf_counter() - t0
    jax.block_until_ready(chained(params, tokens, jnp.int32(0), 1))

    run = 0

    def wall(n: int) -> float:
        nonlocal run
        times = []
        for _ in range(3):
            run += 1
            t0 = time.perf_counter()
            jax.block_until_ready(
                chained(params, tokens, jnp.int32(run), n))
            times.append(time.perf_counter() - t0)
        return min(times)

    t_n, t_1 = wall(n_steps), wall(1)
    dt = (t_n - t_1) / (n_steps - 1) if n_steps > 1 else t_n
    if dt <= 0:
        return {"error": f"jitter swamped the differencing "
                         f"(t_{n_steps}={t_n:.3f}s <= t_1={t_1:.3f}s)"}
    tflops = step_flops / dt / 1e12
    out = {
        "tflops": round(tflops, 2),
        "step_s": round(dt, 4),
        "step_flops_g": round(step_flops / 1e9, 1),
        "params_m": round(n_params / 1e6, 1),
        "geometry": f"llama-{L}L-{h}d-ffn{ffn}-B{B}xT{T}-bf16",
        "compile_s": round(compile_s, 1),
        # Both backends run the same chained N-vs-1 differencing (on CPU
        # the round-trip being cancelled is just ~0).
        "method": "chained-device-time",
    }
    if on_tpu:
        kind = jax.devices()[0].device_kind.lower()
        peak = next((p for sub, p in _TPU_PEAKS if sub in kind), None)
        out["device_kind"] = jax.devices()[0].device_kind
        if peak:
            out["mfu"] = round(step_flops / dt / peak, 4)
            out["peak_tflops"] = round(peak / 1e12, 0)
    return out


def bench_host_synthetics() -> dict:
    """The host-side synthetic table, directly comparable row-for-row to
    the reference's published suite (README.md:309-319 / BASELINE.md):
    bencode encode/decode, blake3_64kb, sha1_info_hash, bt_wire_frame —
    plus the TPU build's own host hot paths (SIMD batched BLAKE3, LZ4
    codec, CDC scan, native 64 KiB framing) so every SCALING.md claim is
    a recorded artifact, not prose. ``vs_ref`` divides by the
    reference's number where one exists."""
    from zest_tpu import bench_suite
    from zest_tpu.native import lib as native

    ref = {"bencode_encode": 206.0, "bencode_decode": 324.0,
           "blake3_64kb": 3517.0, "sha1_info_hash": 755.0,
           "bt_wire_frame": 11943.0, "bt_wire_frame_pure": 11943.0}
    iters_scale = 0.1 if _SMOKE else 1.0

    def scaled(n: int) -> int:
        return max(2, int(n * iters_scale))

    results: dict[str, dict] = {}

    def record(res, rename: dict | None = None) -> None:
        for r in (res if isinstance(res, list) else [res]):
            name = (rename or {}).get(r.name, r.name)
            row = {"mb_per_s": round(r.mb_per_s, 1),
                   "median_ns": round(r.median_ns, 1)}
            best = r.best_mb_per_s
            if best is not None:
                row["best_mb_per_s"] = round(best, 1)
            if name in ref:
                row["vs_ref"] = round(r.mb_per_s / ref[name], 2)
                if best is not None:
                    row["best_vs_ref"] = round(best / ref[name], 2)
            results[name] = row

    # Wire-framing headline (VERDICT r5 item 7): the row named
    # ``bt_wire_frame`` — the one compared against the reference's
    # 11,943 MB/s — is the NATIVE framing path (native/wire.cc), the
    # framing production serving actually runs. The pure-Python
    # roundtrip stays recorded as ``bt_wire_frame_pure`` (the fallback
    # anchor), so a missing native lib shows up as a missing headline
    # row, never as a silently slow headline.
    benches = [
        ("bencode", lambda: bench_suite.bench_bencode(iters=scaled(2000)),
         None),
        ("blake3_host", lambda: bench_suite.bench_blake3_host(
            iters=scaled(200)), None),
        ("sha1_info_hash", lambda: bench_suite.bench_sha1_info_hash(
            iters=scaled(5000)), None),
        ("wire_frame", lambda: bench_suite.bench_wire_frame(
            iters=scaled(5000)), {"bt_wire_frame": "bt_wire_frame_pure"}),
        ("wire_frame_native", lambda: bench_suite.bench_wire_frame_native(
            iters=scaled(2000)), {"xet_frame_64kb": "bt_wire_frame"}),
        ("gearhash_cdc", lambda: bench_suite.bench_gearhash_cdc(
            iters=scaled(20)), None),
    ]
    for name, fn, rename in benches:
        try:
            record(fn(), rename)
        except Exception as exc:
            results.setdefault("errors", {})[name] = (
                f"{type(exc).__name__}: {exc}")

    if native.available():
        # The SIMD multi-chunk path (fold8/fold16 parents) that the GB
        # fetch stage rides — SCALING.md's "4-5 GB/s host BLAKE3" claim.
        n = 64 if _SMOKE else 1024
        data = np.random.default_rng(7).integers(
            0, 256, size=n * CHUNK, dtype=np.uint8).tobytes()
        record(bench_suite._time_fn(
            "blake3_64kb_batch", lambda: native.blake3_batch(data, n, CHUNK),
            len(data), iters=2, repeats=3))

        blob = np.random.default_rng(8).integers(
            0, 256, size=1024 * 1024, dtype=np.uint8).tobytes()
        comp = native.lz4_compress(blob)
        record(bench_suite._time_fn(
            "lz4_encode_1mb_random", lambda: native.lz4_compress(blob),
            len(blob), iters=scaled(20)))
        record(bench_suite._time_fn(
            "lz4_decode_1mb_random",
            lambda: native.lz4_decompress(comp, len(blob)),
            len(blob), iters=scaled(20)))
        text = (b"the quick brown fox jumps over the lazy dog. " * 32768
                )[:1024 * 1024]
        record(bench_suite._time_fn(
            "lz4_encode_1mb_text", lambda: native.lz4_compress(text),
            len(text), iters=scaled(20)))
    results["native"] = native.available()
    return results


def bench_decode_batch() -> dict:
    """Host batch-decode synthetic (ISSUE 3 acceptance): a realistic
    frame stream — mostly stored bf16-like chunks with a compressible
    BG4/LZ4 tail — decoded through ``XorbReader.extract_range_into``
    (i.e. the native descriptor-batch engine when built), 1-core vs
    N-core. ``vs_ref`` divides by the r05 landing-decode figure
    (0.67 GB/s, SCALING.md §2 — the single-scalar-core wall this engine
    exists to break)."""
    from zest_tpu.cas.xorb import XorbBuilder, XorbReader
    from zest_tpu.models.direct import resolve_decode_workers

    ref_gbps = 0.67
    rng = np.random.default_rng(11)
    builder = XorbBuilder()
    chunk = 64 * 1024
    n_chunks = 24 if _SMOKE else 512  # 32 MiB uncompressed at full size
    for i in range(n_chunks):
        if i % 8 == 7:
            # Compressible planar-friendly chunk → BG4/LZ4 scheme.
            base = np.repeat(
                rng.integers(0, 256, chunk // 4, dtype=np.uint8), 4)
            builder.add_chunk(bytes(base))
        else:
            # Incompressible (bf16 weights) → stored.
            builder.add_chunk(
                bytes(rng.integers(0, 256, chunk, dtype=np.uint8)))
    blob = builder.serialize()
    reader = XorbReader(blob)
    total = builder.uncompressed_total
    out = bytearray(total)
    workers = resolve_decode_workers(None)
    reps = 2 if _SMOKE else 8

    def measure(w: int) -> float:
        reader.extract_range_into(0, len(reader), out, workers=w)  # warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(reps):
                reader.extract_range_into(0, len(reader), out, workers=w)
            times.append((time.perf_counter() - t0) / reps)
        return total / min(times) / 1e9

    from zest_tpu.cas.compression import native_batch_available

    gbps_1 = measure(1)
    gbps_n = measure(workers) if workers > 1 else gbps_1
    return {
        "gbps_1core": round(gbps_1, 3),
        "gbps_multicore": round(gbps_n, 3),
        "workers": workers,
        "bytes": total,
        "native": native_batch_available(),
        "vs_ref": round(gbps_n / ref_gbps, 2),
        "ref_gbps": ref_gbps,
    }


def bench_pull_gb() -> dict:
    """End-to-end GB-scale pull: loopback hub → CAS client → verified
    cache → HBM, at real Llama-8B tensor geometry, three cold runs with
    per-stage medians and a loud ``stable`` flag when the spread exceeds
    ±20% (zest_tpu.bench_scale). This is THE BASELINE "time-to-HBM"
    measurement; round 3's 50 MB single-shot version was noise by its
    own admission and is retired.

    Page-cache split: every timed run is preceded by a ``sync()`` so
    the prior run's writeback can't bleed into it; set
    ``ZEST_BENCH_DROP_CACHES=1`` (needs root) for the fully cold-IO
    mode — the achieved mode is recorded under ``pull_gb.page_cache``."""
    from zest_tpu.bench_scale import bench_gb_pull

    gb = float(os.environ.get("ZEST_BENCH_GB", "2.0"))
    runs = int(os.environ.get("ZEST_BENCH_GB_RUNS", "3"))
    # ZEST_BENCH_SCALE divides the geometry (smoke runs; 1 = real 8B
    # shapes — one layer is ~436 MB, so scale=1 floors near 1 GB).
    # Default 2 since ISSUE 8: at 2 GB, scale=1 is a DEGENERATE
    # checkpoint (two ~1 GB embeddings + ONE layer) whose
    # first_layer_ratio is structurally ~0.5 — scale=2 gives the
    # fixture real depth (~14 layers), the shape the streaming
    # headline is measuring.
    scale = int(os.environ.get("ZEST_BENCH_SCALE", "2"))
    # Wall-clock guard: on a slow chip tunnel the repeat runs are
    # dropped (never the checkpoint size) once the budget is spent —
    # one recorded GB-scale run beats a driver-window timeout with
    # none. <= 0 disables the budget (the conventional env-var "off").
    budget = float(os.environ.get("ZEST_BENCH_BUDGET_S", "1200"))
    return bench_gb_pull(gb=gb, runs=runs, scale=scale,
                         budget_s=budget if budget > 0 else None)


def bench_delta_pull() -> dict:
    """Delta pull vs cold pull (ISSUE 10): cold rev-A ``--device`` pull,
    then an in-place hot-swap delta pull of the seeded 1%-changed
    revision B. Headlines: ``delta_bytes_ratio`` (network-fetched
    fraction, ≤3% gate), ``time_to_swap_s`` vs the cold median (≤0.3×
    gate), ``digest_identical`` vs a cold pull of B. Shares pull_gb's
    size/scale knobs; its own run count defaults lower — each run is
    two full pulls plus a one-time digest-oracle third."""
    from zest_tpu.bench_scale import bench_delta_pull as run

    gb = float(os.environ.get("ZEST_BENCH_GB", "2.0"))
    runs = int(os.environ.get("ZEST_BENCH_DELTA_RUNS", "2"))
    scale = int(os.environ.get("ZEST_BENCH_SCALE", "2"))
    budget = float(os.environ.get("ZEST_BENCH_BUDGET_S", "1200"))
    return run(gb=gb, runs=runs, scale=scale,
               budget_s=budget if budget > 0 else None)


def bench_decode(steps: int = 64) -> dict:
    """KV-cached decode throughput (serving path): a tiny random-init
    Llama decodes ``steps`` tokens inside one jitted scan; tok/s from the
    min warm wall-clock (whole-scan dispatch, so the relay round-trip is
    amortized across all steps)."""
    import jax
    import jax.numpy as jnp

    from zest_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(n_ctx=steps + 8, n_embd=256, n_layer=4,
                                 n_head=8, n_kv_head=4, d_ff=512)
    params = llama.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    base = jnp.asarray(list(range(1, 9)), jnp.int32)

    # Salt every timed repeat via the first prompt token — an identical
    # repeated dispatch can be served without re-execution on the relay
    # (same countermeasure as the primary blake3 bench's salt).
    @jax.jit
    def fn(p, first):
        prompt = base.at[0].set(first)
        return llama.generate_cached(p, cfg, prompt, steps)

    t0 = time.perf_counter()
    np.asarray(fn(params, jnp.int32(0)))  # compile + warm
    compile_s = time.perf_counter() - t0
    times = []
    for i in range(1, 4):
        t0 = time.perf_counter()
        np.asarray(fn(params, jnp.int32(i)))
        times.append(time.perf_counter() - t0)
    dt = min(times)
    return {"tok_s": round((steps + base.shape[0]) / dt, 1),
            "steps": steps, "wall_s": round(dt, 3),
            "compile_s": round(compile_s, 1),
            "model": "llama-tiny-4L-256d-bf16"}


def bench_http_warm() -> dict:
    """Warm-request latency through the REAL ``POST /v1/generate`` HTTP
    path (serving-layer overhead: routing, pull idempotence check,
    generator cache hit, cached-jit decode dispatch, SSE framing).

    Runs in a ``JAX_PLATFORMS=cpu`` subprocess: the serving daemon's
    decode would otherwise compile through the chip relay for a model
    this small, and the number this probe defends is the serving-stack
    overhead on warm requests — the chip-side decode rate is
    ``decode.tok_s``. The first request (pull + load + compile) is
    reported separately as ``first_s``."""
    script = r"""
import json, pathlib, sys, tempfile, time
sys.path.insert(0, ".")
sys.path.insert(0, "tests")
# sitecustomize already imported jax and registered the axon plugin;
# the env var alone loses to it at backend init (which can then hang on
# a dead chip tunnel) — pin the config before anything touches devices.
import jax
jax.config.update("jax_platforms", "cpu")
import requests
from fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files
from zest_tpu.api.http_api import HttpApi
from zest_tpu.config import Config

files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
repo = FixtureRepo("bench/http-warm", files, chunks_per_xorb=4)
with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
    rootp = pathlib.Path(root)
    cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                 hf_token="hf_test", endpoint=hub.url, http_port=0)
    api = HttpApi(cfg)
    port = api.start()
    body = {"repo_id": "bench/http-warm", "ids": [1, 2, 3], "steps": 8}
    url = f"http://127.0.0.1:{port}/v1/generate"

    def request():
        t0 = time.perf_counter()
        r = requests.post(url, json=body, timeout=600, stream=True)
        events = [json.loads(l[6:]) for l in
                  r.iter_lines(decode_unicode=True) if l.startswith("data: ")]
        assert events[-1]["event"] == "done", events[-1]
        return time.perf_counter() - t0

    first = request()
    warms = [request() for _ in range(5)]
    api.close()
    print(json.dumps({"first_s": round(first, 3),
                      "warm_s": round(sorted(warms)[2], 4),
                      "warm_min_s": round(min(warms), 4)}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_TRACEBACK_FILTERING="off")
    env.pop("ZEST_BENCH_CHILD", None)
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=600, cwd=str(pathlib.Path(__file__).parent),
    )
    if out.returncode != 0:
        raise RuntimeError(f"http probe failed: {out.stderr[-400:]}")
    result = json.loads(out.stdout.strip().splitlines()[-1])
    result["backend"] = "cpu-subprocess"
    return result


def bench_http_warm_device() -> dict | None:
    """Warm-request latency through ``POST /v1/generate`` with the
    decode on the REAL chip — the end-to-end serving latency a user
    sees: HTTP routing, memoized pull, generator-cache hit, cached-jit
    dispatch through the ~67 ms relay, SSE framing. Chip-only (returns
    None elsewhere); the serving-stack-overhead-only number is
    ``http_warm`` (CPU subprocess). Each request's prompt differs so a
    repeat can't be served by relay replay without executing."""
    import tempfile

    import jax

    if jax.default_backend() != "tpu":
        return None
    import requests

    tests_dir = str(pathlib.Path(__file__).resolve().parent / "tests")
    sys.path.insert(0, tests_dir)
    try:
        from fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files
    finally:
        try:
            sys.path.remove(tests_dir)
        except ValueError:
            pass
    from zest_tpu.api.http_api import HttpApi
    from zest_tpu.config import Config

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("bench/http-warm-tpu", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                     hf_token="hf_test", endpoint=hub.url, http_port=0)
        api = HttpApi(cfg)
        try:
            port = api.start()
            url = f"http://127.0.0.1:{port}/v1/generate"

            def request(i: int) -> float:
                body = {"repo_id": "bench/http-warm-tpu",
                        "ids": [1, 2, 3 + i], "steps": 8}
                t0 = time.perf_counter()
                r = requests.post(url, json=body, timeout=600, stream=True)
                events = [json.loads(l[6:]) for l in
                          r.iter_lines(decode_unicode=True)
                          if l.startswith("data: ")]
                assert events[-1]["event"] == "done", events[-1]
                return time.perf_counter() - t0

            first = request(0)  # pull + load + compile through the relay
            warms = [request(i) for i in range(1, 6)]
        finally:
            api.close()
    return {"first_s": round(first, 3),
            "warm_s": round(sorted(warms)[2], 4),
            "warm_min_s": round(min(warms), 4),
            "backend": "tpu-in-process"}


def bench_host_to_hbm(budget_s: float = 90.0) -> dict:
    """Raw ``jax.device_put`` staging bandwidth, swept to its asymptote.

    A single mid-size transfer is dominated by the per-dispatch relay
    round-trip (~67 ms) — exactly the mistake the blake3 methodology
    note warns about. The sweep doubles the transfer until the measured
    rate stops improving (<10% gain doubling the size twice in a row)
    or the budget runs out; the asymptotic rate is the defensible
    number, and the whole curve is reported so a reader can see where
    latency stopped mattering. Fails loudly (``"stable": false``) if
    the sweep never flattened within budget.

    Sizes ≥ 256 MiB also measure a second lane (``gbps_batched``): the
    same bytes as 64 MiB pieces through the loader's coalesced/donated
    ``device_put`` batch (``models.loader.commit_tensors`` — ONE
    batched dispatch, the PR-8 commit path). The recorded sweeps
    regress from 1.89 to 1.39 GB/s exactly past 256 MiB, where a
    single monolithic transfer stops pipelining; the batched lane is
    the landing's answer, so the artifact records both (ISSUE 20)."""
    import jax

    # Never allocate beyond a quarter of currently-available host RAM
    # (each step needs the host array PLUS its device copy), and never
    # beyond 4 GiB — checked BEFORE the allocation, so the sweep
    # reports stable:false instead of OOMing the host.
    try:
        avail = (os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE"))
        # Clamped to one 64 MiB first step so the sweep always records
        # at least a point (an empty sweep would crash the max() below).
        cap_mbytes = max(64, min(4096, avail // 4 // (1024 * 1024)))
    except (ValueError, OSError):  # pragma: no cover - sysconf missing
        cap_mbytes = 1024

    sweep = []
    t_start = time.perf_counter()
    mbytes = 64
    prev_rate = 0.0
    flat_count = 0
    while mbytes <= cap_mbytes:
        x = np.empty(mbytes * 1024 * 1024, dtype=np.uint8)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.device_put(x).block_until_ready()
            times.append(time.perf_counter() - t0)
        dt = sorted(times)[len(times) // 2]
        rate = len(x) / dt / 1e9
        entry = {"mbytes": mbytes, "gbps": round(rate, 3)}
        if mbytes >= 256:
            from zest_tpu.models.loader import commit_tensors

            piece = 64 * 1024 * 1024
            views = {f"t{k}": x[k * piece:(k + 1) * piece]
                     for k in range(len(x) // piece)}
            times_b = []
            for _ in range(3):
                t0 = time.perf_counter()
                committed = commit_tensors(views, donate=True)
                for a in committed.values():
                    a.block_until_ready()
                times_b.append(time.perf_counter() - t0)
            del committed
            dt_b = sorted(times_b)[len(times_b) // 2]
            entry["gbps_batched"] = round(len(x) / dt_b / 1e9, 3)
        sweep.append(entry)
        # Plateau = the rate stopped CHANGING (|delta| < 10%), twice in
        # a row. A drop is not a plateau: two consecutive degradations
        # (e.g. the host starting to thrash) must not set stable=true
        # and crown the pre-thrash spike an asymptote.
        if prev_rate > 0 and abs(rate - prev_rate) / prev_rate < 0.10:
            flat_count += 1
            if flat_count >= 2:
                break
        else:
            flat_count = 0
        prev_rate = rate
        mbytes *= 2
        if time.perf_counter() - t_start > budget_s:
            break
    best = max(s["gbps"] for s in sweep)
    out = {"gbps": best, "sweep": sweep, "stable": flat_count >= 2}
    batched = [s["gbps_batched"] for s in sweep if "gbps_batched" in s]
    if batched:
        out["gbps_batched"] = max(batched)
    return out


def bench_ici_all_gather() -> dict | None:
    import jax

    if len(jax.devices()) < 2:
        return None  # single-chip driver; the virtual-mesh CI job covers it
    from zest_tpu.bench_suite import bench_ici_all_gather as suite_bench

    r = suite_bench()
    return {"gbps": round(r.mb_per_s / 1e3, 3)}  # mb_per_s is a property


def _persist_partial(out: dict) -> None:
    """Incrementally checkpoint the artifact-in-progress (VERDICT r5
    item 1, first half): after every completed metric the current JSON
    shape is atomically rewritten to ``$ZEST_BENCH_PARTIAL``, so a
    backend death (or tunnel hang → supervisor timeout kill) mid-set
    still leaves every finished row for the supervisor to recover.
    No-op when the env var is unset (direct child runs)."""
    path = os.environ.get("ZEST_BENCH_PARTIAL")
    if not path:
        return
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, path)
    except OSError:  # persistence is best-effort; the bench itself goes on
        pass


def child_main() -> None:
    """The real bench. Runs with a live (already probed) backend; still
    guards every metric individually so one failure can't zero the rest,
    and checkpoints the artifact after every metric (_persist_partial)
    so a mid-set death can't zero the finished ones either."""
    import jax

    try:
        blake3 = bench_blake3_device()
        primary_error = None
    except Exception as exc:  # device path broken: degrade, don't die
        blake3 = host_blake3_fallback()
        primary_error = f"{type(exc).__name__}: {exc}"

    extra = {}
    out = _emit(blake3, device=jax.devices()[0].platform, extra=extra)
    if primary_error:
        out["primary_error"] = primary_error
    _persist_partial(out)

    # Order matters on a one-vCPU host: pull_gb writes ~7 GB through the
    # page cache and its writeback drains for minutes afterwards,
    # polluting any CPU-bound measurement that follows (observed: the
    # same blake3_64kb measured 1.7 GB/s right after pull_gb, 4.2 GB/s
    # on a quiet host). Microbenches run first, the disk-heavy GB pull
    # last.
    extras = [
        ("host_synthetics", bench_host_synthetics),
        ("decode_batch", bench_decode_batch),
        ("mfu", bench_mfu),
        ("decode", bench_decode),
        ("host_to_hbm", bench_host_to_hbm),
        ("http_warm", bench_http_warm),
        ("http_warm_device", bench_http_warm_device),
        ("ici_all_gather", bench_ici_all_gather),
        ("pull_gb", bench_pull_gb),
        # After pull_gb (same disk-heavy class): two pulls + the
        # one-time digest oracle per run.
        ("delta_pull", bench_delta_pull),
    ]
    skip = {s for s in os.environ.get("ZEST_BENCH_SKIP", "").split(",") if s}
    die_after = os.environ.get("ZEST_BENCH_DIE_AFTER")
    for name, fn in extras:
        if name in skip:
            continue
        try:
            result = fn()
        except Exception as exc:
            result = {"error": f"{type(exc).__name__}: {exc}"}
        if result is not None:
            extra[name] = result
            _persist_partial(out)
        if name == die_after:
            # Test hook for the mid-set-death contract (the supervisor
            # tests kill the child here and assert the persisted rows
            # survive into the emitted artifact).
            os._exit(86)

    print(json.dumps(out))


def _emit(blake3: dict, device: str, extra: dict) -> dict:
    """The one-JSON-line shape, built in exactly one place."""
    return {
        "metric": "blake3_64kb_device",
        "value": blake3["mbps"],
        "unit": "MB/s",
        "vs_baseline": round(blake3["mbps"] / BASELINE_MBPS, 3),
        "device": device,
        "batch": blake3["batch"],
        "method": blake3.get("method"),
        "extra": extra,
    }


# --------------------------------------------------------------------
# Supervisor (no jax imports anywhere on this path)
# --------------------------------------------------------------------


def _probe_backend(platform: str | None, timeout_s: float) -> tuple[str | None, str | None]:
    """Subprocess probe: can a jax backend initialize at all?

    Returns (platform_name, None) on success, (None, error) on failure —
    including the round-4 killer, an indefinite hang inside axon init,
    which the subprocess timeout converts into a recorded error."""
    code = (
        "import os, jax\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "print('PLATFORM=' + jax.devices()[0].platform)\n"
    )
    env = dict(os.environ)
    env.pop("ZEST_BENCH_CHILD", None)
    if platform:
        env["JAX_PLATFORMS"] = platform
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"backend init hung >{timeout_s:.0f}s"
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()
        return None, " | ".join(tail[-3:])[-400:]
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    return None, "probe printed no platform"


def _load_partial(path: str) -> dict | None:
    """The child's last checkpointed artifact, or None when it never
    got as far as the primary metric."""
    try:
        with open(path) as f:
            parsed = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    return None


def _run_child(platform: str | None, timeout_s: float) -> tuple[dict | None, str | None]:
    """Run the measurement child; parse its one JSON line.

    The child checkpoints the artifact after every metric into a
    partial file this supervisor hands it (ZEST_BENCH_PARTIAL): a child
    that dies or hangs mid-set no longer loses the round's finished
    rows — the recovered partial is returned with ``"partial": true``
    and the death recorded in ``"partial_error"``. Losing the tail of
    the set beats losing a whole on-chip artifact (VERDICT r5 item 1)."""
    import tempfile

    env = dict(os.environ, ZEST_BENCH_CHILD="1")
    if platform:
        env["JAX_PLATFORMS"] = platform
    fd, partial_path = tempfile.mkstemp(prefix="zest-bench-partial-",
                                        suffix=".json")
    os.close(fd)
    env["ZEST_BENCH_PARTIAL"] = partial_path
    try:
        try:
            out = subprocess.run([sys.executable, __file__], env=env,
                                 capture_output=True, text=True,
                                 timeout=timeout_s)
        except subprocess.TimeoutExpired:
            err = f"bench child hung >{timeout_s:.0f}s"
            parsed = _load_partial(partial_path)
            if parsed is not None:
                parsed["partial"] = True
                parsed["partial_error"] = err
                return parsed, None
            return None, err
        if out.stderr:
            sys.stderr.write(out.stderr[-2000:])
        for line in reversed(out.stdout.strip().splitlines()):
            if line.startswith("{"):
                try:
                    return json.loads(line), None
                except json.JSONDecodeError:
                    continue
        tail = (out.stderr or "").strip().splitlines()
        err = f"rc={out.returncode}: " + " | ".join(tail[-3:])[-400:]
        parsed = _load_partial(partial_path)
        if parsed is not None:
            parsed["partial"] = True
            parsed["partial_error"] = err
            return parsed, None
        return None, err
    finally:
        try:
            os.unlink(partial_path)
        except OSError:
            pass


def main() -> None:
    # 120s is 3-6x the observed live-tunnel init time (~20-40s); on a
    # DEAD tunnel the probe always burns the full timeout twice (retry),
    # so a tighter default keeps the whole fallback path well inside the
    # driver's window while still never cutting off a live chip.
    probe_timeout = float(os.environ.get("ZEST_BENCH_PROBE_TIMEOUT_S", "120"))
    child_timeout = float(os.environ.get("ZEST_BENCH_CHILD_TIMEOUT_S", "2700"))

    requested = os.environ.get("JAX_PLATFORMS") or None
    attempts: list[str | None] = [requested]
    if requested != "cpu":
        attempts.append("cpu")

    errors: dict[str, str] = {}
    non_cpu_failed = False
    tried_children: set[str] = set()

    def error_field(parsed: dict) -> None:
        # "tpu_error" only when a chip-capable attempt actually failed;
        # a cpu-only failure under JAX_PLATFORMS=cpu must not read as a
        # TPU failure to whoever audits the artifact.
        key = "tpu_error" if non_cpu_failed else "backend_errors"
        parsed[key] = "; ".join(f"{k}: {v}" for k, v in errors.items())

    for platform in attempts:
        label = platform or "default"
        plat_name, err = _probe_backend(platform, probe_timeout)
        if err is not None and label != "cpu":
            # The chip sits behind a tunnel that can hiccup transiently
            # (observed: a probe hanging >180s while the very same chip
            # answered minutes before and after). One retry is cheap
            # next to losing the round's only on-chip artifact.
            time.sleep(10)
            plat_name, err2 = _probe_backend(platform, probe_timeout)
            err = None if err2 is None else f"{err}; retry: {err2}"
        if err is not None:
            errors[label] = f"probe: {err}"
            non_cpu_failed = non_cpu_failed or label != "cpu"
            continue
        if plat_name in tried_children:
            continue  # a default probe resolving to cpu already failed
        tried_children.add(plat_name)
        parsed, err = _run_child(platform, child_timeout)
        if parsed is not None:
            if parsed.get("partial"):
                # Recovered rows from a child that died mid-set: the
                # death still counts as this attempt's failure record
                # (a partial TPU artifact beats a complete CPU one, so
                # it is emitted rather than falling through to cpu).
                errors[f"{label}-child"] = (
                    parsed.get("partial_error") or "died mid-set")
                non_cpu_failed = non_cpu_failed or plat_name != "cpu"
            if errors:
                error_field(parsed)
            print(json.dumps(parsed))
            return
        errors[f"{label}-child"] = err or "unknown"
        non_cpu_failed = non_cpu_failed or plat_name != "cpu"

    # Every backend is dead. The metric must still exist: host-native
    # BLAKE3 throughput (pure ctypes — no jax in this process).
    out = _emit(host_blake3_fallback(), device="host", extra={})
    error_field(out)
    print(json.dumps(out))


if __name__ == "__main__":
    child_main() if _IS_CHILD else main()
